"""Build script for the native runtime components.

    python setup.py build_ext --inplace

produces the C++ native runtime extensions:

  examl_tpu/_patterncrunch*.so — pattern-compression core for the parser
  pipeline (io/alignment.py falls back to NumPy when unbuilt)
  examl_tpu/_newickscan*.so — flat-array newick scanner for
  reference-scale trees (io/newick.py falls back to pure Python)
"""

from setuptools import Extension, setup

setup(
    name="examl-tpu-native",
    version="0.1",
    ext_modules=[
        Extension(
            "examl_tpu._patterncrunch",
            sources=["native/patterncrunch.cpp"],
            extra_compile_args=["-O3", "-std=c++17"],
            language="c++",
        ),
        Extension(
            "examl_tpu._newickscan",
            sources=["native/newickscan.cpp"],
            extra_compile_args=["-O3", "-std=c++17"],
            language="c++",
        ),
    ],
)
