"""Build script for the native runtime components.

    python setup.py build_ext --inplace

produces examl_tpu/_patterncrunch*.so, the C++ pattern-compression core
used by the parser pipeline (io/alignment.py falls back to the NumPy path
when the extension has not been built).
"""

from setuptools import Extension, setup

setup(
    name="examl-tpu-native",
    version="0.1",
    ext_modules=[
        Extension(
            "examl_tpu._patterncrunch",
            sources=["native/patterncrunch.cpp"],
            extra_compile_args=["-O3", "-std=c++17"],
            language="c++",
        ),
    ],
)
