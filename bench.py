"""Benchmark: site-CLV updates/sec/chip on the 140-taxon AA test set.

North-star metric from BASELINE.json: CLV (newview) update throughput on
`/root/reference/testData/140` (GTR-family 20-state GAMMA), measured as
  traversal entries x pattern count x rates x states / wall second
over dependency-chained full-tree traversals (each step consumes the
previous step's CLV buffer, so device pipelining cannot overlap steps).
Equivalent reference loop: `newviewIterative` over a full traversal
(`newviewGenericSpecial.c:917-1515`).

vs_baseline compares against one AVX socket of the reference build; the
number comes from tools/avx_baseline.json when the measurement harness
(tools/bench_reference.py) has been run, else a conservative estimate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
DATA = "/root/reference/testData"
# Conservative single-socket AVX estimate until tools/bench_reference.py
# measures the real number on this host (writes tools/avx_baseline.json).
FALLBACK_AVX_UPDATES_PER_SEC = 2.0e9


def _load_instance():
    from examl_tpu.instance import PhyloInstance, default_instance

    phy = os.path.join(DATA, "140")
    mod = os.path.join(DATA, "140.model")
    if os.path.exists(phy):
        inst = default_instance(phy, mod)    # auto dtype: f32 on TPU
        tree = inst.tree_from_newick(open(os.path.join(DATA, "140.tree")).read())
        return inst, tree, "testData/140"
    # Fallback synthetic AA set with the same shape.
    from examl_tpu.io.alignment import build_alignment_data
    rng = np.random.default_rng(0)
    aas = "ARNDCQEGHILKMFPSTWYV"
    names = [f"t{i}" for i in range(140)]
    seqs = ["".join(aas[c] for c in rng.integers(0, 20, 1104))
            for _ in names]
    ad = build_alignment_data(names, seqs, datatype_name="AA")
    inst = PhyloInstance(ad)
    return inst, inst.random_tree(0), "synthetic-140"


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    inst, tree, dataset = _load_instance()
    lnl = inst.evaluate(tree, full=True)

    import jax.numpy as jnp

    from examl_tpu.ops import fastpath

    eng = inst.engines[20]
    _, entries = tree.full_traversal_centroid()
    sched = eng._fast_schedule(entries)
    chunks = sched.chunks
    n_steps = 50

    # n_steps dependency-chained traversals inside ONE jit returning a
    # scalar: immune to async-dispatch/transfer artifacts of the TPU tunnel.
    @jax.jit
    def chained(clv, scaler):
        def body(_, cs):
            return fastpath.run_chunks(eng.models, eng.block_part, eng.tips,
                                       cs[0], cs[1], chunks, eng.scale_exp,
                                       eng.fast_precision)
        clv, scaler = jax.lax.fori_loop(0, n_steps, body, (clv, scaler))
        return jnp.sum(scaler)

    float(chained(eng.clv, eng.scaler))      # compile + warm
    best = 1e18
    for _ in range(3):
        t0 = time.perf_counter()
        float(chained(eng.clv, eng.scaler))
        best = min(best, time.perf_counter() - t0)
    dt = best

    patterns = sum(p.width for p in inst.alignment.partitions)
    rates, states = eng.R, eng.K
    updates = n_steps * len(entries) * patterns * rates * states
    ups = updates / dt

    base_path = os.path.join(REPO, "tools", "avx_baseline.json")
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        avx = float(base["site_clv_updates_per_sec"])
        base_src = base.get("source", "measured")
    else:
        avx = FALLBACK_AVX_UPDATES_PER_SEC
        base_src = "estimate"

    print(json.dumps({
        "metric": "site_clv_updates_per_sec",
        "value": round(ups, 1),
        "unit": "updates/s",
        "vs_baseline": round(ups / avx, 3),
        "dataset": dataset,
        "dtype": str(eng.dtype),
        "lnl": round(float(lnl), 6),
        "ms_per_traversal": round(dt / n_steps * 1000, 3),
        "baseline_source": base_src,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
