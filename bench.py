"""Benchmark: site-CLV updates/sec/chip on the 140-taxon AA test set.

North-star metric from BASELINE.json: CLV (newview) update throughput on
`/root/reference/testData/140` (GTR-family 20-state GAMMA), measured as
  traversal entries x pattern count x rates x states / wall second
over dependency-chained full-tree traversals (each step consumes the
previous step's CLV buffer, so device pipelining cannot overlap steps).
Equivalent reference loop: `newviewIterative` over a full traversal
(`newviewGenericSpecial.c:917-1515`).

vs_baseline compares against one AVX socket of the reference build; the
number comes from tools/avx_baseline.json when the measurement harness
(tools/bench_reference.py) has been run, else a conservative estimate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Budget epoch shared across re-exec/fallback children: a child inherits
# the ORIGINAL process's start time via EXAML_BENCH_T0 so probe time
# already spent counts against the wall budget (the budget protects the
# driver's bench window, not any single process).
try:
    _EPOCH0 = float(os.environ.get("EXAML_BENCH_T0") or time.time())
except ValueError:
    _EPOCH0 = time.time()

import numpy as np


def _elapsed() -> float:
    return time.time() - _EPOCH0


def _budget() -> float:
    try:
        return float(os.environ.get("EXAML_BENCH_BUDGET_S", "480"))
    except ValueError:
        return 480.0


def _num_or_null(x: float, digits: int = 3):
    """Budget-skipped metrics are NaN internally; the JSON line must
    stay RFC-8259 (null), not bare NaN."""
    import math
    return None if math.isnan(x) else round(x, digits)

REPO = os.path.dirname(os.path.abspath(__file__))
DATA = "/root/reference/testData"
# Conservative single-socket AVX estimate until tools/bench_reference.py
# measures the real number on this host (writes tools/avx_baseline.json).
FALLBACK_AVX_UPDATES_PER_SEC = 2.0e9


def _load_instance():
    from examl_tpu.instance import PhyloInstance, default_instance

    phy = os.path.join(DATA, "140")
    mod = os.path.join(DATA, "140.model")
    if os.path.exists(phy):
        inst = default_instance(phy, mod)    # auto dtype: f32 on TPU
        tree = inst.tree_from_newick(open(os.path.join(DATA, "140.tree")).read())
        return inst, tree, "testData/140"
    # Fallback synthetic AA set with the same shape.
    from examl_tpu.io.alignment import build_alignment_data
    rng = np.random.default_rng(0)
    aas = "ARNDCQEGHILKMFPSTWYV"
    names = [f"t{i}" for i in range(140)]
    seqs = ["".join(aas[c] for c in rng.integers(0, 20, 1104))
            for _ in names]
    ad = build_alignment_data(names, seqs, datatype_name="AA")
    inst = PhyloInstance(ad)
    return inst, inst.random_tree(0), "synthetic-140"


def _probe_backend(budgets=(180, 60)) -> bool:
    """Probe the default JAX backend in a SUBPROCESS; a broken
    accelerator plugin can hang its host process inside client init,
    where no in-process timeout can recover.  Multiple tries: a flaky
    tunnel can heal between them."""
    import subprocess
    import sys

    for attempt, budget in enumerate(budgets):
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); "
                 "import jax.numpy as jnp; jnp.zeros(2).block_until_ready()"],
                env=os.environ, capture_output=True, timeout=budget)
            if proc.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < len(budgets):   # no dead wait after the final try
            time.sleep(15)
    return False


def _child_env(cpu: bool) -> dict:
    env = dict(os.environ)
    env["EXAML_BENCH_NO_PROBE"] = "1"
    env["EXAML_BENCH_T0"] = repr(_EPOCH0)
    if not cpu:
        return env
    env["JAX_PLATFORMS"] = "cpu"
    env["EXAML_BENCH_FALLBACK"] = "1"
    # Accelerator plugins loaded via sitecustomize can hang their host
    # process at import even under JAX_PLATFORMS=cpu; strip the plugin's
    # site dir from the child's path.  Path components to strip are
    # env-configurable so the knowledge lives with the deployment.
    strip = os.environ.get("EXAML_BENCH_STRIP_PYTHONPATH",
                           ".axon_site").split(",")
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and not any(c in p.split(os.sep) for c in strip if c)]
    env["PYTHONPATH"] = os.pathsep.join(pp) if pp else ""
    return env


def _spawn_bench(cpu: bool, timeout: float):
    """Run this benchmark in a child process; return its JSON line (str)
    or None.  The child inherits the budget epoch so it skips secondary
    metrics rather than blowing the driver's window."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_child_env(cpu), capture_output=True, text=True,
            timeout=max(60.0, timeout))
    except subprocess.TimeoutExpired as e:
        if e.stderr:
            sys.stderr.write(e.stderr if isinstance(e.stderr, str)
                             else e.stderr.decode(errors="replace"))
        return None
    sys.stderr.write(proc.stderr)
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
                return line
            except ValueError:
                continue
    return None


def _ensure_live_backend() -> None:
    """Probe the default backend; on failure record a CPU fallback run in
    a child, then RE-PROBE late in the wall budget (a flaky tunnel often
    heals within minutes — round-3 lesson) and, if the chip answers,
    supersede the CPU line with a real accelerator run."""
    import sys

    if os.environ.get("EXAML_BENCH_NO_PROBE"):
        return
    if _probe_backend():
        return
    sys.stderr.write("bench: default backend unusable; falling back to "
                     "CPU (will re-probe late in the budget)\n")
    budget = _budget()
    # Generous floor: the old execve path had NO timeout and its "always
    # records a result" guarantee must survive — the child's own budget
    # clock (inherited epoch) handles skipping secondary metrics; the
    # hard kill exists only for a pathological hang.
    cpu_line = _spawn_bench(cpu=True,
                            timeout=max(900.0, budget - _elapsed() + 180))
    # Late retry window: everything left of the budget (plus grace) goes
    # to one more probe + a full accelerator run if the tunnel healed.
    if budget - _elapsed() > 90 and _probe_backend(budgets=(60,)):
        sys.stderr.write("bench: accelerator healed on late re-probe; "
                         "re-running on default backend\n")
        tpu_line = _spawn_bench(cpu=False,
                                timeout=budget - _elapsed() + 240)
        if tpu_line is not None:
            print(tpu_line)
            raise SystemExit(0)
    if cpu_line is not None:
        print(cpu_line)
        raise SystemExit(0)
    raise SystemExit("bench: no variant produced a result")


def _synthetic_instance(ntaxa: int, width: int, datatype: str = "DNA",
                        dtype=None):
    """A synthetic compute-bound benchmark alignment, built WITHOUT
    pattern compression (random sites do not compress; weights are 1):
    big enough that the traversal is HBM/MXU-bound rather than
    dispatch-bound — the regime the small testData sets cannot reach
    (SURVEY §6 recommends 3-4k DNA / ~1k AA patterns PER CORE on the
    reference; one chip replaces a whole socket)."""
    from examl_tpu import datatypes
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.alignment import AlignmentData, PartitionData

    rng = np.random.default_rng(0)
    dt = datatypes.get(datatype)
    if datatype == "DNA":
        codes = rng.choice(np.array([1, 2, 4, 8], dtype=np.uint8),
                           size=(ntaxa, width))
        part = PartitionData(
            name="bench", datatype=dt, model_name="DNA",
            patterns=codes, weights=np.ones(width, dtype=np.int64),
            empirical_freqs=np.full(4, 0.25), use_empirical_freqs=True,
            optimize_freqs=False)
    else:
        codes = rng.integers(0, 20, size=(ntaxa, width), dtype=np.uint8)
        part = PartitionData(
            name="bench", datatype=dt, model_name="LG",
            patterns=codes, weights=np.ones(width, dtype=np.int64),
            empirical_freqs=np.full(20, 0.05), use_empirical_freqs=False,
            optimize_freqs=False)
    inst = PhyloInstance(AlignmentData([f"t{i}" for i in range(ntaxa)],
                                       [part]), dtype=dtype)
    return inst, inst.random_tree(0)


LARGE_CONFIGS = {
    # name: (ntaxa, patterns, datatype) — sized to keep the f32 CLV
    # arena under ~8 GB HBM while holding >1e8 site-updates in flight.
    "dna-large": (140, 524_288, "DNA"),
    "aa-large": (140, 131_072, "AA"),
    "dna-1000": (1_000, 131_072, "DNA"),
}


def _traversal_flops(fn, eng) -> float:
    """XLA's own cost model for one chained-traversal program; NaN when
    the API shape differs across jax versions."""
    try:
        cost = fn.lower(eng.clv, eng.scaler).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost["flops"])
    except Exception:
        return float("nan")


def _measure_traversal(inst, tree, budget: float) -> dict:
    """Auto-tune the full-traversal variants (plain-XLA chunk pipeline,
    fused Pallas chunk kernels, whole-traversal kernel) the way the
    reference picks its fastest ISA backend; return the winner's
    throughput plus XLA-counted FLOP/s and MFU.

    n_steps dependency-chained traversals inside ONE jit returning a
    scalar: immune to async-dispatch/transfer artifacts of the TPU
    tunnel."""
    import jax
    import jax.numpy as jnp

    lnl = inst.evaluate(tree, full=True)
    (eng,) = inst.engines.values()
    _, entries = tree.full_traversal_centroid()
    sched = eng._fast_schedule(entries)
    chunks = sched.chunks
    patterns = sum(p.width for p in inst.alignment.partitions)
    # Scale the chain so one timing rep stays ~O(seconds) on the large
    # configs (~2e9 site-updates per chain) while the small config keeps
    # its 50-step chain.
    per_trav = len(entries) * patterns * eng.R * eng.K
    n_steps = max(5, min(50, int(2e9 / max(per_trav, 1))))

    def chained_fn(body_step):
        @jax.jit
        def chained(clv, scaler):
            def body(_, cs):
                return body_step(cs[0], cs[1])
            clv, scaler = jax.lax.fori_loop(0, n_steps, body, (clv, scaler))
            return jnp.sum(scaler)
        return chained

    def chunks_step(use_pallas):
        def step(clv, scaler):
            eng.use_pallas = use_pallas
            return eng.run_chunks_traced(clv, scaler, chunks)
        return step

    variants = [("xla", chunks_step(False))]
    if eng.use_pallas:               # the engine's own placement decision
        from examl_tpu.ops import pallas_whole
        wsched = pallas_whole.build_flat(entries, eng.ntips,
                                         eng.num_branch_slots)
        variants.append(("pallas", chunks_step(True)))
        variants.append(("pallas-whole",
                         lambda c, s: eng.run_whole_traced(c, s, wsched)))
    # Auto-tune under a wall-clock budget: a variant whose compile blows
    # the budget must not starve the recorded result (the driver's bench
    # window is finite), so later variants are skipped once a number is
    # in hand and the budget is spent.  The clock includes everything
    # since process start (probe, instance build, first evaluate).
    dt, variant, best_fn = None, None, None
    for name, step in variants:
        if dt is not None and _elapsed() > budget:
            sys.stderr.write(f"bench: budget spent; skipping {name}\n")
            continue
        try:
            fn = chained_fn(step)
            float(fn(eng.clv, eng.scaler))       # compile + warm
        except Exception as exc:                 # noqa: BLE001
            sys.stderr.write(f"bench: variant {name} failed: {exc}\n")
            continue
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(eng.clv, eng.scaler))
            d = time.perf_counter() - t0
            if dt is None or d < dt:
                dt, variant, best_fn = d, name, fn
    if dt is None:
        raise RuntimeError("no traversal variant ran successfully")
    eng.use_pallas = (variant in ("pallas", "pallas-whole"))
    eng.pallas_whole = (variant == "pallas-whole")

    import math

    updates = n_steps * len(entries) * patterns * eng.R * eng.K
    flops = _traversal_flops(best_fn, eng)
    try:
        peak = float(os.environ.get("EXAML_PEAK_FLOPS", "1.97e14"))
    except ValueError:
        peak = 1.97e14
    fps = flops / dt
    if math.isnan(fps):          # cost model unavailable: null, not NaN
        fps = None               # (bare NaN breaks the JSON line contract)
    return {
        "ups": updates / dt,
        "dt": dt,
        "n_steps": n_steps,
        "variant": variant,
        "patterns": patterns,
        "lnl": float(lnl),
        "tflops_per_sec": (None if fps is None
                           else round(fps / 1e12, 3)),
        # MFU vs the bf16 MXU peak (v5e ~197 TFLOP/s; override with
        # EXAML_PEAK_FLOPS) — a utilization DIAGNOSTIC, pessimistic for
        # f32 programs whose true ceiling is lower (see ROOFLINE.md:
        # this kernel is bandwidth-bound; low MFU is expected).
        "mfu": None if fps is None else round(fps / peak, 5),
        "eng": eng,
        "entries": entries,
    }


def main() -> None:
    _ensure_live_backend()
    import jax

    jax.config.update("jax_enable_x64", True)
    inst, tree, dataset = _load_instance()
    budget = _budget()
    meas = _measure_traversal(inst, tree, budget)
    lnl = meas["lnl"]
    eng, entries = meas["eng"], meas["entries"]
    dt, variant, n_steps = meas["dt"], meas["variant"], meas["n_steps"]
    ups = meas["ups"]

    # Secondary metrics: per-call latency of the fused search primitives
    # (partial traversal + root lnL; partial traversal + sumtable + full
    # Newton-Raphson) and the batched SPR radius scan.  These are the
    # per-SPR-insertion / per-branch / per-pruned-node costs that
    # dominate end-to-end search time (reference stacks SURVEY §3.2-3.3);
    # dispatch overhead is included on purpose.  Skipped (NaN) when the
    # wall budget is already spent — the primary metric must always be
    # recorded.
    eval_ms = newton_ms = scan_ms = float("nan")
    ncand = 0
    if _elapsed() < budget:
        inner = [tree.nodep[n] for n in tree.inner_numbers()
                 if not tree.is_tip(tree.nodep[n].back.number)][:12]
        for p in inner:     # warm compile variants
            inst.evaluate(tree, p)
            inst.makenewz(tree, p, p.back, p.z, maxiter=16)
        t0 = time.perf_counter()
        for p in inner:
            inst.evaluate(tree, p)
        eval_ms = (time.perf_counter() - t0) / len(inner) * 1000
        t0 = time.perf_counter()
        for p in inner:
            inst.makenewz(tree, p, p.back, p.z, maxiter=16)
        newton_ms = (time.perf_counter() - t0) / len(inner) * 1000

    if _elapsed() < budget:
        from examl_tpu.search import batchscan, spr
        from examl_tpu.tree.topology import hookup
        ctx = spr.SprContext(inst, thorough=False, do_cutoff=False)
        c = tree.centroid_branch()           # a node with a deep window
        p = c if not tree.is_tip(c.number) else c.back
        q1, q2 = p.next.back, p.next.next.back
        p1z, p2z = list(q1.z), list(q2.z)
        spr.remove_node(inst, tree, ctx, p)
        plan = batchscan.plan_for_endpoints(inst, tree, p, q1, q2, 1, 10)
        if plan is not None:                 # tip-locked window: no metric
            batchscan.run_plan(inst, tree, plan)     # compile + warm
            t0 = time.perf_counter()
            batchscan.run_plan(inst, tree, plan)
            scan_ms = (time.perf_counter() - t0) * 1000
            ncand = len(plan.candidates)
        hookup(p.next, q1, p1z)
        hookup(p.next.next, q2, p2z)
        inst.new_view(tree, p)

    base_path = os.path.join(REPO, "tools", "avx_baseline.json")
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        avx = float(base["site_clv_updates_per_sec"])
        base_src = base.get("source", "measured")
    else:
        avx = FALLBACK_AVX_UPDATES_PER_SEC
        base_src = "estimate"

    backend = jax.default_backend()

    # Large compute-bound configs: the 1,104-pattern testData/140 is
    # dispatch-bound (6 ms/traversal at r02) and cannot demonstrate chip
    # capability; the synthetic half-million-pattern configs are where
    # vs_baseline has headroom to mean something.  Accelerator runs only
    # (a CPU host would swap on the 4-7 GB arenas), inside the budget.
    large = {}
    cfg_env = os.environ.get("EXAML_BENCH_LARGE", "dna-large,aa-large")
    configs = []
    for tok in (c.strip() for c in cfg_env.split(",") if c.strip()):
        if tok in LARGE_CONFIGS:
            configs.append(tok)
        else:
            sys.stderr.write(f"bench: unknown EXAML_BENCH_LARGE config "
                             f"{tok!r} (known: "
                             f"{','.join(LARGE_CONFIGS)}); skipping\n")
    for i, large_cfg in enumerate(configs):
        # first config keyed "large_*" (schema continuity), later ones
        # prefixed by their name
        pre = "large" if i == 0 else large_cfg.replace("-", "_")
        if not (backend in ("tpu", "axon") and _elapsed() < budget):
            continue
        linst = ltree = None
        try:
            ntaxa, width, dtname = LARGE_CONFIGS[large_cfg]
            linst, ltree = _synthetic_instance(ntaxa, width, dtname)
            lm = _measure_traversal(linst, ltree, budget)
            large.update({
                f"{pre}_config": large_cfg,
                f"{pre}_updates_per_sec": round(lm["ups"], 1),
                f"{pre}_vs_baseline": round(lm["ups"] / avx, 3),
                f"{pre}_ms_per_traversal":
                    round(lm["dt"] / lm["n_steps"] * 1000, 3),
                f"{pre}_variant": lm["variant"],
                f"{pre}_tflops_per_sec": lm["tflops_per_sec"],
                f"{pre}_mfu": lm["mfu"]})
            del lm
        except Exception as exc:                 # noqa: BLE001
            sys.stderr.write(f"bench: large config {large_cfg} failed: "
                             f"{exc}\n")
            large[f"{pre}_config"] = large_cfg
            large[f"{pre}_error"] = str(exc)
        finally:
            # Free the multi-GB arena before the next config — on the
            # FAILURE path too (an OOM on config 1 must not cascade into
            # config 2 by keeping the dead arena referenced).
            del linst, ltree
    # A fallback run is NEVER comparable to an accelerator number: the
    # baseline is one AVX socket and the metric races the chip against
    # it, so vs_baseline only "counts" when the run executed on tpu/axon
    # (round-3 lesson: BENCH_r03 recorded a CPU number that read like a
    # regression).
    vs_valid = backend in ("tpu", "axon")
    print(json.dumps({
        "metric": "site_clv_updates_per_sec",
        "value": round(ups, 1),
        "unit": "updates/s",
        "vs_baseline": round(ups / avx, 3),
        "vs_baseline_valid": vs_valid,
        "dataset": dataset,
        "dtype": str(eng.dtype),
        "lnl": round(float(lnl), 6),
        "ms_per_traversal": round(dt / n_steps * 1000, 3),
        "traversal_variant": variant,
        "evaluate_ms": _num_or_null(eval_ms),
        "newton_branch_ms": _num_or_null(newton_ms),
        "spr_scan_ms_per_node": _num_or_null(scan_ms),
        "spr_scan_candidates": ncand,
        "tflops_per_sec": meas["tflops_per_sec"],
        "mfu": meas["mfu"],
        **large,
        "baseline_source": base_src,
        "backend": backend,
        **({"note": "accelerator unreachable after probe+retry; "
                    "CPU fallback"}
           if os.environ.get("EXAML_BENCH_FALLBACK") else {}),
    }))


if __name__ == "__main__":
    main()
