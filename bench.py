"""Benchmark: site-CLV updates/sec/chip on the 140-taxon AA test set.

North-star metric from BASELINE.json: CLV (newview) update throughput on
`/root/reference/testData/140` (GTR-family 20-state GAMMA), measured as
  traversal entries x pattern count x rates x states / wall second
over dependency-chained full-tree traversals (each step consumes the
previous step's CLV buffer, so device pipelining cannot overlap steps).
Equivalent reference loop: `newviewIterative` over a full traversal
(`newviewGenericSpecial.c:917-1515`).

Structure (round-4 lesson): every measurement runs in a WORKER
SUBPROCESS executing an ordered stage plan and printing one JSON line
per completed stage.  The parent enforces wall-clock deadlines with
process kills — a single wedged remote compile (the axon tunnel can
block in recv indefinitely) then costs one stage, not the whole bench:
completed stage lines are parsed out of the killed worker's partial
stdout, the hung stage is recorded as such, and a fresh worker resumes
the remaining plan if the chip still answers a probe.

Stages: `s-scan` / `s-chunks` / `s-pallas` / `s-whole` time the four
traversal tiers on testData/140 (scan first — the one tier whose
compile is hardware-proven since r02, so the primary metric always
lands); `L:<config>` are the compute-bound large configs (ROOFLINE.md)
plus CPU-runnable `*-mid` rows for every BASELINE config (AA, PSR, SEV,
bf16) so fallback rounds still carry per-config evidence; `prims` times
the fused search primitives.  Workers dispatch only BANKED programs:
families the per-host bank manifest (ops/bank.py, `--bank`) recorded as
wedged are skipped with a note instead of re-raced, and a worker death
is recorded with its exit signal/returncode so SIGILL, OOM, and
hang-kill are distinguishable in the artifact.

vs_baseline compares against one AVX socket of the reference build and
is only marked valid for accelerator runs (round-3 lesson: a CPU
fallback number must never read like a TPU regression).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Budget epoch shared across parent/worker/fallback children: a child
# inherits the ORIGINAL process's start time via EXAML_BENCH_T0 so time
# already spent counts against the wall budget (the budget protects the
# driver's bench window, not any single process).  The env read happens
# at first use, not import (GL004: an import-time read would freeze the
# value before a parent could set it), against this process's start
# time as the fallback epoch.
_T0 = time.time()

import numpy as np


def _epoch0() -> float:
    try:
        return float(os.environ.get("EXAML_BENCH_T0") or _T0)
    except ValueError:
        return _T0


def _elapsed() -> float:
    return time.time() - _epoch0()


def _budget() -> float:
    try:
        return float(os.environ.get("EXAML_BENCH_BUDGET_S", "480"))
    except ValueError:
        return 480.0


REPO = os.path.dirname(os.path.abspath(__file__))
DATA = "/root/reference/testData"
# Conservative single-socket AVX estimate until tools/bench_reference.py
# measures the real number on this host (writes tools/avx_baseline.json).
FALLBACK_AVX_UPDATES_PER_SEC = 2.0e9

# Order = information value under the wedge risk: the scan tier's
# compile is hardware-proven, so it lands the primary metric AND the
# compute-bound large configs FIRST; the chunk/Pallas tiers follow —
# their compiles are the ones that have hung the tunnel (a killed
# worker can wedge every later stage), so they must not be able to
# cost the headline numbers.  Deliberate trade-off: on a fresh run the
# large configs therefore always measure the SCAN variant (the
# best-variant hint only helps resumed workers); if a faster tier
# proves itself on hardware, promote it by reordering here.
TPU_PLAN = ["s-scan", "L:dna-large", "L:aa-large", "L:dna-bf16",
            "L:dna-psr", "L:dna-sev", "pallas-check", "s-chunks",
            "s-pallas", "s-whole", "prims"]
# The CPU fallback records a (small) large-config row for EVERY
# BASELINE config — DNA, protein, PSR, SEV, bf16 — so each round's
# artifact carries a backend-tagged number per config even when the
# chip never answers (VERDICT r05 Next §3: after three fallback rounds
# no artifact anywhere had a protein/PSR/SEV/bf16 row on any backend).
# Mid configs come right after the proven scan stage and before the
# chunk/prims stages so a budget squeeze drops tiers, not configs.
CPU_PLAN = ["s-scan", "L:dna-mid", "L:aa-mid", "L:psr-mid", "L:sev-mid",
            "L:bf16-mid", "s-chunks", "prims"]

LARGE_CONFIGS = {
    # name: (ntaxa, patterns, datatype, mode) — sized to keep the f32
    # CLV arena under ~8 GB HBM while holding >1e8 site-updates in
    # flight.  mode: "" plain GAMMA; "psr" per-site-rate multipliers
    # ride every P application (BASELINE config 4); "sev" gappy
    # clade-structured alignment traversed on the -S pool (config 5).
    "dna-large": (140, 524_288, "DNA", ""),
    "aa-large": (140, 131_072, "AA", ""),
    "dna-1000": (1_000, 131_072, "DNA", ""),
    "dna-psr": (140, 262_144, "DNA", "psr"),
    "dna-sev": (140, 262_144, "DNA", "sev"),
    # bf16 CLV storage (ROOFLINE lever 3): same shape as dna-large,
    # half the bytes/update — the throughput-ceiling doubler.
    "dna-bf16": (140, 524_288, "DNA", "bf16"),
    # CPU-fallback-sized: compute-bound on a host core, ~1.2 GB f64.
    "dna-mid": (140, 32_768, "DNA", ""),
    # Mid-size companions of BASELINE configs 2-5, CPU-runnable so every
    # round's artifact has a row per config (widths follow the manual's
    # per-core pattern guidance: ~1k AA, 12-16k PSR patterns/core).
    "aa-mid": (140, 8_192, "AA", ""),
    "psr-mid": (140, 16_384, "DNA", "psr"),
    "sev-mid": (140, 16_384, "DNA", "sev"),
    "bf16-mid": (140, 32_768, "DNA", "bf16"),
}


# ---------------------------------------------------------------------------
# instances


def _load_instance():
    from examl_tpu.instance import PhyloInstance, default_instance

    phy = os.path.join(DATA, "140")
    mod = os.path.join(DATA, "140.model")
    if os.path.exists(phy):
        inst = default_instance(phy, mod)    # auto dtype: f32 on TPU
        tree = inst.tree_from_newick(
            open(os.path.join(DATA, "140.tree")).read())
        return inst, tree, "testData/140"
    # Fallback synthetic AA set with the same shape.
    from examl_tpu.io.alignment import build_alignment_data
    rng = np.random.default_rng(0)
    aas = "ARNDCQEGHILKMFPSTWYV"
    names = [f"t{i}" for i in range(140)]
    seqs = ["".join(aas[c] for c in rng.integers(0, 20, 1104))
            for _ in names]
    ad = build_alignment_data(names, seqs, datatype_name="AA")
    inst = PhyloInstance(ad)
    return inst, inst.random_tree(0), "synthetic-140"


def _synthetic_instance(ntaxa: int, width: int, datatype: str = "DNA",
                        dtype=None, mode: str = ""):
    """A synthetic compute-bound benchmark alignment, built WITHOUT
    pattern compression (random sites do not compress; weights are 1):
    big enough that the traversal is HBM/MXU-bound rather than
    dispatch-bound — the regime the small testData sets cannot reach
    (SURVEY §6 recommends 3-4k DNA / ~1k AA patterns PER CORE on the
    reference; one chip replaces a whole socket).

    mode "psr": PSR rate model with a randomized 25-category
    categorization installed (the per-site-rate multiplier path).
    mode "sev": clade-structured gaps (half the taxa per alignment
    half) traversed on the -S pool.
    mode "bf16": bf16 CLV storage tier (f32 compute; EXAML_CLV_DTYPE
    set for the engine build and restored after)."""
    from examl_tpu import datatypes
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.io.alignment import AlignmentData, PartitionData

    rng = np.random.default_rng(0)
    dt = datatypes.get(datatype)
    if datatype == "DNA":
        codes = rng.choice(np.array([1, 2, 4, 8], dtype=np.uint8),
                           size=(ntaxa, width))
    else:
        codes = rng.integers(0, 20, size=(ntaxa, width), dtype=np.uint8)
    if mode == "sev":
        # Clade-structured gaps: taxon half x alignment half (the -S
        # regime).  Subtree-all-gap then triggers on real block runs,
        # as in SEVRATIO.md's clade fixture.
        codes[: ntaxa // 2, : width // 2] = dt.undetermined_code
        codes[ntaxa // 2:, width // 2:] = dt.undetermined_code
    if datatype == "DNA":
        part = PartitionData(
            name="bench", datatype=dt, model_name="DNA",
            patterns=codes, weights=np.ones(width, dtype=np.int64),
            empirical_freqs=np.full(4, 0.25), use_empirical_freqs=True,
            optimize_freqs=False)
    else:
        part = PartitionData(
            name="bench", datatype=dt, model_name="LG",
            patterns=codes, weights=np.ones(width, dtype=np.int64),
            empirical_freqs=np.full(20, 0.05), use_empirical_freqs=False,
            optimize_freqs=False)
    prior_clv_env = os.environ.get("EXAML_CLV_DTYPE")
    if mode == "bf16":
        import jax.numpy as jnp
        dtype = jnp.float32          # the tier requires f32 compute
        os.environ["EXAML_CLV_DTYPE"] = "bf16"
    try:
        inst = PhyloInstance(
            AlignmentData([f"t{i}" for i in range(ntaxa)], [part]),
            dtype=dtype,
            rate_model="PSR" if mode == "psr" else "GAMMA",
            save_memory=(mode == "sev"))
    finally:
        if mode == "bf16":
            if prior_clv_env is None:
                os.environ.pop("EXAML_CLV_DTYPE", None)
            else:
                os.environ["EXAML_CLV_DTYPE"] = prior_clv_env
    if mode == "psr":
        # Install a realistic 25-category lattice so the factorized
        # per-site P path (not a degenerate all-1.0 grid) is timed.
        for gid in range(inst.num_parts):
            cats = np.sort(rng.gamma(2.0, 0.5, 25))
            cat_of = rng.integers(0, 25, inst.patrat[gid].shape[0])
            rates = cats[cat_of]
            mean = float(rates.mean())
            inst.per_site_rates[gid] = cats / mean
            inst.rate_category[gid] = cat_of.astype(np.int32)
        inst.push_site_rates()
    if mode == "sev":
        # Caterpillar in taxon order: the taxon-half gap structure then
        # IS a clade split, the -S regime (SEVRATIO.md).  A random tree
        # scatters the halves and the pool saves almost nothing.
        part = "(t0:0.1,t1:0.1)"
        for i in range(2, ntaxa):
            part = f"({part}:0.1,t{i}:0.1)"
        tree = inst.tree_from_newick(part + ";")
    else:
        tree = inst.random_tree(0)
    return inst, tree


# ---------------------------------------------------------------------------
# worker: one process, one ordered stage plan, one JSON line per stage


def _chained(step, n_steps):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(clv, scaler):
        def body(_, cs):
            return step(cs[0], cs[1])
        c, s = jax.lax.fori_loop(0, n_steps, body, (clv, scaler))
        return jnp.sum(s)
    return fn


def _time_compiled(fn, clv, scaler, reps=3):
    """AOT-compile, pull XLA's FLOP count, then time `reps` executions;
    returns (best_seconds, compile_seconds, flops_or_None).  Timing goes
    through the obs dispatch-timer API — one definition of "dispatch
    time" shared with tools/perf_lab.py, and every measurement lands in
    the metrics registry that rides along in the BENCH artifact."""
    import jax

    from examl_tpu import obs
    with obs.timer("bench.compile_s") as tm:
        compiled = fn.lower(clv, scaler).compile()
    compile_s = tm.elapsed
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost["flops"])
    except Exception:                            # noqa: BLE001
        pass
    dt = obs.time_dispatch(
        lambda: jax.block_until_ready(compiled(clv, scaler)),
        reps=reps, warmup=1, name="bench.dispatch")
    return dt, compile_s, flops


def _n_steps_for(entries, patterns, R, K):
    """Chain length: ~2e9 site-updates per timed rep, 5..50 steps."""
    per_trav = max(len(entries) * patterns * R * K, 1)
    return max(5, min(50, int(2e9 / per_trav)))


def _variant_step(eng, variant, entries):
    """Build the per-traversal step function for one tier."""
    from examl_tpu.ops import kernels

    if variant == "scan":
        if eng.save_memory:
            eng._sev_begin(entries)       # gap/cell bookkeeping + sync
            aux = (eng.sev.slot_read, eng.sev.slot_write)
            tv = eng._traversal_arrays(entries)

            def step(c, s):
                return kernels.traverse_pooled(
                    eng.models, eng.block_part, eng.tips, c, aux[0],
                    aux[1], s, tv, eng.scale_exp, eng.ntips,
                    eng.site_rates)
            return step
        tv = eng._traversal_arrays(entries)

        def step(c, s):
            return kernels.traverse(eng.models, eng.block_part, eng.tips,
                                    c, s, tv, eng.scale_exp, eng.ntips,
                                    eng.site_rates)
        return step
    if variant in ("chunks", "pallas"):
        from examl_tpu.ops import fastpath

        sched = eng._fast_schedule(entries)

        def step(c, s):
            eng.use_pallas = (variant == "pallas")
            return eng.run_segments_traced(c, s, sched)
        # Bounded-program evidence for the bench row (ISSUE 5): ops per
        # traversal (= the launch-latency floor) vs the raw chunk count
        # the pre-bounded path unrolled.
        un, sc, total = fastpath.profile_stats(sched.profile)
        step.program_stats = {"program_chunks": un, "scan_groups": sc,
                              "dispatches_per_traversal": un + sc,
                              "chunks_unrolled": total}
        return step
    if variant == "whole":
        from examl_tpu.ops import pallas_whole
        wsched = pallas_whole.build_flat(entries, eng.ntips,
                                         eng.num_branch_slots)

        def step(c, s):
            eng.use_pallas = True
            return eng.run_whole_traced(c, s, wsched)
        return step
    raise ValueError(f"unknown variant {variant!r}")


def _bytes_per_traversal(entries, ntips: int, patterns: int, R: int,
                         K: int, itemsize: int) -> int:
    """HBM-traffic model for one dependency-chained traversal — now the
    SHARED definition (examl_tpu/obs/traffic.py), used identically by
    the engine's in-run `engine.traffic_bytes` accounting and this
    bench, so a BENCH row's achieved GB/s and a CLI run's gauge can
    never drift (tests/test_flightrec.py pins the delegation).  Paired
    with measured wall time this yields achieved GB/s for the roofline
    comparison (ROOFLINE.md: the 10x target = ~306 GB/s sustained)."""
    from examl_tpu.obs import traffic
    return traffic.bytes_per_traversal(entries, ntips, patterns, R, K,
                                       itemsize)


def _host_schedule_total() -> float:
    """Accumulated host-schedule seconds from the obs registry (the
    `host_schedule` timer every schedule builder observes into)."""
    from examl_tpu import obs
    snap = obs.registry().snapshot()
    return float(snap.get("timers", {})
                 .get("host_schedule", {}).get("total_s") or 0.0)


def _peak_rss_mb():
    """Process peak RSS in MB; None off-POSIX.  ru_maxrss is KB on
    linux but BYTES on macOS."""
    try:
        import resource
        div = 1024.0 * 1024.0 if sys.platform == "darwin" else 1024.0
        return round(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / div, 1)
    except Exception:                            # noqa: BLE001
        return None


def _measure_variant(inst, tree, eng, entries, variant) -> dict:
    import jax

    patterns = sum(p.width for p in inst.alignment.partitions)
    n_steps = _n_steps_for(entries, patterns, eng.R, eng.K)
    if variant in ("pallas", "whole") and jax.default_backend() not in (
            "tpu", "axon") and not eng.pallas_interpret:
        raise RuntimeError("Pallas tiers require the accelerator backend")
    # _variant_step flips eng.use_pallas at trace time; snapshot the
    # engine's own tier decision so later stages (prims) measure the
    # production path, not whichever variant was timed last.
    tier = (eng.use_pallas, eng.pallas_whole)
    sched0 = _host_schedule_total()
    try:
        step = _variant_step(eng, variant, entries)
        fn = _chained(step, n_steps)
        buf = eng._state()[0] if eng.save_memory else eng.clv
        dt, compile_s, flops = _time_compiled(fn, buf, eng.scaler)
    finally:
        eng.use_pallas, eng.pallas_whole = tier
    updates = n_steps * len(entries) * patterns * eng.R * eng.K
    try:
        peak = float(os.environ.get("EXAML_PEAK_FLOPS", "1.97e14"))
    except ValueError:
        peak = 1.97e14
    itemsize = np.dtype(getattr(eng, "storage_dtype", None)
                        or eng.dtype).itemsize
    bytes_per = _bytes_per_traversal(entries, eng.ntips, patterns,
                                     eng.R, eng.K, itemsize)
    out = {
        "variant": variant,
        "ups": updates / dt,
        "ms_per_traversal": round(dt / n_steps * 1000, 3),
        "n_steps": n_steps,
        "compile_s": round(compile_s, 1),
        "patterns": patterns,
        "dtype": str(np.dtype(eng.dtype)),
        "gbps": round(n_steps * bytes_per / dt / 1e9, 2),
        "backend": jax.default_backend(),
        # Host floor vs device throughput (ROOFLINE.md "host floor"):
        # seconds this stage spent building schedules on the host (obs
        # `host_schedule` timer delta) and the worker's peak RSS at
        # stage end (ru_maxrss is monotone per process, so per-stage
        # values bound each stage's true peak from above).
        "host_schedule_s": round(_host_schedule_total() - sched0, 4),
        "peak_rss_mb": _peak_rss_mb(),
    }
    out.update(getattr(step, "program_stats", {}))
    # Regime tag (obs/traffic.classify_regime): is this row's GB/s a
    # bandwidth measurement or a launch-latency-floor artifact?  ops =
    # the program's sequential dependent steps — the bounded chunk
    # program's op count when known, else one per traversal entry (the
    # scan tier's dependent-wave upper bound, conservative toward
    # dispatch-bound).
    from examl_tpu.obs import traffic
    ops = getattr(step, "program_stats", {}).get(
        "dispatches_per_traversal", len(entries))
    out["regime"] = traffic.classify_regime(dt / n_steps, ops)["regime"]
    if flops is not None:
        fps = flops / dt
        # MFU vs the bf16 MXU peak (v5e ~197 TFLOP/s; override with
        # EXAML_PEAK_FLOPS) — a utilization DIAGNOSTIC, pessimistic for
        # f32 programs whose true ceiling is lower (see ROOFLINE.md:
        # this kernel is bandwidth-bound; low MFU is expected).
        out["tflops_per_sec"] = round(fps / 1e12, 3)
        out["mfu"] = round(fps / peak, 5)
    return out


class _WorkerState:
    """Lazily-built shared state for the small-config stages."""

    def __init__(self):
        self.small = None

    def small_state(self):
        if self.small is None:
            inst, tree, dataset = _load_instance()
            (eng,) = inst.engines.values()
            # Reference lnL through the scan tier: the one program whose
            # compile is proven on every backend (the fast tiers are
            # timed as their own stages and may be the thing that hangs).
            prior = eng.force_scan
            eng.force_scan = True
            try:
                lnl = float(inst.evaluate(tree, full=True))
            finally:
                eng.force_scan = prior
            _, entries = tree.full_traversal_centroid()
            self.small = (inst, tree, eng, entries, dataset, lnl)
        return self.small


def _stage_small(state: _WorkerState, variant: str) -> dict:
    inst, tree, eng, entries, dataset, lnl = state.small_state()
    out = _measure_variant(inst, tree, eng, entries, variant)
    out["dataset"] = dataset
    out["lnl"] = lnl
    return out


def _stage_large(cfg: str, variant: str) -> dict:
    ntaxa, width, dtname, mode = LARGE_CONFIGS[cfg]
    inst, tree = _synthetic_instance(ntaxa, width, dtname, mode=mode)
    (eng,) = inst.engines.values()
    if mode in ("psr", "sev"):
        # PSR rides the scan tier (the fast/Pallas tiers are
        # GAMMA-only); the SEV pool likewise traverses via the pooled
        # scan kernel.  Record the mode's own tier honestly instead of
        # inheriting the GAMMA winner hint.
        variant = "scan"
    elif mode == "bf16" and variant in ("pallas", "whole"):
        # The engine refuses Pallas dispatch when storage_dtype !=
        # compute dtype (engine gate); don't bench a combination no
        # production run can use.
        variant = "chunks"
    _, entries = tree.full_traversal_centroid()
    try:
        out = _measure_variant(inst, tree, eng, entries, variant)
        out["config"] = cfg
        if mode:
            out["mode"] = mode
        if mode == "sev":
            # ups counts LOGICAL site updates; the pool computes only
            # stored (non-all-gap) cells, so this row measures -S's
            # effective throughput on gappy data, not raw kernel speed.
            st = eng.sev.stats()
            out["sev_stats"] = {k: v for k, v in st.items()
                                if k != "cell_bytes"}
            if "gbps" in out and st["dense_cells"]:
                # The dense-row traffic model overstates pooled
                # traversals; scale by the stored-cell fraction.
                out["gbps"] = round(out["gbps"] * st["allocated_cells"]
                                    / st["dense_cells"], 2)
        return out
    finally:
        del inst, tree, eng    # free the multi-GB arena before the next
        # config — on the failure path too (an OOM on config 1 must not
        # cascade into config 2 by keeping the dead arena referenced).


def _stage_pallas_check() -> dict:
    """On-device Pallas correctness gate: run the fused chunk kernel and
    the whole-traversal kernel through REAL Mosaic lowering (no
    interpret) on a tiny instance and compare against the XLA fast path
    — so the bench's Pallas tiers never race the chip with unvalidated
    numerics.  (The CPU test battery can only exercise interpret mode;
    round-4's first chip contact surfaced a Mosaic-only failure,
    Precision.HIGH rejection.)"""
    import jax
    import jax.numpy as jnp

    from examl_tpu.ops import fastpath, pallas_newview, pallas_whole

    inst, tree = _synthetic_instance(30, 1024, "DNA", dtype=jnp.float32)
    (eng,) = inst.engines.values()
    _, entries = tree.full_traversal_centroid()
    sched = eng._fast_schedule(entries)
    ref_clv, ref_sc = fastpath.run_chunks(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), sched.chunks, eng.scale_exp,
        eng.fast_precision)
    pal_clv, pal_sc = pallas_newview.run_chunks(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), sched.chunks, eng.scale_exp,
        precision=eng.pallas_precision, interpret=False)
    # Compare only rows a consumer can read (sched.row_of): the chunk
    # pipeline documents junk spill rows past each chunk's real
    # entries, where XLA-vs-Mosaic rounding differences are harmless.
    rows = np.asarray(sorted(sched.row_of.values()))
    ref_clv, ref_sc = np.asarray(ref_clv), np.asarray(ref_sc)
    pal = np.asarray(pal_clv)[rows]
    denom = np.maximum(np.abs(ref_clv[rows]), 1e-30)
    chunk_rel = float(np.max(np.abs(pal - ref_clv[rows]) / denom))
    sc_equal = bool(np.array_equal(ref_sc[rows],
                                   np.asarray(pal_sc)[rows]))

    wsched = pallas_whole.build_flat(entries, eng.ntips,
                                     eng.num_branch_slots)
    w_clv, w_sc = pallas_whole.run_flat(
        eng.models, eng.block_part, eng.tips, jnp.array(eng.clv),
        jnp.array(eng.scaler), wsched, eng.scale_exp,
        eng.pallas_precision, False)
    w_clv, w_sc = np.asarray(w_clv), np.asarray(w_sc)
    whole_rel, w_sc_equal = 0.0, True
    for num, frow in sched.row_of.items():
        wrow = wsched.row_of[num]
        d = np.maximum(np.abs(ref_clv[frow]), 1e-30)
        whole_rel = max(whole_rel, float(np.max(
            np.abs(w_clv[wrow] - ref_clv[frow]) / d)))
        w_sc_equal &= bool(np.array_equal(np.asarray(ref_sc)[frow],
                                          w_sc[wrow]))
    return {
        "ok": sc_equal and w_sc_equal and chunk_rel < 1e-3
        and whole_rel < 1e-3,
        "chunk_rel": chunk_rel, "whole_rel": whole_rel,
        "scalers_equal": sc_equal and w_sc_equal,
    }


def _stage_prims(state: _WorkerState) -> dict:
    """Per-call latency of the fused search primitives (partial
    traversal + root lnL; partial traversal + sumtable + full
    Newton-Raphson) and the batched SPR radius scan — the
    per-SPR-insertion / per-branch / per-pruned-node costs that dominate
    end-to-end search time (reference stacks SURVEY §3.2-3.3); dispatch
    overhead is included on purpose.  Uses the engine's production tier
    selection (Pallas with runtime fallback on TPU)."""
    from examl_tpu import obs

    inst, tree, eng, entries, dataset, lnl = state.small_state()
    out = {}
    sched0 = _host_schedule_total()
    inner = [tree.nodep[n] for n in tree.inner_numbers()
             if not tree.is_tip(tree.nodep[n].back.number)][:12]
    for p in inner:     # warm compile variants
        inst.evaluate(tree, p)
        inst.makenewz(tree, p, p.back, p.z, maxiter=16)
    # evaluate/makenewz return host floats (already blocked); the obs
    # timer is the shared stopwatch, same definition as perf_lab's.
    dt = obs.time_dispatch(
        lambda: [inst.evaluate(tree, p) for p in inner],
        reps=1, warmup=0, name="bench.evaluate")
    out["evaluate_ms"] = round(dt / len(inner) * 1000, 3)
    dt = obs.time_dispatch(
        lambda: [inst.makenewz(tree, p, p.back, p.z, maxiter=16)
                 for p in inner],
        reps=1, warmup=0, name="bench.newton_branch")
    out["newton_branch_ms"] = round(dt / len(inner) * 1000, 3)

    # Whole-tree gradient pass (ops/gradient.py): ALL 2n-3 branch
    # derivatives in one dispatch — the row to read NEXT TO
    # newton_branch_ms (the per-branch cost it replaces), and the
    # dispatches-per-smoothing-round gauge after one gradient-mode
    # sweep (the ROADMAP §5 O(n)->O(1) acceptance number).
    from examl_tpu.optimize import branch as _branch
    if (_branch.grad_smooth_enabled()
            and _branch.grad_smooth_ineligible(inst) is None):
        inst.evaluate(tree, full=True)
        _branch.tree_gradients(inst, tree)     # warm the grad program
        dt = obs.time_dispatch(
            lambda: _branch.tree_gradients(inst, tree),
            reps=1, warmup=0, name="bench.grad_pass")
        out["grad_pass_ms"] = round(dt * 1000, 3)
        _branch.gradient_smooth_tree(inst, tree, 1)
        snap_g = obs.registry().snapshot_light()["gauges"]
        out["smooth_dispatches"] = snap_g.get(
            "engine.dispatches_per_smoothing_round")
    else:
        out["grad_pass_ms"] = None
        out["smooth_dispatches"] = None

    from examl_tpu.search import batchscan, spr
    from examl_tpu.tree.topology import hookup
    ctx = spr.SprContext(inst, thorough=False, do_cutoff=False)
    c = tree.centroid_branch()           # a node with a deep window
    p = c if not tree.is_tip(c.number) else c.back
    q1, q2 = p.next.back, p.next.next.back
    p1z, p2z = list(q1.z), list(q2.z)
    spr.remove_node(inst, tree, ctx, p)
    plan = batchscan.plan_for_endpoints(inst, tree, p, q1, q2, 1, 10)
    if plan is not None:                 # tip-locked window: no metric
        dt = obs.time_dispatch(
            lambda: batchscan.run_plan(inst, tree, plan),
            reps=1, warmup=1, name="bench.spr_scan")   # warmup = compile
        out["spr_scan_ms_per_node"] = round(dt * 1000, 3)
        out["spr_scan_candidates"] = len(plan.candidates)
    hookup(p.next, q1, p1z)
    hookup(p.next.next, q2, p2z)
    inst.new_view(tree, p)
    out["host_schedule_s"] = round(_host_schedule_total() - sched0, 4)
    out["peak_rss_mb"] = _peak_rss_mb()
    return out


# Program families each bench stage dispatches (ops/bank.py labels):
# a family the bank recorded as wedged/broken on THIS host must not be
# dispatched by a bench worker either — the stage is skipped with a
# note instead of re-racing a known wedge (wedge-immune dispatch).
# The scan tier and the fused prims have no entry: they are the
# fallback programs every degradation lands on.
_STAGE_FAMILIES = {"s-chunks": ("fast",), "s-pallas": ("fast",),
                   "s-whole": ("whole",), "pallas-check": ("fast",
                                                           "whole")}


def _bank_degraded_families() -> set:
    """Families the per-host bank manifest marks timeout/error (empty
    when no bank has run here, or EXAML_BENCH_IGNORE_BANK=1)."""
    if os.environ.get("EXAML_BENCH_IGNORE_BANK") == "1":
        return set()
    try:
        from examl_tpu.ops import bank
        return bank.manifest_degraded_families(bank.load_manifest())
    except Exception:                            # noqa: BLE001
        return set()


def _worker(plan, best_hint: str) -> None:
    import jax
    jax.config.update("jax_enable_x64", True)
    try:
        # Durable compiles: a killed worker (stage deadline) must not
        # forfeit the compile it paid for — the resumed worker reloads
        # it from disk instead of re-racing the wedge-prone tunnel.
        from examl_tpu.config import enable_persistent_compilation_cache
        path = enable_persistent_compilation_cache()
        if path:
            sys.stderr.write(f"bench: compile cache at {path}\n")
    except Exception as exc:                     # noqa: BLE001
        sys.stderr.write(f"bench: compile cache unavailable: {exc}\n")
    degraded = _bank_degraded_families()

    state = _WorkerState()
    # best_hint is "variant" or "variant:ups" (a resumed worker must not
    # let a slower locally-measured tier override the parent's known
    # winner for the large-config stages).
    name, _, ups = best_hint.partition(":")
    try:
        best = (name, float(ups) if ups else 0.0)
    except ValueError:
        best = (name, 0.0)
    pallas_invalid = False
    for i, sid in enumerate(plan):
        # The FIRST stage always runs — the primary metric must be
        # recorded even when probe retries ate the wall budget (the
        # parent decides whether spawning is worthwhile at all).
        if i > 0 and _elapsed() > _budget() - 15:
            print(f"##skip {sid} budget", flush=True)
            continue
        if pallas_invalid and sid in ("s-pallas", "s-whole"):
            # The on-device correctness gate failed: numerically wrong
            # tiers must not be timed at all — a fast-but-wrong kernel
            # would win the headline metric and steer the large configs.
            print(f"##skip {sid} pallas-check-failed", flush=True)
            continue
        bad = [f for f in _STAGE_FAMILIES.get(sid, ()) if f in degraded]
        if bad:
            # The bank already proved these programs wedge/break on this
            # host; dispatch only banked programs (EXAML_BENCH_IGNORE_BANK
            # =1 overrides for deliberate re-tests).
            print(f"##skip {sid} bank-degraded:{','.join(bad)}",
                  flush=True)
            continue
        print(f"##start {sid}", flush=True)
        try:
            if sid.startswith("s-"):
                r = _stage_small(state, sid[2:])
                if r["ups"] > best[1]:
                    best = (r["variant"], r["ups"])
            elif sid.startswith("L:"):
                r = _stage_large(sid[2:], best[0])
            elif sid == "pallas-check":
                r = _stage_pallas_check()
                pallas_invalid = not r.get("ok", False)
            elif sid == "prims":
                r = _stage_prims(state)
            else:
                r = {"error": f"unknown stage {sid!r}"}
        except Exception as exc:                 # noqa: BLE001
            r = {"error": f"{type(exc).__name__}: {exc}"}
            if sid == "pallas-check":
                pallas_invalid = True     # couldn't validate = invalid
        r["stage"] = sid
        print(json.dumps(r), flush=True)
    # Ship this worker's metrics-registry snapshot to the parent so every
    # BENCH artifact carries its cause attached (dispatch/compile/cache
    # counters alongside the throughput numbers).
    try:
        from examl_tpu import obs
        print(json.dumps({"stage": "__metrics__",
                          "snapshot": obs.snapshot()}), flush=True)
    except Exception:                            # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# parent: probe, orchestrate workers under deadlines, assemble the line


def _probe_backend(budgets=(180, 60)):
    """Probe the default JAX backend in a SUBPROCESS; a broken
    accelerator plugin can hang its host process inside client init,
    where no in-process timeout can recover.  Multiple tries: a flaky
    tunnel can heal between them.  Returns the backend name, or None."""
    for attempt, budget in enumerate(budgets):
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); "
                 "import jax.numpy as jnp; jnp.zeros(2).block_until_ready();"
                 "print('BACKEND=' + jax.default_backend())"],
                env=dict(os.environ), capture_output=True, text=True,
                timeout=budget)
            if proc.returncode == 0:
                for line in proc.stdout.splitlines():
                    if line.startswith("BACKEND="):
                        return line.split("=", 1)[1].strip()
                return "unknown"
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < len(budgets):   # no dead wait after the final try
            time.sleep(15)
    return None


def _child_env(cpu: bool) -> dict:
    env = dict(os.environ)
    env["EXAML_BENCH_T0"] = repr(_epoch0())
    if not cpu:
        return env
    env["JAX_PLATFORMS"] = "cpu"
    # Accelerator plugins loaded via sitecustomize can hang their host
    # process at import even under JAX_PLATFORMS=cpu; strip the plugin's
    # site dir from the child's path.  Path components to strip are
    # env-configurable so the knowledge lives with the deployment.
    strip = os.environ.get("EXAML_BENCH_STRIP_PYTHONPATH",
                           ".axon_site").split(",")
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and not any(c in p.split(os.sep) for c in strip if c)]
    env["PYTHONPATH"] = os.pathsep.join(pp) if pp else ""
    return env


def _exit_desc(rc) -> str:
    """Worker exit cause via the shared taxonomy
    (examl_tpu/resilience/exitcause.py, stdlib-only BY CONTRACT: the
    bench parent must never import jax — a broken accelerator plugin
    can hang the importing process, which is why the backend probe runs
    in a subprocess).  The bench's rc-None semantics name the action it
    just took: the worker was hang-killed."""
    from examl_tpu.resilience.exitcause import exit_desc
    return exit_desc(rc, none_desc="(hang-killed)")


def _merge_metrics(results: dict, snapshot: dict) -> None:
    """Accumulate a worker's metrics snapshot under results["__metrics__"]
    (a killed worker may be resumed by a fresh one: counters sum, gauges
    take the latest value, timers merge count/total)."""
    acc = results.setdefault("__metrics__",
                             {"counters": {}, "gauges": {}, "timers": {}})
    for name, v in (snapshot.get("counters") or {}).items():
        acc["counters"][name] = acc["counters"].get(name, 0) + v
    acc["gauges"].update(snapshot.get("gauges") or {})
    # Program-observatory rows (obs/programs.py): concatenate across
    # workers so the BENCH artifact names every program each stage
    # compiled/loaded, with compiler-truth cost/memory figures.
    if snapshot.get("programs"):
        acc.setdefault("programs", []).extend(snapshot["programs"])
    from examl_tpu.obs import hist as _hist
    for name, t in (snapshot.get("timers") or {}).items():
        cur = acc["timers"].get(name)
        if cur is None:
            acc["timers"][name] = dict(t)
        else:
            cur["count"] += t.get("count", 0)
            cur["total_s"] += t.get("total_s", 0.0)
            pairs = [(cur.get("min_s"), t.get("min_s"), min),
                     (cur.get("max_s"), t.get("max_s"), max)]
            for key, (a, b, pick) in zip(("min_s", "max_s"), pairs):
                vals = [v for v in (a, b) if v is not None]
                cur[key] = pick(vals) if vals else None
            # Histogram buckets SUM exactly across workers; the merged
            # quantiles recompute from the summed buckets (quantiles
            # themselves never merge).
            buckets = _hist.merge_bucket_dicts(cur.get("buckets"),
                                               t.get("buckets"))
            cur["buckets"] = buckets
            for q in _hist.QUANTILES:
                cur[f"p{int(q * 100)}_s"] = _hist.quantile_from_buckets(
                    buckets, q)


def _parse_worker_output(out: str, results: dict, notes: list):
    """Collect stage JSON lines + ##start/##skip markers; return the id
    of a stage that was started but produced no line (i.e. hung)."""
    started = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("##start "):
            started.append(line.split(None, 1)[1])
        elif line.startswith("##skip "):
            notes.append(line[2:])
        elif line.startswith("{"):
            try:
                d = json.loads(line)
            except ValueError:
                continue
            sid = d.pop("stage", None)
            if sid == "__metrics__":
                _merge_metrics(results, d.get("snapshot") or {})
            elif sid:
                results[sid] = d
    for sid in started:
        if sid not in results:
            return sid
    return None


def _orchestrate(cpu: bool, plan, results: dict, notes: list) -> None:
    """Run the plan to completion across one or more worker processes,
    killing a worker whose current stage exceeds the deadline."""
    plan = [s for s in plan if s not in results]
    best = ""
    for _attempt in range(4):
        if not plan:
            return
        remaining = _budget() - _elapsed()
        if remaining < 45 and results:
            notes.append(f"budget exhausted before: {','.join(plan)}")
            return
        # Cap one worker's window so a first-stage hang cannot eat the
        # whole budget: later attempts (minus the hung stage) still get
        # a window.  The floor keeps slow-but-healthy compiles alive.
        cap = max(240.0, remaining * 0.6) if not cpu else max(
            900.0, remaining + 180)
        args = [sys.executable, os.path.abspath(__file__),
                "--worker", ",".join(plan)]
        if best:
            args += ["--best", best]
        # CPU workers get the full patient window regardless of the
        # remaining budget: the "a result is always recorded" guarantee
        # outranks the wall budget on the fallback path (hang-proof:
        # host compiles never wedge), while accelerator workers are
        # clamped so a wedged tunnel cannot overrun the driver's window.
        timeout_s = cap if cpu else min(cap, remaining + 240)
        try:
            proc = subprocess.run(args, env=_child_env(cpu),
                                  capture_output=True, text=True,
                                  timeout=timeout_s)
            out, err, timed_out = proc.stdout, proc.stderr, False
        except subprocess.TimeoutExpired as e:
            def _text(x):
                return (x.decode(errors="replace")
                        if isinstance(x, bytes) else (x or ""))
            out, err, timed_out = _text(e.stdout), _text(e.stderr), True
        if err:
            sys.stderr.write(err)
        n_before = len([k for k in results if k != "__metrics__"])
        hung = _parse_worker_output(out, results, notes)
        bests = [(r["ups"], r["variant"]) for sid, r in results.items()
                 if sid.startswith("s-") and "ups" in r]
        if bests:
            ups_, name_ = max(bests)
            best = f"{name_}:{ups_:.1f}"
        plan = [s for s in plan if s not in results]
        if not timed_out:
            rc = proc.returncode
            desc = _exit_desc(rc)
            if rc != 0 and hung:
                # The worker DIED inside a specific stage (r05 lesson:
                # "worker exited" hid what were plausibly SIGILLs from
                # mis-featured cached kernels).  That stage is the
                # casualty — record its signal/returncode — and a fresh
                # worker resumes the remaining plan.
                results[hung] = {"error": f"worker died mid-stage {desc}"}
                notes.append(f"stage {hung} died {desc}")
                plan = [s for s in plan if s != hung]
            else:
                for sid in plan:
                    notes.append(
                        f"stage {sid} not run (worker exited {desc})")
                return
        elif hung:
            results[hung] = {"error": "stage deadline exceeded (killed)"}
            notes.append(f"stage {hung} hung; killed worker "
                         + _exit_desc(None))
            plan = [s for s in plan if s != hung]
        elif len([k for k in results if k != "__metrics__"]) == n_before:
            # Worker wedged before its first ##start marker (backend
            # init): retrying the identical plan would burn the budget
            # attempt by attempt.
            notes.append("worker wedged before any stage "
                         + _exit_desc(None) + "; abandoning: "
                         + ",".join(plan))
            return
        if not cpu and plan:
            # A killed client can wedge the tunnel; only respawn if the
            # chip still answers.
            if not _probe_backend(budgets=(60,)):
                notes.append("backend unreachable after kill; "
                             f"abandoning: {','.join(plan)}")
                return
    if plan:
        notes.append(f"attempt limit reached; abandoned: "
                     f"{','.join(plan)}")


def _assemble(results: dict, notes: list, cpu_fallback: bool) -> str:
    smalls = {sid: r for sid, r in results.items()
              if sid.startswith("s-") and "ups" in r}
    prims = results.get("prims", {})
    backend = next((r["backend"] for r in results.values()
                    if "backend" in r), "unknown")
    base_path = os.path.join(REPO, "tools", "avx_baseline.json")
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        avx = float(base["site_clv_updates_per_sec"])
        base_src = base.get("source", "measured")
    else:
        avx = FALLBACK_AVX_UPDATES_PER_SEC
        base_src = "estimate"

    doc = {"metric": "site_clv_updates_per_sec", "unit": "updates/s"}
    if smalls:
        win = max(smalls.values(), key=lambda r: r["ups"])
        doc.update({
            "value": round(win["ups"], 1),
            "vs_baseline": round(win["ups"] / avx, 3),
            "dataset": win.get("dataset"),
            "dtype": win.get("dtype"),
            "lnl": win.get("lnl"),
            "ms_per_traversal": win.get("ms_per_traversal"),
            "traversal_variant": win.get("variant"),
            "tflops_per_sec": win.get("tflops_per_sec"),
            "mfu": win.get("mfu"),
            "achieved_gbps": win.get("gbps"),
            "regime": win.get("regime"),
        })
    else:
        doc.update({"value": 0.0, "vs_baseline": 0.0})
        notes.append("no traversal stage completed")
    # A fallback run is NEVER comparable to an accelerator number: the
    # baseline is one AVX socket and the metric races the chip against
    # it, so vs_baseline only "counts" when the run executed on tpu/axon.
    doc["vs_baseline_valid"] = (backend in ("tpu", "axon")
                                and not cpu_fallback and bool(smalls))
    # Every tier, timed or failed — the hardware-validation record.
    variants = {}
    for sid, r in results.items():
        if sid.startswith("s-"):
            variants[sid[2:]] = (round(r["ups"], 1) if "ups" in r
                                 else r.get("error", "?"))
    if variants:
        doc["variants"] = variants
    for sid, r in results.items():
        if not sid.startswith("L:"):
            continue
        pre = ("large" if sid == "L:dna-large"
               else sid[2:].replace("-", "_"))
        if "ups" in r:
            doc.update({
                f"{pre}_config": r.get("config", sid[2:]),
                f"{pre}_updates_per_sec": round(r["ups"], 1),
                f"{pre}_vs_baseline": round(r["ups"] / avx, 3),
                f"{pre}_ms_per_traversal": r.get("ms_per_traversal"),
                f"{pre}_variant": r.get("variant"),
                f"{pre}_tflops_per_sec": r.get("tflops_per_sec"),
                f"{pre}_mfu": r.get("mfu"),
                f"{pre}_achieved_gbps": r.get("gbps"),
                f"{pre}_regime": r.get("regime")})
            if "mode" in r:
                doc[f"{pre}_mode"] = r["mode"]
            if "sev_stats" in r:
                doc[f"{pre}_sev_stats"] = r["sev_stats"]
        else:
            doc[f"{pre}_error"] = r.get("error", "?")
    # Pallas first-contact validation record (None = stage not run,
    # e.g. CPU fallback; a dict with ok=false blocks trusting the
    # Pallas tier numbers).
    pc = results.get("pallas-check")
    doc["pallas_validated"] = (pc.get("ok", False) if pc and "error"
                               not in pc else None)
    if pc and "error" in pc:
        doc["pallas_check_error"] = pc["error"]
    # Secondary metrics: keys always present (null when the stage was
    # skipped/hung/failed) so consumers can index them unconditionally.
    for key in ("evaluate_ms", "newton_branch_ms", "grad_pass_ms",
                "smooth_dispatches", "spr_scan_ms_per_node",
                "spr_scan_candidates"):
        doc[key] = prims.get(key)
    if "error" in prims:
        doc["prims_error"] = prims["error"]
    doc["baseline_source"] = base_src
    doc["backend"] = backend if backend != "unknown" else (
        "cpu" if cpu_fallback else "unknown")
    # The workers' merged metrics-registry snapshot: every BENCH artifact
    # carries its dispatch/compile/cache counters so a perf regression
    # arrives with its cause attached (e.g. an eviction storm or a
    # Pallas fallback shows up right next to the slower number).
    if "__metrics__" in results:
        doc["metrics"] = results["__metrics__"]
    if notes:
        doc["note"] = "; ".join(notes)
    return json.dumps(doc)


def _plan_from_env(cpu: bool):
    plan = list(CPU_PLAN if cpu else TPU_PLAN)
    cfg_env = os.environ.get("EXAML_BENCH_LARGE")
    if cfg_env is not None and not cpu:
        keep = []
        for tok in (c.strip() for c in cfg_env.split(",") if c.strip()):
            if tok in LARGE_CONFIGS:
                keep.append(f"L:{tok}")
            else:
                sys.stderr.write(
                    f"bench: unknown EXAML_BENCH_LARGE config {tok!r} "
                    f"(known: {','.join(LARGE_CONFIGS)}); skipping\n")
        plan = [s for s in plan if not s.startswith("L:")]
        # insert right after the safe scan stage, preserving request
        # order (large configs outrank the hang-risky tiers — see
        # TPU_PLAN ordering note)
        at = plan.index("s-scan") + 1 if "s-scan" in plan else 0
        plan[at:at] = keep
    return plan


def main() -> None:
    if "--worker" in sys.argv:
        i = sys.argv.index("--worker")
        plan = [s for s in sys.argv[i + 1].split(",") if s]
        best = (sys.argv[sys.argv.index("--best") + 1]
                if "--best" in sys.argv else "scan")
        _worker(plan, best)
        return

    results: dict = {}
    notes: list = []
    backend = _probe_backend()
    if backend is not None:
        # A deliberately CPU-pinned run (JAX_PLATFORMS=cpu) gets the CPU
        # plan AND the patient CPU deadlines: host compiles are slow but
        # never wedge, so kills would only produce false hang reports.
        accel = backend in ("tpu", "axon")
        _orchestrate(cpu=not accel, plan=_plan_from_env(cpu=not accel),
                     results=results, notes=notes)
        if any("ups" in r for r in results.values()):
            print(_assemble(results, notes, cpu_fallback=not accel))
            return
        notes.append("no accelerator stage produced a number; "
                     "falling back to CPU")
    else:
        notes.append("default backend unusable; CPU fallback")
        sys.stderr.write("bench: default backend unusable; falling back "
                         "to CPU (will re-probe late in the budget)\n")
    cpu_results: dict = {}
    _orchestrate(cpu=True, plan=_plan_from_env(True),
                 results=cpu_results, notes=notes)
    # Late retry window: a flaky tunnel often heals within minutes
    # (round-3 lesson) — one more probe + accelerator attempt if the
    # budget allows.
    if _budget() - _elapsed() > 90 and _probe_backend(budgets=(60,)):
        sys.stderr.write("bench: accelerator answered on late re-probe; "
                         "retrying accelerator stages\n")
        retry: dict = {}
        _orchestrate(cpu=False, plan=_plan_from_env(False),
                     results=retry, notes=notes)
        if any("ups" in r for r in retry.values()):
            print(_assemble(retry, notes, cpu_fallback=False))
            return
    if any("ups" in r for r in cpu_results.values()):
        print(_assemble(cpu_results, notes, cpu_fallback=True))
        return
    raise SystemExit("bench: no stage produced a result")


if __name__ == "__main__":
    main()
