"""One-pass analytic branch gradients: all 2n-3 edge derivatives of a
tree in O(1) device dispatches.

ExaML's `smoothTree`/`treeEvaluate` (reference `searchAlgo.c:127-436`)
serialize one Newton solve per branch — O(n) sequential
sumtable+derivative round trips per smoothing sweep, the dispatch
storm BENCH r03/r04 measured at ~10x the cost of a full likelihood
evaluation.  Ji et al. (arXiv:2303.04390) show every branch gradient
is computable from one post-order plus one pre-order linear pass;
BEAGLE 4.1 ships the same edge-derivative machinery as its production
gradient path.  This module is that machinery for the jax engine:

* The POST-ORDER partials are the engine's ordinary full traversal —
  the CLV arena after `run_traversal(flat, full=True)`, unchanged.
* The PRE-ORDER ("outroot") pass is the SAME wave schedule executed in
  reverse wave order (`GradStructure` packs `FlatTraversal`'s waves
  backwards into the scan-tier [L, W] shape): each post-order entry
  (v <- l, r) emits the root-directed complements of its two children,
  out(l) = (P(z_up(v)) out(v)) * (P(zr) D(r)) and symmetrically for r
  (`kernels.outroot_wave`), filling a second arena indexed by node
  number.  The recursion grounds at the traversal's root edge (p, q):
  out(p) = D(q) and out(q) = D(p), copied from the CLV arena.
* The EDGE-DERIVATIVE contraction then runs for EVERY edge at once:
  for edge (v, c) with branch z, `kernels.sumtable(out(c), D(c))`
  followed by `kernels.nr_derivatives(st, z)` yields (dlnL/dlz,
  d2lnL/dlz2) — identical arithmetic to the per-branch Newton path,
  batched over edges in fixed-size chunks inside one `lax.scan` so
  peak memory stays at one chunk of sumtables, not E of them.

Per-site CLV rescaling cancels in every dsite/lsite ratio the
derivatives are built from, so the outroot pass rescales VALUES (same
threshold/multiplier as newview) but tracks no counts.

Shapes are bucketed (`bucket_len`/`next_pow2`) so the jitted gradient
program — keyed ("grad", L, W, n_chunks), and therefore eligible for
the exported program bank (ops/export_bank.py: a restart deserializes
the compiled gradient pass instead of recompiling it) — is a tiny
closed family
shared across topologies, like the scan tier: topology ships as data.
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np

from examl_tpu.ops import kernels
from examl_tpu.ops.kernels import OutrootTraversal
from examl_tpu.utils import bucket_len, next_pow2, z_slots

# Edges per edge-derivative chunk: one chunk of sumtables
# [GRAD_CHUNK, B, lane, R, K] is the gradient program's peak transient
# beyond the outroot arena (mirrors batchscan.CAND_CHUNK).
GRAD_CHUNK = 32


class GradStructure:
    """The topology+root structural half of a gradient plan (cacheable
    per `FlatTraversal.topo_key`, like the engine's schedule-structure
    cache): the reversed wave packing, the per-entry upper-branch
    source map, and the edge table.  Branch-length values and CLV
    gather indices are refreshed per dispatch by `grad_arrays` (z moves
    every smoothing sweep; the row map follows the engine's layout)."""

    __slots__ = ("n", "ntips", "n_edges", "n_steps", "wave_w",
                 "n_chunks", "scratch", "roots",
                 "pk", "pk_pad", "up_row", "lrow", "rrow",
                 "zu_src", "zu_side", "edge_node", "edge_pad",
                 "edge_x_row", "edge_z_src", "edge_z_side")

    def __init__(self, flat, wave_cap: int):
        n = flat.n
        ntips = flat.ntips
        parent = np.asarray(flat.parent, dtype=np.int64)
        left = np.asarray(flat.left, dtype=np.int64)
        right = np.asarray(flat.right, dtype=np.int64)
        self.n = n
        self.ntips = ntips
        self.scratch = 2 * ntips - 2          # outroot arena scratch row
        # Root-edge endpoints: the two nodes no entry computes as a
        # child (the traversal is rooted at the edge between them).
        mask = np.ones(2 * ntips - 1, dtype=bool)
        mask[0] = False
        mask[left] = False
        mask[right] = False
        roots = np.flatnonzero(mask)
        assert roots.shape[0] == 2, roots
        self.roots = (int(roots[0]), int(roots[1]))
        # Branch ABOVE each entry's parent node: the (entry, side)
        # whose zl/zr defines it; root-adjacent entries (-1) read the
        # root-edge z.
        src_e = np.full(2 * ntips - 1, -1, dtype=np.int64)
        src_s = np.zeros(2 * ntips - 1, dtype=np.int64)
        src_e[left] = np.arange(n)
        src_s[left] = 0
        src_e[right] = np.arange(n)
        src_s[right] = 1
        self.zu_src = src_e[parent]
        self.zu_side = src_s[parent]
        # Reverse wave packing into [L, W]: post-order waves walked
        # backwards, each wave split into <=W-wide sub-steps (entries
        # within a wave are independent in the pre-order direction too
        # — a same-wave entry can never have written the outroot row
        # another reads, since that would put its defining entry in an
        # earlier post-order wave than itself).
        sizes = np.asarray(flat.wave_sizes, dtype=np.int64)
        W = min(next_pow2(int(sizes.max())), wave_cap) if n else 1
        offs = np.concatenate([[0], np.cumsum(sizes)])
        steps = []
        for w in range(len(sizes) - 1, -1, -1):
            lo, hi = int(offs[w]), int(offs[w + 1])
            for s in range(lo, hi, W):
                steps.append(np.arange(s, min(s + W, hi), dtype=np.int64))
        L = bucket_len(len(steps)) if steps else bucket_len(1)
        pk = np.full((L, W), -1, dtype=np.int64)
        for i, st in enumerate(steps):
            pk[i, :st.shape[0]] = st
        self.pk = pk
        self.pk_pad = pk < 0
        self.n_steps = L
        self.wave_w = W
        pke = np.where(self.pk_pad, 0, pk)
        self.up_row = np.where(self.pk_pad, self.scratch,
                               parent[pke] - 1).astype(np.int32)
        self.lrow = np.where(self.pk_pad, self.scratch,
                             left[pke] - 1).astype(np.int32)
        self.rrow = np.where(self.pk_pad, self.scratch,
                             right[pke] - 1).astype(np.int32)
        # Edge table: edge 0 is the root edge (its complement partial is
        # the initialized out[p-1] = D(q)); edges 1+2i / 2+2i are entry
        # i's left / right child edges.  E = 2n+1 = 2*ntips-3.
        E = 2 * n + 1
        self.n_edges = E
        edge_node = np.empty(E, dtype=np.int64)
        edge_node[0] = self.roots[0]
        edge_node[1::2] = left
        edge_node[2::2] = right
        ez_src = np.empty(E, dtype=np.int64)
        ez_src[0] = -1
        ez_src[1::2] = np.arange(n)
        ez_src[2::2] = np.arange(n)
        ez_side = np.zeros(E, dtype=np.int64)
        ez_side[2::2] = 1
        nc = max(1, next_pow2(-(-E // GRAD_CHUNK)))
        Epad = nc * GRAD_CHUNK
        self.n_chunks = nc

        def padE(a, fill):
            out = np.full(Epad, fill, dtype=a.dtype)
            out[:E] = a
            return out

        self.edge_node = padE(edge_node, 1)
        self.edge_pad = padE(np.zeros(E, dtype=np.int64), 1).astype(bool)
        self.edge_x_row = np.where(
            self.edge_pad, self.scratch,
            padE(edge_node, 1) - 1).astype(np.int32)
        self.edge_z_src = padE(ez_src, -1)
        self.edge_z_side = padE(ez_side, 0)


def build_structure(flat, wave_cap: int) -> GradStructure:
    return GradStructure(flat, wave_cap)


def _entry_z(flat, num_slots: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-entry branch vectors widened to the engine's slot count
    (same normalization as fastpath.refresh_z)."""
    zl, zr = flat.zl, flat.zr
    if zl.shape[1] != num_slots:
        zl = np.stack([z_slots(z, num_slots) for z in zl])
        zr = np.stack([z_slots(z, num_slots) for z in zr])
    return zl, zr


def grad_arrays(gs: GradStructure, flat, row_map: np.ndarray,
                num_slots: int, root_z):
    """The per-dispatch dynamic half: CLV gather indices resolved
    through the engine's CURRENT row map and branch vectors re-read
    from the (freshly smoothed) traversal.  Pure numpy fancy indexing —
    the only per-sweep host work on a structure-cache hit.

    Returns (pre [OutrootTraversal leaves as numpy], ex_rows, ey_gidx,
    ez) ready for device_put."""
    ntips = gs.ntips
    zl, zr = _entry_z(flat, num_slots)
    rz = np.asarray(z_slots(root_z, num_slots), dtype=np.float64)
    src = np.where(gs.zu_src < 0, 0, gs.zu_src)
    zu = np.where((gs.zu_side == 0)[:, None], zl[src], zr[src])
    zu = np.where((gs.zu_src < 0)[:, None], rz[None, :], zu)  # root edge

    def gidx(nodes):
        r = row_map[nodes]
        return np.where(nodes <= ntips, nodes - 1,
                        ntips + r).astype(np.int32)

    pke = np.where(gs.pk_pad, 0, gs.pk)
    lnode = np.asarray(flat.left, dtype=np.int64)[pke]
    rnode = np.asarray(flat.right, dtype=np.int64)[pke]
    lg = np.where(gs.pk_pad, 0, gidx(lnode)).astype(np.int32)
    rg = np.where(gs.pk_pad, 0, gidx(rnode)).astype(np.int32)

    def pkz(zarr):
        out = np.ones(gs.pk.shape + (num_slots,), dtype=np.float64)
        out[~gs.pk_pad] = zarr[gs.pk[~gs.pk_pad]]
        return out

    pre = (gs.up_row, gs.lrow, gs.rrow, lg, rg,
           pkz(zu), pkz(zl), pkz(zr))

    T = GRAD_CHUNK
    ey = np.where(gs.edge_pad, 0, gidx(gs.edge_node)).astype(np.int32)
    ezs = np.where(gs.edge_z_src < 0, 0, gs.edge_z_src)
    ez = np.where((gs.edge_z_side == 0)[:, None], zl[ezs], zr[ezs])
    ez = np.where((gs.edge_z_src < 0)[:, None], rz[None, :], ez)
    ez[gs.edge_pad] = 1.0
    return (pre,
            gs.edge_x_row.reshape(gs.n_chunks, T),
            ey.reshape(gs.n_chunks, T),
            ez.reshape(gs.n_chunks, T, num_slots))


def edge_gradients(models, block_part, weights, tips, clv, scaler, out,
                   ex_rows, ey_gidx, ez, num_slots: int, ntips: int,
                   site_rates=None):
    """(d1, d2) [n_chunks*GRAD_CHUNK, C] for every edge at once: one
    `lax.scan` over edge chunks, each chunk a batched sumtable +
    derivative contraction (identical arithmetic to the per-branch
    Newton path's `sumtable`/`nr_derivatives`)."""
    def body(carry, x):
        xr, yg, z = x
        X = out[xr]                               # [T, B, lane, R, K]
        Y, _sc = kernels.gather_child(tips, clv, scaler, yg, ntips)
        st = jax.vmap(
            lambda a, b: kernels.sumtable(models, block_part, a, b))(X, Y)
        d1, d2 = jax.vmap(
            lambda s, zz: kernels.nr_derivatives(
                models, block_part, weights, s, zz, num_slots,
                site_rates))(st, z)
        return carry, (d1, d2)

    _, (d1, d2) = jax.lax.scan(body, None, (ex_rows, ey_gidx, ez))
    return d1.reshape(-1, num_slots), d2.reshape(-1, num_slots)


def newton_step(z: np.ndarray, d1: np.ndarray, d2: np.ndarray
                ) -> np.ndarray:
    """One batched full-Newton update over all branches [E, C] — the
    single-iteration body of the reference NR loop
    (`makenewzGenericSpecial.c:1133-1349`) vectorized over edges: the
    bad-curvature branch-shortening move (z <- 0.37 z + 0.63), the
    0.25 z + 0.75 step cap, the exp(min(-d1/d2, 100)) multiplicative
    step.  Where curvature is unusable (d2 >= 0) the shortening move
    IS the safeguarded line-search direction the reference uses.
    Damping is the CALLERS' job: the smoothers scale the returned step
    in lz space through their per-branch Rprop ladder (capped at
    EXAML_GRAD_DAMPING) — one mechanism, not two."""
    from examl_tpu.constants import ZMAX, ZMIN

    z = np.clip(z, ZMIN, ZMAX)
    bad = (d2 >= 0.0) & (z < ZMAX)
    with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
        tantmp = np.where(d2 < 0.0, -d1 / np.where(d2 < 0.0, d2, 1.0),
                          np.inf)
        cap = 0.25 * z + 0.75
        znr = np.where(tantmp < 100.0,
                       np.maximum(z * np.exp(np.minimum(tantmp, 100.0)),
                                  ZMIN),
                       cap)
    znr = np.minimum(np.minimum(znr, cap), ZMAX)
    return np.where(bad, 0.37 * z + 0.63, znr)
