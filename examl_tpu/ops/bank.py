"""Ahead-of-time program banking: compile every device program the run
will dispatch BEFORE the search starts, in killable subprocess workers.

Why (VERDICT r05, Weak §1-2 / Next §5): the engine's program families
compile lazily at first dispatch, and on the remote-compile TPU tunnel a
pathological compile blocks the main thread in recv with no Python-level
recourse — round 4 wedged a whole hardware window that way, and the
in-process 180 s watchdog (`engine._guard_first_call`) can only *advise*.
BEAGLE's lesson for likelihood engines on parallel architectures is the
same: kernel selection and setup cost must be paid once, off the
critical path.  Banking makes the watchdog's advice *action*:

* `enumerate_families()` derives, from the run's config alone, the
  program families the run will dispatch — the same labels
  `_guard_first_call` stamps on compile spans/counters (`traverse`,
  `trav_eval`, `evaluate`, `newton`, `sumtable`, `derivs`, the `fast`
  chunk tier, the Pallas `whole` tier, the batched-SPR `scan`/`thscan`
  programs, PSR's `rate_scan`).
* `run_bank()` compiles them in PARALLEL KILLABLE SUBPROCESS workers
  against the persistent compilation cache (keyed by a host-feature
  fingerprint, `config.enable_persistent_compilation_cache`), with a
  HARD per-family deadline: a family whose compile exceeds
  `--compile-timeout` gets its worker killed, is recorded as degraded,
  and the run falls back to the scan-tier program (the one family
  hardware-proven on every backend) via the existing escape-hatch envs
  (`EXAML_FAST_TRAVERSAL=0`, `EXAML_PALLAS=0`, `EXAML_BATCH_SCAN=0`).
* `warm_instance()` then first-calls every banked family in the MAIN
  process inside the CLI's bank phase — now disk-cache hits — so the
  search phase performs ZERO first-call compiles and a wedge-prone
  compile can never run unmonitored on the hot path.
* the per-host **bank manifest** (stored next to the persistent cache
  entries) records banked/degraded verdicts; `bench.py` workers consult
  it so bench stages never dispatch a family that wedged this host.

Multi-host runs bank per process before the collective barrier
(`parallel/launch.bank_barrier`): each host's cache is local disk, so
each process pays its own (parallel, killable) banking pass.  Caveat:
a bank worker cannot join the parent's distributed process group, so
mesh-sharded program variants may still compile at first dispatch in
the main process — those compiles remain watchdogged and their families
still carry the bank's degradation verdicts.

Worker protocol (mirrors bench.py's staged workers): one `##start
<family>` marker line per family, then one JSON result line
(`{"family", "seconds", "ok"}`) or a `##skip <family> <reason>` line;
a final `{"family": "__metrics__", ...}` line ships the worker's obs
registry snapshot so per-family compile seconds land in the parent's
registry under `bank.*`.  EXAML_BANK_TEST_HANG=<fam[,fam]> makes the
worker hang at those families (test hook for the kill path).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from examl_tpu import obs
from examl_tpu.resilience import faults
from examl_tpu.resilience.exitcause import exit_desc

# Families with no in-run fallback: they ARE the scan tier (wave-batched
# lax.scan programs) every degradation lands on.  A timeout here is
# reported loudly but cannot be routed around.
CORE_FAMILIES = ("traverse", "trav_eval", "evaluate", "newton",
                 "sumtable", "derivs")

# family -> (env var pinned on degradation, value, what the run loses).
# Setting the env BEFORE the main process builds its engines routes
# every later dispatch around the wedged family — the same escape
# hatches the watchdog has always named, now pulled automatically.
FALLBACK_ENV = {
    "fast": (("EXAML_FAST_TRAVERSAL", "0"),
             "full traversals pinned to the scan tier"),
    "universal": (("EXAML_UNIVERSAL", "0"),
                  "universal interpreter disabled (specialized chunk "
                  "programs or scan tier)"),
    "whole": (("EXAML_PALLAS", "0"),
              "whole-traversal Pallas kernel disabled (XLA fast path "
              "or scan tier)"),
    "grad": (("EXAML_GRAD_SMOOTH", "0"),
             "whole-tree gradient smoothing disabled (per-branch "
             "Newton path)"),
    "scan": (("EXAML_BATCH_SCAN", "0"),
             "sequential SPR scans (per-candidate dispatches)"),
    "thscan": (("EXAML_BATCH_THOROUGH", "0"),
               "sequential thorough-arm SPR rescoring"),
}

MANIFEST_NAME = "bank_manifest.json"

# Process-wide bank state: which families this run banked (consulted by
# engine._guard_first_call to attribute first-call compiles), whether we
# are inside the bank phase right now (main-process warm), and whether
# this is a multi-process run whose MESH-SHARDED program variants cannot
# bank in workers (ROADMAP §4: workers cannot join the parent's
# distributed process group, so those first compiles run in-process —
# watchdogged, not killable).
_STATE = {"active": False, "banked": set(), "degraded": {},
          "in_phase": False, "pinned": {}, "sharded_residual": False,
          "enumerated": set()}


def reset() -> None:
    """Clear the process-wide bank state (one run = one bank record —
    callers invoking the CLI repeatedly in one process must not carry a
    previous run's banked-set or degradation verdicts), INCLUDING the
    escape-hatch env pins `_apply_degradations` set: a wedge verdict is
    per-run evidence, not a permanent process setting."""
    for var, prior in _STATE["pinned"].items():
        if prior is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prior
    _STATE.update(active=False, banked=set(), degraded={},
                  in_phase=False, pinned={}, sharded_residual=False,
                  enumerated=set())


def active() -> bool:
    return _STATE["active"]


def in_bank_phase() -> bool:
    return _STATE["in_phase"]


def is_banked(family: str) -> bool:
    return family in _STATE["banked"]


def degraded() -> Dict[str, str]:
    return dict(_STATE["degraded"])


def sharded_residual(family: Optional[str] = None) -> bool:
    """True when this banked run is multi-process, i.e. its mesh-sharded
    program variants could NOT bank in workers and legitimately
    first-compile in the main process (watchdogged).  The engine's
    first-call monitor uses this to count
    `engine.first_calls.inprocess_sharded` instead of the
    enumeration-gap acceptance counter `unbanked` — but ONLY for
    families the bank actually ENUMERATED (pass `family`): a family the
    enumeration missed entirely is a genuine gap and must still trip
    `unbanked`, multi-process or not."""
    if not _STATE["sharded_residual"]:
        return False
    return family is None or family in _STATE["enumerated"]


def _world_size() -> int:
    try:
        import jax
        return jax.process_count()
    except Exception:                 # noqa: BLE001
        return 1


def _declared_mesh(args) -> Optional[dict]:
    """The run's declared (S, T) fabric record for the manifest, or
    None when no `--mesh`/EXAML_MESH fabric is requested (1x1 counts as
    none).  Device-free: the bank phase must be able to stamp the
    declaration even before the main process's fabric goes live."""
    try:
        from examl_tpu.parallel.launch import mesh_spec_requested
        from examl_tpu.parallel.sharding import (declared_fabric_specs,
                                                 parse_mesh_spec)
        spec = mesh_spec_requested(args)
        if not spec:
            return None
        s, t = parse_mesh_spec(spec)
    except Exception:                 # noqa: BLE001 — a malformed spec
        # is the CLI's error to raise; the bank just declines to stamp.
        return None
    if (s, t) == (1, 1):
        return None
    return declared_fabric_specs(s, t)


# ---------------------------------------------------------------------------
# family enumeration


def enumerate_families(mode: str = "d", psr: bool = False,
                       save_memory: bool = False,
                       env: Optional[dict] = None) -> List[str]:
    """The program families a run with this config will dispatch, scan
    tier first (the fallback target must bank before anything that can
    degrade onto it), deduplicated in order.  Pure config arithmetic —
    workers later skip members that turn out inapplicable on the live
    backend (e.g. the batched SPR scan is accelerator-gated).

    The `fast` family's per-shape variants are keyed by the BUCKETED
    chunk profile (ops/fastpath.py: width ladder + coalescing + scan
    groups), not raw per-chunk widths — topologies of similar shape
    share one profile, so the family's program set is bounded and a
    worker-compiled variant is a persistent-cache hit for every later
    topology minting the same profile (cross-topology reuse is proven
    by tests/test_fastpath.py and the manifest records the layout
    constants that key it)."""
    e = os.environ if env is None else env
    fams = list(CORE_FAMILIES)
    if e.get("EXAML_FAST_TRAVERSAL") != "0" and not psr and not save_memory:
        # The universal interpreter banks BEFORE the specialized chunk
        # family (degradation order pallas -> chunk -> universal ->
        # scan: the fallback target must be warm before anything that
        # can degrade onto it).  Its family set is tiny and CLOSED —
        # one program per (alphabet, table bucket, slot bucket,
        # with_eval), none per topology — which is what converts the
        # bank from "pre-compile everything you might meet" to
        # "compile once, serve forever".
        if e.get("EXAML_UNIVERSAL") != "0":
            fams.append("universal")
        fams.append("fast")
        if e.get("EXAML_PALLAS") == "whole":
            fams.append("whole")
    if not save_memory and e.get("EXAML_GRAD_SMOOTH") != "0":
        # Whole-tree gradient smoothing (ops/gradient.py): one program
        # per bucketed (steps, width, chunks) shape — like the scan
        # tier, a small closed family whose key is shape, not topology.
        fams.append("grad")
    if psr:
        fams.append("rate_scan")
    if mode in ("d", "o") and e.get("EXAML_BATCH_SCAN") != "0":
        fams.append("scan")
        if e.get("EXAML_BATCH_THOROUGH") != "0":
            fams.append("thscan")
    return list(dict.fromkeys(fams))


def chunk_layout_info() -> dict:
    """The bounded-chunk-layout constants in effect — recorded in the
    bank manifest so a cache whose layout knobs differ from the current
    run's is visibly stale (the knobs change the profile alphabet and
    therefore every `fast`-family program shape)."""
    from examl_tpu.ops import fastpath, universal
    mw, cap, tail = fastpath._knobs()
    info = {"bounded": fastpath.bounded_default(), "min_width": mw,
            "chunk_cap": cap, "tail_width": tail}
    # Universal-interpreter coverage: whether the zero-recompile tier
    # is on and how big its closed class alphabet is — a manifest
    # reader can tell at a glance that this cache serves ANY topology
    # through the banked universal family, not just enumerated
    # profiles.
    info["universal"] = {
        "enabled": os.environ.get("EXAML_UNIVERSAL", "") != "0",
        "alphabet_classes": len(universal.alphabet((mw, cap))),
    }
    return info


def spec_from_args(args) -> dict:
    """JSON-serializable worker spec: everything a subprocess needs to
    rebuild the run's engines with identical program shapes."""
    x64 = False
    try:
        import jax
        x64 = bool(jax.config.jax_enable_x64)   # config read: no backend
    except Exception:
        pass
    return {
        "bytefile": args.bytefile,
        "tree_file": getattr(args, "tree_file", None),
        "seed": getattr(args, "seed", 12345),
        "model": getattr(args, "model", "GAMMA"),
        "categories": getattr(args, "categories", 25),
        "median": bool(getattr(args, "median", False)),
        "per_partition_bl": bool(getattr(args, "per_partition_bl", False)),
        "save_memory": bool(getattr(args, "save_memory", False)),
        "mode": getattr(args, "mode", "d"),
        "single_device": bool(getattr(args, "single_device", False)),
        "x64": x64,
    }


# ---------------------------------------------------------------------------
# warming: the dispatches that force each family's first-call compile.
# Shared verbatim by the subprocess workers (cold compiles into the
# persistent cache) and the main process's bank-phase warm pass (disk
# cache hits) so both sides trace the SAME programs.


def _applicability(inst, family: str) -> Optional[str]:
    """None when `family` applies to this instance on this backend,
    else a short skip reason."""
    from examl_tpu.search import spr

    engines = list(inst.engines.values())
    if family == "fast":
        if inst.psr or inst.save_memory:
            return "fast path is GAMMA/dense-only"
        if all(e.force_scan or e.fast_slack == 0 for e in engines):
            return "fast path disabled (EXAML_FAST_TRAVERSAL=0)"
        return None
    if family == "universal":
        from examl_tpu.ops import fastpath
        if inst.psr or inst.save_memory:
            return "universal interpreter is GAMMA/dense-only"
        if all(e.force_scan or e.fast_slack == 0 for e in engines):
            return "fast path disabled (EXAML_FAST_TRAVERSAL=0)"
        if all(getattr(e, "universal_off", True) for e in engines):
            return "universal interpreter disabled (EXAML_UNIVERSAL=0)"
        if not fastpath.bounded_default():
            return "legacy unbounded layout (EXAML_BOUNDED_CHUNKS=0)"
        return None
    if family == "whole":
        if not any(e.pallas_whole for e in engines):
            return "whole-traversal kernel needs EXAML_PALLAS=whole on TPU"
        return None
    if family == "grad":
        if inst.save_memory:
            return "whole-tree gradients need the dense CLV arena (-S)"
        if any(e.sharding is not None for e in inst.engines.values()):
            return "whole-tree gradient smoothing is single-process"
        return None
    if family == "rate_scan":
        return None if inst.psr else "GAMMA run has no rate scan"
    if family == "scan":
        if not spr.batched_scan_enabled(inst):
            return "batched SPR scan gated off (CPU backend)"
        return None
    if family == "thscan":
        if not spr.thorough_batched_ok(inst):
            return "batched thorough arm gated off"
        return None
    return None


def warm_family(inst, tree, family: str) -> None:
    """Dispatch the calls whose first invocation compiles `family`'s
    programs.  Mutates engine/tree state freely — callers (worker
    processes; the CLI bank phase, which runs before the search loads
    its own tree) do not depend on it."""
    import numpy as np

    engines = list(inst.engines.values())

    def scan_tier():
        """Context: pin every engine to the scan tier, restore after."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            prior = [e.force_scan for e in engines]
            for e in engines:
                e.force_scan = True
            try:
                yield
            finally:
                for e, p in zip(engines, prior):
                    e.force_scan = p
        return cm()

    def inner_node():
        for n in tree.inner_numbers():
            nd = tree.nodep[n]
            if not tree.is_tip(nd.back.number):
                return nd
        return tree.nodep[tree.inner_numbers()[0]]

    if family == "traverse":
        with scan_tier():
            tree.invalidate_all()
            p = tree.centroid_branch()
            entries = (inst._collect(tree, p, True)
                       + inst._collect(tree, p.back, True))
            inst.run_traversal(entries, full=True)
            inst.new_view(tree, inner_node())      # small-L partial bucket
        return
    if family == "trav_eval":
        with scan_tier():
            inst.evaluate(tree, full=True)
            inst.evaluate(tree, p=inner_node())    # partial-L variant
        return
    if family == "evaluate":
        with scan_tier():
            inst.evaluate(tree, full=True)
            p = inner_node()
            for eng in engines:
                eng.evaluate(p.number, p.back.number, p.z)
        return
    if family == "newton":
        with scan_tier():
            inst.evaluate(tree, full=True)
            p = inner_node()
            inst.makenewz(tree, p, p.back, p.z, maxiter=16)
        return
    if family in ("sumtable", "derivs"):
        with scan_tier():
            inst.evaluate(tree, full=True)
            p = inner_node()
            for eng in engines:
                st = eng.make_sumtable(p.number, p.back.number)
                eng.branch_derivatives(st, p.z)
        return
    if family in ("fast", "whole"):
        # The engine's natural full-traversal tier (XLA chunks on CPU,
        # Pallas chunks on TPU; `whole` when EXAML_PALLAS=whole): both
        # the traverse-only and fused traverse+evaluate variants.
        tree.invalidate_all()
        p = tree.centroid_branch()
        entries = (inst._collect(tree, p, True)
                   + inst._collect(tree, p.back, True))
        inst.run_traversal(entries, full=True)
        inst.evaluate(tree, full=True)
        return
    if family == "universal":
        # The topology-as-data interpreter: pin the tier, dispatch both
        # variants (traverse-only + fused eval).  The compiled programs
        # are keyed by bucket sizes, not topology, so THIS warm covers
        # every later topology whose buckets fit (`pick_pads` reuses
        # any compiled bucket) — the zero-recompile serving warmup.
        prior = [e.universal_force for e in engines]
        for e in engines:
            e.universal_force = True
        try:
            tree.invalidate_all()
            p = tree.centroid_branch()
            entries = (inst._collect(tree, p, True)
                       + inst._collect(tree, p.back, True))
            inst.run_traversal(entries, full=True)
            inst.evaluate(tree, full=True)
        finally:
            for e, v in zip(engines, prior):
                e.universal_force = v
        return
    if family == "grad":
        # The whole-tree gradient pass over the run's own tree: the
        # bucketed (steps, width, chunks) shapes this compiles are the
        # exact shapes every smoothing sweep of the search reuses.
        from examl_tpu.optimize.branch import tree_gradients
        inst.evaluate(tree, full=True)
        tree_gradients(inst, tree)
        return
    if family == "rate_scan":
        from examl_tpu.optimize.psr import MIN_RATE
        tree.invalidate_all()
        p, entries = tree.full_traversal()
        G = 2 if inst.save_memory else 8     # psr.py grid chunk sizes
        for g in (1, G):
            for states, bucket in inst.buckets.items():
                grid = np.maximum(np.full(
                    (bucket.num_blocks, bucket.lane, g), 1.0), MIN_RATE)
                inst.engines[states].rate_scan(entries, p.number,
                                               p.back.number, p.z, grid)
        return
    if family in ("scan", "thscan"):
        from examl_tpu.search import batchscan, spr
        from examl_tpu.tree.topology import hookup

        inst.evaluate(tree, full=True)
        ctx = spr.SprContext(inst, thorough=(family == "thscan"),
                             do_cutoff=False)
        c = tree.centroid_branch()
        p = c if not tree.is_tip(c.number) else c.back
        q1, q2 = p.next.back, p.next.next.back
        p1z, p2z = list(q1.z), list(q2.z)
        spr.remove_node(inst, tree, ctx, p)
        plan = batchscan.plan_for_endpoints(inst, tree, p, q1, q2, 1, 10)
        try:
            if plan is not None:
                if family == "thscan":
                    batchscan.run_plan_thorough(inst, tree, plan)
                else:
                    batchscan.run_plan(inst, tree, plan)
        finally:
            hookup(p.next, q1, p1z)
            hookup(p.next.next, q2, p2z)
            inst.new_view(tree, p)
        return
    raise ValueError(f"unknown program family {family!r}")


# ---------------------------------------------------------------------------
# worker subprocess


def _build_run(spec: dict):
    """Rebuild (inst, tree) from a worker spec — the same construction
    path as cli.main._run, single-process."""
    from examl_tpu.cli.main import _load_alignment, _read_trees
    from examl_tpu.instance import PhyloInstance

    if spec.get("x64"):
        from examl_tpu.config import enable_x64
        enable_x64()
    import jax

    sharding = None
    if not spec.get("single_device") and len(jax.devices()) > 1:
        from examl_tpu.parallel.sharding import make_mesh, site_sharding
        sharding = site_sharding(make_mesh())
    data = _load_alignment(spec["bytefile"],
                           block_multiple=(sharding.num_devices
                                           if sharding else 1))
    inst = PhyloInstance(
        data, ncat=4, use_median=spec.get("median", False),
        per_partition_branches=spec.get("per_partition_bl", False),
        rate_model=spec.get("model", "GAMMA"),
        psr_categories=spec.get("categories", 25),
        save_memory=spec.get("save_memory", False), sharding=sharding,
        block_multiple=(sharding.num_devices if sharding else 1))
    if spec.get("tree_file"):
        tree = inst.tree_from_newick(_read_trees(spec["tree_file"])[0])
    else:
        tree = inst.random_tree(seed=spec.get("seed", 0))
    return inst, tree


def _worker(spec_path: str, families: List[str]) -> None:
    from examl_tpu.config import enable_persistent_compilation_cache

    with open(spec_path) as f:
        spec = json.load(f)
    cache = enable_persistent_compilation_cache()
    print(json.dumps({"family": "__cache__", "path": cache}), flush=True)

    hang = set((os.environ.get("EXAML_BANK_TEST_HANG") or "").split(","))
    # Instance construction (alignment load, device placement) gets its
    # OWN deadline window: on a large run it can legitimately take
    # longer than one family's compile budget, and charging it to the
    # first family would cascade false timeouts (each respawned worker
    # rebuilds and times out again).  The parent treats a __setup__
    # timeout as fatal for this worker's whole plan, no requeue.
    print("##start __setup__", flush=True)
    try:
        t0 = time.perf_counter()
        inst, tree = _build_run(spec)
        print(json.dumps({"family": "__setup__", "ok": True,
                          "seconds": round(time.perf_counter() - t0, 3)}),
              flush=True)
    except Exception as exc:                  # noqa: BLE001
        print(json.dumps({"family": "__setup__", "ok": False,
                          "error": f"{type(exc).__name__}: {exc}"}),
              flush=True)
        return
    for family in families:
        print(f"##start {family}", flush=True)
        if family in hang:                    # test hook: a wedged compile
            time.sleep(3600)
        # Fault seam (resilience/faults.py): `bank.worker` kills or
        # hangs THIS worker at family start — the parent's deadline
        # kill, mid-compile-death classification and requeue paths are
        # all exercisable on CPU (EXAML_FAULTS propagates via env).
        faults.fire("bank.worker")
        try:
            reason = _applicability(inst, family)
            if reason is not None:
                print(f"##skip {family} {reason}", flush=True)
                continue
            t0 = time.perf_counter()
            warm_family(inst, tree, family)
            print(json.dumps({"family": family, "ok": True,
                              "seconds": round(time.perf_counter() - t0,
                                               3)}), flush=True)
        except Exception as exc:              # noqa: BLE001
            print(json.dumps({"family": family, "ok": False,
                              "error": f"{type(exc).__name__}: {exc}"}),
                  flush=True)
    try:
        print(json.dumps({"family": "__metrics__",
                          "snapshot": obs.snapshot()}), flush=True)
    except Exception:                         # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# parent orchestrator


class _Worker:
    """One killable compile worker: Popen + a reader thread that tracks
    the family currently compiling (for the per-family deadline) and
    collects result lines."""

    def __init__(self, plan: List[str], spec_path: str, env: dict):
        self.plan = list(plan)
        self.results: Dict[str, dict] = {}
        self.snapshot: Optional[dict] = None
        self.cache_path: Optional[str] = None
        self.current: Optional[tuple] = None     # (family, t0)
        self.started: List[str] = []
        self.last_progress = time.time()
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "examl_tpu.ops.bank", "--worker",
             spec_path, ",".join(plan)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        self.thread = threading.Thread(target=self._read, daemon=True)
        self.thread.start()

    def _read(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            self.last_progress = time.time()
            if line.startswith("##start "):
                fam = line.split(None, 1)[1]
                self.started.append(fam)
                self.current = (fam, time.time())
            elif line.startswith("##skip "):
                parts = line.split(None, 2)
                self.results[parts[1]] = {
                    "status": "skipped",
                    "reason": parts[2] if len(parts) > 2 else ""}
                self.current = None
            elif line.startswith("{"):
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                fam = d.get("family")
                if fam == "__metrics__":
                    self.snapshot = d.get("snapshot")
                elif fam == "__cache__":
                    self.cache_path = d.get("path")
                elif fam:
                    self.results[fam] = {
                        "status": "banked" if d.get("ok") else "error",
                        "seconds": d.get("seconds"),
                        "error": d.get("error")}
                    self.current = None
        self.proc.stdout.close()

    def overdue(self, timeout: float) -> Optional[str]:
        cur = self.current
        if cur is not None and time.time() - cur[1] > timeout:
            return cur[0]
        return None

    def wedged_silent(self, timeout: float) -> bool:
        """True when the worker has produced NO output for well past
        the deadline with no family in flight — a hang before the first
        ##start (backend/client init: the round-3/4 tunnel failure
        mode), which the per-family deadline alone cannot see."""
        return (self.current is None
                and time.time() - self.last_progress > timeout + 60.0)

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass


def _worker_env() -> dict:
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if repo not in pp:
        env["PYTHONPATH"] = os.pathsep.join([repo] + pp)
    return env


def _default_workers() -> int:
    env = os.environ.get("EXAML_BANK_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    # Parallel workers ONLY when the backend is known-CPU: accelerator
    # backends are exclusive-access (one worker owns the chip at a
    # time, and it must RELEASE it before the main process initializes
    # — run_bank runs before the parent touches jax), and an UNSET
    # JAX_PLATFORMS on a TPU host means jax will autodetect libtpu, so
    # the safe default there is a single sequential (still killable)
    # worker.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return min(4, os.cpu_count() or 1)
    return 1


def run_bank(args, log=lambda msg: None, timeout: Optional[float] = None,
             workers: Optional[int] = None) -> Dict[str, dict]:
    """Bank every program family for the run described by `args` (the
    CLI namespace): parallel killable subprocess compiles with a hard
    per-family deadline, persistent-cache population, obs accounting,
    degradation env pinning, and the per-host manifest.  Returns
    {family: {"status": banked|timeout|error|skipped, ...}}.

    Single-process runs invoke this BEFORE the parent touches its
    backend: on exclusive-access accelerators the worker must be able
    to own (and release) the device, and a worker kill must never take
    the parent's device handle with it.  Multi-host runs CANNOT honor
    that ordering — `init_distributed` has already initialized the
    parent's backend — so on exclusive accelerators their workers may
    fail to acquire the device and those families compile lazily
    in-process (watchdogged); environment errors like that never pin
    degradations (`_is_wedge`)."""
    import tempfile

    reset()
    timeout = timeout if timeout is not None else float(
        getattr(args, "compile_timeout", None) or 180.0)
    psr = getattr(args, "model", "GAMMA") == "PSR"
    families = enumerate_families(mode=getattr(args, "mode", "d"),
                                  psr=psr,
                                  save_memory=getattr(args, "save_memory",
                                                      False))
    obs.inc("bank.families", len(families))
    report: Dict[str, dict] = {}
    # Exported program bank (ops/export_bank.py): families a cold
    # restart will DESERIALIZE need no subprocess compile worker — this
    # is what turns a supervised retry or autoscaled cold start from
    # "full bank phase" into "load ladder".  Coverage checks only the
    # backend-independent stamps here (this runs before the parent may
    # touch its backend); an artifact whose platform later disagrees
    # costs a counted fall-through to the watchdogged in-process
    # compile, never a wrong result.
    from examl_tpu.ops import export_bank
    all_families = list(families)
    if export_bank.enabled():
        # Dataset guard for the worker skip: artifact loadability is
        # SIGNATURE-level (avals), so another dataset's same-named
        # artifacts must not skip this run's compile workers only to
        # miss at warm time.  ntaxa reads from the byteFile header —
        # no backend touch, honoring the bank's ordering contract.
        ntaxa = None
        try:
            from examl_tpu.io.bytefile import read_bytefile_meta
            ntaxa = read_bytefile_meta(args.bytefile).ntaxa
        except Exception:                     # noqa: BLE001 — raw
            pass                              # PHYLIP input: no filter
        cover = export_bank.family_coverage(families, ntaxa=ntaxa)
        for fam in cover:
            report[fam] = {"status": "exported",
                           "artifacts": cover[fam]}
        if cover:
            obs.inc("bank.exported_families", len(cover))
            log(f"bank: {len(cover)} of {len(families)} families "
                "covered by exported artifacts; their compile workers "
                "are skipped (" + ", ".join(sorted(cover)) + ")")
        families = [f for f in families if f not in cover]
    spec_fd, spec_path = tempfile.mkstemp(suffix=".json",
                                          prefix="examl_bank_")
    with os.fdopen(spec_fd, "w") as f:
        json.dump(spec_from_args(args), f)
    env = _worker_env()
    env["EXAML_COMPILE_TIMEOUT"] = repr(timeout)

    nw = workers or _default_workers()
    nw = max(1, min(nw, len(families)))
    plans = [families[i::nw] for i in range(nw)]
    if families:
        log(f"banking {len(families)} program families in {nw} compile "
            f"worker(s), {timeout:.0f}s/family deadline: "
            + ", ".join(families))
    else:
        log("banking: every enumerated family is served by the "
            "exported bank; no compile workers spawned")

    def merge_results(w):
        report.update({k: v for k, v in w.results.items()
                       if k not in report and not k.startswith("__")})

    t_bank = time.perf_counter()
    live = [_Worker(plan, spec_path, env) for plan in plans if plan]
    cache_path = None
    try:
        while live:
            time.sleep(0.2)
            still = []
            for w in live:
                fam = w.overdue(timeout)
                if fam is not None:
                    w.kill()
                    w.proc.wait()
                    w.thread.join(timeout=5)
                    cache_path = cache_path or w.cache_path
                    done = w.results.get(fam)
                    if done is not None and done.get(
                            "status") == "banked":
                        # Finished within the poll window: a deadline
                        # RACE, not a wedge — keep the success (the
                        # worker is dead either way; the rest requeue).
                        log(f"bank: {fam} completed at the deadline "
                            "edge; kept")
                    elif fam == "__setup__":
                        for fam2 in w.plan:
                            if fam2 not in report:
                                report[fam2] = {
                                    "status": "error",
                                    "error": "worker setup (instance "
                                             "build / backend init) "
                                             "exceeded the deadline"}
                        obs.inc("bank.worker_wedges")
                        log("bank: worker setup exceeded the deadline; "
                            "its families will compile lazily "
                            "(watchdogged)")
                        merge_results(w)
                        continue
                    else:
                        report[fam] = {"status": "timeout",
                                       "seconds": timeout}
                        obs.inc("bank.timeouts")
                        log(f"bank: family '{fam}' exceeded the "
                            f"{timeout:.0f}s compile deadline; worker "
                            "killed")
                    merge_results(w)
                    # Requeue what the dead worker never finished.
                    rest = [x for x in w.plan
                            if x != fam and x not in w.results
                            and x not in report]
                    if rest:
                        still.append(_Worker(rest, spec_path, env))
                    continue
                if w.proc.poll() is None:
                    if w.wedged_silent(timeout):
                        w.kill()
                        w.proc.wait()
                        w.thread.join(timeout=5)
                        cache_path = cache_path or w.cache_path
                        merge_results(w)
                        for fam2 in w.plan:
                            if fam2 not in report:
                                report[fam2] = {
                                    "status": "error",
                                    "error": "worker wedged before its "
                                             "next family (killed)"}
                        obs.inc("bank.worker_wedges")
                        log("bank: a compile worker went silent past "
                            "the deadline before starting a family; "
                            "killed")
                        continue
                    still.append(w)
                    continue
                w.thread.join(timeout=5)
                cache_path = cache_path or w.cache_path
                merge_results(w)
                if w.snapshot:
                    _merge_worker_metrics(w.snapshot)
                rc = w.proc.returncode
                died = next((f for f in reversed(w.started)
                             if f not in w.results), None)
                if rc != 0 and died is not None \
                        and not died.startswith("__"):
                    # The worker died INSIDE one family (SIGILL/
                    # SIGSEGV/OOM-kill): that family alone carries the
                    # verdict; the never-attempted rest requeues into a
                    # fresh worker — branding untried families as
                    # wedged would gate healthy bench stages for no
                    # reason.
                    report[died] = {"status": "error",
                                    "error": "worker died mid-compile "
                                             + _exit_desc(rc)}
                    log(f"bank: {died} killed its worker "
                        f"{_exit_desc(rc)}")
                    rest = [x for x in w.plan
                            if x != died and x not in w.results
                            and x not in report]
                    if rest:
                        still.append(_Worker(rest, spec_path, env))
                    continue
                setup = w.results.get("__setup__", {})
                cause = (setup.get("error")
                         or "worker exited " + _exit_desc(rc))
                for fam2 in w.plan:
                    if fam2 not in report:
                        report[fam2] = {"status": "error",
                                        "error": cause}
            live = still
    finally:
        for w in live:
            w.kill()
        try:
            os.unlink(spec_path)
        except OSError:
            pass
    obs.observe("bank.wall_seconds", time.perf_counter() - t_bank)
    if cache_path is None and families:
        # Without a persistent cache the workers' compiles are NOT
        # durable: the main-process warm pass will re-compile cold
        # (in-process, watchdogged).  The kill+degrade protection for
        # wedged families still stands — that is subprocess-side — but
        # say loudly that the compile-time transfer is lost.  (A run
        # whose every family is exported-covered spawned no worker and
        # learned no cache path — that is the zero-compile fast path,
        # not a missing cache.)
        obs.inc("bank.no_cache")
        log("bank: persistent compile cache unavailable (no host "
            "fingerprint, or EXAML_COMPILE_CACHE=0) — worker compiles "
            "are not durable; the warm pass will recompile in-process")

    for fam, r in report.items():
        st = r.get("status")
        if st == "banked":
            obs.inc("bank.banked")
            if r.get("seconds") is not None:
                obs.observe(f"bank.compile.{fam}", float(r["seconds"]))
            log(f"bank: {fam} compiled in {r.get('seconds', 0):.1f}s")
        elif st == "skipped":
            obs.inc("bank.skipped")
            log(f"bank: {fam} skipped ({r.get('reason', '')})")
        elif st == "error":
            obs.inc("bank.errors")
            log(f"bank: {fam} FAILED ({r.get('error', '?')})")
    _apply_degradations(report, log)
    _STATE["active"] = True
    # Exported families join the banked set: if a rejected artifact
    # later forces a guarded in-process compile, that first call is a
    # member of a family the bank DID provision (first_calls.banked),
    # not an enumeration gap.
    _STATE["banked"] = {f for f, r in report.items()
                        if r.get("status") in ("banked", "exported")}
    _STATE["enumerated"] = set(all_families)
    decl = _declared_mesh(args)
    if decl is not None:
        # ISSUE 17: a `--mesh`/EXAML_MESH run's shardings are DECLARED
        # — axis names, mesh shape, per-leaf PartitionSpecs — so the
        # manifest records them verbatim: a relocating loader (or an
        # operator reading the manifest) re-declares the same
        # NamedShardings instead of trusting procid-implicit placement.
        for r in report.values():
            r["mesh_declared"] = decl
        obs.inc("bank.mesh_declared", len(report))
        log(f"bank: declared {decl['site_shards']}x"
            f"{decl['tree_shards']} fabric shardings recorded in the "
            "manifest for every enumerated family")
    world = _world_size()
    if world > 1:
        # ROADMAP §4 observability: workers cannot join this job's
        # distributed process group, so every family's MESH-SHARDED
        # variant still first-compiles in the main process (watchdogged,
        # not killable).  Make the residual exposure explicit — in the
        # manifest AND in `engine.first_calls.inprocess_sharded` —
        # instead of letting chip-round artifacts hide it in `unbanked`.
        _STATE["sharded_residual"] = True
        for r in report.values():
            # A mesh-built family already carries its DECLARED
            # shardings above — `mesh_declared` supersedes the
            # placement-implicit residual marker for those programs.
            if "mesh_declared" not in r:
                r["mesh_sharded_inprocess"] = True
        obs.inc("bank.sharded_residual_families", len(report))
        log(f"bank: {world}-process job — mesh-sharded program variants "
            "cannot bank in workers (no process group); their first "
            "compiles run in-process, watchdogged "
            "(engine.first_calls.inprocess_sharded)")
    _save_manifest(cache_path, report, log)
    return report


def _exit_desc(rc: Optional[int]) -> str:
    """Worker exit cause — the shared taxonomy (resilience/exitcause.py)
    with the bank's poll semantics (rc None = still running)."""
    return exit_desc(rc, none_desc="(still running)")


def _merge_worker_metrics(snapshot: dict) -> None:
    """Fold a worker's compile accounting into the parent registry under
    the bank namespace: the per-family compile seconds the subprocess
    paid are this run's bank-phase compile record."""
    for name, v in (snapshot.get("counters") or {}).items():
        if name.startswith("engine.compile") or name.startswith(
                "engine.watchdog"):
            obs.inc("bank." + name, v)


def _is_wedge(r: dict) -> bool:
    """A verdict that justifies routing around the family: a hard
    compile-deadline kill, or a worker death BY SIGNAL inside it
    (SIGILL/SIGSEGV/OOM-kill — r05's failure class).  A plain nonzero
    returncode (import error, device already held by the parent, a
    raised exception) is an environment problem, not a wedge: degrading
    on it would silently pin a healthy run to the scan tier, so those
    stay recorded-but-dispatchable (the main process compiles them
    lazily, watchdogged)."""
    if r.get("status") == "timeout":
        return True
    # Match the structured "(signal NAME)" marker `_exit_desc` emits,
    # not the bare word: ordinary exception texts mentioning "signal"
    # (e.g. "signal only works in main thread") are environment errors.
    return r.get("status") == "error" and "(signal " in (r.get("error")
                                                         or "")


def _apply_degradations(report: Dict[str, dict], log) -> None:
    """Pin the escape-hatch envs for every WEDGED family (see
    `_is_wedge`), BEFORE the main process builds its engines (which
    read the envs at construction) — the watchdog's advice, executed.
    The prior env values are remembered so `reset()` can unpin them
    (one run's verdicts must not leak into the next run in-process)."""
    for fam, r in report.items():
        if not _is_wedge(r):
            continue
        _STATE["degraded"][fam] = r.get("status")
        hatch = FALLBACK_ENV.get(fam)
        if hatch is None:
            obs.log(f"EXAML: bank: scan-tier family '{fam}' "
                    f"{r.get('status')} — no fallback exists for the "
                    "fallback tier itself; the run may compile it "
                    "in-process (watchdogged)")
            continue
        (var, val), cost = hatch
        if var not in _STATE["pinned"]:
            _STATE["pinned"][var] = os.environ.get(var)
        os.environ[var] = val
        obs.inc("bank.fallbacks")
        obs.log(f"EXAML: bank: family '{fam}' {r.get('status')}; "
                f"pinned {var}={val} — {cost}")


# ---------------------------------------------------------------------------
# manifest (per host, next to the persistent cache entries)


def _save_manifest(cache_path: Optional[str], report: Dict[str, dict],
                   log) -> None:
    """Write this run's verdicts, MERGED over the existing manifest: a
    config that does not enumerate some family (e.g. a PSR run, which
    has no 'fast') must not erase a prior run's wedge verdict for it —
    bench gating depends on those surviving until a bank re-proves the
    family healthy."""
    if not cache_path:
        return
    path = os.path.join(cache_path, MANIFEST_NAME)
    # Same advisory flock as export_bank._update_exports: leased fleet
    # ranks (and that module's own export writes) share this file, and
    # an unlocked read-modify-write here could overwrite a concurrent
    # rank's freshly-recorded export entries with a stale read.
    lock_fd = None
    try:
        try:
            import fcntl
            lock_fd = os.open(path + ".lock",
                              os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        except Exception:                     # noqa: BLE001 — advisory
            lock_fd = None
        prior = load_manifest(cache_path) or {}
        families = dict(prior.get("families") or {})
        families.update(report)
        doc = {"version": 1, "updated": time.time(),
               "chunk_layout": chunk_layout_info(),
               "families": families}
        if prior.get("exports"):
            # The exported-artifact index (ops/export_bank.py) shares
            # this manifest: a banking pass must never erase the
            # records a cold restart's load ladder depends on.
            doc["exports"] = prior["exports"]
        try:
            with open(path, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
            log(f"bank manifest -> {path}")
        except OSError as exc:
            log(f"bank manifest not written ({exc})")
    finally:
        if lock_fd is not None:
            try:
                os.close(lock_fd)             # releases the flock
            except OSError:
                pass


def load_manifest(cache_path: Optional[str] = None) -> Optional[dict]:
    """The current host's bank manifest, or None.  With no explicit
    path, reads next to the configured persistent cache dir (callers
    must have enabled the cache first)."""
    if cache_path is None:
        from examl_tpu.config import persistent_cache_dir
        cache_path = persistent_cache_dir()
    if not cache_path:
        return None
    try:
        with open(os.path.join(cache_path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def manifest_degraded_families(manifest: Optional[dict]) -> set:
    """Families a previous bank on this host recorded as WEDGED
    (deadline kill or death-by-signal, `_is_wedge`) — dispatchers
    (bench.py stages) must route around them.  Plain environment errors
    do not gate: they say nothing about the program."""
    if not manifest:
        return set()
    return {f for f, r in (manifest.get("families") or {}).items()
            if _is_wedge(r)}


# ---------------------------------------------------------------------------
# main-process warm pass


def warm_instance(inst, tree, report: Dict[str, dict], log) -> None:
    """First-call every banked family in the MAIN process, inside the
    bank phase: with the persistent cache populated by the workers these
    are disk-cache hits, so the engine's `_guard_first_call` fires — and
    its compile counters accrue — here rather than mid-search.  A warm
    failure only forfeits the warm (the family recompiles lazily,
    watchdogged, like before banking existed).

    Families with status "exported" warm through the export-bank load
    ladder instead: their first calls DESERIALIZE (ops/export_bank.py
    — `bank.export.hits`, no compile, no guard), and any rejected
    artifact falls through to the persistent-cache/compile rung right
    here in the bank phase rather than mid-search."""
    _STATE["in_phase"] = True
    try:
        for fam in [f for f in report
                    if report[f].get("status") in ("banked",
                                                   "exported")]:
            if _applicability(inst, fam) is not None:
                continue
            try:
                with obs.timer(f"bank.warm.{fam}"):
                    warm_family(inst, tree, fam)
            except Exception as exc:          # noqa: BLE001
                obs.inc("bank.warm_errors")
                log(f"bank: main-process warm of '{fam}' failed "
                    f"({type(exc).__name__}: {exc}); the family will "
                    "compile lazily")
    finally:
        _STATE["in_phase"] = False


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) >= 3 and argv[0] == "--worker":
        _worker(argv[1], [f for f in argv[2].split(",") if f])
        return 0
    sys.stderr.write("usage: python -m examl_tpu.ops.bank --worker "
                     "<spec.json> <fam1,fam2,...>\n")
    return 2


if __name__ == "__main__":
    sys.exit(main())
