"""Whole-traversal Pallas kernel: one Mosaic program per full traversal.

Stage 2 of the SURVEY §7.2(9) Pallas path (stage 1 = per-chunk kernels,
ops/pallas_newview.py): the ENTIRE wave-scheduled traversal runs as one
`pallas_call` with grid=(entries,), eliminating every XLA op boundary
between chunks and letting output DMA overlap the next entry's compute.

Uniformity: a one-hot tip contraction costs the same MXU passes as the
dense child dot (both pad to 128 lanes), so tip children are expanded
in-kernel from their uint8 codes with a rate-tiled indicator table
`tab2[c, (r,k)] = table[c,k]` — ONE dot, no case split; every grid step
is identical:

  x_child = is_tip ? one_hot(codes) @ tab2 : DMA(clv[row])
  y       = x_child @ blockdiag_R(P)       (streamed from XLA; HIGH
                                            precision, all-positive sums,
                                            NUMERICS.md)
  v       = yl * yr, rescale check, async DMA out to clv[write_row]

Write-after-read safety: children always come from earlier waves and at
most ONE output copy is ever in flight (single landing slot), so a wait
on the pending copy at each wave boundary — flagged by the prefetched
`sync[e]` bit — is sufficient; within a wave the copy overlaps compute.

Reference semantics: `newviewIterative` over a full traversal
(`newviewGenericSpecial.c:917-1515`), tip handling per the MIC tip
scheme (`mic_native_dna.c:132-165`).  f32 only, like stage 1.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from examl_tpu.ops import kernels
from examl_tpu.tree.topology import Tree, TraversalEntry

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# tier (and its interpret-mode tests) runs across jax versions.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


class FlatSchedule(NamedTuple):
    """Wave-ordered per-entry metadata (host arrays)."""
    e_real: int                 # entry count
    meta: np.ndarray            # [E, 8] int32: l_tip r_tip l_row r_row
                                #                w_row sync pad pad
    l_code: np.ndarray          # [E] tip index of left child (or 0)
    r_code: np.ndarray
    zl: np.ndarray              # [E, C]
    zr: np.ndarray
    row_of: Dict[int, int]


def build_flat(entries: List[TraversalEntry], ntips: int,
               num_slots: int) -> FlatSchedule:
    """Wave-order entries; parents take consecutive arena rows from 0
    (same row-layout discipline as the chunked fast path)."""
    from examl_tpu.utils import z_slots

    waves = Tree.schedule_waves(entries)
    flat: List[TraversalEntry] = []
    sync_flags: List[int] = []
    for wave in waves:
        for i, e in enumerate(wave):
            flat.append(e)
            sync_flags.append(1 if i == 0 else 0)
    E = len(flat)
    row_of: Dict[int, int] = {e.parent: i for i, e in enumerate(flat)}

    def child(num: int) -> Tuple[int, int, int]:
        if num <= ntips:
            return 1, 0, num - 1
        return 0, row_of[num], 0

    meta = np.zeros((E, 8), np.int32)
    l_code = np.zeros(E, np.int32)
    r_code = np.zeros(E, np.int32)
    zl = np.ones((E, num_slots))
    zr = np.ones((E, num_slots))
    for i, e in enumerate(flat):
        lt, lr, lc = child(e.left)
        rt, rr, rc = child(e.right)
        meta[i, :6] = (lt, rt, lr, rr, i, sync_flags[i])
        l_code[i], r_code[i] = lc, rc
        zl[i] = z_slots(e.zl, num_slots)
        zr[i] = z_slots(e.zr, num_slots)
    return FlatSchedule(e_real=E, meta=meta, l_code=l_code, r_code=r_code,
                        zl=zl, zr=zr, row_of=row_of)


def _kernel(meta_ref, clv_hbm, scaler_hbm, pb_ref, codes_ref, tab_ref,
            clv_out, scaler_out,
            xl_s, xr_s, scl_s, scr_s, v_s, sc_s,
            sem_xl, sem_sl, sem_xr, sem_sr, sem_v, sem_sc,
            *, E: int, C: int, minlik: float, two_e: float,
            precision):
    e = pl.program_id(0)
    l_tip = meta_ref[e, 0]
    r_tip = meta_ref[e, 1]
    l_row = meta_ref[e, 2]
    r_row = meta_ref[e, 3]
    w_row = meta_ref[e, 4]
    sync = meta_ref[e, 5]

    def out_wait():
        pltpu.make_async_copy(v_s, clv_out.at[0], sem_v).wait()
        pltpu.make_async_copy(sc_s, scaler_out.at[0], sem_sc).wait()

    # Wave boundary: the (single) in-flight output copy must land before
    # this wave reads any arena row.
    @pl.when(jnp.logical_and(sync == 1, e > 0))
    def _():
        out_wait()

    # Child fetches: DMA for inner children, in-kernel one-hot expansion
    # for tips (started first so the DMA overlaps the tip dots).
    @pl.when(l_tip == 0)
    def _():
        pltpu.make_async_copy(clv_out.at[l_row], xl_s, sem_xl).start()
        pltpu.make_async_copy(scaler_out.at[l_row], scl_s, sem_sl).start()

    @pl.when(r_tip == 0)
    def _():
        pltpu.make_async_copy(clv_out.at[r_row], xr_s, sem_xr).start()
        pltpu.make_async_copy(scaler_out.at[r_row], scr_s, sem_sr).start()

    tab = tab_ref[:]                                        # [C, RK]

    def tip_x(codes):                                       # [B, L] int32
        oh = (codes[:, :, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (1, 1, C), 2))
        return jax.lax.dot_general(oh.astype(tab.dtype), tab,
                                   (((2,), (0,)), ((), ())),
                                   precision=precision)     # [B, L, RK]

    def dot_b(x, pb):
        return jax.lax.dot_general(
            x, pb, (((2,), (1,)), ((0,), (0,))), precision=precision,
            preferred_element_type=jnp.float32)

    @pl.when(l_tip == 1)
    def _():
        xl_s[:] = tip_x(codes_ref[0, 0])
        scl_s[:] = jnp.zeros_like(scl_s)

    @pl.when(r_tip == 1)
    def _():
        xr_s[:] = tip_x(codes_ref[0, 1])
        scr_s[:] = jnp.zeros_like(scr_s)

    @pl.when(l_tip == 0)
    def _():
        pltpu.make_async_copy(clv_out.at[l_row], xl_s, sem_xl).wait()
        pltpu.make_async_copy(scaler_out.at[l_row], scl_s, sem_sl).wait()

    @pl.when(r_tip == 0)
    def _():
        pltpu.make_async_copy(clv_out.at[r_row], xr_s, sem_xr).wait()
        pltpu.make_async_copy(scaler_out.at[r_row], scr_s, sem_sr).wait()

    yl = dot_b(xl_s[:], pb_ref[0, 0])
    yr = dot_b(xr_s[:], pb_ref[0, 1])
    v = yl * yr
    needs = jnp.max(jnp.abs(v), axis=2) < minlik            # [B, L]
    v = jnp.where(needs[:, :, None], v * two_e, v)
    sc = scl_s[:] + scr_s[:] + needs.astype(jnp.int32)

    # The landing slot is reused every entry: mid-wave, wait the previous
    # entry's copy before overwriting (its target row is disjoint from
    # everything this wave reads, so only the slot needs protecting).
    @pl.when(jnp.logical_and(sync == 0, e > 0))
    def _():
        out_wait()

    v_s[:] = v
    sc_s[:] = sc
    pltpu.make_async_copy(v_s, clv_out.at[w_row], sem_v).start()
    pltpu.make_async_copy(sc_s, scaler_out.at[w_row], sem_sc).start()

    @pl.when(e == E - 1)                                    # drain
    def _():
        out_wait()


def run_flat(models, block_part, tips, clv, scaler, sched: FlatSchedule,
             scale_exp: int, precision=None, interpret: bool = False):
    """Execute a flat schedule as ONE pallas_call.  clv [rows,B,L,R,K]."""
    return run_flat_arrays(models, block_part, tips, clv, scaler,
                           sched.e_real, jnp.asarray(sched.meta),
                           jnp.asarray(sched.l_code),
                           jnp.asarray(sched.r_code), sched.zl, sched.zr,
                           scale_exp, precision, interpret)


def run_flat_arrays(models, block_part, tips, clv, scaler, E: int,
                    meta, l_code, r_code, zl, zr, scale_exp: int,
                    precision=None, interpret: bool = False):
    """Traceable form: schedule as arrays (meta is the scalar-prefetch
    operand; E is static)."""
    if precision is None:
        # Explicit HIGH passes through and fails in Mosaic lowering —
        # see pallas_newview.run_chunks_pallas; the engine maps HIGH to
        # HIGHEST for the Pallas tiers (engine.py `pallas_precision`).
        precision = jax.lax.Precision.HIGHEST
    rows, B, L, R, K = clv.shape
    RK = R * K
    C = tips.table.shape[0]
    minlik = float(np.asarray(2.0, np.float64) ** (-scale_exp))
    two_e = float(np.asarray(2.0, np.float64) ** scale_exp)

    # Every P matrix of the traversal in one batched einsum, expanded to
    # block-diagonal form in XLA and streamed per entry: [E, 2, B, RK, RK].
    eyeR = jnp.eye(R, dtype=clv.dtype)

    def blockdiag(z):
        p = kernels.p_matrices_wave(models, jnp.asarray(z, clv.dtype))
        pb = jnp.einsum("wmrak,rs->wmrksa", p, eyeR)
        return pb.reshape(pb.shape[0], -1, RK, RK)[:, block_part]

    pb_all = jnp.stack([blockdiag(zl), blockdiag(zr)], axis=1)

    codes = jnp.stack([tips.codes[l_code].astype(jnp.int32),
                       tips.codes[r_code].astype(jnp.int32)],
                      axis=1)                               # [E, 2, B, L]

    # tab2[c, (r,k)] = table[c, k]: the rate-tiled tip indicator, so a
    # tip expands with ONE dot.  Tiled in-graph so the whole function
    # is traceable.
    tab2 = jnp.tile(tips.table.astype(jnp.float32), (1, R))

    clvf = clv.reshape(rows, B, L, RK)
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(E,),
        in_specs=[
            any_spec,                                       # clv
            any_spec,                                       # scaler
            pl.BlockSpec((1, 2, B, RK, RK),
                         lambda e, *_: (e, 0, 0, 0, 0)),
            pl.BlockSpec((1, 2, B, L), lambda e, *_: (e, 0, 0, 0)),
            pl.BlockSpec((C, RK), lambda e, *_: (0, 0)),    # tab2
        ],
        out_specs=[any_spec, any_spec],
        scratch_shapes=[
            pltpu.VMEM((B, L, RK), clv.dtype),              # xl
            pltpu.VMEM((B, L, RK), clv.dtype),              # xr
            pltpu.VMEM((B, L), jnp.int32),                  # scl
            pltpu.VMEM((B, L), jnp.int32),                  # scr
            pltpu.VMEM((B, L, RK), clv.dtype),              # v slot
            pltpu.VMEM((B, L), jnp.int32),                  # sc slot
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(
        _kernel, E=E, C=C, minlik=minlik, two_e=two_e,
        precision=precision)
    clvf, scaler = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(clvf.shape, clvf.dtype),
                   jax.ShapeDtypeStruct(scaler.shape, scaler.dtype)],
        # inputs: 0 meta, 1 clv, 2 scaler, 3 pb_all, 4 codes, 5 tab2
        input_output_aliases={1: 0, 2: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(meta, clvf, scaler, pb_all, codes, tab2)
    return clvf.reshape(rows, B, L, R, K), scaler
