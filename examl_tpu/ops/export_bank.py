"""AOT-exported program bank: zero-compile restart and cold start.

Every recovery path the resilience stack earned — supervisor retry,
gang/fleet rank respawn, autoscaled replicas — still pays the full
bank/warm phase (tens of seconds to minutes of compilation) before its
first dispatch, so MTTR is dominated by recompilation rather than by
the failure itself.  The universal interpreter made the program family
CLOSED and tiny (ROADMAP §5/§9), which is exactly the precondition for
serializing it: this module persists each compiled executable next to
the persistent XLA cache so a cold or restarted process DESERIALIZES
programs instead of compiling them, in the compile-once-ship-everywhere
mold of "Automatic Full Compilation ... to Cloud TPUs" (PAPERS.md,
1810.09868) — with BEAGLE 4.1's cross-architecture packaging caution
applied as hard version/fingerprint keying rather than hope.

Mechanism
---------
* **Artifact** = one serialized compiled executable per family x
  jit-key bucket: `jax.experimental.serialize_executable` pickles the
  UNLOADED PjRt executable (plus its arg/result pytrees), which —
  unlike a `jax.export` StableHLO module, which must still be XLA-
  compiled at load — reloads with ZERO compile work.  The price is
  version lock-in, so every artifact is stamped with the jax/jaxlib
  versions, the `jax.export` calling-convention version, this bank's
  own ABI ordinal, the backend platform build string, and the PR2
  host-feature fingerprint; any mismatch is a load REJECTION, never a
  deserialization attempt.
* **Bank directory** = `<persistent cache partition>/export_bank/`,
  artifacts staged + fsync'd + atomically renamed (GL007), each
  recorded in the partition's `bank_manifest.json` under `"exports"`
  with a content digest.
* **Load ladder** (per program, at first dispatch of each jit-key
  bucket): exported artifact -> persistent-XLA-cache compile ->
  fresh compile.  EVERY load failure — version/ABI skew, fingerprint
  mismatch, truncated or corrupt artifact, deserialize exception,
  avals drift between the caller and the compiled signature — falls
  through to the next rung with an explicit counter
  (`bank.export.{hits,misses,corrupt,rejected.<reason>}`) and a ledger
  event, and a rejected artifact is QUARANTINED (renamed aside, its
  manifest entry dropped) so it cannot re-fail every restart.  The
  fall-through is a counter-carrying downgrade to the normal bank
  phase, not a distinct failure cause: nothing in this module may
  crash a run.

`EXAML_EXPORT_BANK` = `off` (default) / `on` / `require`.  The bank is
opt-in like the other measured tiers (EXAML_FLEET_UNIBATCH,
EXAML_CLV_DTYPE): serialized executables are pinned to one
jaxlib+platform build, and the per-dispatch signature lookup costs a
few microseconds of host time, so the operator enables it per
deployment (serving fleets, supervised long runs, autoscaled
replicas).  `require` turns any fall-through into a hard error — the
CI cold-start gate's mode, proving the zero-compile path end to end.
The mode is read when a program is CREATED (engine construction), not
per dispatch.

Scope: single-process, default-device engines.  Mesh-sharded and -S
(SEV) program variants keep the in-process compile path (ROADMAP §4:
their executables embed mesh/device state this bank does not attempt
to relocate); `engine.first_calls.inprocess_sharded` keeps counting
that residual exposure.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Callable, Dict, Optional

from examl_tpu import obs

ENV_VAR = "EXAML_EXPORT_BANK"
DIR_NAME = "export_bank"
ARTIFACT_SUFFIX = ".jexe"
QUARANTINE_SUFFIX = ".quarantined"

# Bump when the artifact layout or the wrapper's signature derivation
# changes: an old artifact must REJECT (rejected.abi), not deserialize
# into a wrong calling convention.
EXPORT_ABI = 1

# Process state: the in-memory loaded-executable memo (several engines
# with identical shapes — bench builds many — share one deserialize).
# One run = one record: cli.main resets alongside bank.reset().
_STATE: Dict[str, object] = {"mem": {}}


class ExportBankRequired(RuntimeError):
    """EXAML_EXPORT_BANK=require and a program could not be served from
    an exported artifact — the CI gate for the zero-compile path."""


def reset() -> None:
    """Drop loaded-executable memos (one run = one export-bank record;
    in-process callers invoking the CLI repeatedly must not serve a
    previous run's deserialized executables past an env change)."""
    _STATE["mem"] = {}


def mode() -> str:
    """"off" | "on" | "require" from EXAML_EXPORT_BANK.  Loud on typos
    (matching EXAML_CLV_DTYPE): a silently-misspelled opt-in would run
    every restart cold while the operator believes otherwise."""
    v = (os.environ.get(ENV_VAR) or "").strip().lower()
    if v in ("", "0", "off", "no"):
        return "off"
    if v in ("1", "on", "yes"):
        return "on"
    if v == "require":
        return "require"
    raise ValueError(f"{ENV_VAR}={v!r}: expected off/on/require")


def enabled() -> bool:
    try:
        return mode() != "off"
    except ValueError:
        return False


def bank_dir(create: bool = False) -> Optional[str]:
    """The exported-artifact directory inside the CURRENT persistent
    cache partition (config.persistent_cache_dir), or None when no
    cache is configured — the export bank shares the cache's
    platform+fingerprint scoping, so a host that must not share
    compiled code cannot share artifacts either."""
    from examl_tpu.config import persistent_cache_dir
    cache = persistent_cache_dir()
    if not cache:
        return None
    d = os.path.join(cache, DIR_NAME)
    if create:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return None
    return d if os.path.isdir(d) else (None if not create else d)


def host_meta() -> dict:
    """The version/ABI/fingerprint stamp every artifact carries and
    every load must match."""
    import jax
    import jaxlib

    from examl_tpu import config as _config

    meta = {"abi": EXPORT_ABI, "format": "pjrt-pickle-v1",
            "jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "calling_convention": _calling_convention()}
    try:
        dev = jax.devices()[0]
        meta["platform"] = dev.platform
        meta["platform_version"] = getattr(dev.client,
                                           "platform_version", "?")
    except Exception:                        # noqa: BLE001
        meta["platform"] = meta["platform_version"] = "?"
    meta["fingerprint"] = _config.host_feature_fingerprint() or ""
    return meta


def _calling_convention() -> Optional[int]:
    """jax.export's calling-convention version — recorded so a future
    jax that changes the exported ABI rejects by stamp, not by crash."""
    try:
        from jax import export as _jexport
        for attr in ("maximum_supported_calling_convention_version",
                     "maximum_supported_serialization_version"):
            v = getattr(_jexport, attr, None)
            if v is not None:
                return int(v)
    except Exception:                        # noqa: BLE001
        pass
    return None


def _meta_reject_reason(entry: dict, meta: dict) -> Optional[str]:
    """First mismatching stamp of a manifest entry vs this process, or
    None when the artifact is admissible."""
    if entry.get("abi") != meta["abi"] or \
            entry.get("format") != meta["format"] or \
            entry.get("calling_convention") != meta["calling_convention"]:
        return "abi"
    if entry.get("jax") != meta["jax"] or \
            entry.get("jaxlib") != meta["jaxlib"]:
        return "version"
    if entry.get("platform") != meta["platform"] or \
            entry.get("platform_version") != meta["platform_version"]:
        return "platform"
    if entry.get("fingerprint") != meta["fingerprint"]:
        return "fingerprint"
    return None


# ---------------------------------------------------------------------------
# manifest: the "exports" section of bank_manifest.json


def _manifest_path(d: Optional[str] = None) -> Optional[str]:
    d = d or bank_dir()
    if not d:
        return None
    from examl_tpu.ops.bank import MANIFEST_NAME
    return os.path.join(os.path.dirname(d), MANIFEST_NAME)


def read_exports(d: Optional[str] = None) -> Dict[str, dict]:
    """{sig: artifact entry} from the partition's bank manifest."""
    path = _manifest_path(d)
    if not path:
        return {}
    try:
        with open(path) as f:
            return dict(json.load(f).get("exports") or {})
    except (OSError, ValueError):
        return {}


def _update_exports(mutate: Callable[[Dict[str, dict]], None]) -> None:
    """Read-modify-write the manifest's exports section, staged +
    fsync'd + atomically renamed (GL007): a crash mid-update must never
    publish a torn manifest, since every later restart's load ladder
    reads it.  Other manifest sections (families, chunk_layout) are
    preserved verbatim, and the read-modify-write holds an advisory
    flock: the `--bank` compile workers export their families in
    PARALLEL processes, and an unlocked RMW would silently drop a
    concurrent worker's entries (its artifacts would then re-export on
    the next populate — correct but wasteful)."""
    path = _manifest_path()
    if not path:
        return
    lock_fd = None
    try:
        try:
            import fcntl
            lock_fd = os.open(path + ".lock",
                              os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        except Exception:                    # noqa: BLE001 — advisory
            lock_fd = None
        doc = {}
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        if not isinstance(doc, dict):
            doc = {}
        doc.setdefault("version", 1)
        exports = dict(doc.get("exports") or {})
        mutate(exports)
        doc["exports"] = exports
        doc["updated"] = time.time()
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as exc:
        obs.log(f"EXAML: export bank: manifest update failed ({exc}); "
                "artifacts remain usable from their own stamps on the "
                "next successful write")
    finally:
        if lock_fd is not None:
            try:
                os.close(lock_fd)            # releases the flock
            except OSError:
                pass


def family_coverage(families=None, ntaxa=None) -> Dict[str, int]:
    """{family: artifact count} of admissible exported artifacts — the
    signal `bank.run_bank` uses to SKIP subprocess compile workers for
    families a cold restart will deserialize instead.

    Runs BEFORE the parent touches its backend (the bank's ordering
    contract on exclusive-access accelerators), so the platform build
    string is not yet knowable: admissibility here checks the
    backend-independent stamps (ABI, jax/jaxlib, host fingerprint) and
    scans every cache partition for this host.  A partition whose
    platform later disagrees costs a rejected-artifact fall-through to
    the watchdogged in-process compile — bounded and counted, never
    wrong results.

    `ntaxa` (when the caller can derive it pre-backend, e.g. from the
    byteFile header) filters out artifacts exported from a DIFFERENT
    dataset: artifact loadability is signature-level (avals), so
    name-level coverage from another dataset's artifacts would skip
    compile workers only to miss at warm time.  Same-taxa datasets
    with different pattern widths remain a residual (bounded by the
    watchdogged in-process compile and the hits==0 evidence)."""
    if not enabled():
        return {}
    from examl_tpu.config import host_feature_fingerprint
    from examl_tpu.ops.bank import MANIFEST_NAME

    import jax.version as _jv
    import jaxlib.version as _jlv
    fp = host_feature_fingerprint() or ""
    want = None if families is None else set(families)
    cover: Dict[str, int] = {}
    for mpath in _candidate_manifests(MANIFEST_NAME):
        try:
            with open(mpath) as f:
                exports = json.load(f).get("exports") or {}
        except (OSError, ValueError):
            continue
        for entry in exports.values():
            fam = entry.get("family")
            if not fam or (want is not None and fam not in want):
                continue
            if entry.get("abi") != EXPORT_ABI:
                continue
            if entry.get("jax") != _jv.__version__ or \
                    entry.get("jaxlib") != _jlv.__version__:
                continue
            if entry.get("fingerprint") != fp:
                continue
            if ntaxa is not None and entry.get("ntips") is not None \
                    and entry["ntips"] != ntaxa:
                continue
            cover[fam] = cover.get(fam, 0) + 1
    return cover


def _candidate_manifests(manifest_name: str):
    """Manifest paths to scan pre-backend: the configured partition if
    jax already knows one, else every partition under the cache root
    (the per-entry stamps do the host filtering)."""
    from examl_tpu.config import persistent_cache_dir
    cache = persistent_cache_dir()
    if cache:
        p = os.path.join(cache, manifest_name)
        return [p] if os.path.exists(p) else []
    env = os.environ.get("EXAML_COMPILE_CACHE")
    if env == "0":
        return []
    root = env or os.path.expanduser("~/.cache/examl_tpu/xla")
    out = []
    try:
        for sub in sorted(os.listdir(root)):
            p = os.path.join(root, sub, manifest_name)
            if os.path.exists(p):
                out.append(p)
    except OSError:
        pass
    return out


def artifact_count() -> int:
    return len(read_exports())


def startup_info() -> str:
    """One info-file line for CLI startup: where the bank lives and how
    much of it is admissible right now."""
    from examl_tpu.config import persistent_cache_dir
    if not persistent_cache_dir():
        # Distinct from "bank dir not created yet": the first populate
        # run legitimately has no export_bank/ subdirectory until its
        # first artifact stages one.
        return ("exported program bank: enabled, but no persistent "
                "cache partition is configured — artifacts cannot "
                "persist (set EXAML_COMPILE_CACHE)")
    d = bank_dir(create=True)
    cover = family_coverage()
    return (f"exported program bank: {d} ({artifact_count()} artifacts, "
            f"{len(cover)} admissible families, mode {mode()})")


# ---------------------------------------------------------------------------
# signature: family x jit-key bucket -> stable artifact id


def _never() -> bool:
    return False


def jax_leaves(args) -> list:
    import jax
    return jax.tree_util.tree_leaves(args)


def _leaf_sig(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        # Python scalars trace as weak-typed 0-d avals: the executable
        # is value-independent, so the TYPE is the whole signature.
        return (type(leaf).__name__,)
    return (tuple(shape), str(getattr(leaf, "dtype", "?")),
            bool(getattr(leaf, "weak_type", False)))


def _route_key(args) -> tuple:
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


def signature(static_key: str, rkey: tuple) -> str:
    """Stable hex id of one program: the engine's program-identity
    constants + jit-cache key (`static_key`, already repr'd) and the
    flattened arg avals.  Identical run configs derive identical
    signatures in different processes — that is the whole point."""
    treedef, leafs = rkey
    text = "|".join((static_key, str(treedef), repr(leafs)))
    return hashlib.sha1(text.encode()).hexdigest()[:20]


# ---------------------------------------------------------------------------
# load ladder


def _ledger(status: str, family: str, sig: str, **fields) -> None:
    obs.ledger_event("export", status=status, family=family, sig=sig,
                     **fields)


def _quarantine(entry: dict, family: str, sig: str, reason: str) -> None:
    """Rename a rejected artifact aside and drop its manifest entry so
    it cannot re-fail every restart; the quarantined file stays on disk
    for postmortems."""
    d = bank_dir()
    fname = entry.get("file") if entry else None
    if d and fname:
        path = os.path.join(d, fname)
        try:
            if os.path.exists(path):
                # graftlint: disable=GL007 -- atomicity-only rename of
                # an already-rejected artifact; its content is exactly
                # what we refuse to trust, so durability adds nothing
                os.replace(path, path + QUARANTINE_SUFFIX)
        except OSError:
            pass
    _update_exports(lambda ex: ex.pop(sig, None))
    obs.inc("bank.export.quarantined")
    _ledger("quarantined", family, sig, reason=reason)
    obs.log(f"EXAML: export bank: artifact for family '{family}' "
            f"({sig}) rejected ({reason}) and quarantined; the program "
            "falls back to the persistent-cache/compile rung")


def _reject(reason: str, family: str, sig: str,
            entry: Optional[dict] = None, quarantine: bool = True) -> None:
    obs.inc(f"bank.export.rejected.{reason}")
    _ledger("rejected", family, sig, reason=reason)
    if quarantine and entry is not None:
        _quarantine(entry, family, sig, reason)
    elif entry is not None and reason == "missing":
        # Stale manifest entry pointing at a deleted artifact: nothing
        # to quarantine — just stop advertising it.
        _update_exports(lambda ex: ex.pop(sig, None))


def load(family: str, sig: str):
    """One rung of the ladder: the deserialized executable for `sig`,
    or None after counting exactly why.  Never raises — any failure
    (including an armed `bank.export.load` fault) is a fall-through."""
    mem = _STATE["mem"]
    if sig in mem:
        return mem[sig]
    try:
        with obs.timer("bank.export_load_seconds"):
            loaded = _load_uncached(family, sig)
    except Exception as exc:                 # noqa: BLE001 — incl. faults
        obs.inc("bank.export.rejected.error")
        _ledger("rejected", family, sig, reason="error",
                error=f"{type(exc).__name__}: {exc}"[:200])
        return None
    if loaded is not None:
        mem[sig] = loaded
        # Program observatory (obs/programs.py): a deserialized
        # executable answers cost/memory analysis directly, so a
        # zero-compile cold start (engine.compile_count == 0, the
        # guard never fires) still gets its registry row — source
        # "exported", compile seconds 0 by construction.
        from examl_tpu.obs import programs as _programs
        _programs.record_loaded(family, sig, loaded)
    return loaded


def _load_uncached(family: str, sig: str):
    from examl_tpu.resilience import faults
    faults.fire("bank.export.load")
    d = bank_dir()
    if d is None:
        obs.inc("bank.export.misses")
        return None
    entry = read_exports(d).get(sig)
    if entry is None:
        obs.inc("bank.export.misses")
        _ledger("miss", family, sig)
        return None
    reason = _meta_reject_reason(entry, host_meta())
    if reason is not None:
        _reject(reason, family, sig, entry)
        return None
    path = os.path.join(d, entry.get("file") or "")
    if not entry.get("file") or not os.path.exists(path):
        _reject("missing", family, sig, entry, quarantine=False)
        return None
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        _reject("missing", family, sig, entry, quarantine=False)
        return None
    if hashlib.sha256(blob).hexdigest() != entry.get("digest"):
        # Truncated writes and flipped manifest digests both land here:
        # either way the bytes are not the bytes the stamp promised.
        _reject("digest", family, sig, entry)
        return None
    try:
        from jax.experimental import serialize_executable as _se
        rec = pickle.loads(blob)
        loaded = _se.deserialize_and_load(rec["payload"], rec["in_tree"],
                                          rec["out_tree"])
    except Exception as exc:                 # noqa: BLE001
        obs.inc("bank.export.corrupt")
        _ledger("rejected", family, sig, reason="corrupt",
                error=f"{type(exc).__name__}: {exc}"[:200])
        _quarantine(entry, family, sig, "corrupt")
        return None
    obs.inc("bank.export.hits")
    _ledger("hit", family, sig)
    return loaded


# ---------------------------------------------------------------------------
# export


def export(lowered, family: str, sig: str,
           entry_meta: Optional[dict] = None) -> bool:
    """Serialize one program into the bank: compile the traced lowering
    with the persistent XLA cache BYPASSED, pickle the unloaded
    executable, verify it deserializes, stage + fsync + rename, record
    the manifest entry.  Failures only forfeit the artifact
    (`bank.export.write_errors`); the run already has its compiled
    program.

    The cache bypass is load-bearing, not an optimization miss: an
    XLA:CPU executable that was itself LOADED from the compilation
    cache re-serializes into a blob whose JIT'd symbols are absent
    ("Symbols not found" at deserialize — measured on jaxlib 0.4.36),
    so the artifact must come from a genuinely fresh compile.  That
    one extra compile is paid once per artifact lifetime, in the
    populate run, off every restart's critical path — exactly the
    trade this bank exists to make.  The pre-publish verify makes the
    guarantee local: a blob that cannot deserialize HERE is never
    published to fail on some future cold start."""
    d = bank_dir(create=True)
    if d is None:
        return False
    t0 = time.perf_counter()
    try:
        from examl_tpu.resilience import faults
        faults.fire("bank.export.write")
        import jax
        from jax.experimental import serialize_executable as _se
        # The export compile must be HERMETIC: an executable the
        # persistent-cache machinery has touched — serialized for a
        # cache write, or deserialized from a cache hit — re-serializes
        # into a blob whose JIT'd symbols are gone ("Symbols not found"
        # at deserialize; measured on XLA:CPU, jaxlib 0.4.36).  So for
        # the duration of this one compile the cache is fully torn down
        # (reset_cache drops the dir-pinned singleton — a plain config
        # update is IGNORED by an already-initialized cache) and the
        # no-op compiler option (explicitly its default value: codegen
        # and numerics untouched) busts jax's in-memory compile memo,
        # which would otherwise hand back the guarded call's
        # cache-tainted executable.  The verify below gates
        # publication either way.
        prior_cache = jax.config.jax_compilation_cache_dir
        _cc = None
        try:
            from jax._src import compilation_cache as _cc
        except Exception:                    # noqa: BLE001
            _cc = None

        def _drop_cache_singleton():
            # Guarded separately: a future jax renaming reset_cache
            # must degrade to "export without the teardown" (verify
            # still gates publication), never leave the restore half
            # of the try/finally unreached.
            if _cc is not None:
                try:
                    _cc.reset_cache()
                except Exception:            # noqa: BLE001
                    pass

        try:
            jax.config.update("jax_compilation_cache_dir", None)
            _drop_cache_singleton()
            try:
                compiled = lowered.compile(compiler_options={
                    "xla_embed_ir_in_executable": False})
            except Exception:                # noqa: BLE001 — backends
                # that reject the option (non-CPU compilers) fall back
                # to a plain AOT compile; verify still gates.
                compiled = lowered.compile()
        finally:
            jax.config.update("jax_compilation_cache_dir", prior_cache)
            # Next cache use re-initializes against the restored dir;
            # nothing on disk was touched.
            _drop_cache_singleton()
        payload, in_tree, out_tree = _se.serialize(compiled)
        _se.deserialize_and_load(payload, in_tree, out_tree)  # verify
        blob = pickle.dumps({"payload": payload, "in_tree": in_tree,
                             "out_tree": out_tree},
                            protocol=pickle.HIGHEST_PROTOCOL)
        fname = f"{family}-{sig}{ARTIFACT_SUFFIX}"
        path = os.path.join(d, fname)
        # pid-suffixed stage (like the manifest RMW): two fleet ranks
        # exporting the same signature concurrently must never share a
        # stage file — a truncating reopen would publish a torn blob.
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        entry = dict(host_meta(), family=family, file=fname,
                     digest=hashlib.sha256(blob).hexdigest(),
                     size=len(blob), created=time.time(),
                     **(entry_meta or {}))
        _update_exports(lambda ex: ex.__setitem__(sig, entry))
        obs.inc("bank.export.writes")
        obs.observe("bank.export_write_seconds",
                    time.perf_counter() - t0)
        _ledger("written", family, sig, bytes=len(blob))
        return True
    except Exception as exc:                 # noqa: BLE001 — incl. faults
        obs.inc("bank.export.write_errors")
        _ledger("write_error", family, sig,
                error=f"{type(exc).__name__}: {exc}"[:200])
        obs.log(f"EXAML: export bank: serializing family '{family}' "
                f"failed ({type(exc).__name__}: {exc}); the run keeps "
                "its compiled program, only the artifact is lost")
        return False


# ---------------------------------------------------------------------------
# the dispatch wrapper (the engine's program-creation seams call this)


def wrap(raw_fn, fallback, family: str, static_key,
         exportable: bool = True, entry_meta: Optional[dict] = None):
    """Route a jitted program through the export bank.

    `raw_fn` is the bare `jax.jit` callable (used for `.lower()` at
    export time — tracing only, before any donation), `fallback` the
    watchdog-guarded callable the engine would otherwise install.  Per
    distinct arg signature (= jit-key bucket) the FIRST dispatch
    resolves the ladder: a loadable artifact serves every later call
    with zero compiles and the compile watchdog never fires; a miss
    dispatches the guarded fallback (persistent-XLA-cache rung) and
    then serializes the freshly-compiled program for the next restart.

    Returns `fallback` unchanged when the bank is off or the program is
    ineligible (sharded / SEV / off-default-device engines), so the
    steady-state dispatch path pays nothing it did not opt into."""
    m = mode()                    # read at program creation, loud on typos
    if m == "off" or not exportable:
        return fallback
    skey = repr(static_key)
    routes: Dict[tuple, Callable] = {}

    def _resolve(rkey):
        sig = signature(skey, rkey)
        # Memory admission before deserialization: loading an exported
        # executable mints device buffers, so when the governor denies
        # the family's predicted peak the ladder falls through to the
        # guarded compile rung — whose cache_put seam evicts cold
        # programs first instead of stacking a fresh load on a full
        # device.  (`require` mode outranks the governor: an explicit
        # zero-compile contract must fail loudly, not quietly compile.)
        from examl_tpu.resilience import memgov
        if m != "require" and not memgov.admit_program(
                family, seam="export_bank.load"):
            return fallback
        loaded = load(family, sig)
        if loaded is not None:
            def first_hit(*args):
                try:
                    out = loaded(*args)
                except TypeError as exc:
                    # Avals drift: the artifact's compiled signature no
                    # longer matches what this run dispatches (layout
                    # knob change, schedule drift).  The check fires
                    # before execution, so donated buffers are intact
                    # for the fallback.
                    _reject("avals_drift", family, sig,
                            read_exports().get(sig), quarantine=True)
                    obs.log("EXAML: export bank: avals drift on family "
                            f"'{family}' ({type(exc).__name__}); "
                            "falling back to compile")
                    routes[rkey] = fallback
                    return fallback(*args)
                except Exception as exc:     # noqa: BLE001
                    # Environment errors (device placement, runtime
                    # init): not the artifact's fault — reject without
                    # quarantine so a healthy host keeps it.  Retry via
                    # the compile fallback ONLY if the failure happened
                    # before execution donated any input buffer: a
                    # mid-execution fault leaves donated args deleted,
                    # and re-dispatching them would crash with a
                    # misleading secondary error — that fault is a
                    # genuine device error and must propagate as
                    # itself (matching the engine's own semantics for
                    # post-donation runtime faults).
                    _reject("error", family, sig, quarantine=False)
                    obs.log("EXAML: export bank: loaded program for "
                            f"family '{family}' failed to run "
                            f"({type(exc).__name__}: {exc}); falling "
                            "back to compile")
                    routes[rkey] = fallback
                    if any(getattr(a, "is_deleted", _never)()
                           for a in jax_leaves(args)):
                        raise
                    return fallback(*args)
                routes[rkey] = loaded
                return out
            return first_hit
        if m == "require":
            raise ExportBankRequired(
                f"{ENV_VAR}=require but program family '{family}' "
                f"(signature {signature(skey, rkey)}) has no loadable "
                "exported artifact")
        if bank_dir(create=True) is None:
            return fallback

        def miss_route(*args):
            lowered = None
            try:
                # Trace BEFORE the guarded call: lowering only reads
                # avals, and the fallback donates/consumes the buffers.
                lowered = raw_fn.lower(*args)
            except Exception as exc:         # noqa: BLE001
                obs.inc("bank.export.write_errors")
                obs.log("EXAML: export bank: lowering family "
                        f"'{family}' for export failed "
                        f"({type(exc).__name__}: {exc})")
            out = fallback(*args)
            if lowered is not None:
                export(lowered, family, sig, entry_meta=entry_meta)
            routes[rkey] = fallback
            return out
        return miss_route

    def dispatch(*args):
        rkey = _route_key(args)
        route = routes.get(rkey)
        if route is None:
            route = routes[rkey] = _resolve(rkey)
        return route(*args)

    return dispatch
