from examl_tpu.ops.engine import LikelihoodEngine, DeviceModels  # noqa: F401
