"""Topology-as-data universal interpreter: ONE compiled executable for
every topology.

The bounded chunk tier (ops/fastpath.py) already packs a traversal's
entire schedule into seven array leaves — segment descriptors, chunk
windows, kinds, child index/code arrays, per-chunk zl/zr — but its
compiled program is still SPECIALIZED: the segment profile is the jit
key, each segment's window is sliced statically inside the trace, and a
topology whose bucketed profile was never seen pays a first-call
compile.  A long-lived `--serve` process therefore keeps meeting novel
profiles forever, and the bank can only pre-compile what it can
enumerate (ROADMAP items 4-5).

This module inverts the design, the way BEAGLE's operation-queue API
does on GPUs (PAPERS.md, Ayres et al. 4.1: operations are CALL-TIME
lists, not compile-time programs) expressed XLA-natively per the
Julia->TPU lesson (PAPERS.md, 1810.09868: keep control flow structured,
feed the schedule in as data):

* The chunk sequence becomes a runtime DESCRIPTOR TABLE.  Every chunk
  is split into UNIFORM steps of the ladder floor width (`MIN_WIDTH`;
  valid because chunk entries are independent and all ladder widths
  are floor multiples — per-entry arithmetic is untouched), so the
  class alphabet collapses to the three tip cases alone and every
  step's tensor shapes are identical.
* One `lax.scan` walks the table; its body `lax.switch`es over the
  3-kind alphabet.  A branch only COMPUTES its step's rows — the
  identical `fastpath.chunk_applier` arithmetic the specialized
  program unrolls (the shared `values` half of the kernel) — and the
  arena `dynamic_update_slice` happens OUTSIDE the conditional.  This
  split is load-bearing: XLA copies carry buffers that are written
  inside cond branches (measured 7.6x on CPU), while read-only
  operands flow through for free.
* Table length and packed-slot count bucket through `utils.bucket_len`
  (<=25% padding); padding steps REPLAY the final step — PR5's
  replay-step discipline: a step reads only rows written strictly
  before it and rewrites its own rows with identical values, so replay
  is idempotent and no scratch arithmetic leaks into real rows.

The jit key collapses from the per-topology segment profile to
`("universal", (floor, cap), table_bucket, slot_bucket, with_eval)` — a
tiny CLOSED family — so any topology of any size runs through an
already-banked executable with zero first-call compiles.  That closure
is also what makes the family SERIALIZABLE: the exported program bank
(ops/export_bank.py) persists each bucket pair's compiled executable
next to the XLA cache, so a restarted or autoscaled process
deserializes the interpreter instead of compiling it — the
zero-compile property extends from "within one process" to "across
process lifetimes".  Dispatch
reuses any already-compiled bucket pair that fits (`pick_pads`,
mirroring the fleet tier's smallest-compiled-pow2 discipline), so a
serving process never compiles again after warmup.  The price is
sequential depth: the interpreter runs O(packed slots / floor) scan
steps instead of the specialized program's O(log n) fused ops — the
zero-compile tier for serving novel topologies, not a replacement for
the chunk tier on a hot profile.

The interpreter always executes the plain-XLA chunk kernel: it is the
PORTABILITY tier — the escape ladder runs pallas -> chunk ->
universal -> scan — and a Mosaic kernel inside every switch branch
would multiply compile surface for the tier whose whole point is
compiling once.  Opt out with `EXAML_UNIVERSAL=0`; force with
`EXAML_UNIVERSAL=force` (what the supervisor's degradation ladder pins
between the chunk and scan rungs).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Set, Tuple

import numpy as np

from examl_tpu.utils import bucket_len


class UniversalIneligible(ValueError):
    """This layout cannot run through the interpreter (a chunk width
    off the ladder — the legacy unbounded layout — or an empty
    traversal).  Callers fall back to the specialized program."""


def width_ladder(mw: int, cap: int) -> Tuple[int, ...]:
    """The bucketed-width ladder {mw, 2mw, ..., cap} (fastpath's
    `_bucket_w` floor/cap ladder)."""
    widths = []
    w = mw
    while w < cap:
        widths.append(w)
        w *= 2
    widths.append(cap)
    return tuple(widths)


def alphabet_key() -> Tuple[int, int]:
    """(min_width, cap) — the layout knobs that determine step width
    and table splitting; rides in every universal jit key so env-tuned
    EXAML_CHUNK_MIN_WIDTH/CAP runs can never alias programs."""
    from examl_tpu.ops import fastpath
    mw, cap, _tail = fastpath._knobs()
    return (mw, cap)


def alphabet(knobs: Optional[Tuple[int, int]] = None
             ) -> Tuple[Tuple[int, int], ...]:
    """The closed class alphabet: the three tip cases, all at the
    UNIFORM step width (the ladder floor).  Uniform width is what lets
    every switch branch return identically-shaped small results so the
    arena write can live outside the conditional."""
    if knobs is None:
        knobs = alphabet_key()
    mw, _cap = knobs
    return tuple((k, mw) for k in (0, 1, 2))


class UniversalTable(NamedTuple):
    """Host-side descriptor table of one layout, in execution order:
    every chunk split into uniform floor-width steps (scan-group steps
    and their replay padding already expanded by the packed layout,
    `fastpath._pack_structure`)."""
    n_chunks: int           # step count (table rows before padding)
    slots: int              # real packed slot count P
    cls: np.ndarray         # [n_chunks] int32 class id into alphabet()
    slot: np.ndarray        # [n_chunks] int32 packed-slot offset
    base: np.ndarray        # [n_chunks] int32 first arena row written


def build_table(profile, base: np.ndarray,
                knobs: Optional[Tuple[int, int]] = None) -> UniversalTable:
    """Flatten a bounded segment profile into the runtime descriptor
    table, splitting every chunk into floor-width steps.  `base` is the
    layout's per-chunk arena-base array (host).  Splitting is exact:
    ladder widths are all multiples of the floor, chunk entries are
    independent, and every per-entry op in the kernel batches over the
    width axis, so sub-steps compute bit-identical rows.  Raises
    UniversalIneligible for off-ladder widths (legacy unbounded layout)
    or an empty profile."""
    from examl_tpu.ops import fastpath

    if knobs is None:
        knobs = alphabet_key()
    mw, cap = knobs
    kinds_w = list(fastpath.iter_profile_chunks(profile))
    if not kinds_w:
        raise UniversalIneligible("empty traversal")
    ks = np.fromiter((k for k, _ in kinds_w), np.int64, len(kinds_w))
    ws = np.fromiter((w for _, w in kinds_w), np.int64, len(kinds_w))
    offladder = ((ws % mw) != 0) | (ws > cap) | (ws < 1)
    if offladder.any():
        bad = ws[offladder]
        raise UniversalIneligible(
            f"chunk widths {sorted(set(int(b) for b in bad))} off the "
            f"ladder (floor {mw}, cap {cap}) — unbounded layout?")
    base = np.asarray(base, np.int64)
    if base.shape[0] != len(kinds_w):
        raise UniversalIneligible(
            f"base array length {base.shape[0]} != chunk count "
            f"{len(kinds_w)}")
    reps = ws // mw
    slot0 = np.concatenate([[0], np.cumsum(ws)[:-1]])
    n = int(reps.sum())
    # Sub-step index j within its chunk: 0..reps-1 per chunk.
    j = (np.arange(n, dtype=np.int64)
         - np.repeat(np.concatenate([[0], np.cumsum(reps)[:-1]]), reps))
    return UniversalTable(
        n_chunks=n, slots=int(ws.sum()),
        cls=np.repeat(ks, reps).astype(np.int32),
        slot=(np.repeat(slot0, reps) + j * mw).astype(np.int32),
        base=(np.repeat(base, reps) + j * mw).astype(np.int32))


def pad_table(table: UniversalTable, npad: int):
    """Descriptor arrays padded to `npad` rows by REPLAYING the final
    step (PR5 discipline: idempotent — the final step re-reads rows
    written strictly before it and rewrites its own rows with identical
    values), so a larger already-compiled bucket can serve a smaller
    table with no scratch arithmetic touching real rows."""
    assert npad >= table.n_chunks
    pad = npad - table.n_chunks
    if pad == 0:
        return table.cls, table.slot, table.base
    return (np.concatenate([table.cls, np.full(pad, table.cls[-1])]),
            np.concatenate([table.slot, np.full(pad, table.slot[-1])]),
            np.concatenate([table.base, np.full(pad, table.base[-1])]))


def pick_pads(minted: Set[Tuple[int, int]], n_chunks: int,
              slots: int) -> Tuple[int, int]:
    """(table_bucket, slot_bucket) for a dispatch: the least-waste
    ALREADY-COMPILED bucket pair that fits — replay padding is
    idempotent, so any larger bucket serves correctly — else the
    natural `bucket_len` pair.  Reuse is capped at 2x each axis:
    replay steps cost real chunk applies, and a 4x-padded dispatch
    would trade the compile we avoided for permanent arithmetic.
    Callers add the returned pair to `minted` (mirrors the fleet
    tier's `_pick_jpad` smallest-compiled-pow2 discipline)."""
    fits = [(tn, tp) for tn, tp in minted
            if n_chunks <= tn <= 2 * n_chunks and slots <= tp <= 2 * slots]
    if fits:
        return min(fits, key=lambda t: (t[0] + t[1], t))
    return bucket_len(n_chunks), bucket_len(slots)


def pad_slots(arr: np.ndarray, ppad: int, fill=0) -> np.ndarray:
    """A packed per-slot host array padded to the slot bucket.  Padding
    slots are never read: descriptor padding replays the final REAL
    step, whose window lies inside the real slot range."""
    P = arr.shape[0]
    assert ppad >= P
    if ppad == P:
        return arr
    out = np.full((ppad,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[:P] = arr
    return out


def run_universal(alpha, cls, slot, cbase, lidx, ridx, lcode, rcode,
                  zl, zr, clv, scaler, values, select: bool = False):
    """The interpreter body (traced): one `lax.scan` over the
    descriptor table; each step `lax.switch`es to its tip-case class,
    dynamic-slices the floor-width windows out of the packed arrays at
    the step's slot offset, and COMPUTES the step's rows with the
    shared chunk kernel (`values` — the compute half of
    `fastpath.chunk_applier`).  The arena writes happen here, outside
    the conditional, so the carry is never copied through the switch.
    Program length is O(1) regardless of topology or table length —
    THE property that makes the jit key topology-independent.

    `select=True` replaces the `lax.switch` with `lax.select_n` over
    ALL THREE class branches — a gather-style select of computed
    values, bit-identical to the switch (select_n picks one branch's
    exact results; no arithmetic blending) at ~3x the per-step compute.
    This is the VMAPPED (fleet unibatch) form: under vmap a batched
    switch index degenerates to executing every branch anyway, and the
    explicit select keeps the arena writes outside any conditional
    (the GL001 cond-write hazard cannot re-enter through a batching
    rule) while letting MIXED-PROFILE job batches share one compiled
    program — the tables differ per job, the program does not."""
    import jax
    import jax.numpy as jnp

    from examl_tpu.ops.fastpath import FastChunk

    W = alpha[0][1]
    assert all(w == W for _, w in alpha), "alphabet must be uniform-width"

    def make_branch(kind):
        def branch(clv, scaler, off):
            def win(a):
                return jax.lax.dynamic_slice_in_dim(a, off, W)
            ch = FastChunk(kind, W, jnp.int32(0), win(lidx), win(ridx),
                           win(lcode), win(rcode), win(zl), win(zr))
            return values(clv, scaler, ch)
        return branch

    branches = [make_branch(k) for k, _ in alpha]

    def body(carry, x):
        c, s = carry
        ci, off, b = x
        if select:
            outs = [br(c, s, off) for br in branches]
            v = jax.lax.select_n(ci, *[v for v, _ in outs])
            sc = jax.lax.select_n(ci, *[sc for _, sc in outs])
        else:
            v, sc = jax.lax.switch(ci, branches, c, s, off)
        z0 = jnp.zeros((), b.dtype)
        c = jax.lax.dynamic_update_slice(c, v.astype(c.dtype),
                                         (b, z0, z0, z0, z0))
        s = jax.lax.dynamic_update_slice(s, sc, (b, z0, z0))
        return (c, s), None

    (clv, scaler), _ = jax.lax.scan(body, (clv, scaler),
                                    (cls, slot, cbase))
    return clv, scaler
