"""SEV memory saving (`-S`): block-granular CLV pool with gap sharing.

Reference design (`-S`, SURVEY §5.7): per-node gap bit-vectors, CLVs
allocated only for non-gap sites, and one shared `gapColumn` CLV per node
for all-gap sites (`axml.c:2152-2171`, `newviewGenericSpecial.c:139-160`,
`_GAPPED_SAVE` kernel variants; 70 GB -> 19 GB claim `axml.c:874-876`).

TPU-native re-design: data-dependent per-node CLV lengths are hostile to
XLA's static shapes, so the saving is expressed as INDIRECTION at 128-site
block granularity instead of per-site compaction.  A (node row, block)
cell whose subtree is all-gap in that block is not stored: reads map it to
one shared constant all-ones cell (an all-gap subtree's CLV is exactly 1:
P(z) rows sum to 1, and products of ones stay ones, never rescaled);
writes map it to a scratch cell.  Real cells live in a flat pool
`[S, lane, R, K]` that grows on demand; the host tracks per-node gap
bitsets (AND of the children's, updated with every traversal it builds,
the reference's in-kernel `x3_gap = x1_gap & x2_gap`) and a free list, so
topology changes reallocate only the recomputed nodes' cells.

Zero-weight padding blocks are all-gap for every tip, so SEV also stops
paying for lane padding.  Granularity note: a block with ANY non-gap site
is stored whole — the reference compacts per site, so its ratio is better
on alignments whose gaps do not align to 128-column runs; block
granularity is what keeps every shape static for XLA.

SEV x sharding — WIRED (round 4; `-S` no longer forces single-device,
parallel/launch.py): the pool's cell axis is irregular while the mesh
shards the block axis, and the composition that preserves both is:

1. Partition the block axis over the mesh exactly as the dense path
   does (contiguous ranges of B, `parallel/packing.py`).
2. Give each device ITS OWN pool over ITS block range: gap bitsets are
   per-(node, block), so cell allocation decomposes cleanly by block —
   no cell ever crosses a device boundary by construction.
3. Run the whole engine under `shard_map` over the sites axis: inside
   the mapped program every reference to (pool, slot maps) is the
   device-local shard, the traversal kernel is IDENTICAL to today's
   single-device pooled kernel, and the only cross-device communication
   stays the per-partition lnL/derivative `psum` the dense path already
   does.  Slot maps become per-device [rows, B_local] int32 arrays built
   by the host from the same bitsets, stacked [ndev, rows, B_local].
4. Pool capacity must be per-device-uniform for static shapes: cap =
   max over devices of that device's cell count (pow2-bucketed like
   today); gappy regions are typically spatially clustered, so the
   waste is bounded by one growth bucket.
5. Per-process SELECTIVE loading composes: each process's SevState
   covers only its block window (tip bitsets from the sliced reader,
   `io/bytefile.py`), slot maps assemble globally from the local
   windows (`make_array_from_process_local_data`), and the region
   capacity + dirty flag agree through one tiny host allgather per
   sync — called unconditionally so the collective stays aligned
   across processes.

Implementation map: per-device cell regions + uniform cap in SevState
below; shard_map program construction in
engine._build_sev_mapped_programs; explicit lnL/derivative psums via
the kernels' axis_name; the batched SPR scan maps the same way
(search/batchscan.py scan_program, candidate lnLs psummed); equivalence
tests tests/test_sev.py::test_sev_sharded_*.  The batched THOROUGH arm
maps the same way (batchscan.thorough_program: per-NR-iteration
derivative psums inside the on-device Newton loops, one final lnL
psum).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from examl_tpu.tree.topology import TraversalEntry

ONES_CELL = 0      # shared constant all-ones cell (read target of gap cells)
SCRATCH_CELL = 1   # write target of gap cells; content never read
FIRST_DATA_CELL = 2


class SevState:
    """Host bookkeeping + device arrays for one engine's CLV pool."""

    def __init__(self, tip_codes: np.ndarray, undetermined_code: int,
                 num_rows: int, B: int, lane: int, R: int, K: int, dtype,
                 ndev: int = 1, zeros_pool=None, put_slot=None,
                 global_regions: int | None = None, cap_reduce=None):
        """ndev > 1 activates the sharded layout (SEV x sharding, design
        notes above): the block axis is split into contiguous per-device
        ranges, every cell id is LOCAL to its range's pool region, and
        the device pool is [global_regions * cap, lane, R, K] — under
        shard_map each device sees exactly its [cap, ...] region and the
        local ids index it directly.

        Multi-host selective loading: `tip_codes`/`B` cover only THIS
        process's block window, `ndev` counts its LOCAL regions, and
        `global_regions` the whole mesh; `cap_reduce(local_max_cells,
        dirty)` returns the process-agreed (capacity target, any-dirty)
        pair (an allgather — called on EVERY sync so the collective
        stays aligned across processes, and a slot re-upload entered by
        one process is entered by all).  zeros_pool(shape, dtype)
        allocates the pool (the engine passes a born-sharded allocator —
        the pool must never stage whole on one device) and put_slot
        places slot maps (global assembly from the local window);
        defaults are plain jnp for the single-device case."""
        if B % max(ndev, 1):
            raise ValueError(f"SEV x sharding needs the block count ({B}) "
                             f"divisible by its region count ({ndev}); "
                             "the packing planner pads blocks to the mesh")
        self.B, self.lane, self.R, self.K = B, lane, R, K
        self.dtype = dtype
        self.ndev = max(ndev, 1)
        self.global_regions = global_regions or self.ndev
        self.B_local = B // self.ndev
        self._cap_reduce = cap_reduce or (lambda x, d: (x, d))
        self._zeros_pool = zeros_pool or (
            lambda shape, dt: jnp.zeros(shape, dtype=dt))
        self._put_slot = put_slot or jnp.asarray
        ntips = tip_codes.shape[0]
        codes = tip_codes.reshape(ntips, B, lane)
        self.tip_gap = (codes == undetermined_code).all(axis=2)  # [ntips, B]
        self.ntips = ntips
        self.num_rows = num_rows
        self.node_gap = np.ones((num_rows, B), dtype=bool)
        self.cell_of = np.full((num_rows, B), -1, dtype=np.int64)
        self.free: List[List[int]] = [[] for _ in range(self.ndev)]
        self.next_cell: List[int] = [FIRST_DATA_CELL] * self.ndev
        self.cap = 0                          # per-device region capacity
        self.pool = None         # device [global_regions*cap, lane, R, K]
        self.slot_read = None                 # device [num_rows, B] int32
        self.slot_write = None
        self.dirty = True

    # -- gap bookkeeping ----------------------------------------------------

    def _gap_of(self, num: int) -> np.ndarray:
        if num <= self.ntips:
            return self.tip_gap[num - 1]
        return self.node_gap[num - self.ntips - 1]

    def update_for_entries(self, entries: List[TraversalEntry]) -> None:
        """Refresh gap bits + cell allocations for nodes about to be
        recomputed (post-order, so children update before parents)."""
        Bl = self.B_local
        for e in entries:
            row = e.parent - self.ntips - 1
            g = self._gap_of(e.left) & self._gap_of(e.right)
            need = ~g
            have = self.cell_of[row] >= 0
            if not np.array_equal(need, have):
                self.dirty = True
                # Allocation is per device range: a cell id is local to
                # the range that owns its block, so drop/grow masks are
                # processed range by range.
                for d in range(self.ndev):
                    sl = slice(d * Bl, (d + 1) * Bl)
                    co = self.cell_of[row, sl]
                    drop = have[sl] & ~need[sl]
                    if drop.any():
                        self.free[d].extend(int(c) for c in co[drop])
                        co[drop] = -1
                    grow = need[sl] & ~have[sl]
                    n = int(grow.sum())
                    if n:
                        co[grow] = self._alloc(n, d)
            self.node_gap[row] = g

    def _alloc(self, n: int, d: int = 0) -> np.ndarray:
        out = np.empty(n, dtype=np.int64)
        free = self.free[d]
        take = min(n, len(free))
        for i in range(take):
            out[i] = free.pop()
        for i in range(take, n):
            out[i] = self.next_cell[d]
            self.next_cell[d] += 1
        return out

    # -- batched-scan scratch region ----------------------------------------

    def ensure_scan_rows(self, n: int) -> int:
        """Carve a DENSE scratch scan region of >= n rows out of the pool
        (pow2 bucketed like the dense arena's region): scan rows get a
        real cell for EVERY block — uppass CLVs mix the whole far side of
        the tree, so they have no gap structure to exploit — appended
        below the node rows in the slot maps.  This is what lets the
        one-dispatch SPR scan run under -S (the reference's `-S` runs its
        normal SPR loop on gapped kernels; here the batched scan IS the
        SPR loop, so the pool carves it a region).  Returns the region's
        base row index."""
        if not hasattr(self, "scan_base"):
            self.scan_base = self.num_rows
            self.scan_cap = 0
        if n > self.scan_cap:
            from examl_tpu.utils import next_pow2
            grow = next_pow2(n) - self.scan_cap
            self.node_gap = np.concatenate(
                [self.node_gap, np.zeros((grow, self.B), dtype=bool)])
            new_cells = np.empty((grow, self.B), dtype=np.int64)
            Bl = self.B_local
            for d in range(self.ndev):
                new_cells[:, d * Bl:(d + 1) * Bl] = self._alloc(
                    grow * Bl, d).reshape(grow, Bl)
            self.cell_of = np.concatenate([self.cell_of, new_cells])
            self.num_rows += grow
            self.scan_cap += grow
            self.dirty = True
        self.sync()
        return self.scan_base

    # -- device sync ---------------------------------------------------------

    def sync(self) -> None:
        """Grow the pool if needed and re-upload slot maps if changed.

        The per-device region capacity is uniform (max over devices,
        static shapes for shard_map); growth copies each region into its
        slice of the new pool, so local cell ids stay valid."""
        # cap_reduce runs UNCONDITIONALLY: in a multi-process job it is
        # a collective (allgather), so every process must reach it on
        # every sync regardless of local growth pressure.  The dirty
        # flag reduces too (any-process-dirty -> all re-upload): slot
        # assembly from local windows must be entered by every process.
        max_next, dirty = self._cap_reduce(max(self.next_cell),
                                           self.dirty)
        self.dirty = bool(dirty)
        max_next = int(max_next)
        if self.pool is None or max_next > self.cap:
            new_cap = max(64, int(max_next * 1.3) + 8)
            G = self.global_regions
            new_pool = self._zeros_pool(
                (G * new_cap, self.lane, self.R, self.K), self.dtype)
            bases = np.arange(G, dtype=np.int64) * new_cap
            new_pool = new_pool.at[bases + ONES_CELL].set(1.0)
            if self.pool is not None:
                # one region-preserving copy (a per-region loop would
                # materialize the full new pool G times)
                new_pool = new_pool.reshape(
                    G, new_cap, self.lane, self.R, self.K
                ).at[:, :self.cap].set(self.pool.reshape(
                    G, self.cap, self.lane, self.R, self.K)
                ).reshape(G * new_cap, self.lane, self.R, self.K)
            self.pool = new_pool
            self.cap = new_cap
        if self.dirty:
            self.slot_read = self._put_slot(
                np.where(self.cell_of >= 0, self.cell_of,
                         ONES_CELL).astype(np.int32))
            self.slot_write = self._put_slot(
                np.where(self.cell_of >= 0, self.cell_of,
                         SCRATCH_CELL).astype(np.int32))
            self.dirty = False

    # -- reporting ------------------------------------------------------------

    def stats(self) -> dict:
        allocated = (sum(self.next_cell)
                     - self.ndev * FIRST_DATA_CELL
                     - sum(len(f) for f in self.free))
        dense = self.num_rows * self.B
        return {
            "allocated_cells": int(allocated),
            "dense_cells": int(dense),
            "cell_bytes": int(self.lane * self.R * self.K
                              * jnp.dtype(self.dtype).itemsize),
            "saving_ratio": 1.0 - allocated / max(dense, 1),
        }
