"""SEV memory saving (`-S`): block-granular CLV pool with gap sharing.

Reference design (`-S`, SURVEY §5.7): per-node gap bit-vectors, CLVs
allocated only for non-gap sites, and one shared `gapColumn` CLV per node
for all-gap sites (`axml.c:2152-2171`, `newviewGenericSpecial.c:139-160`,
`_GAPPED_SAVE` kernel variants; 70 GB -> 19 GB claim `axml.c:874-876`).

TPU-native re-design: data-dependent per-node CLV lengths are hostile to
XLA's static shapes, so the saving is expressed as INDIRECTION at 128-site
block granularity instead of per-site compaction.  A (node row, block)
cell whose subtree is all-gap in that block is not stored: reads map it to
one shared constant all-ones cell (an all-gap subtree's CLV is exactly 1:
P(z) rows sum to 1, and products of ones stay ones, never rescaled);
writes map it to a scratch cell.  Real cells live in a flat pool
`[S, lane, R, K]` that grows on demand; the host tracks per-node gap
bitsets (AND of the children's, updated with every traversal it builds,
the reference's in-kernel `x3_gap = x1_gap & x2_gap`) and a free list, so
topology changes reallocate only the recomputed nodes' cells.

Zero-weight padding blocks are all-gap for every tip, so SEV also stops
paying for lane padding.  Granularity note: a block with ANY non-gap site
is stored whole — the reference compacts per site, so its ratio is better
on alignments whose gaps do not align to 128-column runs; block
granularity is what keeps every shape static for XLA.

SEV x sharding — design (not yet wired):
The obstacle is ONLY that the pool's cell axis is irregular while the
mesh shards the block axis.  The composition that preserves both:

1. Partition the block axis over the mesh exactly as the dense path
   does (contiguous ranges of B, `parallel/packing.py`).
2. Give each device ITS OWN pool over ITS block range: gap bitsets are
   per-(node, block), so cell allocation decomposes cleanly by block —
   no cell ever crosses a device boundary by construction.
3. Run the whole engine under `shard_map` over the sites axis: inside
   the mapped program every reference to (pool, slot maps) is the
   device-local shard, the traversal kernel is IDENTICAL to today's
   single-device pooled kernel, and the only cross-device communication
   stays the per-partition lnL/derivative `psum` the dense path already
   does.  Slot maps become per-device [rows, B_local] int32 arrays built
   by the host from the same bitsets, stacked [ndev, rows, B_local].
4. Pool capacity must be per-device-uniform for static shapes: cap =
   max over devices of that device's cell count (pow2-bucketed like
   today); gappy regions are typically spatially clustered, so the
   waste is bounded by one growth bucket.
5. Multi-host selective loading composes for free: gap bitsets derive
   from tip codes, which the sliced reader already delivers per block
   range (`io/bytefile.py`).

Cost estimate: the engine change is mechanical (today's `_state()`
tuple moves inside `shard_map`); the host change is indexing bitsets by
block range.  Deferred because `-S` exists to save MEMORY, and the
first-order memory win at scale is per-process selective loading +
sharded dense arenas, which already landed this round.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from examl_tpu.tree.topology import TraversalEntry

ONES_CELL = 0      # shared constant all-ones cell (read target of gap cells)
SCRATCH_CELL = 1   # write target of gap cells; content never read
FIRST_DATA_CELL = 2


class SevState:
    """Host bookkeeping + device arrays for one engine's CLV pool."""

    def __init__(self, tip_codes: np.ndarray, undetermined_code: int,
                 num_rows: int, B: int, lane: int, R: int, K: int, dtype):
        self.B, self.lane, self.R, self.K = B, lane, R, K
        self.dtype = dtype
        ntips = tip_codes.shape[0]
        codes = tip_codes.reshape(ntips, B, lane)
        self.tip_gap = (codes == undetermined_code).all(axis=2)  # [ntips, B]
        self.ntips = ntips
        self.num_rows = num_rows
        self.node_gap = np.ones((num_rows, B), dtype=bool)
        self.cell_of = np.full((num_rows, B), -1, dtype=np.int64)
        self.free: List[int] = []
        self.next_cell = FIRST_DATA_CELL
        self.cap = 0
        self.pool = None                      # device [S, lane, R, K]
        self.slot_read = None                 # device [num_rows, B] int32
        self.slot_write = None
        self.dirty = True

    # -- gap bookkeeping ----------------------------------------------------

    def _gap_of(self, num: int) -> np.ndarray:
        if num <= self.ntips:
            return self.tip_gap[num - 1]
        return self.node_gap[num - self.ntips - 1]

    def update_for_entries(self, entries: List[TraversalEntry]) -> None:
        """Refresh gap bits + cell allocations for nodes about to be
        recomputed (post-order, so children update before parents)."""
        for e in entries:
            row = e.parent - self.ntips - 1
            g = self._gap_of(e.left) & self._gap_of(e.right)
            need = ~g
            have = self.cell_of[row] >= 0
            if not np.array_equal(need, have):
                self.dirty = True
                drop = have & ~need
                if drop.any():
                    self.free.extend(int(c) for c in self.cell_of[row][drop])
                    self.cell_of[row][drop] = -1
                grow = need & ~have
                n = int(grow.sum())
                if n:
                    self.cell_of[row][grow] = self._alloc(n)
            self.node_gap[row] = g

    def _alloc(self, n: int) -> np.ndarray:
        out = np.empty(n, dtype=np.int64)
        take = min(n, len(self.free))
        for i in range(take):
            out[i] = self.free.pop()
        for i in range(take, n):
            out[i] = self.next_cell
            self.next_cell += 1
        return out

    # -- batched-scan scratch region ----------------------------------------

    def ensure_scan_rows(self, n: int) -> int:
        """Carve a DENSE scratch scan region of >= n rows out of the pool
        (pow2 bucketed like the dense arena's region): scan rows get a
        real cell for EVERY block — uppass CLVs mix the whole far side of
        the tree, so they have no gap structure to exploit — appended
        below the node rows in the slot maps.  This is what lets the
        one-dispatch SPR scan run under -S (the reference's `-S` runs its
        normal SPR loop on gapped kernels; here the batched scan IS the
        SPR loop, so the pool carves it a region).  Returns the region's
        base row index."""
        if not hasattr(self, "scan_base"):
            self.scan_base = self.num_rows
            self.scan_cap = 0
        if n > self.scan_cap:
            from examl_tpu.utils import next_pow2
            grow = next_pow2(n) - self.scan_cap
            self.node_gap = np.concatenate(
                [self.node_gap, np.zeros((grow, self.B), dtype=bool)])
            new_cells = self._alloc(grow * self.B).reshape(grow, self.B)
            self.cell_of = np.concatenate([self.cell_of, new_cells])
            self.num_rows += grow
            self.scan_cap += grow
            self.dirty = True
        self.sync()
        return self.scan_base

    # -- device sync ---------------------------------------------------------

    def sync(self) -> None:
        """Grow the pool if needed and re-upload slot maps if changed."""
        if self.pool is None or self.next_cell > self.cap:
            new_cap = max(64, int(self.next_cell * 1.3) + 8)
            new_pool = jnp.zeros((new_cap, self.lane, self.R, self.K),
                                 dtype=self.dtype)
            new_pool = new_pool.at[ONES_CELL].set(1.0)
            if self.pool is not None:
                new_pool = new_pool.at[:self.cap].set(self.pool)
            self.pool = new_pool
            self.cap = new_cap
        if self.dirty:
            self.slot_read = jnp.asarray(
                np.where(self.cell_of >= 0, self.cell_of,
                         ONES_CELL).astype(np.int32))
            self.slot_write = jnp.asarray(
                np.where(self.cell_of >= 0, self.cell_of,
                         SCRATCH_CELL).astype(np.int32))
            self.dirty = False

    # -- reporting ------------------------------------------------------------

    def stats(self) -> dict:
        allocated = self.next_cell - FIRST_DATA_CELL - len(self.free)
        dense = self.num_rows * self.B
        return {
            "allocated_cells": int(allocated),
            "dense_cells": int(dense),
            "cell_bytes": int(self.lane * self.R * self.K
                              * jnp.dtype(self.dtype).itemsize),
            "saving_ratio": 1.0 - allocated / max(dense, 1),
        }
