"""LikelihoodEngine: device-resident CLV state + jitted kernel dispatch.

One engine instance manages one state-count bucket (see parallel/packing.py):
the CLV tensor `[rows, blocks, lane, rates, states]`, the per-(row, site)
scaling exponents, and jit-compiled traversal / root-evaluation / derivative
programs.  Traversal programs are compiled per wave-schedule shape [L, W]
(W a capped power of two, L a multiple of 4) so partial traversals
(typically 3-4 entries, reference `newviewGenericSpecial.c:925`) and full
traversals each reuse a handful of compiled variants.

CLV rows are indexed by tree-node number - 1 (tips 1..n hold their constant
tip indicator vectors, inner nodes n+1..2n-2 are recomputed on traversal);
the last row is scratch for padding entries.  This mirrors the reference's
one-CLV-per-inner-node memory scheme (`axml.h:533-629` xVector).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from examl_tpu import obs
from examl_tpu.obs import traffic as _traffic
from examl_tpu.models.gtr import ModelParams
from examl_tpu.ops import kernels
from examl_tpu.ops.kernels import DeviceModels, Traversal
from examl_tpu.parallel.packing import PackedBucket
from examl_tpu.tree.topology import FlatTraversal, TraversalEntry
from examl_tpu.utils import z_slots as _z_slots


def stack_models(models: Sequence[ModelParams],
                 branch_indices: Sequence[int], dtype,
                 psr: bool = False) -> DeviceModels:
    from examl_tpu.models.lg4 import LG4Params

    R = models[0].ncat
    assert all(m.ncat == R for m in models)
    arr = lambda xs: jnp.asarray(np.stack(xs), dtype=dtype)

    def per_cat(m, field_lg4, field):
        """[R, ...] per-category tensor: LG4 models supply one per
        category, plain models tile their single one."""
        if isinstance(m, LG4Params):
            return np.stack(getattr(m, field_lg4))
        return np.broadcast_to(getattr(m, field),
                               (R,) + getattr(m, field).shape)

    def weights_of(m):
        if psr:
            return np.ones(R)
        if isinstance(m, LG4Params):
            return np.asarray(m.rate_weights)
        return np.full(R, 1.0 / R)

    return DeviceModels(
        eign=arr([per_cat(m, "eign_list", "eign") for m in models]),
        ev=arr([per_cat(m, "ev_list", "ev") for m in models]),
        ei=arr([per_cat(m, "ei_list", "ei") for m in models]),
        freqs=arr([per_cat(m, "freqs_list", "freqs") for m in models]),
        gamma_rates=arr([m.gamma_rates for m in models]),
        rate_weights=arr([weights_of(m) for m in models]),
        part_branch=jnp.asarray(np.asarray(branch_indices, dtype=np.int32)),
    )


from examl_tpu.utils import next_pow2 as _next_pow2


def _bucket_len(n: int) -> int:
    """Round a traversal length up to a bucketed size (utils.bucket_len:
    multiples of 4 up to 16, then <=25% geometric buckets).  Keeps the
    number of compiled traversal variants O(log n) while a padding wave
    costs a full W-wide newview, so the waste per call stays bounded."""
    from examl_tpu.utils import bucket_len
    return bucket_len(n)


class LikelihoodEngine:
    _obs_seq = 0                 # gauge-name ordinal (see _register_obs)

    def __init__(self, bucket: PackedBucket, models: Sequence[ModelParams],
                 ntips: int, num_branch_slots: int = 1,
                 branch_indices: Optional[Sequence[int]] = None,
                 dtype=jnp.float64, sharding=None,
                 scale_exp: Optional[int] = None, wave_width: int = 8,
                 psr: bool = False, save_memory: bool = False):
        self.bucket = bucket
        self.ntips = ntips
        self.psr = psr
        self.save_memory = save_memory
        self.dtype = jnp.dtype(dtype)
        self.scale_exp = (scale_exp if scale_exp is not None
                          else kernels.default_scale_exponent(self.dtype))
        self.num_branch_slots = num_branch_slots
        self.wave_width = wave_width
        self.num_parts = bucket.num_parts
        # CLV rows hold INNER nodes only plus one scratch row; tips live as
        # packed uint8 codes with an indicator lookup table, materialized on
        # the fly inside the kernels (the reference's yVector + tipVector
        # scheme, `axml.h:533-629` -- tip CLVs are never stored, which more
        # than halves likelihood-buffer memory).  Row assignment is a HOST
        # map (`row_map`): full traversals relayout rows in wave order so
        # the fast path writes contiguous slices (ops/fastpath.py); partial
        # traversals update rows in place through the map.  The arena keeps
        # `fast_slack` rows of headroom for the fast path's padded writes.
        self.n_inner = max(ntips - 2, 1)
        # EXAML_FAST_TRAVERSAL=0 forces the wave-batched scan tier for
        # full traversals too (escape hatch: the chunk pipeline is the
        # faster program, but the scan program is the one whose compile
        # is proven on every backend; see bench.py stage isolation).
        # Runtime-togglable via `force_scan` (the arena keeps its slack).
        import os as _fos
        self.force_scan = _fos.environ.get("EXAML_FAST_TRAVERSAL",
                                           "") == "0"
        # Universal interpreter tier (ops/universal.py): topology-as-
        # data execution of the SAME bounded chunk layout through one
        # compiled lax.scan/lax.switch program whose jit key is
        # bucket sizes + the (kind, width) alphabet, not the
        # per-topology segment profile.  EXAML_UNIVERSAL=0 opts out
        # (mirroring EXAML_FAST_TRAVERSAL); "force"/"always" pins every
        # eligible full traversal to the interpreter — the supervisor's
        # chunk->universal degradation rung and the equivalence tests'
        # lever.  Default: available, taken when a serving caller sets
        # `route_novel_to_universal` and the specialized program for a
        # profile is not already compiled (zero-recompile serving).
        self._universal_env = _fos.environ.get("EXAML_UNIVERSAL", "")
        self.universal_off = self._universal_env == "0"
        self.universal_force = self._universal_env in ("force", "always")
        self.route_novel_to_universal = False
        self._last_universal = False   # the most recent fast dispatch
                                       # ran the interpreter (tier tag)
        # Slack floor: the bounded chunk layout pads narrow chunks up to
        # the width floor and points the scanned tail's padding
        # sub-chunks at the slack region, so the arena headroom follows
        # the live layout knobs (fastpath.slack_rows; the build asserts
        # max_write fits in any case).
        from examl_tpu.ops import fastpath as _fastpath
        self.fast_slack = (0 if psr or save_memory
                           else _fastpath.slack_rows(ntips))
        self.num_rows = self.n_inner + self.fast_slack + 1
        self.scratch_row = self.num_rows - 1
        self.row_map = np.full(2 * ntips - 1, -1, dtype=np.int64)
        for num in range(ntips + 1, 2 * ntips - 1):
            self.row_map[num] = num - ntips - 1
        # Precision for the fast path's CHILD CLV contractions only.  These
        # sums are all-positive (transition probabilities x likelihoods, no
        # cancellation), so 3-pass bf16 (HIGH) costs 0.016 lnL absolute on
        # testData/140 (1.2e-7 relative, NUMERICS.md) while halving MXU
        # passes vs HIGHEST; P-matrix eigen-recomposition and the root
        # evaluation stay at HIGHEST (cancellation-prone -- the measurement
        # that rejected HIGH globally was dominated by those).  CPU ignores
        # the knob (always true f32/f64).  EXAML_DOT_PRECISION overrides.
        import os as _pos
        # CLV STORAGE dtype (ROOFLINE.md lever 3): the newview kernel is
        # HBM-bandwidth-bound, so storing the arena in bf16 (compute
        # stays f32: gathers upcast after the load, stores downcast
        # before it) halves bytes/update and doubles the throughput
        # ceiling.  Opt-in via EXAML_CLV_DTYPE=bf16 — each CLV cell is
        # rounded once per node level, so the lnL bound must be
        # re-measured per analysis (see NUMERICS.md).  A non-f32 compute
        # dtype (f64 parity runs) ignores the knob: a globally-exported
        # env var must not crash unrelated jobs.
        _clv_env = _pos.environ.get("EXAML_CLV_DTYPE", "")
        if _clv_env in ("bf16", "bfloat16") and self.dtype == jnp.float32:
            self.storage_dtype = jnp.dtype(jnp.bfloat16)
        elif _clv_env in ("", "0", "same", "bf16", "bfloat16"):
            self.storage_dtype = self.dtype
        else:
            raise ValueError(f"EXAML_CLV_DTYPE={_clv_env!r}: expected "
                             "bf16/bfloat16 or unset")
        _prec = _pos.environ.get("EXAML_DOT_PRECISION", "high").upper()
        if _prec not in ("DEFAULT", "HIGH", "HIGHEST"):
            raise ValueError(
                f"EXAML_DOT_PRECISION={_prec!r}: expected one of "
                "default/high/highest")
        self.fast_precision = getattr(jax.lax.Precision, _prec)
        # LRU-bounded: topology churn during a search mints distinct
        # wave profiles without bound; evicting beyond 32 keeps
        # compiled-program memory bounded (recompiling a re-seen profile
        # costs seconds, holding hundreds costs GBs).
        from collections import OrderedDict
        self._fast_jit_cache = OrderedDict()
        self._fast_jit_cache_cap = 32
        # Schedule-STRUCTURE cache (tentpole of the host-path scale
        # work): the immutable half of a fast-path schedule — chunk
        # layout, child index/code arrays, row map — keyed by the
        # traversal's 128-bit topology signature (FlatTraversal.
        # topo_key, a function of topology + root edge only).  The
        # branch-length-only full traversals that dominate model
        # optimization and repeated evaluations hit here and skip the
        # Python schedule rebuild entirely, refreshing only z
        # (fastpath.refresh_z).  Self-validating: an SPR/NNI topology
        # change mints a different signature, so a stale structure can
        # never be served — explicit invalidation (sched_cache_
        # invalidate, called from the search's commit seams) is memory
        # hygiene plus the obs evidence, not a correctness requirement.
        self._sched_cache = OrderedDict()
        self._sched_cache_cap = 8
        # Universal-interpreter descriptor tables (host arrays derived
        # from a FastStructure: class ids, slot offsets, padded index
        # copies), keyed like the structure cache by topology signature
        # — content-keyed, so staleness is impossible and eviction is
        # only memory hygiene.
        self._universal_tables = OrderedDict()
        self._universal_tables_cap = 8
        # Whole-tree gradient plans (ops/gradient.py): the reversed
        # wave packing + edge table, a function of topology + root
        # edge only — keyed like the structure cache by topology
        # signature (content-keyed: staleness impossible, eviction is
        # hygiene).  z values and CLV gather indices refresh per
        # dispatch.
        self._grad_structs = OrderedDict()
        self._grad_structs_cap = 8
        self.sharding = sharding
        self.pallas_interpret = _pos.environ.get(
            "EXAML_PALLAS_INTERPRET", "") == "1"
        # EXAML_PALLAS: 0 = off, 1 = per-chunk kernels (default),
        # whole = one kernel per full traversal (ops/pallas_whole.py).
        self._pallas_env = _pos.environ.get("EXAML_PALLAS", "1")
        self._want_pallas = self._pallas_env != "0"
        self.use_pallas = False        # decided once tensors are placed
        self.pallas_whole = False
        self._pallas_proven = False    # a Pallas program completed here

        lane = bucket.lane
        B = bucket.num_blocks              # GLOBAL (jit program shapes)
        self.B, self.lane = B, lane
        self.R = models[0].ncat
        self.K = bucket.states
        if bucket.is_local:
            if sharding is None:
                raise ValueError("a local (sliced) bucket requires a "
                                 "site-axis sharding")

        if branch_indices is None:
            branch_indices = [0] * self.num_parts
        self._branch_indices = list(branch_indices)
        self.models = stack_models(models, branch_indices, self.dtype,
                                   psr=psr)
        # Per-site rate multipliers (PSR/CAT model); None selects the
        # GAMMA path in every kernel.  Placed like every per-site tensor
        # (block axis sharded) so multi-process jobs hold a global
        # array; under selective loading each process contributes only
        # its block window (reference per-rank CAT state,
        # `optimizeModel.c:2135-2254` — here the categorization itself
        # is global on every process, see optimize/psr.py).
        self.site_rates = (self._put_blocks(
            self._local_block_window(np.ones((B, lane, 1),
                                             dtype=self.dtype)),
            lambda s: s.sites)
            if psr else None)

        Bl = bucket.local_num_blocks
        self.block_part = self._put_blocks(
            bucket.block_part, lambda s: s.blocks)
        self.weights = self._put_blocks(
            np.asarray(bucket.weights.reshape(Bl, lane), dtype=self.dtype),
            lambda s: s.sites)

        self.tips = self._build_tip_state()
        if save_memory:
            from examl_tpu.ops.sev import SevState
            if sharding is not None and sharding.tree_shards > 1:
                # The CLI names this (S, T) combination precisely; this
                # is the engine-level backstop for embedded callers.
                raise ValueError(
                    f"-S cannot compose with a {sharding.site_shards}x"
                    f"{sharding.tree_shards} fabric: the SEV pool "
                    "holds one arena per instance, so per-job arenas "
                    "cannot stack along the tree axis (Sx1 only)")
            self.clv = None
            gdev = sharding.num_devices if sharding is not None else 1
            local_ndev, cap_reduce = gdev, None
            if sharding is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as _P
                from examl_tpu.parallel.sharding import SITE_AXIS as _SA
                _pool_sh = NamedSharding(sharding.mesh, _P(_SA))
                _slot_sh = NamedSharding(sharding.mesh, _P(None, _SA))

                # Born sharded: -S exists because the pool only fits
                # when split across devices, so it must never stage
                # whole on one device (reuses the dense arena's
                # born-sharded allocator).
                zeros_pool = (lambda shape, dt:
                              self._zeros_sharded(shape, dt,
                                                  lambda _: _pool_sh))

                if bucket.is_local:
                    # Multi-host selective loading: this process's
                    # bookkeeping covers its block window only; slot
                    # maps assemble globally from the local windows, and
                    # the region capacity / dirty flag agree via a tiny
                    # host allgather (the reference's per-rank data +
                    # Allreduce'd bookkeeping, byteFile.c:278-382).
                    if B % gdev:
                        raise ValueError(
                            "-S selective loading needs the GLOBAL "
                            f"block count ({B}) divisible by the mesh "
                            f"size ({gdev}); pad the instance with "
                            "block_multiple=num_devices")
                    b_per_dev = B // gdev
                    if (bucket.local_num_blocks % b_per_dev
                            or bucket.block_offset % b_per_dev):
                        raise ValueError(
                            "-S selective loading needs the process "
                            "block window aligned to whole devices "
                            f"(window {bucket.block_offset}+"
                            f"{bucket.local_num_blocks} blocks, "
                            f"{b_per_dev} blocks/device)")
                    local_ndev = bucket.local_num_blocks // b_per_dev

                    def cap_reduce(local_max, dirty):
                        from jax.experimental import multihost_utils
                        pair = multihost_utils.process_allgather(
                            np.asarray([local_max, int(dirty)],
                                       np.int64))
                        return int(pair[:, 0].max()), bool(
                            pair[:, 1].any())

                    def put_slot(arr):
                        return jax.make_array_from_process_local_data(
                            _slot_sh, np.asarray(arr))
                else:
                    put_slot = lambda x: jax.device_put(jnp.asarray(x),
                                                        _slot_sh)
            else:
                zeros_pool = put_slot = None
            self.sev = SevState(bucket.tip_codes, self._undetermined_code(),
                                self.num_rows, bucket.local_num_blocks,
                                lane, self.R, self.K,
                                self.storage_dtype, ndev=local_ndev,
                                zeros_pool=zeros_pool, put_slot=put_slot,
                                global_regions=gdev,
                                cap_reduce=cap_reduce)
        else:
            self.sev = None
            self.clv = self._zeros_sharded(
                (self.num_rows, B, lane, self.R, self.K),
                self.storage_dtype, lambda s: s.clv)
        self.scaler = self._zeros_sharded((self.num_rows, B, lane),
                                          jnp.int32, lambda s: s.scaler)
        # Fused Pallas chunk kernels, gated on where the CLV arena actually
        # LIVES (a jax.default_device(cpu) fallback leaves
        # jax.default_backend() == "tpu", and lowering Mosaic kernels onto
        # CPU devices crashes -- the platform must come from the placed
        # tensor, not the default backend).  The plain-XLA fast path
        # remains for CPU/f64 parity runs.  EXAML_PALLAS=0 disables;
        # EXAML_PALLAS_INTERPRET=1 forces interpreted kernels anywhere
        # (tests).
        if self.clv is not None:
            platform = next(iter(self.clv.devices())).platform
            self.use_pallas = (
                self._want_pallas and self.dtype == jnp.float32
                and self.storage_dtype == self.dtype
                and sharding is None
                and (self.pallas_interpret
                     or platform in ("tpu", "axon")))
            self.pallas_whole = (self.use_pallas
                                 and self._pallas_env == "whole")

        # One jitted traversal program; jax recompiles per padded entry-count
        # shape (powers of two, so only a handful of variants exist).  The
        # CLV/scaler buffers are donated: they are replaced by the outputs,
        # never read again.  site_rates rides along as a traced argument
        # (None on the GAMMA path).
        from examl_tpu.parallel.sharding import SITE_AXIS as _SAX
        self._axis_name = (_SAX if (save_memory and sharding is not None)
                           else None)
        if self._axis_name is not None:
            self._build_sev_mapped_programs()
        else:
            self._jit_traverse = jax.jit(self._traverse_only_impl,
                                         donate_argnums=(0, 1))
            self._jit_evaluate = jax.jit(self._evaluate_impl)
            self._jit_trav_eval = jax.jit(self._trav_eval_impl,
                                          donate_argnums=(0, 1))
            self._jit_newton = jax.jit(self._newton_impl,
                                       donate_argnums=(0, 1))
            self._jit_sumtable = jax.jit(self._sumtable_impl)
            self._jit_derivs = jax.jit(self._derivs_impl)
        self._jit_rate_scan = jax.jit(self._rate_scan_impl)
        # Exported program bank (ops/export_bank.py): program-identity
        # constants that are INVISIBLE in the arg avals — two programs
        # with identical input shapes but different engine constants
        # (scale exponent, dot precision, partition count, chunk-layout
        # knobs) must never share a serialized executable.  Eligibility
        # is single-process default-device engines only: mesh-sharded
        # and -S pooled executables embed placement state the bank does
        # not relocate (ROADMAP §4 keeps counting that residual).
        # The mesh shape is part of the program family (ISSUE 17): a
        # 2x2-fabric executable partitions differently from a 4x1 or an
        # unsharded one even at identical avals, so the (S, T) term
        # keys every shared-cache entry and export-artifact signature.
        mesh_term = (None if self.sharding is None
                     else (self.sharding.site_shards,
                           self.sharding.tree_shards))
        self._export_identity = (
            "prog-v1", self.K, str(self.dtype), str(self.storage_dtype),
            int(self.scale_exp), str(self.fast_precision),
            self.num_parts, self.num_branch_slots, self.ntips,
            bool(self.psr), _fastpath._knobs(), self.wave_width,
            mesh_term)
        self._exportable = (self.sharding is None and not save_memory
                            and self.clv is not None
                            and next(iter(self.clv.devices()))
                            == jax.devices()[0])
        # Core programs get the same timed/watchdogged first-call monitor
        # as the shared-cache fast programs: any program family's compile
        # can wedge the remote tunnel, so every family must be able to
        # name itself from the watchdog and account its compile seconds.
        # The export-bank wrapper sits OUTSIDE the guard: a deserialized
        # executable serves the dispatch without the guard (or any
        # compile) ever firing, a miss falls through to the guarded
        # compile and serializes its result for the next cold start.
        from examl_tpu.ops import export_bank as _export_bank
        for attr, family in (("_jit_traverse", "traverse"),
                             ("_jit_evaluate", "evaluate"),
                             ("_jit_trav_eval", "trav_eval"),
                             ("_jit_newton", "newton"),
                             ("_jit_sumtable", "sumtable"),
                             ("_jit_derivs", "derivs"),
                             ("_jit_rate_scan", "rate_scan")):
            raw = getattr(self, attr)
            guarded = self._guard_first_call(raw, family)
            setattr(self, attr, _export_bank.wrap(
                raw, guarded, family, (family,) + self._export_identity,
                exportable=self._exportable,
                entry_meta={"ntips": self.ntips}))
        # In-engine traffic accounting (obs/traffic.py, the shared
        # roofline model): true (unpadded) pattern count for the bytes
        # model, per-tier windowed achieved-GB/s accumulators fed by
        # the timed blocking dispatch path (per-tier so a scan-tier
        # recompute among chunk-tier evals can never blend into the
        # wrong gauge), and the sequential-op count of the most recent
        # schedule (the launch-floor term of the regime classifier).
        self._patterns_true = int(np.sum(bucket.part_widths))
        self._traffic_win: Dict[str, _traffic.TrafficWindow] = {}
        self._traffic_led: Dict[str, float] = {}
        self._last_dispatch_ops = 1
        self._register_obs()

    # -- observability ------------------------------------------------------

    def _register_obs(self) -> None:
        """Publish this engine's gauges into the process metrics registry
        via a weakref-bound snapshot collector (ISSUE: CLV arena bytes,
        rescale counts) — zero per-dispatch cost, the device is touched
        only when a snapshot is taken."""
        import weakref

        obs.inc("engine.instances")
        # Unique per engine: two same-state engines (bench builds several
        # K=4 instances in one process) must not alias each other's
        # gauges — the ordinal disambiguates.
        seq = LikelihoodEngine._obs_seq
        LikelihoodEngine._obs_seq += 1
        self._obs_tag = f"s{self.K}.e{seq}"
        self._update_arena_gauge()
        if self.sharding is not None:
            # Declared-mesh axis gauges (ISSUE 17): instance-wide (every
            # engine of one run shares the mesh), rendered by
            # tools/run_report.py and tools/top.py next to the fleet's
            # per-slice dispatch counters.
            obs.gauge("engine.mesh_site_shards", self.sharding.site_shards)
            obs.gauge("engine.mesh_tree_shards", self.sharding.tree_shards)
        ref = weakref.ref(self)

        def _collect():
            eng = ref()
            if eng is None:
                return False
            eng._update_arena_gauge()
            try:
                # Total accumulated scaling counts across the arena — the
                # host-visible residue of on-device rescale events.  Only
                # safe single-process: a one-sided reduction over a
                # multi-process global array would hang the job.
                if eng.sharding is None and eng.scaler is not None:
                    obs.gauge("engine.rescale_scale_counts." + eng._obs_tag,
                              int(jnp.sum(eng.scaler)))
            except Exception:
                pass
            return True

        obs.add_collector(_collect)

    def _update_arena_gauge(self) -> None:
        itemsize = np.dtype(self.storage_dtype).itemsize
        if self.clv is not None:
            nbytes = (self.num_rows * self.B * self.lane * self.R
                      * self.K * itemsize)
        elif self.sev is not None and self.sev.pool is not None:
            nbytes = int(np.prod(self.sev.pool.shape)) * itemsize
        else:
            nbytes = 0
        obs.gauge(f"engine.clv_arena_bytes.{self._obs_tag}", nbytes)

    # -- traffic accounting (shared roofline model, obs/traffic.py) ---------

    def _dispatch_tier(self, fast: bool) -> str:
        """Tier label for the traffic gauges: which program family moved
        the bytes (scan = the wave-batched fallback; chunk = XLA fast
        path; pallas / whole = the Mosaic tiers; universal = the
        topology-as-data interpreter)."""
        if not fast:
            return "scan"
        if self._last_universal:
            return "universal"
        if self.pallas_whole:
            return "whole"
        if self.use_pallas:
            return "pallas"
        return "chunk"

    def _tier_for(self, entries, full: bool) -> str:
        """Tier a traversal over `entries` will actually dispatch on
        (full + fast-eligible -> the engine's fast tier; everything
        else — partial, PSR, -S, force_scan — runs the scan tier)."""
        if full and len(entries):
            if isinstance(entries, FlatTraversal):
                fast = self._fast_eligible_flat(entries)
            else:
                fast = self._fast_eligible(entries)
            return self._dispatch_tier(fast)
        return "scan"

    def _traversal_traffic_bytes(self, entries) -> int:
        """Modeled HBM bytes of one traversal over `entries` (a
        TraversalEntry list or a FlatTraversal) — the SAME closed form
        bench.py's byte accounting delegates to."""
        itemsize = np.dtype(self.storage_dtype).itemsize
        if isinstance(entries, FlatTraversal):
            tips = int((np.asarray(entries.left) <= self.ntips).sum()
                       + (np.asarray(entries.right) <= self.ntips).sum())
            return _traffic.bytes_per_traversal_counts(
                entries.n, tips, self._patterns_true, self.R, self.K,
                itemsize)
        return _traffic.bytes_per_traversal(
            entries, self.ntips, self._patterns_true, self.R, self.K,
            itemsize)

    def _scan_plan_traffic_bytes(self, plan) -> int:
        """Modeled HBM bytes of one batched-scan dispatch: the downpass
        orientation fixes (plain TraversalEntry rows) PLUS the uppass
        entries, each writing one scan row and reading its two child
        refs (a (kind, v) ref with a non-slot kind and v <= ntips is a
        tip code row — the same tip test the shared model applies)."""
        up = plan.up_entries
        tips = sum(1 for e in up for kind, v in (e.left, e.right)
                   if kind != "slot" and v <= self.ntips)
        itemsize = np.dtype(self.storage_dtype).itemsize
        return (self._traversal_traffic_bytes(list(plan.down_entries))
                + _traffic.bytes_per_traversal_counts(
                    len(up), tips, self._patterns_true, self.R, self.K,
                    itemsize))

    def _record_traffic(self, nbytes: int, tier: str,
                        wall_s: Optional[float] = None,
                        window: bool = True) -> None:
        """Account one dispatch's modeled bytes; blocking full-traversal
        dispatches (wall_s given) additionally land in the `dispatch`
        latency histogram and — unless `window=False` — feed the
        windowed achieved-GB/s gauge with the regime verdict, so every
        metrics snapshot states WHICH regime its number came from.
        Callers pass window=False when the measured wall contains a
        first-call COMPILE: the histogram must keep it (that p99 is the
        point), but a compile-dominated window would publish a
        near-zero GB/s wrongly tagged bandwidth-meaningful."""
        obs.inc("engine.traffic_bytes", nbytes)
        # Drift gate (obs/programs.py): reconcile this dispatch's
        # analytic bytes with the serving program's XLA bytes-accessed
        # (program.model_drift_pct.<tier>) and learn which source can
        # back the tier's achieved-GB/s row.  The model stays the
        # gauge's denominator either way — the tag makes a chip-round
        # row self-describing, the gate makes model bugs evidence.
        from examl_tpu.obs import programs as _programs
        src = _programs.model_vs_xla(tier, nbytes)
        if wall_s is None:
            return
        # The `dispatch` timer the ISSUE/bench share: wall of one
        # BLOCKING traversal dispatch — its p99 is where a launch-floor
        # stall or surprise recompile shows up in any CLI snapshot.
        obs.observe("dispatch", wall_s)
        if not window:
            return
        win = self._traffic_win.get(tier)
        if win is None:
            win = self._traffic_win[tier] = _traffic.TrafficWindow()
        out = win.add(nbytes, wall_s, self._last_dispatch_ops)
        if out is None:
            return
        gbps, regime, n = out
        # Per-engine tagged like clv_arena_bytes/program_chunks: a
        # DNA+AA instance has two engines whose windows close
        # interleaved — untagged, the snapshot would quote whichever
        # partition's verdict landed last as the run's.
        label = f"{tier}.{self._obs_tag}"
        obs.gauge(f"engine.achieved_gbps.{label}", round(gbps, 3))
        obs.gauge(f"engine.regime_dispatch_bound.{label}",
                  1.0 if regime["regime"] == "dispatch-bound" else 0.0)
        # source: model|xla for the row's bytes figure (1.0 = an XLA
        # bytes-accessed figure exists for the serving program and the
        # drift gauge above reconciles the two).
        obs.gauge(f"engine.traffic_source_xla.{label}",
                  1.0 if src == "xla" else 0.0)
        # Live HBM telemetry rides the traffic-window cadence: one
        # rate-limited device.memory_stats() sample per closed window.
        _programs.sample_memory()
        # Ledger cadence is rate-limited per tier (the gauges above
        # always carry the LATEST verdict): a flight recorder wants
        # periodic bandwidth samples on the timeline, not one line per
        # window when tests shrink the window to a single dispatch.
        now = time.time()
        if now - self._traffic_led.get(tier, 0.0) >= \
                _traffic.LEDGER_EVENT_INTERVAL_S:
            self._traffic_led[tier] = now
            obs.ledger_event("traffic.window", tier=tier,
                             gbps=round(gbps, 3), dispatches=n,
                             source=src, **regime)

    def _sev_spec_vocab(self) -> dict:
        """PartitionSpec vocabulary + shard_map wrapper for the SEV x
        sharding programs — shared by the engine's core programs and the
        batched-scan program (search/batchscan.py)."""
        from jax.sharding import PartitionSpec as P

        from examl_tpu.parallel.sharding import SITE_AXIS as AX

        # jax.shard_map graduated from jax.experimental after 0.4.x.
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:
            from jax.experimental.shard_map import shard_map

        mesh = self.sharding.mesh
        REP = P()

        def wrap(impl, in_specs, out_specs, donate=()):
            mapped = shard_map(impl, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs)
            return jax.jit(mapped, donate_argnums=donate)

        return {
            "rep": REP,
            "pool": P(AX),                        # [ndev*cap, lane, R, K]
            "scaler": P(None, AX),                # [rows, B, lane]
            "aux": (P(None, AX), P(None, AX)),    # slot_read, slot_write
            "blocks": P(AX),                      # block_part [B]
            "sites": P(AX),                       # weights [B, lane]
            # site_rates [B, lane, 1] shards its block axis under PSR;
            # GAMMA passes the literal None leaf, whose spec must be
            # None for the pytrees to match.
            "sr": P(AX) if self.psr else None,
            "tips": kernels.TipState(codes=P(None, AX), table=REP),
            "models": DeviceModels(*(REP,) * len(DeviceModels._fields)),
            "traversal": Traversal(*(REP,) * len(Traversal._fields)),
            "wrap": wrap,
        }

    def _build_sev_mapped_programs(self) -> None:
        """SEV x sharding: the pooled programs run under `jax.shard_map`.

        The pool's cell axis is irregular while the mesh shards blocks,
        so GSPMD cannot prove the pool gathers local; shard_map makes
        the guarantee structural: each device's program sees ITS pool
        region [cap, lane, R, K] (cell ids are region-local,
        ops/sev.py), its block range of the slot maps / tip codes /
        weights, and runs the IDENTICAL pooled kernel — the only
        cross-device traffic is the lnL / derivative psum the kernels
        emit when axis_name is set (the reference's MPI Allreduces,
        `evaluateGenericSpecial.c:968-973`,
        `makenewzGenericSpecial.c:1241-1248`)."""
        v = self._sev_spec_vocab()
        (REP, pool_s, sc_s, aux_s, b_s, bl_s, tips_s, dm_s, tv_s, sr_s,
         wrap) = (v["rep"], v["pool"], v["scaler"], v["aux"], v["blocks"],
                  v["sites"], v["tips"], v["models"], v["traversal"],
                  v["sr"], v["wrap"])

        self._jit_traverse = wrap(
            self._traverse_only_impl,
            (pool_s, sc_s, aux_s, tv_s, dm_s, b_s, tips_s, sr_s),
            (pool_s, sc_s), donate=(0, 1))
        self._jit_evaluate = wrap(
            self._evaluate_impl,
            (pool_s, sc_s, aux_s, REP, REP, REP, dm_s, b_s, bl_s,
             tips_s, sr_s),
            REP)
        self._jit_trav_eval = wrap(
            self._trav_eval_impl,
            (pool_s, sc_s, aux_s, tv_s, REP, REP, REP, dm_s, b_s, bl_s,
             tips_s, sr_s),
            (pool_s, sc_s, REP), donate=(0, 1))
        self._jit_newton = wrap(
            self._newton_impl,
            (pool_s, sc_s, aux_s, tv_s, REP, REP, REP, REP, REP, dm_s,
             b_s, bl_s, tips_s, sr_s),
            (pool_s, sc_s, REP), donate=(0, 1))
        st_s = b_s                          # sumtable [B, lane, R, K]
        self._jit_sumtable = wrap(
            self._sumtable_impl,
            (pool_s, sc_s, aux_s, REP, REP, dm_s, b_s, tips_s),
            st_s)
        self._jit_derivs = wrap(
            self._derivs_impl,
            (st_s, REP, dm_s, b_s, bl_s, sr_s),
            (REP, REP))

    # -- construction helpers ---------------------------------------------

    def _datatype(self):
        from examl_tpu import datatypes
        if self.K == 4:
            return datatypes.DNA
        if self.K == 20:
            return datatypes.AA
        return datatypes.BINARY

    def _undetermined_code(self) -> int:
        return self._datatype().undetermined_code

    def _build_tip_state(self) -> kernels.TipState:
        dt = self._datatype()
        table = self._put_replicated(
            np.asarray(dt.tip_indicator_table(), dtype=self.dtype))
        codes = self.bucket.tip_codes.astype(np.uint8).reshape(
            self.ntips, self.bucket.local_num_blocks, self.lane)
        return kernels.TipState(
            codes=self._put_blocks(codes, lambda s: s.scaler), table=table)

    # -- tensor placement ---------------------------------------------------
    # Single-device: plain jnp arrays.  Sharded, global bucket: device_put
    # of full-width host arrays.  Sharded, LOCAL bucket (multi-host
    # selective loading): this process holds only its contiguous window of
    # the block axis, and the global array is assembled from per-process
    # shards — host memory never sees the full width (the reference's
    # per-rank site slices, `byteFile.c:278-382`).

    def _local_block_window(self, host_global: np.ndarray) -> np.ndarray:
        """This process's contiguous block window of a GLOBAL block-axis
        host array (identity on global buckets): the bridge between
        host-global state (PSR rates, rate-scan grids — identical on
        every process) and `_put_blocks`, which under selective loading
        expects only the local window."""
        if self.bucket.is_local:
            o = self.bucket.block_offset
            return host_global[o:o + self.bucket.local_num_blocks]
        return host_global

    def _put_blocks(self, host: np.ndarray, pick):
        """Place a block-axis host array (full width, or the local window
        of a local bucket) under the sharding member pick selects."""
        if self.sharding is None:
            return jnp.asarray(host)
        sh = pick(self.sharding)
        if self.bucket.is_local:
            return jax.make_array_from_process_local_data(sh, host)
        return jax.device_put(jnp.asarray(host), sh)

    def _put_replicated(self, host: np.ndarray):
        if self.sharding is None:
            return jnp.asarray(host)
        return jax.device_put(jnp.asarray(host), self.sharding.replicated)

    def _zeros_sharded(self, shape, dtype, pick):
        """A zero array born with its final sharding: no single-device
        (or single-process) staging of the full-size buffer — the CLV
        arena is the framework's dominant allocation."""
        if self.sharding is None:
            return jnp.zeros(shape, dtype=dtype)
        npdtype = np.dtype(dtype)

        def shard_zeros(idx):
            shard_shape = tuple(
                len(range(*sl.indices(dim))) for sl, dim in zip(idx, shape))
            return np.zeros(shard_shape, dtype=npdtype)

        return jax.make_array_from_callback(shape, pick(self.sharding),
                                            shard_zeros)

    def set_models(self, models: Sequence[ModelParams]) -> None:
        self.models = stack_models(models, self._branch_indices, self.dtype,
                                   psr=self.psr)

    def invalidate_tips_changed(self) -> None:
        self.tips = self._build_tip_state()

    # -- traversal ---------------------------------------------------------

    def _pack_traversal(self, entries, parent_row, gidx) -> Traversal:
        """Wave-schedule entries into [L, W] with a capped wave width.

        Waves wider than `wave_width` are chunked over several steps (their
        entries are independent, so any split is valid); narrow waves pad to
        W.  This keeps padding waste ~W/2 entries per wave while collapsing
        the sequential step count from len(entries) to ~len(waves).  W is a
        capped power of two and L is size-bucketed (_bucket_len) so only
        O(log n) compiled variants exist.  parent_row/gidx map an entry's
        parent to its arena row and a child id to its gather index (normal
        traversals use the row_map; the batched scan targets its scratch
        region)."""
        from examl_tpu.tree.topology import Tree
        raw = Tree.schedule_waves(entries)
        cap = self.wave_width
        W = min(_next_pow2(max((len(w) for w in raw), default=1)), cap)
        waves = [w[i:i + W] for w in raw for i in range(0, len(w), W)]
        # L rounds up into geometric buckets (<=25% padding waves, O(log n)
        # compiled variants -- see _bucket_len).  An empty traversal stays
        # empty (lax.scan over length 0) so fused traverse+evaluate/newton
        # calls on already-oriented CLVs cost no newview.
        L = _bucket_len(len(waves))
        C = self.num_branch_slots
        parent = np.full((L, W), self.scratch_row, dtype=np.int32)
        left = np.zeros((L, W), dtype=np.int32)
        right = np.zeros((L, W), dtype=np.int32)
        zl = np.ones((L, W, C), dtype=np.float64)
        zr = np.ones((L, W, C), dtype=np.float64)
        for li, wave in enumerate(waves):
            for wi, e in enumerate(wave):
                parent[li, wi] = parent_row(e)
                left[li, wi] = gidx(e.left)
                right[li, wi] = gidx(e.right)
                zl[li, wi, :] = _z_slots(e.zl, C)
                zr[li, wi, :] = _z_slots(e.zr, C)
        return Traversal(parent=jnp.asarray(parent), left=jnp.asarray(left),
                         right=jnp.asarray(right),
                         zl=jnp.asarray(zl, dtype=self.dtype),
                         zr=jnp.asarray(zr, dtype=self.dtype))

    def _traversal_arrays(self, entries: List[TraversalEntry]) -> Traversal:
        with obs.timer("host_schedule"):
            tv = self._pack_traversal(
                entries, lambda e: self.row_map[e.parent], self._gidx)
        # Sequential dependent steps of the scan-tier program = the wave
        # count L: the launch-floor term the regime classifier uses.
        self._last_dispatch_ops = int(tv.parent.shape[0])
        return tv

    def _gidx(self, num: int) -> int:
        """gather_child index of a node: tips by code slot, inner nodes by
        ntips + current arena row (see kernels.gather_child)."""
        if num <= self.ntips:
            return num - 1
        return self.ntips + int(self.row_map[num])

    def set_site_rates(self, rates: np.ndarray) -> None:
        """Install per-site rate multipliers [B, lane] (PSR model).

        `rates` is the GLOBAL array (identical on every process in a
        multi-host job); placement shards the block axis like every
        other per-site tensor, and under selective loading only this
        process's block window is materialized on its devices."""
        assert self.psr
        self.site_rates = self._put_blocks(
            self._local_block_window(
                np.asarray(rates, dtype=self.dtype).reshape(
                    self.B, self.lane, 1)), lambda s: s.sites)

    def _pallas_failed(self, exc: Exception) -> None:
        """Permanently demote this engine to the validated XLA fast path
        after a Mosaic compile/lowering failure (the Pallas tiers were
        developed against interpret mode; real-hardware lowering bugs
        must degrade, not abort the search).  Only UNPROVEN kernels are
        demoted — once a Pallas program has completed on this engine, a
        later failure is a transient device error (OOM, tunnel hiccup)
        that must propagate, not silently cost the rest of a multi-hour
        search its fast path (the caller re-raises in that case).
        Donated buffers survive a compile-time failure (donation happens
        at execution), which is the failure class Mosaic produces; a
        post-donation runtime fault leaves the arena deleted and the
        retry will surface it."""
        import warnings
        obs.inc("engine.pallas_fallbacks")
        obs.instant("pallas_fallback",
                    args={"error": f"{type(exc).__name__}: {exc}"[:300]})
        obs.ledger_event("tier.fallback", engine=self._obs_tag,
                         to="chunk",
                         error=f"{type(exc).__name__}: {exc}"[:300])
        warnings.warn(
            "EXAML: Pallas kernel dispatch failed (%s: %s); permanently "
            "falling back to the XLA fast path for this engine. Set "
            "EXAML_PALLAS=0 to silence." % (type(exc).__name__, exc),
            RuntimeWarning, stacklevel=3)
        self.use_pallas = False
        self.pallas_whole = False
        self._fast_jit_cache.clear()

    def run_traversal(self, entries: List[TraversalEntry],
                      full: bool = False) -> None:
        """Recompute CLVs for `entries` — a TraversalEntry list, or (for
        full traversals) a `FlatTraversal`, which takes the cached-
        structure fast path and falls back to the legacy list form for
        the scan/PSR/SEV tiers."""
        if not len(entries):
            return
        obs.inc("engine.dispatch_count")
        obs.inc("engine.traversal_entries", len(entries))
        # Traffic bytes only: this path does not block on the result,
        # so its wall time would measure submission, not the traversal
        # — the windowed GB/s gauge is fed by the blocking fused paths.
        self._record_traffic(self._traversal_traffic_bytes(entries),
                             self._tier_for(entries, full))
        flat = entries if isinstance(entries, FlatTraversal) else None
        with obs.device_span("engine:traverse",
                             args={"entries": len(entries),
                                   "full": bool(full)}):
            if flat is not None:
                if full and self._fast_eligible_flat(flat):
                    try:
                        self._run_fast_flat(flat)
                        self._pallas_proven = self.use_pallas
                    except Exception as exc:   # Mosaic lowering/compile
                        if not self.use_pallas or self._pallas_proven:
                            raise
                        self._pallas_failed(exc)
                        self._run_fast_flat(flat)
                    return
                entries = flat.to_entries()
            if full and self._fast_eligible(entries):
                try:
                    self._run_fast_traversal(entries)
                    self._pallas_proven = self.use_pallas
                except Exception as exc:       # Mosaic lowering/compile
                    if not self.use_pallas or self._pallas_proven:
                        raise
                    self._pallas_failed(exc)
                    self._run_fast_traversal(entries)
                return
            if self.save_memory:
                self._sev_begin(entries)
            tv = self._traversal_arrays(entries)
            buf, aux = self._state()
            buf, self.scaler = self._jit_traverse(
                buf, self.scaler, aux, tv, self.models, self.block_part,
                self.tips, self.site_rates)
            self._set_buf(buf)

    def _guard_first_call(self, fn, family: str = "program", key=None):
        """Wrap a freshly-jitted program so its FIRST invocation (= the
        compile) runs as a timed, event-emitting compile monitor: on the
        axon/TPU remote-compile tunnel a pathological compile blocks in
        recv with no Python-level recourse (observed round 4: the chunk
        program never returned), so after the compile deadline
        (EXAML_COMPILE_TIMEOUT, the CLI's --compile-timeout; default
        180 s) a daemon thread tells the user WHICH program family is
        stuck and which escape hatch pins the hardware-proven scan tier
        — through stderr AND the run info file (obs log sink), so the
        operator need not guess.  Compile happens in C++ with the GIL
        released, so the timer thread does run while the main thread is
        stuck.  Installed at every fast-program cache miss, so
        recompiles after a Mosaic-failure fallback (or LRU eviction)
        are guarded too.  The first call is counted and timed into the
        registry (engine.compile_count / engine.compile_seconds
        [.family]) and emits a `compile:<family>` span — a wedged
        compile leaves the span's unmatched "B" event as the trace's
        last line.

        Under `--bank` (ops/bank.py) this watchdog is the LAST line of
        defense, not the first: every family compiles ahead of time in
        a killable subprocess with a HARD deadline, and main-process
        first calls run inside the bank phase as persistent-cache hits.
        The wrapper attributes each first call accordingly
        (engine.compile_count.bank_phase vs
        engine.first_calls.banked/unbanked) so the run artifacts prove
        where compile time was actually paid."""
        state = {"first": True}

        def call(*args):
            if not state["first"]:
                return fn(*args)
            state["first"] = False
            import os as _os
            import threading
            import time as _time

            from examl_tpu.ops import bank

            try:
                limit = float(_os.environ.get("EXAML_COMPILE_TIMEOUT")
                              or 180.0)
            except ValueError:
                limit = 180.0
            done = threading.Event()
            # Program observatory (obs/programs.py): count persistent-
            # cache hits around the compile to attribute its source,
            # and trace the lowering BEFORE the dispatch donates its
            # buffers — the registry row's cost/memory analyses come
            # from AOT-compiling this trace (a cache deserialize when
            # the persistent cache is armed), never from re-dispatching.
            from examl_tpu.obs import programs as _programs
            cache_hits0 = _programs.xla_cache_hits()
            lowered = _programs.prelower(fn, args, family)

            def bark():
                if not done.wait(limit):
                    obs.inc("engine.watchdog_barks")
                    obs.log(
                        "EXAML: a device-program compile (program family "
                        f"'{family}') has taken >{limit:.0f}s — if this "
                        "never returns, rerun with --bank (ahead-of-time "
                        "banking kills wedged compiles and degrades to "
                        "the scan tier), or pin EXAML_FAST_TRAVERSAL=0 "
                        "(scan tier), EXAML_PALLAS=0, or "
                        "EXAML_BATCH_SCAN=0 (sequential SPR scans), "
                        "depending on which program is compiling.")

            threading.Thread(target=bark, daemon=True).start()
            t0 = _time.perf_counter()
            # Ledger bracketing mirrors the trace span: a wedged compile
            # leaves the unmatched "start" as the rank's last ledger
            # event, naming the guilty family in the merged timeline.
            obs.ledger_event("compile", family=family, status="start")
            try:
                with obs.span(f"compile:{family}", cat="compile"):
                    # Fault seam: `compile.hang` sleeps here (default
                    # 3600 s), making the first call indistinguishable
                    # from a wedged remote compile — the watchdog bark,
                    # bank deadline-kill and supervisor paths are all
                    # exercisable on CPU through this one line.
                    from examl_tpu.resilience import faults
                    faults.fire("compile.hang")
                    return fn(*args)
            finally:
                done.set()
                dt = _time.perf_counter() - t0
                obs.ledger_event("compile", family=family, status="end",
                                 seconds=round(dt, 3))
                obs.inc("engine.compile_count")
                obs.inc("engine.compile_seconds", dt)
                obs.inc(f"engine.compile_seconds.{family}", dt)
                # Histogram-carrying timer alongside the counter sum:
                # one pathological compile must be visible as a p99,
                # not averaged into compile_seconds.
                obs.observe(f"engine.compile_seconds.{family}", dt)
                if bank.in_bank_phase():
                    # Banked run, bank phase: the designed place for
                    # every first call (compile time lives here, off
                    # the search's critical path).
                    obs.inc("engine.compile_count.bank_phase")
                    obs.inc("engine.compile_seconds.bank_phase", dt)
                elif bank.active():
                    # Banked run, search phase: a banked family minting
                    # a new shape variant is expected (persistent-cache
                    # hit); an UNBANKED first call means the bank's
                    # enumeration missed a family — the acceptance
                    # counter for wedge immunity.  A family the bank
                    # ATTEMPTED but had to degrade is a separate case:
                    # scan-tier families have no escape hatch ("no
                    # fallback exists for the fallback tier itself"),
                    # so when their worker loses the compile deadline
                    # on a loaded host the run legitimately compiles
                    # them in-process — that is the watchdogged path
                    # the bank's own log promises, not an enumeration
                    # gap, and it must not trip the acceptance counter.
                    if bank.is_banked(family):
                        obs.inc("engine.first_calls.banked")
                    elif family in bank.degraded():
                        obs.inc("engine.first_calls.degraded_inprocess")
                        obs.inc("engine.first_calls."
                                f"degraded_inprocess.{family}")
                    elif bank.sharded_residual(family):
                        # Multi-process run AND the bank enumerated
                        # this family: its mesh-sharded variant can
                        # only first-compile here (workers cannot join
                        # the process group — ROADMAP §4).  This is the
                        # bank's DOCUMENTED residual wedge exposure,
                        # not an enumeration gap; a family the
                        # enumeration MISSED falls through to
                        # `unbanked`, the pure acceptance counter.
                        obs.inc("engine.first_calls.inprocess_sharded")
                        obs.inc("engine.first_calls."
                                f"inprocess_sharded.{family}")
                    else:
                        obs.inc("engine.first_calls.unbanked")
                        obs.inc(f"engine.first_calls.unbanked.{family}")
                _programs.record(
                    family, key if key is not None else family,
                    ("xla-cache"
                     if _programs.xla_cache_hits() > cache_hits0
                     else "fresh"),
                    dt, lowered=lowered)

        return call

    @staticmethod
    def _cache_family(key) -> str:
        """Program family of a shared-cache key: external builders prefix
        their keys with a string tag ("scan"/"thscan"/"whole"/...); the
        engine's own chunk-profile keys are the "fast" family."""
        if isinstance(key, tuple) and key and isinstance(key[0], str):
            return key[0]
        return "fast"

    # -- shared program cache (LRU) -----------------------------------------
    # External program builders (search/batchscan.py, quartets_batch.py)
    # share _fast_jit_cache through these two helpers so they get the
    # same move_to_end-on-hit / trim-on-insert / compile-watchdog
    # discipline as the engine's own fast programs — without it a hot
    # scan program sits at the LRU-oldest slot and wave-profile churn
    # evicts it, and its recompile runs unguarded.

    def cache_get(self, key):
        fn = self._fast_jit_cache.get(key)
        if fn is not None:
            self._fast_jit_cache.move_to_end(key)
            obs.inc("engine.cache_hits")
        else:
            obs.inc("engine.cache_misses")
        return fn

    def cache_put(self, key, fn):
        # Guard, then export-wrap: an exported-bank hit serves the
        # dispatch from a deserialized executable (the guard — and the
        # compile it monitors — never fires); a miss runs the guarded
        # compile and serializes it for the next cold start.  The cache
        # key rides into the artifact signature: two programs with
        # identical avals but different static closures (chunk profile,
        # bucket pair) must never share an artifact.
        from examl_tpu.ops import export_bank
        from examl_tpu.resilience import memgov
        family = self._cache_family(key)
        if not memgov.admit_program(family, seam="engine.cache_put"):
            # Predicted peak exceeds the remaining budget: evict cold
            # cached executables and per-topology device caches BEFORE
            # the compile mints more device memory.  Counted
            # (mem.evictions) — never a silent crash, and the put
            # proceeds either way: eviction is the reaction, admission
            # never blocks a needed program.
            memgov.evict_engine(self)
        guarded = self._guard_first_call(fn, family, key=key)
        fn = export_bank.wrap(fn, guarded, family,
                              (key,) + self._export_identity,
                              exportable=self._exportable,
                              entry_meta={"ntips": self.ntips})
        self._fast_jit_cache[key] = fn
        while len(self._fast_jit_cache) > self._fast_jit_cache_cap:
            self._fast_jit_cache.popitem(last=False)
            obs.inc("engine.cache_evictions")
        return fn

    def _run_fast_traversal(self, entries: List[TraversalEntry]) -> None:
        from examl_tpu.ops import universal
        if self.pallas_whole and not self.universal_force:
            self._run_whole(entries)
            return
        sched = self._fast_schedule(entries)
        self._last_universal = False
        if self._universal_take(sched.profile, with_eval=False):
            try:
                self._run_universal_sched(sched)
                return
            except universal.UniversalIneligible:
                obs.inc("engine.universal_ineligible")
        self._note_fast_program(sched.profile)
        fn = self._fast_fn_flat(sched.profile, with_eval=False)
        self.clv, self.scaler = fn(
            self.clv, self.scaler, sched.base, sched.lidx, sched.ridx,
            sched.lcode, sched.rcode, sched.zl, sched.zr, self.models,
            self.block_part, self.tips)
        self._install_row_map(sched)

    # -- engine state: dense CLV buffer or SEV pool -------------------------
    # Every device program takes (buf, scaler, aux): dense aux = (),
    # SEV aux = (slot_read, slot_write).  buf and scaler are donated; aux
    # is not (the engine keeps the slot maps across calls).

    def _sev_begin(self, entries: List[TraversalEntry]):
        """Update gap/cell bookkeeping for a traversal and sync device."""
        self.sev.update_for_entries(entries)
        self.sev.sync()

    def _state(self):
        if self.save_memory:
            if self.sev.pool is None:
                self.sev.sync()
            return self.sev.pool, (self.sev.slot_read, self.sev.slot_write)
        return self.clv, ()

    def _set_buf(self, buf) -> None:
        if self.save_memory:
            self.sev.pool = buf
        else:
            self.clv = buf

    def _gather(self, buf, aux, scaler, idx, tips):
        if self.save_memory:
            return kernels.gather_child_pooled(tips, buf, aux[0], scaler,
                                               idx, self.ntips)
        return kernels.gather_child(tips, buf, scaler, idx, self.ntips)

    def _traverse_kernel(self, buf, aux, scaler, tv, dm, block_part, tips,
                         sr):
        if self.save_memory:
            return kernels.traverse_pooled(dm, block_part, tips, buf,
                                           aux[0], aux[1], scaler, tv,
                                           self.scale_exp, self.ntips, sr)
        return kernels.traverse(dm, block_part, tips, buf, scaler, tv,
                                self.scale_exp, self.ntips, sr)

    def _traverse_only_impl(self, buf, scaler, aux, tv, dm, block_part,
                            tips, sr):
        return self._traverse_kernel(buf, aux, scaler, tv, dm, block_part,
                                     tips, sr)

    # -- fast full-traversal path (ops/fastpath.py) ------------------------

    def _fast_eligible(self, entries: List[TraversalEntry]) -> bool:
        """The fast path relayouts the whole arena, so it requires a
        traversal covering every inner node (full=True callers after
        invalidate_all) and the GAMMA kernels (PSR keeps the scan path)."""
        return (not self.psr and not self.force_scan
                and self.fast_slack > 0
                and len(entries) == self.n_inner)

    def _fast_schedule(self, entries: List[TraversalEntry]):
        from examl_tpu.ops import fastpath
        with obs.timer("host_schedule"):
            sched = fastpath.build_schedule(entries, self.ntips,
                                            self.num_branch_slots,
                                            self.dtype)
        assert sched.max_write <= self.num_rows - 1, \
            (sched.max_write, self.num_rows)
        return sched

    def _install_row_map(self, sched) -> None:
        ro = sched.row_of
        if isinstance(ro, dict):
            self.row_map[:] = -1
            for num, row in ro.items():
                self.row_map[num] = row
        else:                       # FastStructure: vectorized array copy
            self.row_map[:ro.shape[0]] = ro

    # -- cached schedule structures (flat fast path) -------------------------

    def sched_cache_invalidate(self) -> None:
        """Drop cached schedule structures (search commit seams call
        this through instance.invalidate_schedules after an SPR/NNI
        topology change or a checkpoint restore).  Purely hygiene +
        evidence: the topology-signature keys already guarantee a stale
        structure can never be served."""
        if self._sched_cache:
            obs.inc("engine.sched_cache.invalidate")
            self._sched_cache.clear()
        self._universal_tables.clear()
        self._grad_structs.clear()

    def _fast_structure(self, flat):
        from examl_tpu.ops import fastpath
        st = self._sched_cache.get(flat.topo_key)
        if st is not None:
            self._sched_cache.move_to_end(flat.topo_key)
            obs.inc("engine.sched_cache.hit")
            return st
        obs.inc("engine.sched_cache.miss")
        st = fastpath.build_structure(flat, self.ntips)
        assert st.max_write <= self.num_rows - 1, \
            (st.max_write, self.num_rows)
        self._sched_cache[flat.topo_key] = st
        while len(self._sched_cache) > self._sched_cache_cap:
            self._sched_cache.popitem(last=False)
            obs.inc("engine.sched_cache.evictions")
        return st

    def _fast_eligible_flat(self, flat) -> bool:
        return (not self.psr and not self.force_scan
                and self.fast_slack > 0 and flat.n == self.n_inner)

    def _note_fast_program(self, profile) -> None:
        """Publish the bounded chunk program's size gauges: unrolled
        blocks after coalescing, scan groups, and the per-traversal
        operation count (the launch-latency floor the bounded layout
        exists to shrink) — landing in `--metrics` snapshots and BENCH
        rows.  Tagged per engine like the other engine gauges
        (_register_obs): two engines (DNA+AA instance, bench's several
        K=4 instances) must not overwrite each other's program size."""
        from examl_tpu.ops import fastpath
        un, sc, total = fastpath.profile_stats(profile)
        tag = "." + self._obs_tag
        obs.gauge("engine.program_chunks" + tag, un)
        obs.gauge("engine.scan_groups" + tag, sc)
        obs.gauge("engine.dispatches_per_traversal" + tag, un + sc)
        obs.gauge("engine.chunk_blocks_total" + tag, total)
        self._last_dispatch_ops = un + sc     # regime launch-floor term

    def _fast_fn_flat(self, profile, with_eval: bool):
        """Jitted chunk program over the PACKED structure + z arrays:
        each segment's window is sliced statically from the profile
        inside the trace (scan groups reshape theirs to [glen, step]),
        so a dispatch carries 7 array leaves total instead of 7 per
        chunk.  The key IS the BUCKETED segment profile (not raw
        per-chunk widths) — two topologies of similar shape mint the
        same key and share one compiled program, which is the point of
        width bucketing (tests/test_fastpath.py asserts the cache-hit
        counters).  Key leads with "fast" — same program family as
        before for the bank/watchdog accounting; the legacy entry-list
        path dispatches through this same cache entry."""
        key = ("fast", profile, "flat", with_eval)
        fn = self.cache_get(key)
        if fn is not None:
            return fn

        def impl(clv, scaler, base, lidx, ridx, lcode, rcode, zl, zr,
                 dm, block_part, tips):
            return self._run_segments_impl(
                dm, block_part, tips, clv, scaler, profile, base, lidx,
                ridx, lcode, rcode, zl, zr)

        def impl_eval(clv, scaler, base, lidx, ridx, lcode, rcode, zl,
                      zr, p_idx, q_idx, z, dm, block_part, weights,
                      tips):
            clv, scaler = self._run_segments_impl(
                dm, block_part, tips, clv, scaler, profile, base, lidx,
                ridx, lcode, rcode, zl, zr)
            lnl = kernels.root_log_likelihood(
                dm, block_part, weights, tips, clv, scaler, p_idx, q_idx,
                z, self.num_parts, self.scale_exp, self.ntips, None)
            return clv, scaler, lnl

        return self.cache_put(key, jax.jit(
            impl_eval if with_eval else impl, donate_argnums=(0, 1)))

    def _run_fast_flat(self, flat, p_num=None, q_num=None, z=None):
        """Fast full traversal (and optional fused root evaluation) from
        a FlatTraversal: cached structure + fresh z only.  The universal
        interpreter (ops/universal.py) takes the dispatch when forced or
        when novel-profile routing is on and no specialized program for
        this profile exists — same layout, same chunk arithmetic, but a
        topology-independent jit key."""
        from examl_tpu.ops import fastpath, universal
        if self.pallas_whole and not self.universal_force:
            return self._run_whole(flat.to_entries(), p_num, q_num, z)
        with obs.timer("host_schedule"):
            st = self._fast_structure(flat)
        self._last_universal = False
        if self._universal_take(st.profile, p_num is not None):
            try:
                return self._run_universal_flat(flat, st, p_num, q_num, z)
            except universal.UniversalIneligible:
                obs.inc("engine.universal_ineligible")
        with obs.timer("host_schedule"):
            zl, zr = fastpath.refresh_z(st, flat, self.num_branch_slots,
                                        self.dtype)
        self._note_fast_program(st.profile)
        if p_num is None:
            fn = self._fast_fn_flat(st.profile, with_eval=False)
            self.clv, self.scaler = fn(
                self.clv, self.scaler, st.base, st.lidx, st.ridx,
                st.lcode, st.rcode, zl, zr, self.models, self.block_part,
                self.tips)
            self._install_row_map(st)
            return None
        fn = self._fast_fn_flat(st.profile, with_eval=True)
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots),
                         dtype=self.dtype)
        self.clv, self.scaler, out = fn(
            self.clv, self.scaler, st.base, st.lidx, st.ridx, st.lcode,
            st.rcode, zl, zr, jnp.int32(self._gidx_of(st, p_num)),
            jnp.int32(self._gidx_of(st, q_num)), zv, self.models,
            self.block_part, self.weights, self.tips)
        self._install_row_map(st)
        return np.asarray(out)

    # -- universal interpreter tier (ops/universal.py) ----------------------
    # Topology-as-data: the bounded layout's packed arrays ship as
    # RUNTIME data into one compiled lax.scan whose body lax.switches
    # over the fixed (kind, width) alphabet.  The jit key is
    # ("universal", alphabet, table_bucket, slot_bucket, with_eval) — a
    # tiny closed family — so any topology runs through an
    # already-banked program with zero first-call compiles.  lnL is
    # bit-identical to the specialized chunk program by construction:
    # identical chunk sequence, identical `chunk_applier` arithmetic,
    # identical order (tests/test_universal.py pins it).

    def _universal_take(self, profile, with_eval: bool) -> bool:
        """Should this full-traversal dispatch run the interpreter?
        force > routing; routing diverts only profiles whose
        specialized program is not already compiled (an already-hot
        profile keeps its ~1.3x-faster specialized dispatch)."""
        if self.universal_off:
            return False
        if self.universal_force:
            return True
        if not self.route_novel_to_universal:
            return False
        return ("fast", profile, "flat", with_eval) \
            not in self._fast_jit_cache

    def _universal_akey(self):
        """(min_width, cap): the layout-knob identity a table's step
        splitting and a program's switch alphabet must agree on."""
        from examl_tpu.ops import universal
        return universal.alphabet_key()

    def _universal_entry(self, profile, base_h, idx_h, cache_key=None):
        """Descriptor-table cache entry: the host table plus lazily
        padded per-bucket copies of the descriptor and index arrays
        (content-keyed by topology signature when available; an entry
        built under a different alphabet — env-retuned knobs, a grown
        arena — rebuilds, since class ids index the alphabet)."""
        from examl_tpu.ops import universal
        akey = self._universal_akey()
        if cache_key is not None:
            ent = self._universal_tables.get(cache_key)
            if ent is not None and ent["akey"] == akey:
                self._universal_tables.move_to_end(cache_key)
                return ent
        ent = {"table": universal.build_table(profile, base_h, akey),
               "idx": idx_h, "desc": {}, "pads": {}, "akey": akey}
        if cache_key is not None:
            self._universal_tables[cache_key] = ent
            while len(self._universal_tables) > self._universal_tables_cap:
                self._universal_tables.popitem(last=False)
        return ent

    def _universal_minted(self, akey, with_eval: bool):
        """The (table_bucket, slot_bucket) pairs whose interpreter
        program is ACTUALLY resident in the jit cache right now —
        derived from the cache keys rather than shadow state, so every
        invalidation path (LRU eviction, the Pallas-failure bulk
        clear, an env knob retune changing the alphabet key) keeps
        `pick_pads` honest for free."""
        return {(k[2], k[3]) for k in self._fast_jit_cache
                if isinstance(k, tuple) and len(k) == 5
                and k[0] == "universal" and k[1] == akey
                and k[4] == with_eval}

    def _universal_args(self, ent, with_eval: bool):
        """(npad, ppad, desc, idx) for one dispatch: buckets picked
        from the compiled-program set (replay padding is idempotent,
        so any larger compiled bucket serves correctly).  The padded
        descriptor and index arrays are memoized per bucket on the
        entry DEVICE-RESIDENT — like FastStructure's packed arrays, a
        cached serving dispatch ships only the two fresh z arrays."""
        from examl_tpu.ops import universal
        table = ent["table"]
        npad, ppad = universal.pick_pads(
            self._universal_minted(ent["akey"], with_eval),
            table.n_chunks, table.slots)
        desc = ent["desc"].get(npad)
        if desc is None:
            desc = ent["desc"][npad] = jax.device_put(
                list(universal.pad_table(table, npad)))
        idx = ent["pads"].get(ppad)
        if idx is None:
            idx = ent["pads"][ppad] = jax.device_put(
                [universal.pad_slots(np.asarray(a), ppad)
                 for a in ent["idx"]])
        return npad, ppad, desc, idx

    def _run_universal_flat(self, flat, st, p_num=None, q_num=None,
                            z=None):
        """Interpreter dispatch from a cached FastStructure: descriptor
        table + packed index copies are cached per topology signature;
        only the z arrays (padded to the slot bucket) are fresh."""
        from examl_tpu.ops import fastpath
        with_eval = p_num is not None
        with obs.timer("host_schedule"):
            ent = self._universal_entry(
                st.profile, np.asarray(st.base),
                (st.lidx, st.ridx, st.lcode, st.rcode),
                cache_key=flat.topo_key)
            npad, ppad, desc, idx = self._universal_args(ent, with_eval)
            zl, zr = fastpath.refresh_z(st, flat, self.num_branch_slots,
                                        self.dtype, total_slots=ppad)
        return self._universal_dispatch(st, desc, idx, zl, zr, npad,
                                        ppad, p_num, q_num, z)

    def _run_universal_sched(self, sched, p_num=None, q_num=None,
                             z=None):
        """Interpreter dispatch from a legacy entry-list FastSchedule
        (bank warming, entry-list callers): same program, host arrays
        padded on the fly (no topology signature to cache under)."""
        from examl_tpu.ops import universal
        with_eval = p_num is not None
        base_h, li, ri, lc, rc, zl_h, zr_h = sched._host
        with obs.timer("host_schedule"):
            ent = self._universal_entry(sched.profile, base_h,
                                        (li, ri, lc, rc))
            npad, ppad, desc, idx = self._universal_args(ent, with_eval)
            zl = jnp.asarray(universal.pad_slots(zl_h, ppad, fill=1),
                             self.dtype)
            zr = jnp.asarray(universal.pad_slots(zr_h, ppad, fill=1),
                             self.dtype)
        return self._universal_dispatch(sched, desc, idx, zl, zr, npad,
                                        ppad, p_num, q_num, z)

    def _universal_dispatch(self, sched, desc, idx, zl, zr, npad: int,
                            ppad: int, p_num, q_num, z):
        """Ship the padded table + packed layout as data through the
        bucketed interpreter program and install the layout's row map
        (identical post-state to the specialized dispatch)."""
        with_eval = p_num is not None
        obs.inc("engine.universal_dispatches")
        tag = "." + self._obs_tag
        obs.gauge("engine.universal_steps" + tag, npad)
        obs.gauge("engine.universal_slots" + tag, ppad)
        # The interpreter is ONE device op, but its scan walks npad
        # dependent steps — the launch-floor term the regime classifier
        # uses (same accounting as the scan tier's wave count).
        self._last_dispatch_ops = npad
        self._last_universal = True
        fn = self._universal_fn(npad, ppad, with_eval)
        cls, slot, cbase = desc
        li, ri, lc, rc = idx
        if not with_eval:
            self.clv, self.scaler = fn(
                self.clv, self.scaler, cls, slot, cbase, li, ri, lc, rc,
                zl, zr, self.models, self.block_part, self.tips)
            self._install_row_map(sched)
            return None
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots),
                         dtype=self.dtype)
        self.clv, self.scaler, out = fn(
            self.clv, self.scaler, cls, slot, cbase, li, ri, lc, rc, zl,
            zr, jnp.int32(self._gidx_of(sched, p_num)),
            jnp.int32(self._gidx_of(sched, q_num)), zv, self.models,
            self.block_part, self.weights, self.tips)
        self._install_row_map(sched)
        return np.asarray(out)

    def _universal_fn(self, npad: int, ppad: int, with_eval: bool):
        """The ONE jitted interpreter program per (alphabet, buckets,
        with_eval) — the `("universal", ...)` cache family, with its
        own compile-watchdog label via `_cache_family`.  Always the
        plain-XLA chunk kernel: the interpreter is the portability rung
        below the chunk tier (pallas -> chunk -> universal -> scan),
        and a Mosaic kernel in every switch branch would multiply the
        compile surface of the tier whose point is compiling once."""
        from examl_tpu.ops import fastpath, universal
        akey = self._universal_akey()
        key = ("universal", akey, npad, ppad, with_eval)
        fn = self.cache_get(key)
        if fn is not None:
            return fn
        alpha = universal.alphabet(akey)

        def run(clv, scaler, cls, slot, cbase, lidx, ridx, lcode, rcode,
                zl, zr, dm, block_part, tips):
            apply = fastpath.chunk_applier(dm, block_part, tips,
                                           self.scale_exp,
                                           self.fast_precision)
            return universal.run_universal(
                alpha, cls, slot, cbase, lidx, ridx, lcode, rcode, zl,
                zr, clv, scaler, apply.values)

        def impl_eval(clv, scaler, cls, slot, cbase, lidx, ridx, lcode,
                      rcode, zl, zr, p_idx, q_idx, zv, dm, block_part,
                      weights, tips):
            clv, scaler = run(clv, scaler, cls, slot, cbase, lidx, ridx,
                              lcode, rcode, zl, zr, dm, block_part, tips)
            lnl = kernels.root_log_likelihood(
                dm, block_part, weights, tips, clv, scaler, p_idx, q_idx,
                zv, self.num_parts, self.scale_exp, self.ntips, None)
            return clv, scaler, lnl

        return self.cache_put(key, jax.jit(
            impl_eval if with_eval else run, donate_argnums=(0, 1)))

    @property
    def pallas_precision(self):
        """Precision handed to the Pallas tiers: Mosaic lowers only
        DEFAULT and HIGHEST ("Unsupported dot precision: HIGH" on real
        v5e hardware), so the engine's HIGH default — a 3-pass-bf16
        XLA-path optimization — maps to HIGHEST inside kernels, where
        operands already sit in VMEM and extra passes cost no HBM.
        Harnesses that pass an explicit HIGH to the pallas modules still
        fail loudly (perf_lab precision sweeps must not mislabel rows)."""
        if self.fast_precision == jax.lax.Precision.HIGH:
            return jax.lax.Precision.HIGHEST
        return self.fast_precision

    def _chunk_applier(self, dm, block_part, tips):
        """The per-chunk kernel on the engine-selected backend path
        (fused Pallas on TPU, plain XLA elsewhere) — shared by the
        unrolled reference executor and the bounded segment program."""
        if self.use_pallas:
            from examl_tpu.ops import pallas_newview
            return pallas_newview.chunk_applier(
                dm, block_part, tips, self.scale_exp,
                precision=self.pallas_precision,
                interpret=self.pallas_interpret)
        from examl_tpu.ops import fastpath
        return fastpath.chunk_applier(dm, block_part, tips,
                                      self.scale_exp,
                                      self.fast_precision)

    def _run_chunks_impl(self, dm, block_part, tips, clv, scaler, chunks):
        """Unrolled chunk-list execution (traced); the reference
        strategy external harnesses time (bench.py, perf lab)."""
        apply = self._chunk_applier(dm, block_part, tips)
        for ch in chunks:
            clv, scaler = apply(clv, scaler, ch)
        return clv, scaler

    def _run_segments_impl(self, dm, block_part, tips, clv, scaler,
                           profile, base, lidx, ridx, lcode, rcode, zl,
                           zr):
        """Bounded-program execution over the packed 7-leaf layout
        (fastpath.run_segments): O(#segments) program ops — unrolled
        hot chunks plus lax.scan long-tail groups — on the
        engine-selected backend path."""
        from examl_tpu.ops import fastpath
        apply = self._chunk_applier(dm, block_part, tips)
        return fastpath.run_segments(profile, base, lidx, ridx, lcode,
                                     rcode, zl, zr, clv, scaler, apply)

    def run_chunks_traced(self, clv, scaler, chunks):
        """Traceable chunk execution for harnesses that build their own
        jit around the fast path (bench.py, perf lab)."""
        return self._run_chunks_impl(self.models, self.block_part,
                                     self.tips, clv, scaler, chunks)

    def run_segments_traced(self, clv, scaler, sched):
        """Traceable bounded-program execution from a FastSchedule (the
        program the engine actually dispatches per full traversal) for
        external harnesses (bench.py chunk tier)."""
        return self._run_segments_impl(
            self.models, self.block_part, self.tips, clv, scaler,
            sched.profile, sched.base, sched.lidx, sched.ridx,
            sched.lcode, sched.rcode, sched.zl, sched.zr)

    # -- whole-traversal Pallas path (ops/pallas_whole.py) ------------------

    def _whole_fn(self, E: int, with_eval: bool):
        key = ("whole", E, with_eval)
        fn = self.cache_get(key)
        if fn is not None:
            return fn
        from examl_tpu.ops import pallas_whole

        def run(clv, scaler, meta, lc, rc, zl, zr, dm, bp, tips):
            return pallas_whole.run_flat_arrays(
                dm, bp, tips, clv, scaler, E, meta, lc, rc, zl, zr,
                self.scale_exp, self.pallas_precision,
                self.pallas_interpret)

        def impl_eval(clv, scaler, meta, lc, rc, zl, zr, p_idx, q_idx,
                      zv, dm, bp, weights, tips):
            clv, scaler = run(clv, scaler, meta, lc, rc, zl, zr, dm, bp,
                              tips)
            lnl = kernels.root_log_likelihood(
                dm, bp, weights, tips, clv, scaler, p_idx, q_idx, zv,
                self.num_parts, self.scale_exp, self.ntips, None)
            return clv, scaler, lnl

        return self.cache_put(key, jax.jit(impl_eval if with_eval else run,
                                           donate_argnums=(0, 1)))

    def _whole_args(self, entries):
        from examl_tpu.ops import pallas_whole
        sched = pallas_whole.build_flat(entries, self.ntips,
                                        self.num_branch_slots)
        return sched, (jnp.asarray(sched.meta),
                       jnp.asarray(sched.l_code),
                       jnp.asarray(sched.r_code),
                       jnp.asarray(sched.zl, dtype=self.dtype),
                       jnp.asarray(sched.zr, dtype=self.dtype))

    def _run_whole(self, entries, p_num=None, q_num=None, z=None):
        # One fused Mosaic program = one sequential device op: the
        # whole tier's launch floor for the regime classifier (a stale
        # scan-tier wave count here would wrongly stamp a whole-tier
        # bandwidth number dispatch-bound).
        self._last_dispatch_ops = 1
        self._last_universal = False
        sched, args = self._whole_args(entries)
        if p_num is None:
            fn = self._whole_fn(sched.e_real, with_eval=False)
            self.clv, self.scaler = fn(self.clv, self.scaler, *args,
                                       self.models, self.block_part,
                                       self.tips)
            self._install_row_map(sched)
            return None
        fn = self._whole_fn(sched.e_real, with_eval=True)
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots),
                         dtype=self.dtype)
        self.clv, self.scaler, out = fn(
            self.clv, self.scaler, *args,
            jnp.int32(self._gidx_of(sched, p_num)),
            jnp.int32(self._gidx_of(sched, q_num)), zv, self.models,
            self.block_part, self.weights, self.tips)
        self._install_row_map(sched)
        return np.asarray(out)

    def run_whole_traced(self, clv, scaler, sched):
        """Traceable whole-traversal execution for external harnesses
        (bench.py): schedule built once on host, kernel traced inline."""
        from examl_tpu.ops import pallas_whole
        return pallas_whole.run_flat(
            self.models, self.block_part, self.tips, clv, scaler, sched,
            self.scale_exp, self.pallas_precision, self.pallas_interpret)

    # -- batched SPR radius scan (search/batchscan.py) ----------------------

    def ensure_scan_rows(self, n: int) -> int:
        """Grow the arena by a scratch scan region of >= n rows (pow2
        bucketed so reallocation and recompilation stay O(log n) over a
        search); returns the region's base row.  The fast path and the
        normal traversals never touch rows above their original arena, so
        the region is free scratch between scan dispatches."""
        if self.save_memory:
            base = self.sev.ensure_scan_rows(n)
            if self.sev.num_rows > self.num_rows:
                # The scaler stays DENSE under -S ([rows, B, lane] int32,
                # ~1/64 the bytes of a CLV row): it must grow with the
                # pool's scan rows or traverse_pooled's scatter silently
                # drops scan-row scaler writes (JAX OOB scatter = drop)
                # and candidate lnLs lose their scale counts.
                grow = self.sev.num_rows - self.num_rows
                self.scaler = self._grow_rows(self.scaler, grow,
                                              self.sharding and
                                              self.sharding.scaler)
                self.num_rows = self.sev.num_rows
            return base
        if not hasattr(self, "_scan_base"):
            self._scan_base = self.num_rows
            self._scan_cap = 0
        if n > self._scan_cap:
            grow = _next_pow2(n) - self._scan_cap
            self.clv = self._grow_rows(self.clv, grow,
                                       self.sharding and self.sharding.clv)
            self.scaler = self._grow_rows(self.scaler, grow,
                                          self.sharding and
                                          self.sharding.scaler)
            self._scan_cap += grow
            self.num_rows += grow
        return self._scan_base

    @staticmethod
    def _grow_rows(arr, grow: int, sharding):
        """Append `grow` zero rows, keeping the array committed to its
        sharding: the pad is placed BEFORE the concatenate — eagerly
        concatenating a committed global array with an uncommitted
        process-local one is undefined in a multi-process run, and the
        row axis is never the sharded axis so concat preserves the
        operands' placement."""
        pad = jnp.zeros((grow,) + arr.shape[1:], arr.dtype)
        if sharding is not None:
            pad = jax.device_put(pad, sharding)
        return jnp.concatenate([arr, pad])

    def _scan_traversal_arrays(self, down_entries, up_entries, base: int):
        """Wave-schedule the orientation fixes AND the uppass entries into
        ONE set of Traversal arrays (one traverse, one dispatch).  Slot
        ids are encoded above the node-number range so Tree.schedule_waves
        resolves node->node, node->slot, and slot->slot dependencies
        uniformly; down entries write normal arena rows through the row
        map, up entries write the scan region."""
        from examl_tpu.tree.topology import TraversalEntry

        SLOT0 = 2 * self.ntips + 1

        def ref_id(ref):
            kind, v = ref
            return SLOT0 + v if kind == "slot" else v

        pseudo = list(down_entries) + [
            TraversalEntry(SLOT0 + e.slot, ref_id(e.left),
                           ref_id(e.right), e.zl, e.zr)
            for e in up_entries]

        def parent_row(e) -> int:
            if e.parent >= SLOT0:
                return base + (e.parent - SLOT0)
            return self.row_map[e.parent]

        def gidx(ident: int) -> int:
            if ident >= SLOT0:
                return self.ntips + base + (ident - SLOT0)
            return self._gidx(ident)

        with obs.timer("host_schedule"):
            return self._pack_traversal(pseudo, parent_row, gidx)

    def _scan_dispatch_arrays(self, plan, base: int, T: int):
        """Shared padding/chunk plumbing for the scan programs: gather
        indices for candidates and their uppass rows, padded to a pow2
        number of T-wide chunks (O(log n) compiled variants)."""
        N = len(plan.candidates)
        n_chunks = max(1, _next_pow2((N + T - 1) // T))
        npad = n_chunks * T
        qg = np.zeros(npad, np.int32)
        upg = np.zeros(npad, np.int32)
        for i, c in enumerate(plan.candidates):
            qg[i] = self._gidx(c.q_num)
            upg[i] = self.ntips + base + c.up_slot
        return n_chunks, npad, qg, upg

    def batched_scan(self, plan) -> np.ndarray:
        """Uppass traversal + all candidate insertion scores in one
        dispatch; returns this engine's per-candidate lnL sums [N].
        Works on the dense arena and on -S SEV pools alike (gap bits for
        the orientation fixes update first; the scan region is carved
        from the pool by ensure_scan_rows)."""
        from examl_tpu.search import batchscan

        obs.inc("engine.dispatch_count")
        obs.inc("engine.traversal_entries",
                len(plan.down_entries) + len(plan.up_entries))
        self._record_traffic(self._scan_plan_traffic_bytes(plan), "scan")
        if self.save_memory:
            self.sev.update_for_entries(plan.down_entries)
        base = self.ensure_scan_rows(len(plan.up_entries))
        tv = self._scan_traversal_arrays(plan.down_entries,
                                         plan.up_entries, base)
        T = batchscan.CAND_CHUNK
        n_chunks, npad, qg, upg = self._scan_dispatch_arrays(plan, base, T)
        C = self.num_branch_slots
        zc = np.ones((npad, C), dtype=np.float64)
        for i, c in enumerate(plan.candidates):
            zc[i] = _z_slots(c.z, C)
        fn = batchscan.scan_program(self, n_chunks)
        zp = jnp.asarray(_z_slots(plan.zp, C), dtype=self.dtype)
        buf, aux = self._state()
        with obs.device_span("engine:spr_scan",
                             args={"candidates": len(plan.candidates),
                                   "chunks": n_chunks}):
            buf, self.scaler, lnls = fn(
                buf, self.scaler, aux, tv,
                jnp.asarray(qg.reshape(n_chunks, T)),
                jnp.asarray(upg.reshape(n_chunks, T)),
                jnp.asarray(zc.reshape(n_chunks, T, C), dtype=self.dtype),
                jnp.int32(self._gidx(plan.s_num)), zp,
                self.models, self.block_part, self.weights, self.tips,
                self.site_rates)
        self._set_buf(buf)
        return np.asarray(lnls)[:len(plan.candidates)]

    def batched_thorough(self, plan):
        """Thorough-arm companion of `batched_scan`: triangle Newton,
        localSmooth, and scoring per candidate in one dispatch; returns
        (lnls [N], smoothed branch triplets [N, 3]).  Works on the dense
        arena and on -S SEV pools (sharded or not) alike, like the lazy
        arm."""
        from examl_tpu.search import batchscan

        obs.inc("engine.dispatch_count")
        obs.inc("engine.traversal_entries",
                len(plan.down_entries) + len(plan.up_entries))
        self._record_traffic(self._scan_plan_traffic_bytes(plan), "scan")
        if self.save_memory:
            self.sev.update_for_entries(plan.down_entries)
        base = self.ensure_scan_rows(len(plan.up_entries))
        tv = self._scan_traversal_arrays(plan.down_entries,
                                         plan.up_entries, base)
        T = batchscan.TH_CHUNK
        n_chunks, npad, qg, upg = self._scan_dispatch_arrays(plan, base, T)
        zq0 = np.full(npad, float(np.asarray(plan.zp, np.float64)[0]))
        for i, c in enumerate(plan.candidates):
            zq0[i] = float(np.asarray(c.q_slot.z, np.float64)[0])
        fn = batchscan.thorough_program(self, n_chunks)
        buf, aux = self._state()
        with obs.device_span("engine:spr_thorough",
                             args={"candidates": len(plan.candidates),
                                   "chunks": n_chunks}):
            buf, self.scaler, lnls, es = fn(
                buf, self.scaler, aux, tv,
                jnp.asarray(qg.reshape(n_chunks, T)),
                jnp.asarray(upg.reshape(n_chunks, T)),
                jnp.asarray(zq0.reshape(n_chunks, T), dtype=self.dtype),
                jnp.int32(self._gidx(plan.s_num)), self.models,
                self.block_part, self.weights, self.tips, self.site_rates)
        self._set_buf(buf)
        N = len(plan.candidates)
        return np.asarray(lnls)[:N], np.asarray(es)[:N]

    # -- evaluation --------------------------------------------------------

    def _evaluate_impl(self, buf, scaler, aux, p_idx, q_idx, z, dm,
                       block_part, weights, tips, sr):
        xp, sp = self._gather(buf, aux, scaler, p_idx, tips)
        xq, sq = self._gather(buf, aux, scaler, q_idx, tips)
        return kernels.root_log_likelihood_from(
            dm, block_part, weights, xp, sp, xq, sq, z, self.num_parts,
            self.scale_exp, sr, axis_name=self._axis_name)

    def evaluate(self, p_num: int, q_num: int, z: Sequence[float]) -> np.ndarray:
        """Per-partition lnL [M] at branch (p,q); CLVs must be current."""
        obs.inc("engine.dispatch_count")
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots), dtype=self.dtype)
        buf, aux = self._state()
        with obs.device_span("engine:evaluate"):
            out = self._jit_evaluate(buf, self.scaler, aux,
                                     jnp.int32(self._gidx(p_num)),
                                     jnp.int32(self._gidx(q_num)),
                                     zv, self.models, self.block_part,
                                     self.weights, self.tips,
                                     self.site_rates)
        return np.asarray(out)

    # -- fused single-dispatch entry points ---------------------------------
    # Traversal + root evaluation (resp. + sumtable + the whole NR loop) in
    # ONE device program: the reference pays one reduction round-trip per
    # evaluateGeneric and one per NR iteration (SURVEY §3.2-3.3); here each
    # search step is a single dispatch.

    def _trav_eval_impl(self, buf, scaler, aux, tv, p_idx, q_idx, z, dm,
                        block_part, weights, tips, sr):
        buf, scaler = self._traverse_kernel(buf, aux, scaler, tv, dm,
                                            block_part, tips, sr)
        lnl = self._evaluate_impl(buf, scaler, aux, p_idx, q_idx, z, dm,
                                  block_part, weights, tips, sr)
        return buf, scaler, lnl

    def traverse_evaluate(self, entries: List[TraversalEntry], p_num: int,
                          q_num: int, z: Sequence[float],
                          full: bool = False) -> np.ndarray:
        obs.inc("engine.dispatch_count")
        obs.inc("engine.traversal_entries", len(entries))
        nbytes = self._traversal_traffic_bytes(entries)
        compiles0 = obs.registry().counter("engine.compile_count")
        t0 = time.perf_counter()
        with obs.device_span("engine:trav_eval",
                             args={"entries": len(entries),
                                   "full": bool(full)}):
            out = self._traverse_evaluate(entries, p_num, q_num, z, full)
        # This path BLOCKS (np.asarray on the lnL), so the elapsed wall
        # covers the whole traversal: full traversals feed the windowed
        # achieved-GB/s gauge (partial ones — a few entries around one
        # branch — only account bytes; their wall is dominated by the
        # root evaluation and would read as launch floor).  A dispatch
        # whose span contained a first-call compile keeps its histogram
        # observation but is excluded from the bandwidth window.
        self._record_traffic(
            nbytes, self._tier_for(entries, full),
            wall_s=(time.perf_counter() - t0) if full and len(entries)
            else None,
            window=(obs.registry().counter("engine.compile_count")
                    == compiles0))
        return out

    def _traverse_evaluate(self, entries: List[TraversalEntry], p_num: int,
                           q_num: int, z: Sequence[float],
                           full: bool = False) -> np.ndarray:
        if isinstance(entries, FlatTraversal):
            flat = entries
            if full and flat.n and self._fast_eligible_flat(flat):
                try:
                    out = self._run_fast_flat(flat, p_num, q_num, z)
                    self._pallas_proven = self.use_pallas
                    return out
                except Exception as exc:       # Mosaic lowering/compile
                    if not self.use_pallas or self._pallas_proven:
                        raise
                    self._pallas_failed(exc)
                    return self._run_fast_flat(flat, p_num, q_num, z)
            entries = flat.to_entries()
        if full and entries and self._fast_eligible(entries):
            try:
                out = self._trav_eval_fast(entries, p_num, q_num, z)
                self._pallas_proven = self.use_pallas
                return out
            except Exception as exc:           # Mosaic lowering/compile
                if not self.use_pallas or self._pallas_proven:
                    raise
                self._pallas_failed(exc)
                return self._trav_eval_fast(entries, p_num, q_num, z)
        if self.save_memory:
            self._sev_begin(entries)
        tv = self._traversal_arrays(entries)
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots), dtype=self.dtype)
        buf, aux = self._state()
        buf, self.scaler, out = self._jit_trav_eval(
            buf, self.scaler, aux, tv, jnp.int32(self._gidx(p_num)),
            jnp.int32(self._gidx(q_num)), zv, self.models, self.block_part,
            self.weights, self.tips, self.site_rates)
        self._set_buf(buf)
        return np.asarray(out)

    def _trav_eval_fast(self, entries, p_num, q_num, z) -> np.ndarray:
        from examl_tpu.ops import universal
        if self.pallas_whole and not self.universal_force:
            return self._run_whole(entries, p_num, q_num, z)
        sched = self._fast_schedule(entries)
        self._last_universal = False
        if self._universal_take(sched.profile, with_eval=True):
            try:
                return self._run_universal_sched(sched, p_num, q_num, z)
            except universal.UniversalIneligible:
                obs.inc("engine.universal_ineligible")
        self._note_fast_program(sched.profile)
        fn = self._fast_fn_flat(sched.profile, with_eval=True)
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots),
                         dtype=self.dtype)
        self.clv, self.scaler, out = fn(
            self.clv, self.scaler, sched.base, sched.lidx, sched.ridx,
            sched.lcode, sched.rcode, sched.zl, sched.zr,
            jnp.int32(self._gidx_of(sched, p_num)),
            jnp.int32(self._gidx_of(sched, q_num)), zv, self.models,
            self.block_part, self.weights, self.tips)
        self._install_row_map(sched)
        return np.asarray(out)

    def _gidx_of(self, sched, num: int) -> int:
        """gather_child index of a node against a schedule's NEW layout
        WITHOUT installing it: a kernel failure between schedule build
        and dispatch must not leave self.row_map pointing at rows the
        arena does not hold (shared by the chunk and whole-traversal
        fast paths)."""
        if num <= self.ntips:
            return num - 1
        return self.ntips + sched.row_of[num]

    def _newton_impl(self, buf, scaler, aux, tv, p_idx, q_idx, z0,
                     maxiters, conv, dm, block_part, weights, tips, sr):
        buf, scaler = self._traverse_kernel(buf, aux, scaler, tv, dm,
                                            block_part, tips, sr)
        xp, _ = self._gather(buf, aux, scaler, p_idx, tips)
        xq, _ = self._gather(buf, aux, scaler, q_idx, tips)
        st = kernels.sumtable(dm, block_part, xp, xq)
        z = kernels.newton_raphson_branch(dm, block_part, weights, st, z0,
                                          maxiters, conv,
                                          self.num_branch_slots, sr,
                                          axis_name=self._axis_name)
        return buf, scaler, z

    def newton_branch(self, entries: List[TraversalEntry], p_num: int,
                      q_num: int, z0: np.ndarray, maxiter: int,
                      conv_mask: Optional[np.ndarray] = None) -> np.ndarray:
        """Fused traversal + sumtable + NR-to-convergence; returns new z [C]."""
        obs.inc("engine.dispatch_count")
        obs.inc("engine.newton_dispatches")
        obs.inc("engine.traversal_entries", len(entries))
        self._record_traffic(self._traversal_traffic_bytes(entries),
                             "scan")
        if self.save_memory:
            self._sev_begin(entries)
        tv = self._traversal_arrays(entries)
        C = self.num_branch_slots
        if conv_mask is None:
            conv_mask = np.zeros(C, dtype=bool)
        buf, aux = self._state()
        with obs.device_span("engine:newton",
                             args={"entries": len(entries),
                                   "maxiter": int(maxiter)}):
            buf, self.scaler, z = self._jit_newton(
                buf, self.scaler, aux, tv, jnp.int32(self._gidx(p_num)),
                jnp.int32(self._gidx(q_num)), jnp.asarray(z0),
                jnp.full(C, maxiter, dtype=jnp.int32),
                jnp.asarray(conv_mask), self.models, self.block_part,
                self.weights, self.tips, self.site_rates)
        self._set_buf(buf)
        return np.asarray(z, dtype=np.float64)

    # -- PSR rate-grid scan -------------------------------------------------

    def _rate_scan_impl(self, tips, tv, p_idx, q_idx, z, grid, dm,
                        block_part):
        """Full traversal + per-site-per-candidate root lnL for one grid
        chunk [B, lane, G]; scratch CLVs live only inside this program."""
        G = grid.shape[2]
        clv = jnp.zeros((self.num_rows, self.B, self.lane, G, self.K),
                        dtype=self.dtype)
        scaler = jnp.zeros((self.num_rows, self.B, self.lane),
                           dtype=jnp.int32)
        clv, scaler = kernels.traverse(dm, block_part, tips, clv, scaler,
                                       tv, self.scale_exp, self.ntips,
                                       grid)
        return kernels.per_rate_site_lnls(dm, block_part, tips, clv,
                                          scaler, p_idx, q_idx, z, grid,
                                          self.scale_exp, self.ntips)

    def rate_scan(self, entries: List[TraversalEntry], p_num: int,
                  q_num: int, z: Sequence[float],
                  grid: np.ndarray) -> np.ndarray:
        """Per-site lnL under each candidate rate: grid [B, lane, G] ->
        [B, lane, G].  entries must be a FULL traversal for branch (p,q).

        TPU-native replacement for the reference's per-site
        `evaluatePartialGeneric` scan (SURVEY §7.3(5)).
        """
        assert self.psr
        obs.inc("engine.dispatch_count")
        obs.inc("engine.traversal_entries", len(entries))
        tv = self._traversal_arrays(entries)
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots), dtype=self.dtype)
        # `grid` is GLOBAL [B, lane, G] (every process builds the same
        # one from the host-global patrat); a selective-loading process
        # contributes only its block window to the sharded device array.
        grid_dev = self._put_blocks(
            self._local_block_window(np.asarray(grid, dtype=self.dtype)),
            lambda s: s.sites)
        with obs.device_span("engine:rate_scan",
                             args={"grid": int(grid.shape[-1])}):
            out = self._jit_rate_scan(
                self.tips, tv, jnp.int32(self._gidx(p_num)),
                jnp.int32(self._gidx(q_num)), zv, grid_dev, self.models,
                self.block_part)
        if self.sharding is not None and jax.process_count() > 1:
            # Multi-host: the per-site scan result is block-sharded
            # across processes; the host-side PSR crawl/categorization
            # needs the global view on EVERY process (deterministic, so
            # all processes categorize identically — the reference
            # gathers to rank 0 and scatters back instead,
            # `optimizeModel.c:2135-2254`; an allgather of the same
            # payload replaces both legs).
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(out, tiled=True))
        return np.asarray(out)

    # -- branch derivatives ------------------------------------------------

    def _sumtable_impl(self, buf, scaler, aux, p_idx, q_idx, dm,
                       block_part, tips):
        xp, _ = self._gather(buf, aux, scaler, p_idx, tips)
        xq, _ = self._gather(buf, aux, scaler, q_idx, tips)
        return kernels.sumtable(dm, block_part, xp, xq)

    def _derivs_impl(self, st, z, dm, block_part, weights, sr):
        return kernels.nr_derivatives(dm, block_part, weights,
                                      st, z, self.num_branch_slots, sr,
                                      axis_name=self._axis_name)

    def make_sumtable(self, p_num: int, q_num: int) -> jax.Array:
        obs.inc("engine.dispatch_count")
        buf, aux = self._state()
        with obs.device_span("engine:sumtable"):
            return self._jit_sumtable(buf, self.scaler, aux,
                                      jnp.int32(self._gidx(p_num)),
                                      jnp.int32(self._gidx(q_num)),
                                      self.models, self.block_part,
                                      self.tips)

    def branch_derivatives(self, st: jax.Array, z: Sequence[float]):
        obs.inc("engine.dispatch_count")
        zv = jnp.asarray(_z_slots(z, self.num_branch_slots), dtype=self.dtype)
        with obs.device_span("engine:derivs"):
            d1, d2 = self._jit_derivs(st, zv, self.models, self.block_part,
                                      self.weights, self.site_rates)
        return np.asarray(d1), np.asarray(d2)

    # -- whole-tree analytic gradients (ops/gradient.py) --------------------
    # One pre-order (outroot) pass over the reversed wave schedule plus
    # one batched edge-derivative contraction gives (d1, d2) for ALL
    # 2n-3 branches in a single dispatch — the O(n)->O(1) replacement
    # for the per-branch sumtable+Newton round trips that dominate
    # smoothTree/treeEvaluate on large trees (ROADMAP §5).

    def grad_eligible(self) -> bool:
        """The gradient pass runs on the dense CLV arena (any tier's
        post-order output); -S SEV pools keep the per-branch path."""
        return not self.save_memory

    def _grad_structure(self, flat):
        from examl_tpu.ops import gradient
        gs = self._grad_structs.get(flat.topo_key)
        if gs is not None:
            self._grad_structs.move_to_end(flat.topo_key)
            return gs
        gs = gradient.build_structure(flat, self.wave_width)
        self._grad_structs[flat.topo_key] = gs
        while len(self._grad_structs) > self._grad_structs_cap:
            self._grad_structs.popitem(last=False)
        return gs

    def _grad_impl(self, clv, scaler, p_row, q_row, p_gidx, q_gidx, tvp,
                   ex_rows, ey_gidx, ez, dm, block_part, weights, tips,
                   sr):
        """Traced gradient program: outroot-arena init at the root edge
        (out(p) = D(q), out(q) = D(p)), the reverse-wave sibling-combine
        pass, then the chunked all-edges derivative contraction.  The
        outroot arena lives only inside this program; clv/scaler are
        read-only (NOT donated — the engine keeps serving them)."""
        from examl_tpu.ops import gradient
        out = jnp.zeros((2 * self.ntips - 1, self.B, self.lane, self.R,
                         self.K), dtype=self.dtype)
        dq, _ = kernels.gather_child(tips, clv, scaler, q_gidx, self.ntips)
        dp, _ = kernels.gather_child(tips, clv, scaler, p_gidx, self.ntips)
        out = out.at[p_row].set(dq.astype(out.dtype))
        out = out.at[q_row].set(dp.astype(out.dtype))
        out = kernels.outroot_pass(dm, block_part, tips, clv, scaler, out,
                                   tvp, self.scale_exp, self.ntips, sr)
        return gradient.edge_gradients(
            dm, block_part, weights, tips, clv, scaler, out, ex_rows,
            ey_gidx, ez, self.num_branch_slots, self.ntips, sr)

    def whole_tree_gradients(self, flat, root_z):
        """(d1, d2) [E, C]: lnL gradient and curvature w.r.t. lz = log z
        for every branch of the FULL traversal `flat`, in ONE dispatch.

        Edge order: edge 0 is the traversal's root edge; edges 1+2i /
        2+2i are entry i's left / right child branches (flat order).
        PRECONDITION: the CLV arena is current for `flat` (a
        `run_traversal(flat, full=True)` — any tier — just ran);
        `root_z` is the root edge's branch vector.

        The jit key is shape-only — ("grad", steps, width, chunks), all
        bucketed — so like the scan tier this is a tiny closed program
        family and topology ships as runtime data.
        """
        from examl_tpu.ops import gradient
        from examl_tpu.ops.kernels import OutrootTraversal
        if not self.grad_eligible():
            raise RuntimeError("whole-tree gradients need the dense CLV "
                               "arena (-S SEV pools keep the per-branch "
                               "Newton path)")
        gs = self._grad_structure(flat)
        with obs.timer("host_schedule"):
            pre, ex_rows, ey_gidx, ez = gradient.grad_arrays(
                gs, flat, self.row_map, self.num_branch_slots, root_z)
        key = ("grad", _bucket_len(gs.n_steps), _next_pow2(gs.wave_w),
               _next_pow2(gs.n_chunks))
        fn = self.cache_get(key)
        if fn is None:
            fn = self.cache_put(key, jax.jit(self._grad_impl))
        obs.inc("engine.dispatch_count")
        obs.inc("engine.grad_pass_dispatches")
        itemsize = np.dtype(self.storage_dtype).itemsize
        tip_children = int((np.asarray(flat.left) <= self.ntips).sum()
                           + (np.asarray(flat.right) <= self.ntips).sum())
        nbytes = _traffic.bytes_per_grad_pass(
            gs.n, tip_children, gs.n_edges, self._patterns_true, self.R,
            self.K, itemsize)
        compiles0 = obs.registry().counter("engine.compile_count")
        p, q = gs.roots
        up_row, lrow, rrow, lg, rg, zu, zl, zr = pre
        tvp = OutrootTraversal(
            up_row=jnp.asarray(up_row), lrow=jnp.asarray(lrow),
            rrow=jnp.asarray(rrow), left=jnp.asarray(lg),
            right=jnp.asarray(rg),
            zu=jnp.asarray(zu, dtype=self.dtype),
            zl=jnp.asarray(zl, dtype=self.dtype),
            zr=jnp.asarray(zr, dtype=self.dtype))
        t0 = time.perf_counter()
        with obs.device_span("engine:grad_pass",
                             args={"edges": gs.n_edges,
                                   "steps": gs.n_steps}):
            d1, d2 = fn(self.clv, self.scaler,
                        jnp.int32(p - 1), jnp.int32(q - 1),
                        jnp.int32(self._gidx(p)), jnp.int32(self._gidx(q)),
                        tvp, jnp.asarray(ex_rows), jnp.asarray(ey_gidx),
                        jnp.asarray(ez, dtype=self.dtype), self.models,
                        self.block_part, self.weights, self.tips,
                        self.site_rates)
            # Blocking by contract: the host-side batched Newton update
            # consumes d1/d2 — this sync IS the gradient measurement
            # (the registered seam, like the trav-eval family).
            d1 = np.asarray(d1, dtype=np.float64)
            d2 = np.asarray(d2, dtype=np.float64)
        dt = time.perf_counter() - t0
        obs.observe("engine.grad_pass", dt)
        # The gradient program is one device op whose scan walks
        # n_steps + n_chunks dependent steps — the launch-floor term.
        self._last_dispatch_ops = gs.n_steps + gs.n_chunks
        self._record_traffic(
            nbytes, "grad", wall_s=dt,
            window=(obs.registry().counter("engine.compile_count")
                    == compiles0))
        return d1[:gs.n_edges], d2[:gs.n_edges]


