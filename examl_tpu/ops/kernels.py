"""Device kernels of the likelihood engine (jnp; Pallas variants can slot in).

TPU-native re-design of the reference's hand-vectorized kernel inventory
(ExaML `newviewGenericSpecial.c`, `evaluateGenericSpecial.c`,
`makenewzGenericSpecial.c`, SSE3/AVX/MIC backends): ONE shape-polymorphic
kernel set over a packed site axis, with the state count (2/4/20), rate
count and partition count as static dimensions.  All functions are pure and
jit/vmap/shard-safe; the site axis is laid out as [B blocks x lane] so
per-partition P matrices are gathered per block (see parallel/packing.py).

Index conventions (einsum letters):
  b block, l lane, r rate category, j eigen index, a/k state, m partition,
  n CLV row, e traversal entry, c branch slot (per-partition branch lengths).

CLV scaling follows the reference scheme (`newviewGenericSpecial.c:604-616`):
when every entry of a site's CLV drops below 2^-E the site is multiplied by
2^E and an integer per-(node, site) scaler increments; lnL adds
scaler * log(2^-E).  E is 256 for float64 (as the reference) and 64 for
float32.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

# All contractions run at full input precision: on TPU the MXU otherwise
# truncates f32 operands to bf16, which costs ~4 decimal digits of CLV
# accuracy — far outside the reference-parity budget.  HIGHEST keeps f32
# einsums exact (multi-pass) and is a no-op for f64/CPU.
einsum = functools.partial(jnp.einsum, precision=jax.lax.Precision.HIGHEST)


def _acc_dtype(dtype) -> jnp.dtype:
    """Accumulator dtype for site sums: f64 when x64 is live, else f32.

    Per-site values are fine in f32, but summing O(10^5)-magnitude lnL over
    many sites in f32 loses ~1e-2 absolute; the (cheap, elementwise) final
    reductions therefore accumulate in f64 whenever available.
    """
    if jnp.dtype(dtype) == jnp.float64 or jax.config.jax_enable_x64:
        return jnp.dtype(jnp.float64)
    return jnp.dtype(dtype)


class DeviceModels(NamedTuple):
    """Stacked per-partition model tensors for one state-count bucket.

    Eigensystems and frequencies carry a rate-category axis so LG4M/LG4X
    (one matrix per category, reference `makeP_FlexLG4`) and plain models
    (identical slices across R) share one kernel set.
    """
    eign: jax.Array         # [M, R, K]  negated eigenvalues, [...,0] == 0
    ev: jax.Array           # [M, R, K, K] right eigenvectors (columns)
    ei: jax.Array           # [M, R, K, K] left eigenvectors (rows)
    freqs: jax.Array        # [M, R, K]
    gamma_rates: jax.Array  # [M, R]
    rate_weights: jax.Array  # [M, R] category weights (1/R for GAMMA)
    part_branch: jax.Array  # [M] int32: branch slot per partition (0 if linked)


class Traversal(NamedTuple):
    """Fixed-size padded traversal descriptor (host-built).

    Entries are wave-scheduled (`Tree.schedule_waves`): axis 0 runs over
    dependency waves executed sequentially, axis 1 over the independent
    entries of a wave executed as one batched newview.  `parent` indexes
    INNER CLV rows (node number - ntips - 1); `left`/`right` are 0-based
    node indices (tips < ntips resolve against the tip-code table, the
    reference's yVector+tipVector scheme — tip CLVs are never stored).
    Padding entries point children at node 0 and the parent at the
    scratch row.
    """
    parent: jax.Array       # [L, W] int32 inner CLV row
    left: jax.Array         # [L, W] int32 node index (tip or inner)
    right: jax.Array        # [L, W] int32
    zl: jax.Array           # [L, W, C] branch z to left child
    zr: jax.Array           # [L, W, C]


class TipState(NamedTuple):
    """Device-resident tip data: packed codes + indicator lookup table."""
    codes: jax.Array        # [ntips, B, lane] uint8/int32 state codes
    table: jax.Array        # [num_codes, K] 0/1 indicator vectors


def gather_child(tips: TipState, clv: jax.Array, scaler: jax.Array,
                 idx: jax.Array, ntips: int):
    """CLV + scaler of child nodes given 0-based node indices idx [...].

    Tips (idx < ntips) materialize their indicator vectors from the code
    table on the fly (scaler 0); inner nodes read the stored CLV row
    (idx - ntips).  Both gathers run and a select picks — the tip gather
    is a uint8 lookup, negligible next to the CLV read it replaces.
    """
    R = clv.shape[3]
    idx = jnp.asarray(idx)          # plain ints (static callers) included
    is_tip = idx < ntips
    tip_idx = jnp.clip(idx, 0, ntips - 1)
    codes = tips.codes[tip_idx]                      # [..., B, lane]
    tip_clv = tips.table[codes]                      # [..., B, lane, K]
    tip_clv = jnp.broadcast_to(
        tip_clv[..., :, :, None, :],
        tip_clv.shape[:-1] + (R, tip_clv.shape[-1]))
    inner_idx = jnp.clip(idx - ntips, 0, clv.shape[0] - 1)
    # astype: the arena may store CLVs in a narrower dtype (bf16 storage
    # tier, EXAML_CLV_DTYPE) — the cast happens after the (halved) HBM
    # read and is a no-op when storage == compute.
    inner_clv = clv[inner_idx].astype(tips.table.dtype)
    sel = is_tip[..., None, None, None, None]
    x = jnp.where(sel, tip_clv, inner_clv)
    sc = jnp.where(is_tip[..., None, None], 0, scaler[inner_idx])
    return x, sc


def default_scale_exponent(dtype, backend: str | None = None) -> int:
    """Rescale threshold exponent E (threshold 2^-E, multiplier 2^E).

    float64 on CPU uses the reference's 256.  On TPU float64 is emulated as
    float-float pairs whose exponent range is float32's (underflow near
    2^-126), and float32 anywhere has the same floor — both need rescaling
    long before products of two CLVs approach 2^-126, so use 32.
    """
    if backend is None:
        import jax
        backend = jax.default_backend()
    if jnp.dtype(dtype) == jnp.float64 and backend == "cpu":
        return 256
    return 32


def scale_constants(dtype, scale_exp: int):
    e = scale_exp
    two_e = jnp.asarray(2.0, dtype) ** e
    minlik = jnp.asarray(2.0, dtype) ** (-e)
    log_min = -e * jnp.log(jnp.asarray(2.0, dtype))
    return minlik, two_e, log_min


def branch_decay(models: DeviceModels, z: jax.Array) -> jax.Array:
    """d[m, r, j] = exp(eign_rj * rate_r * log z_m), the eigenvalue decay.

    z: [C] per-branch-slot values; each partition selects its slot.
    Mirrors reference `makeP`/`makeP_FlexLG4`
    (`newviewGenericSpecial.c:78-206`).
    """
    zm = z[models.part_branch]                              # [M]
    lz = jnp.log(zm)
    return jnp.exp(models.eign
                   * models.gamma_rates[:, :, None]
                   * lz[:, None, None])                     # [M, R, K]


def p_matrices(models: DeviceModels, z: jax.Array) -> jax.Array:
    """P[m, r, a, k] = sum_j ev[r,a,j] d[r,j] ei[r,j,k] per partition."""
    d = branch_decay(models, z)
    return einsum("mraj,mrj,mrjk->mrak", models.ev, d, models.ei)


def apply_p(pmat: jax.Array, block_part: jax.Array, x: jax.Array) -> jax.Array:
    """y[b,l,r,a] = sum_k P[part(b),r,a,k] * x[b,l,r,k]."""
    pb = pmat[block_part]                                   # [B, R, K, K]
    return einsum("brak,blrk->blra", pb, x)


def p_matrices_wave(models: DeviceModels, z: jax.Array) -> jax.Array:
    """P[w, m, r, a, k] for one wave of branch vectors z [W, C]."""
    d = jax.vmap(lambda zz: branch_decay(models, zz))(z)    # [W, M, R, K]
    return einsum("mraj,wmrj,mrjk->wmrak", models.ev, d, models.ei)


def psr_decay(models: DeviceModels, block_part: jax.Array,
              site_rates: jax.Array, z: jax.Array) -> jax.Array:
    """Per-site eigenvalue decay d[b,l,r,j] = exp(eign_j * rate_blr * log z).

    The PSR (CAT) analogue of `branch_decay`: every site carries its own
    rate multiplier (reference per-site `patrat`/`rateCategory`,
    `optimizeModel.c:1792-2507`), so the transition matrix differs per
    site and is never materialized — newview/evaluate apply it in
    factorized form (EI contraction, decay scaling, EV contraction).
    site_rates: [B, lane, R] (R = 1 in normal PSR compute; R = G during
    the batched rate-grid scan).
    """
    zb = z[models.part_branch][block_part]                  # [B]
    lz = jnp.log(zb)
    # PSR models are single-category; use the category-0 eigensystem.
    eb = models.eign[block_part][:, 0, :]                   # [B, K]
    return jnp.exp(eb[:, None, None, :]
                   * site_rates[:, :, :, None]
                   * lz[:, None, None, None])               # [B, lane, R, K]


def apply_p_factorized(models: DeviceModels, block_part: jax.Array,
                       d: jax.Array, x: jax.Array) -> jax.Array:
    """y = EV · (d * (EI · x)) with per-site decay d [..., B, lane, R, K].

    Equivalent to applying P(z, r_site) without building per-site P
    matrices; the two contractions are MXU matmuls over the state axis.
    """
    eib = models.ei[block_part][:, 0]                       # [B, K, K] (PSR)
    evb = models.ev[block_part][:, 0]
    u = einsum("bjk,...blrk->...blrj", eib, x)
    u = u * d
    return einsum("baj,...blrj->...blra", evb, u)


def newview_wave(models: DeviceModels, block_part: jax.Array,
                 xl: jax.Array, xr: jax.Array,
                 zl: jax.Array, zr: jax.Array, scale_exp: int,
                 site_rates=None):
    """Combine child CLVs into parent CLVs for one wave of W entries.

    xl, xr: [W, B, lane, R, K]; zl, zr: [W, C].
    Returns (clv [W,B,lane,R,K], scale_inc [W,B,lane]).
    Reference semantics: `newviewGAMMA_FLEX` (`newviewGenericSpecial.c:430-682`)
    and the CAT kernels when site_rates is given, batched over independent
    traversal entries.
    """
    if site_rates is None:
        pl = p_matrices_wave(models, zl)[:, block_part]     # [W, B, R, K, K]
        pr = p_matrices_wave(models, zr)[:, block_part]
        yl = einsum("wbrak,wblrk->wblra", pl, xl)
        yr = einsum("wbrak,wblrk->wblra", pr, xr)
    else:
        dl = jax.vmap(lambda zz: psr_decay(models, block_part, site_rates,
                                           zz))(zl)         # [W, B, l, R, K]
        dr = jax.vmap(lambda zz: psr_decay(models, block_part, site_rates,
                                           zz))(zr)
        yl = apply_p_factorized(models, block_part, dl, xl)
        yr = apply_p_factorized(models, block_part, dr, xr)
    v = yl * yr
    minlik, two_e, _ = scale_constants(v.dtype, scale_exp)
    vmax = jnp.max(jnp.abs(v), axis=(3, 4))                 # [W, B, lane]
    needs = vmax < minlik
    v = jnp.where(needs[:, :, :, None, None], v * two_e, v)
    return v, needs.astype(jnp.int32)


def traverse(models: DeviceModels, block_part: jax.Array, tips: TipState,
             clv: jax.Array, scaler: jax.Array, tv: Traversal,
             scale_exp: int, ntips: int, site_rates=None):
    """Execute a wave-scheduled traversal: lax.scan over waves, each wave a
    batched newview over its independent entries.

    clv: [Ninner, B, lane, R, K]; scaler: [Ninner, B, lane] int32 (inner
    nodes + one scratch row; tip children materialize from `tips`).
    Padding entries write to the scratch row (host sets parent=Ninner-1);
    within a wave the scatter indices are unique except for scratch
    duplicates, whose value is never read.
    Reference: `newviewIterative` (`newviewGenericSpecial.c:917-1515`).
    """
    def body(carry, e):
        clv, scaler = carry
        parent, left, right, zl, zr = e
        xl, sl = gather_child(tips, clv, scaler, left, ntips)
        xr, sr = gather_child(tips, clv, scaler, right, ntips)
        v, inc = newview_wave(models, block_part, xl, xr,
                              zl, zr, scale_exp, site_rates)
        sc = sl + sr + inc                                  # [W, B, lane]
        clv = clv.at[parent].set(v.astype(clv.dtype),
                                 unique_indices=False)
        scaler = scaler.at[parent].set(sc, unique_indices=False)
        return (clv, scaler), None

    (clv, scaler), _ = jax.lax.scan(
        body, (clv, scaler),
        (tv.parent, tv.left, tv.right, tv.zl, tv.zr))
    return clv, scaler


def gather_child_pooled(tips: TipState, pool: jax.Array,
                        slot_read: jax.Array, scaler: jax.Array,
                        idx: jax.Array, ntips: int):
    """SEV variant of `gather_child`: inner CLVs live in a block-cell pool.

    pool: [S, lane, R, K]; slot_read: [rows, B] int32 mapping (row, block)
    to a pool cell, with all-gap cells mapped to the shared constant
    all-ones cell 0 — the TPU-native form of the reference's single shared
    `gapColumn` CLV per node (`newviewGenericSpecial.c:139-160`).
    """
    R = pool.shape[2]
    idx = jnp.asarray(idx)
    is_tip = idx < ntips
    tip_idx = jnp.clip(idx, 0, ntips - 1)
    codes = tips.codes[tip_idx]                      # [..., B, lane]
    tip_clv = tips.table[codes]                      # [..., B, lane, K]
    tip_clv = jnp.broadcast_to(
        tip_clv[..., :, :, None, :],
        tip_clv.shape[:-1] + (R, tip_clv.shape[-1]))
    row = jnp.clip(idx - ntips, 0, slot_read.shape[0] - 1)
    cells = slot_read[row]                           # [..., B]
    inner_clv = pool[cells].astype(tips.table.dtype)  # [..., B, lane, R, K]
    sel = is_tip[..., None, None, None, None]
    x = jnp.where(sel, tip_clv, inner_clv)
    sc = jnp.where(is_tip[..., None, None], 0, scaler[row])
    return x, sc


def traverse_pooled(models: DeviceModels, block_part: jax.Array,
                    tips: TipState, pool: jax.Array, slot_read: jax.Array,
                    slot_write: jax.Array, scaler: jax.Array,
                    tv: Traversal, scale_exp: int, ntips: int,
                    site_rates=None):
    """SEV traversal: like `traverse`, but CLV cells live in the pool.

    slot_write maps all-gap (row, block) cells to a scratch cell whose
    content is never read; their value is the constant cell 0 on the read
    side, so all-gap subtrees cost one shared cell of memory — the
    reference's `-S` design (`axml.c:2152-2171`, `_GAPPED_SAVE` kernels)
    re-expressed as static-shape pool indirection.
    """
    def body(carry, e):
        pool, scaler = carry
        parent, left, right, zl, zr = e
        xl, sl = gather_child_pooled(tips, pool, slot_read, scaler, left,
                                     ntips)
        xr, sr = gather_child_pooled(tips, pool, slot_read, scaler, right,
                                     ntips)
        v, inc = newview_wave(models, block_part, xl, xr,
                              zl, zr, scale_exp, site_rates)
        sc = sl + sr + inc                               # [W, B, lane]
        cells = slot_write[parent]                       # [W, B]
        pool = pool.at[cells].set(v.astype(pool.dtype),
                                  unique_indices=False)
        scaler = scaler.at[parent].set(sc, unique_indices=False)
        return (pool, scaler), None

    (pool, scaler), _ = jax.lax.scan(
        body, (pool, scaler),
        (tv.parent, tv.left, tv.right, tv.zl, tv.zr))
    return pool, scaler


class OutrootTraversal(NamedTuple):
    """Fixed-size padded PRE-ORDER traversal descriptor (host-built by
    ops/gradient.py): the post-order wave schedule executed in REVERSE
    wave order, each entry emitting the root-directed (outroot)
    partials of its two children.  `up_row` indexes the outroot arena
    (node number - 1; every node has a row, the last row is scratch);
    `left`/`right` are gather indices against the post-order CLV arena
    (tips by code slot, inner by ntips + arena row, exactly
    `gather_child`'s convention).  `zu` is the branch ABOVE the entry's
    parent node (the root edge z for the two root-adjacent entries).
    Padding entries read and write the scratch row."""
    up_row: jax.Array       # [L, W] int32 outroot-arena row of the parent
    lrow: jax.Array         # [L, W] int32 outroot row written for left
    rrow: jax.Array         # [L, W] int32 outroot row written for right
    left: jax.Array         # [L, W] int32 gather index of left child
    right: jax.Array        # [L, W] int32 gather index of right child
    zu: jax.Array           # [L, W, C] branch above the parent
    zl: jax.Array           # [L, W, C]
    zr: jax.Array           # [L, W, C]


def outroot_wave(models: DeviceModels, block_part: jax.Array,
                 xu: jax.Array, xl: jax.Array, xr: jax.Array,
                 zu: jax.Array, zl: jax.Array, zr: jax.Array,
                 scale_exp: int, site_rates=None):
    """Sibling-combine for one wave of W pre-order entries.

    xu: the parent's outroot partial [W, B, lane, R, K] (complement of
    the parent's subtree, located at the grandparent's end of the
    parent's upper branch); xl, xr: the children's post-order CLVs.
    Returns (out_l, out_r): out_l = (P(zu) xu) * (P(zr) xr) is the
    complement of the LEFT child's subtree located at the parent — the
    mirror image of `newview_wave`'s child combine, with the sibling's
    down partial standing in for one child and the transported outroot
    partial for the other (Ji et al. 2303.04390's pre-order recursion;
    BEAGLE 4.1's edge-derivative pre-order buffers).

    Rescaling applies the same threshold/multiplier discipline as
    `newview_wave` but tracks NO counts: every edge-gradient consumer
    is a dsite/lsite ratio in which per-site scale factors cancel
    exactly (`nr_derivatives` never reads scalers), so keeping the
    values in floating range is sufficient.
    """
    if site_rates is None:
        pu = p_matrices_wave(models, zu)[:, block_part]     # [W, B, R, K, K]
        pl = p_matrices_wave(models, zl)[:, block_part]
        pr = p_matrices_wave(models, zr)[:, block_part]
        yu = einsum("wbrak,wblrk->wblra", pu, xu)
        yl = einsum("wbrak,wblrk->wblra", pl, xl)
        yr = einsum("wbrak,wblrk->wblra", pr, xr)
    else:
        du = jax.vmap(lambda zz: psr_decay(models, block_part, site_rates,
                                           zz))(zu)          # [W, B, l, R, K]
        dl = jax.vmap(lambda zz: psr_decay(models, block_part, site_rates,
                                           zz))(zl)
        dr = jax.vmap(lambda zz: psr_decay(models, block_part, site_rates,
                                           zz))(zr)
        yu = apply_p_factorized(models, block_part, du, xu)
        yl = apply_p_factorized(models, block_part, dl, xl)
        yr = apply_p_factorized(models, block_part, dr, xr)
    minlik, two_e, _ = scale_constants(yu.dtype, scale_exp)

    def rescale(v):
        vmax = jnp.max(jnp.abs(v), axis=(3, 4))             # [W, B, lane]
        return jnp.where((vmax < minlik)[:, :, :, None, None], v * two_e, v)

    return rescale(yu * yr), rescale(yu * yl)


def outroot_pass(models: DeviceModels, block_part: jax.Array,
                 tips: TipState, clv: jax.Array, scaler: jax.Array,
                 out: jax.Array, tv: OutrootTraversal, scale_exp: int,
                 ntips: int, site_rates=None) -> jax.Array:
    """Execute a pre-order traversal: lax.scan over reversed waves, each
    wave a batched `outroot_wave` over its independent entries — the
    exact mirror of `traverse`, filling the outroot arena `out`
    [2*ntips-1, B, lane, R, K] (rows by node number - 1, last row
    scratch) instead of the CLV arena.  `out` must arrive with the two
    root rows initialized (out[p-1] = D(q), out[q-1] = D(p)); `clv` and
    `scaler` are read-only (the post-order partials)."""
    def body(carry, e):
        out = carry
        up_row, lrow, rrow, left, right, zu, zl, zr = e
        xu = out[up_row]
        xl, _ = gather_child(tips, clv, scaler, left, ntips)
        xr, _ = gather_child(tips, clv, scaler, right, ntips)
        ol, orr = outroot_wave(models, block_part, xu, xl, xr,
                               zu, zl, zr, scale_exp, site_rates)
        out = out.at[lrow].set(ol.astype(out.dtype), unique_indices=False)
        out = out.at[rrow].set(orr.astype(out.dtype), unique_indices=False)
        return out, None

    out, _ = jax.lax.scan(
        body, out, (tv.up_row, tv.lrow, tv.rrow, tv.left, tv.right,
                    tv.zu, tv.zl, tv.zr))
    return out


def site_likelihoods(models: DeviceModels, block_part: jax.Array,
                     xp: jax.Array, xq: jax.Array, z: jax.Array,
                     site_rates=None):
    """Per-site likelihood L[b,l] at the root branch (p,q) with branch z.

    L = sum_r w_r sum_k f_k * xp_k * (P(z) xq)_k
    Reference: `evaluateGAMMA_FLEX` (`evaluateGenericSpecial.c:154-231`) or
    the CAT evaluate kernels when site_rates is given.
    """
    if site_rates is None:
        y = apply_p(p_matrices(models, z), block_part, xq)  # [B,l,R,K]
    else:
        d = psr_decay(models, block_part, site_rates, z)
        y = apply_p_factorized(models, block_part, d, xq)
    fb = models.freqs[block_part]                           # [B, R, K]
    wb = models.rate_weights[block_part]                    # [B, R]
    return einsum("brk,br,blrk,blrk->bl", fb, wb, xp, y)


def per_rate_site_lnls(models: DeviceModels, block_part: jax.Array,
                       tips: TipState, clv: jax.Array, scaler: jax.Array,
                       p_idx, q_idx, z: jax.Array, site_rates: jax.Array,
                       scale_exp: int, ntips: int):
    """Per-site, per-rate-candidate log likelihood [B, lane, R].

    The batched on-device replacement for the reference's per-site rate
    scan (`evaluatePartialGeneric` called once per site per trial rate,
    `optimizeModel.c:1792-1922`): one traversal per rate-grid chunk
    produces every site's lnL under every candidate rate at once.
    """
    xp, sp = gather_child(tips, clv, scaler, p_idx, ntips)
    xq, sq = gather_child(tips, clv, scaler, q_idx, ntips)
    d = psr_decay(models, block_part, site_rates, z)
    y = apply_p_factorized(models, block_part, d, xq)
    fb = models.freqs[block_part][:, 0]                     # [B, K] (PSR)
    lsite = einsum("bk,blrk,blrk->blr", fb, xp, y)          # [B, lane, R]
    acc = _acc_dtype(lsite.dtype)
    _, _, log_min = scale_constants(acc, scale_exp)
    sc = (sp + sq).astype(acc)                              # [B, lane]
    lsite = jnp.maximum(lsite, jnp.finfo(lsite.dtype).tiny)
    return jnp.log(lsite).astype(acc) + sc[:, :, None] * log_min


def root_log_likelihood(models: DeviceModels, block_part: jax.Array,
                        weights: jax.Array, tips: TipState,
                        clv: jax.Array, scaler: jax.Array,
                        p_idx, q_idx, z: jax.Array, num_parts: int,
                        scale_exp: int, ntips: int, site_rates=None):
    """Per-partition log likelihoods [M] after a traversal.

    weights: [B, lane] pattern weights (0 on padding); p_idx/q_idx are
    0-based node indices (tip or inner).
    Reference: `evaluateGeneric` + the lnL Allreduce
    (`evaluateGenericSpecial.c:897-1001`); here the cross-device sum is the
    segment/jnp sum over the sharded block axis (XLA inserts the collective).
    """
    xp, sp = gather_child(tips, clv, scaler, p_idx, ntips)
    xq, sq = gather_child(tips, clv, scaler, q_idx, ntips)
    return root_log_likelihood_from(models, block_part, weights, xp, sp,
                                    xq, sq, z, num_parts, scale_exp,
                                    site_rates)


def root_log_likelihood_from(models: DeviceModels, block_part: jax.Array,
                             weights: jax.Array, xp, sp, xq, sq,
                             z: jax.Array, num_parts: int, scale_exp: int,
                             site_rates=None, axis_name=None):
    """root_log_likelihood over pre-gathered root CLVs (pooled/SEV path).

    axis_name: set when tracing under shard_map (SEV x sharding) — the
    segment sum then only covers the device-local blocks, so the
    cross-device half of the reference's lnL Allreduce
    (`evaluateGenericSpecial.c:968-973`) is an explicit psum here
    (GSPMD inserts it automatically on the dense path; shard_map does
    not)."""
    lsite = site_likelihoods(models, block_part, xp, xq, z, site_rates)
    acc = _acc_dtype(lsite.dtype)
    _, _, log_min = scale_constants(acc, scale_exp)
    sc = (sp + sq).astype(acc)
    lsite = jnp.maximum(lsite, jnp.finfo(lsite.dtype).tiny)
    site_lnl = weights.astype(acc) * (jnp.log(lsite).astype(acc)
                                      + sc * log_min)       # [B, lane]
    block_lnl = jnp.sum(site_lnl, axis=1)                   # [B]
    out = jax.ops.segment_sum(block_lnl, block_part, num_segments=num_parts)
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


def newton_raphson_branch(models: DeviceModels, block_part: jax.Array,
                          weights: jax.Array, st: jax.Array, z0: jax.Array,
                          maxiters0: jax.Array, conv0: jax.Array,
                          num_slots: int, site_rates=None, axis_name=None):
    """Branch-length Newton-Raphson to convergence, fully on device.

    Replaces the reference's host-driven NR loop with one Allreduce per
    iteration (`topLevelMakenewz`, `makenewzGenericSpecial.c:1133-1349`)
    by a single `lax.while_loop` whose body computes the derivative sums
    (with their cross-device psum via the sharded-site reduction) — the
    fusion SURVEY §7.3(2) calls out as the key latency fix on TPU.

    Semantics per branch slot (mirroring the reference, including the
    bad-curvature branch-shortening z <- 0.37 z + 0.63, the 0.25 zprev +
    0.75 step cap, and the give-up-after-(maxiter+20) reset to z0):
    iterate z <- z * exp(-lnL'/lnL'') until |z - zprev| <= zstep.
    """
    from examl_tpu.constants import ZMAX, ZMIN

    acc = _acc_dtype(st.dtype)
    z0a = z0.astype(acc)
    zmin = jnp.asarray(ZMIN, acc)
    zmax = jnp.asarray(ZMAX, acc)

    def derivs(z):
        d1, d2 = nr_derivatives(models, block_part, weights, st,
                                z.astype(st.dtype), num_slots, site_rates,
                                axis_name)
        return d1.astype(acc), d2.astype(acc)

    def cond(s):
        return ~jnp.all(s[4])

    def body(s):
        z, zprev, zstep, maxiters, outer, curvat = s
        fresh = ~outer & curvat
        zprev = jnp.where(fresh, z, zprev)
        zstep = jnp.where(fresh, (1.0 - ZMAX) * z + ZMIN, zstep)
        curvat = jnp.where(fresh, False, curvat)
        z = jnp.clip(z, zmin, zmax)
        d1, d2 = derivs(z)
        active = ~outer & ~curvat
        bad = active & (d2 >= 0.0) & (z < zmax)
        z = jnp.where(bad, 0.37 * z + 0.63, z)
        zprev = jnp.where(bad, z, zprev)
        curvat = jnp.where(active & ~bad, True, curvat)
        step = curvat & ~outer
        tantmp = jnp.where(d2 < 0.0, -d1 / jnp.where(d2 < 0.0, d2, 1.0),
                           jnp.inf)
        cap = 0.25 * zprev + 0.75
        znr = jnp.where(tantmp < 100.0,
                        jnp.maximum(z * jnp.exp(jnp.minimum(tantmp, 100.0)),
                                    zmin),
                        cap)
        znr = jnp.minimum(znr, cap)
        z2 = jnp.where(step & (d2 < 0.0), znr, z)
        z2 = jnp.minimum(z2, zmax)
        maxiters = jnp.where(step, maxiters - 1, maxiters)
        moving = jnp.abs(z2 - zprev) > zstep
        gave_up = moving & (maxiters < -20)
        z2 = jnp.where(step & gave_up, z0a, z2)
        outer = jnp.where(step, ~moving | gave_up, outer)
        return (z2, zprev, zstep, maxiters, outer, curvat)

    init = (z0a, z0a, jnp.zeros_like(z0a), maxiters0, conv0,
            jnp.ones_like(conv0))
    z, *_ = jax.lax.while_loop(cond, body, init)
    return z


def sumtable(models: DeviceModels, block_part: jax.Array,
             xp: jax.Array, xq: jax.Array) -> jax.Array:
    """st[b,l,r,j] = (sum_k f_rk xp_k ev_r[k,j]) * (sum_k ei_r[j,k] xq_k).

    With this table L(lz) = sum_j st_j exp(eign_rj rate_r lz) per site, so
    branch derivatives w.r.t. lz = log z are cheap per NR iteration.
    Reference: `makenewzIterative` sum kernels
    (`makenewzGenericSpecial.c:251-326`).
    """
    evb = models.ev[block_part]                             # [B, R, K, K]
    eib = models.ei[block_part]
    fb = models.freqs[block_part]                           # [B, R, K]
    ap = einsum("brk,blrk,brkj->blrj", fb, xp, evb)
    bq = einsum("brjk,blrk->blrj", eib, xq)
    return ap * bq


def nr_derivatives(models: DeviceModels, block_part: jax.Array,
                   weights: jax.Array, st: jax.Array, z: jax.Array,
                   num_slots: int, site_rates=None, axis_name=None):
    """(lnL', lnL'') w.r.t. lz summed over sites, per branch slot [C].

    Reference: `coreGAMMA_FLEX` / `coreGTRCAT` + derivative Allreduce
    (`makenewzGenericSpecial.c:394-619, 1241-1248`).
    """
    wb = models.rate_weights[block_part]                    # [B, R]
    if site_rates is None:
        d = branch_decay(models, z)                         # [M, R, K]
        e1 = models.eign * models.gamma_rates[:, :, None]   # [M, R, K]
        db = d[block_part]                                  # [B, R, K]
        e1b = e1[block_part]
        lsite = einsum("br,blrj,brj->bl", wb, st, db)
        dsite = einsum("br,blrj,brj,brj->bl", wb, st, db, e1b)
        d2site = einsum("br,blrj,brj,brj,brj->bl", wb, st, db, e1b, e1b)
    else:
        db = psr_decay(models, block_part, site_rates, z)   # [B, l, R, K]
        e1b = (models.eign[block_part][:, 0][:, None, None, :]
               * site_rates[:, :, :, None])                 # [B, l, R, K]
        lsite = einsum("br,blrj,blrj->bl", wb, st, db)
        dsite = einsum("br,blrj,blrj,blrj->bl", wb, st, db, e1b)
        d2site = einsum("br,blrj,blrj,blrj,blrj->bl", wb, st, db, e1b, e1b)

    lsite = jnp.maximum(lsite, jnp.finfo(lsite.dtype).tiny)
    acc = _acc_dtype(lsite.dtype)
    dlnl = (dsite / lsite).astype(acc)
    d2lnl = (d2site / lsite).astype(acc) - dlnl * dlnl
    wacc = weights.astype(acc)
    blk_d1 = jnp.sum(wacc * dlnl, axis=1)
    blk_d2 = jnp.sum(wacc * d2lnl, axis=1)
    per_part_d1 = jax.ops.segment_sum(blk_d1, block_part,
                                      num_segments=models.eign.shape[0])
    per_part_d2 = jax.ops.segment_sum(blk_d2, block_part,
                                      num_segments=models.eign.shape[0])
    d1 = jax.ops.segment_sum(per_part_d1, models.part_branch,
                             num_segments=num_slots)
    d2 = jax.ops.segment_sum(per_part_d2, models.part_branch,
                             num_segments=num_slots)
    if axis_name is not None:                # shard_map (SEV x sharding):
        d1 = jax.lax.psum(d1, axis_name)     # the derivative Allreduce
        d2 = jax.lax.psum(d2, axis_name)     # (makenewz...c:1241-1248)
    return d1, d2
