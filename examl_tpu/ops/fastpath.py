"""Fast full-traversal path: case-split wave chunks, MXU-shaped dots.

The TPU-native re-architecture of the reference's newview inner loops
(ExaML `newviewGenericSpecial.c:1263-1497` dispatch over TIP_TIP /
TIP_INNER / INNER_INNER kernels, and the MIC backend's tip-product
precompute `umpX`, `mic_native_dna.c:132-165`), driven by what the MXU
and XLA actually reward (measured, tools/perf_lab.py):

* Waves of independent entries are split by tip case and executed as a
  statically unrolled sequence of chunks (no `lax.scan`), each chunk one
  batched dot over its natural (power-of-two padded) width.
* The per-rate P application is folded into ONE block-diagonal
  [R*K, R*K] contraction per child — 4x fewer MXU row-streams than R
  separate [K, K] dots at identical numerics (the blocks are exact).
* Tip children never materialize CLVs: a per-chunk `ump[code, r, a] =
  sum_k P[r,a,k] * tipvec[code,k]` table is contracted against one-hot
  code vectors — tip state never touches HBM at CLV width.
* Parents of one chunk occupy CONTIGUOUS rows of a wave-ordered CLV
  arena, so every write is a `dynamic_update_slice` that XLA performs
  in place — the `.at[].set` scatter inside scan was measured to copy
  the whole CLV buffer every step (half the runtime).

The engine caches the jitted chunk-runner per wave profile AND the
schedule's immutable structure per topology signature (`FastStructure`,
built at array rate from a `FlatTraversal` by `build_structure`): only
the per-chunk zl/zr branch arrays are rebuilt per call (`refresh_z`) —
branch lengths change every traversal, the chunk layout only on
topology changes.  A node->row map lets the scan path (partial
traversals during search) and this path share one arena.  The legacy
per-entry `build_schedule` remains as the uncached reference
implementation (equivalence-tested, and still used for entry-list
callers like bench tiers and bank warming).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from examl_tpu.ops import kernels
from examl_tpu.tree.topology import Tree, TraversalEntry


class FastChunk(NamedTuple):
    """One case-homogeneous batch of independent newview entries.

    kind: 0 = tip-tip, 1 = tip-inner (tip is always the left child),
    2 = inner-inner.  Arrays are device-resident, width-padded.
    """
    kind: int
    width: int
    base: jax.Array         # scalar int32: first arena row written
    lidx: jax.Array         # [W] arena row of left child (kind 2)
    ridx: jax.Array         # [W] arena row of right child (kind 1, 2)
    lcode: jax.Array        # [W] 0-based tip index of left child (kind 0, 1)
    rcode: jax.Array        # [W] 0-based tip index of right child (kind 0)
    zl: jax.Array           # [W, C]
    zr: jax.Array           # [W, C]


class FastSchedule(NamedTuple):
    chunks: Tuple[FastChunk, ...]
    row_of: Dict[int, int]      # node number -> arena row
    profile: Tuple[Tuple[int, int], ...]   # ((kind, width), ...) jit key
    num_rows: int               # rows actually holding real entries
    max_write: int              # highest row index written + 1 (incl. spill)


class FastStructure(NamedTuple):
    """The IMMUTABLE half of a fast-path schedule: everything that is a
    function of topology + traversal root only (chunk kinds/widths,
    child index/code arrays, the arena row map) — cacheable across the
    branch-length-only traversals that dominate model optimization and
    repeated full evaluations.  The cheap DYNAMIC half (per-chunk
    zl/zr) is rebuilt per call by `refresh_z` through the stored
    entry->slot permutation.

    Child/code arrays are stored PACKED along one padded slot axis
    (device-resident, transferred once); the jitted program slices each
    chunk's window statically from the profile, so a cached dispatch
    ships only the two fresh z arrays to the device."""
    profile: Tuple[Tuple[int, int], ...]   # ((kind, width), ...) jit key
    base: jax.Array             # [n_chunks] int32: first arena row written
    lidx: jax.Array             # [P] packed left-child arena rows
    ridx: jax.Array             # [P]
    lcode: jax.Array            # [P] packed 0-based tip indices
    rcode: jax.Array            # [P]
    row_of: np.ndarray          # [2*ntips-1] node number -> row (-1 tips)
    z_src: np.ndarray           # [P] flat-entry index per slot (-1 pad)
    z_swap: np.ndarray          # [P] slot's children were canonicalized
    num_rows: int
    max_write: int


def build_structure(flat, ntips: int) -> FastStructure:
    """Vectorized schedule-structure build from a FlatTraversal: the
    per-entry Python loop of `build_schedule` replaced by numpy sort/
    scatter over the whole traversal (this is what makes a 120k-taxon
    schedule build array-rate).  Produces the identical chunk layout —
    same (wave, kind) grouping, same pow2 widths, same row assignment
    discipline — as `build_schedule` on the same wave order."""
    n = flat.n
    left = flat.left
    right = flat.right
    wave_id = np.repeat(np.arange(flat.wave_sizes.shape[0], dtype=np.int64),
                        flat.wave_sizes)
    lt = left <= ntips
    rt = right <= ntips
    swap = (~lt) & rt                     # canonicalize: tip child left
    el = np.where(swap, right, left)
    er = np.where(swap, left, right)
    kind = 2 - (lt.astype(np.int64) + rt.astype(np.int64))
    order = np.argsort(wave_id * 3 + kind, kind="stable")
    # Row of an entry = its position in (wave, kind)-sorted order: waves
    # pack consecutively, kind groups advance by their REAL size (pow2
    # spill overwrites later rows before anything reads them).
    row_of = np.full(2 * ntips - 1, -1, dtype=np.int64)
    row_of[flat.parent[order]] = np.arange(n)
    skey = (wave_id * 3 + kind)[order]
    starts = np.flatnonzero(np.r_[True, skey[1:] != skey[:-1]])
    sizes = np.diff(np.r_[starts, n])
    widths = np.asarray([_pow2(int(g)) for g in sizes], dtype=np.int64)
    poff = np.concatenate([[0], np.cumsum(widths)[:-1]])
    P = int(widths.sum())
    kinds = kind[order][starts]
    profile = tuple((int(k), int(w)) for k, w in zip(kinds, widths))
    # Packed slot layout: destination of sorted entry i.
    dst = (np.repeat(poff, sizes)
           + np.arange(n) - np.repeat(starts, sizes))
    el_s = el[order]
    er_s = er[order]
    lt_s = (lt | rt)[order]               # post-swap: left tip (kind 0/1)
    rt_s = (lt & rt)[order]               # post-swap: right tip (kind 0)
    lidx = np.zeros(P, np.int32)
    ridx = np.zeros(P, np.int32)
    lcode = np.zeros(P, np.int32)
    rcode = np.zeros(P, np.int32)
    z_src = np.full(P, -1, np.int64)
    z_swap = np.zeros(P, bool)
    lidx[dst] = np.where(lt_s, 0, row_of[el_s])
    ridx[dst] = np.where(rt_s, 0, row_of[er_s])
    lcode[dst] = np.where(lt_s, el_s - 1, 0)
    rcode[dst] = np.where(rt_s, er_s - 1, 0)
    z_src[dst] = order
    z_swap[dst] = swap[order]
    dev = jax.device_put([starts.astype(np.int32), lidx, ridx, lcode,
                          rcode])
    return FastStructure(profile=profile, base=dev[0], lidx=dev[1],
                         ridx=dev[2], lcode=dev[3], rcode=dev[4],
                         row_of=row_of, z_src=z_src, z_swap=z_swap,
                         num_rows=n,
                         max_write=int((starts + widths).max()) if n else 0)


def refresh_z(st: FastStructure, flat, num_slots: int, dtype):
    """The DYNAMIC half of a cached schedule: permute the traversal's
    branch-length vectors into packed chunk-slot order (canonical swap
    applied, padding slots at z=1) — pure numpy fancy indexing, the
    only per-call host work on a schedule-cache hit."""
    zl_f = flat.zl
    zr_f = flat.zr
    if zl_f.shape[1] != num_slots:
        from examl_tpu.utils import z_slots
        zl_f = np.stack([z_slots(z, num_slots) for z in zl_f])
        zr_f = np.stack([z_slots(z, num_slots) for z in zr_f])
    P = st.z_src.shape[0]
    ok = st.z_src >= 0
    src = st.z_src[ok]
    sw = st.z_swap[ok, None]
    zl = np.ones((P, num_slots))
    zr = np.ones((P, num_slots))
    zl[ok] = np.where(sw, zr_f[src], zl_f[src])
    zr[ok] = np.where(sw, zl_f[src], zr_f[src])
    return jax.device_put([np.asarray(zl, dtype), np.asarray(zr, dtype)])


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def build_schedule(entries: List[TraversalEntry], ntips: int,
                   num_slots: int, dtype, base_row: int = 0,
                   row_of_existing: Dict[int, int] | None = None,
                   ) -> FastSchedule:
    """Wave-schedule entries into case-split chunks writing rows
    base_row, base_row+1, ... in wave order.

    row_of_existing resolves inner children computed OUTSIDE these
    entries (partial traversals); full traversals need none.
    """
    from examl_tpu.utils import z_slots

    waves = Tree.schedule_waves(entries)
    row_of: Dict[int, int] = {}
    lookup = row_of_existing or {}

    def child_row(num: int) -> int:
        if num in row_of:
            return row_of[num]
        return lookup[num]

    host_chunks: List[tuple] = []
    rows = base_row
    max_write = base_row
    for wave in waves:
        def ntip(e):
            return (e.left <= ntips) + (e.right <= ntips)
        groups = ([e for e in wave if ntip(e) == 2],
                  [e for e in wave if ntip(e) == 1],
                  [e for e in wave if ntip(e) == 0])
        base = rows
        for wi, e in enumerate(groups[0] + groups[1] + groups[2]):
            row_of[e.parent] = base + wi
        off = 0
        for kind, grp in ((0, groups[0]), (1, groups[1]), (2, groups[2])):
            if not grp:
                continue
            W = _pow2(len(grp))
            lidx = np.zeros(W, np.int32)
            ridx = np.zeros(W, np.int32)
            lcode = np.zeros(W, np.int32)
            rcode = np.zeros(W, np.int32)
            zl = np.ones((W, num_slots))
            zr = np.ones((W, num_slots))
            one_slot = num_slots == 1
            for wi, e in enumerate(grp):
                lt, rt = e.left <= ntips, e.right <= ntips
                ezl, ezr = e.zl, e.zr
                el, er = e.left, e.right
                if not lt and rt:      # canonicalize: tip child on the left
                    el, er, ezl, ezr = er, el, ezr, ezl
                    lt, rt = True, False
                lidx[wi] = 0 if lt else child_row(el)
                ridx[wi] = 0 if rt else child_row(er)
                lcode[wi] = el - 1 if lt else 0
                rcode[wi] = er - 1 if rt else 0
                if one_slot:           # hot path: z_slots dominates at 50k+
                    zl[wi, 0] = ezl[0]
                    zr[wi, 0] = ezr[0]
                else:
                    zl[wi] = z_slots(ezl, num_slots)
                    zr[wi] = z_slots(ezr, num_slots)
            host_chunks.append(
                (kind, W, np.int32(base + off), lidx, ridx, lcode, rcode,
                 np.asarray(zl, dtype), np.asarray(zr, dtype)))
            max_write = max(max_write, base + off + W)
            off += len(grp)
        rows = base + off
    # ONE batched host->device transfer for every chunk's arrays: at 50k
    # taxa this is ~1,500 chunks x 7 arrays, and per-array jnp.asarray
    # device_puts dominated the whole schedule build (~1.5 s of 2.3 s);
    # the batched put is ~30 ms.
    flat = [a for hc in host_chunks for a in hc[2:]]
    dev = iter(jax.device_put(flat))
    chunks = [FastChunk(kind=kind, width=W, base=next(dev),
                        lidx=next(dev), ridx=next(dev), lcode=next(dev),
                        rcode=next(dev), zl=next(dev), zr=next(dev))
              for (kind, W, *_rest) in host_chunks]
    profile = tuple((c.kind, c.width) for c in chunks)
    return FastSchedule(chunks=tuple(chunks), row_of=row_of,
                        profile=profile, num_rows=rows, max_write=max_write)


def run_chunks(models: kernels.DeviceModels, block_part: jax.Array,
               tips: kernels.TipState, clv: jax.Array, scaler: jax.Array,
               chunks, scale_exp: int, precision) -> Tuple[jax.Array, jax.Array]:
    """Execute the chunk sequence (traced; shapes static per profile).

    clv is [rows, B, lane, R, K]; writes spill up to width-1 junk rows
    past each chunk's real entries — the arena reserves slack for the
    final chunk and intermediate spill is overwritten by later chunks
    before anything reads it.
    """
    rows, B, lane, R, K = clv.shape
    RK = R * K
    M = models.eign.shape[0]
    C = tips.table.shape[0]
    cdt = tips.table.dtype        # COMPUTE dtype; the arena may store
    eyeR = jnp.eye(R, dtype=cdt)  # narrower (bf16 tier, EXAML_CLV_DTYPE)
    HI = jax.lax.Precision.HIGHEST

    def tip_child(p, code):
        # ump[w,m,c,(r a)] = sum_k tipvec[c,k] P[w,m,r,a,k]; contracted
        # against exact one-hot code vectors (MIC umpX generalization).
        W = code.shape[0]
        ump = jnp.einsum("ck,wmrak->wmcra", tips.table, p, precision=HI)
        ump = ump.reshape(W, M, C, RK)[:, block_part]       # [W,B,C,RK]
        oh = jax.nn.one_hot(tips.codes[code], C, dtype=cdt)
        return jax.lax.dot_general(oh, ump,
                                   (((3,), (2,)), ((0, 1), (0, 1))),
                                   precision=precision)

    def inner_child(p, idx, clv):
        # block-diagonal (r,k)->(r,a) contraction: exact same arithmetic
        # as per-rate P application, one MXU-friendly [RK,RK] dot.
        W = idx.shape[0]
        pb = jnp.einsum("wmrak,rs->wmrksa", p, eyeR).reshape(W, M, RK, RK)
        pb = pb[:, block_part]                              # [W,B,RK,RK]
        x = clv[idx].astype(cdt).reshape(W, B, lane, RK)
        return jax.lax.dot_general(x, pb,
                                   (((3,), (2,)), ((0, 1), (0, 1))),
                                   precision=precision)

    minlik, two_e, _ = kernels.scale_constants(cdt, scale_exp)
    for ch in chunks:
        pl = kernels.p_matrices_wave(models, ch.zl)         # [W,M,R,K,K]
        pr = kernels.p_matrices_wave(models, ch.zr)
        W = ch.width
        if ch.kind == 0:
            yl = tip_child(pl, ch.lcode)
            yr = tip_child(pr, ch.rcode)
            sc = jnp.zeros((W, B, lane), jnp.int32)
        elif ch.kind == 1:
            yl = tip_child(pl, ch.lcode)
            yr = inner_child(pr, ch.ridx, clv)
            sc = scaler[ch.ridx]
        else:
            yl = inner_child(pl, ch.lidx, clv)
            yr = inner_child(pr, ch.ridx, clv)
            sc = scaler[ch.lidx] + scaler[ch.ridx]
        v = yl * yr                                         # [W,B,lane,RK]
        needs = jnp.max(jnp.abs(v), axis=3) < minlik
        v = jnp.where(needs[..., None], v * two_e, v)
        sc = sc + needs.astype(jnp.int32)
        z0 = jnp.zeros((), ch.base.dtype)
        clv = jax.lax.dynamic_update_slice(
            clv, v.reshape(W, B, lane, R, K).astype(clv.dtype),
            (ch.base, z0, z0, z0, z0))
        scaler = jax.lax.dynamic_update_slice(scaler, sc, (ch.base, z0, z0))
    return clv, scaler
