"""Fast full-traversal path: case-split wave chunks, MXU-shaped dots,
with a BOUNDED program: width bucketing, chunk coalescing and a scanned
long tail keep the compiled chunk program at O(log n) operations.

The TPU-native re-architecture of the reference's newview inner loops
(ExaML `newviewGenericSpecial.c:1263-1497` dispatch over TIP_TIP /
TIP_INNER / INNER_INNER kernels, and the MIC backend's tip-product
precompute `umpX`, `mic_native_dna.c:132-165`), driven by what the MXU
and XLA actually reward (measured, tools/perf_lab.py):

* Waves of independent entries are split by tip case and executed as
  chunks, each chunk one batched dot over its padded width.
* The per-rate P application is folded into ONE block-diagonal
  [R*K, R*K] contraction per child — 4x fewer MXU row-streams than R
  separate [K, K] dots at identical numerics (the blocks are exact).
* Tip children never materialize CLVs: a per-chunk `ump[code, r, a] =
  sum_k P[r,a,k] * tipvec[code,k]` table is contracted against one-hot
  code vectors — tip state never touches HBM at CLV width.
* Parents of one chunk occupy CONTIGUOUS rows of a wave-ordered CLV
  arena, so every write is a `dynamic_update_slice` that XLA performs
  in place — the `.at[].set` scatter inside scan was measured to copy
  the whole CLV buffer every step (half the runtime).

Program-size discipline (the BEAGLE lesson: library-scale phylogenetics
lives or dies on operation scheduling cost, not FLOPs).  A naive
schedule is one unrolled block per (wave, kind) chunk — ~1,500 blocks
at 50k taxa, which costs XLA tens of minutes of CPU compile and pays a
per-block launch-latency floor every traversal.  Three coordinated
moves bound it:

1. WIDTH BUCKETING — chunk widths quantize to a geometric ladder with a
   floor (`MIN_WIDTH`, default 8) and a cap (`CHUNK_CAP`, default 1024;
   wider chunks split into cap-width pieces).  The `(kind, width)`
   alphabet is therefore small and FIXED, so profiles — and with them
   jit keys and bank program families — are shared across topologies of
   similar shape instead of being unique per tree.
2. CHUNK COALESCING — runs of small same-kind chunks from adjacent
   waves merge into one padded chunk when a vectorized dependency check
   proves no merged entry reads a row the merged chunk itself writes
   (entries within a wave are independent, so any split is valid; the
   cross-wave merge is valid exactly when the check passes).  Arena
   rows are assigned in final emission order, so merged writes stay
   contiguous `dynamic_update_slice`s.
3. SCANNED LONG TAIL — maximal runs of chunks with an identical
   bucketed step shape (same `(kind, width)` for head runs produced by
   cap-splitting; same per-wave `((kind, width), ...)` signature for
   the narrow tail waves, absent kinds normalized to width-`MIN_WIDTH`
   padding sub-chunks) collapse into ONE `lax.scan` over stacked chunk
   arrays.  Scan lengths bucket geometrically; padding steps REPLAY the
   run's final step, which is idempotent (a chunk reads only rows
   written strictly before it and rewrites its own rows with identical
   values), so no scratch arithmetic leaks into real rows.

The resulting `profile` is a tuple of segments — `("u", kind, width)`
for an unrolled block, `("s", glen, ((kind, width), ...))` for a scan
group — and IS the jit key: program length is O(#segments) ~ O(log n)
(measured: 50k taxa, 1,511 raw chunks -> ~70 unrolled blocks + ~35 scan
groups), and execution order equals wave order chunk for chunk, so the
bounded program's lnL is bit-identical to the unbounded unroll.

`build_structure` (vectorized, from a `FlatTraversal`) and the legacy
per-entry `build_schedule` both produce the IDENTICAL bounded layout
(equivalence contract, tests/test_scale.py + tests/test_fastpath.py);
the engine caches the immutable structure per topology signature and
refreshes only the packed z arrays per call (`refresh_z`).
`EXAML_BOUNDED_CHUNKS=0` restores the legacy one-block-per-chunk
layout (escape hatch + the equivalence-test reference).
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from examl_tpu.ops import kernels
from examl_tpu.tree.topology import Tree, TraversalEntry
from examl_tpu.utils import bucket_len, next_pow2

# -- bounded-layout knobs ----------------------------------------------------
# The ladder alphabet is {MIN_WIDTH, 2*MIN_WIDTH, ..., CHUNK_CAP}: small and
# fixed, so two topologies of similar shape produce the SAME profile and
# share one compiled program (and one bank family / persistent-cache entry).

MIN_WIDTH = 8        # width floor (EXAML_CHUNK_MIN_WIDTH)
CHUNK_CAP = 1024     # width cap; wider chunks split (EXAML_CHUNK_CAP)
TAIL_WIDTH = 64      # waves whose chunks all bucket <= this join the
                     # scanned tail (EXAML_CHUNK_TAIL_WIDTH)
MIN_SCAN = 4         # shorter runs stay unrolled (replay padding would
                     # dominate them)


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return max(1, int(v))
    except ValueError:
        return default


def _knobs() -> Tuple[int, int, int]:
    mw = next_pow2(_env_int("EXAML_CHUNK_MIN_WIDTH", MIN_WIDTH))
    cap = max(mw, next_pow2(_env_int("EXAML_CHUNK_CAP", CHUNK_CAP)))
    tail = max(mw, next_pow2(_env_int("EXAML_CHUNK_TAIL_WIDTH",
                                      TAIL_WIDTH)))
    return mw, cap, tail


def bounded_default() -> bool:
    """Bounded layout unless EXAML_BOUNDED_CHUNKS=0 (escape hatch; also
    the reference layout for the equivalence tests)."""
    return os.environ.get("EXAML_BOUNDED_CHUNKS", "") != "0"


def slack_rows(ntips: int) -> int:
    """Arena slack rows the bounded layout needs: headroom for padded
    chunk writes past the real rows AND the dedicated pad region the
    scanned tail's width-MIN_WIDTH padding sub-chunks write (base = n).
    Derived from the LIVE knobs so an env-tuned EXAML_CHUNK_MIN_WIDTH
    is provisioned for, not crashed on (every build still asserts
    max_write against the arena)."""
    mw, _cap, _tailw = _knobs()
    floor = 2 * mw
    return min(max(64, floor), max(next_pow2(ntips), floor))


class FastChunk(NamedTuple):
    """One case-homogeneous batch of independent newview entries.

    kind: 0 = tip-tip, 1 = tip-inner (tip is always the left child),
    2 = inner-inner.  Arrays are device-resident, width-padded.
    """
    kind: int
    width: int
    base: jax.Array         # scalar int32: first arena row written
    lidx: jax.Array         # [W] arena row of left child (kind 2)
    ridx: jax.Array         # [W] arena row of right child (kind 1, 2)
    lcode: jax.Array        # [W] 0-based tip index of left child (kind 0, 1)
    rcode: jax.Array        # [W] 0-based tip index of right child (kind 0)
    zl: jax.Array           # [W, C]
    zr: jax.Array           # [W, C]


class FastStructure(NamedTuple):
    """The IMMUTABLE half of a fast-path schedule: everything that is a
    function of topology + traversal root only (segment profile, chunk
    widths/bases, packed child index/code arrays, the arena row map) —
    cacheable across the branch-length-only traversals that dominate
    model optimization and repeated full evaluations.  The cheap
    DYNAMIC half (packed per-slot zl/zr) is rebuilt per call by
    `refresh_z` through the stored entry->slot map.

    Child/code arrays are stored PACKED along one padded slot axis
    (device-resident, transferred once); the jitted program slices each
    segment's window statically from the profile (scan groups reshape
    theirs to [glen, step_width]), so a cached dispatch ships only the
    two fresh z arrays to the device.

    `profile` is the BUCKETED segment tuple (see module docstring), not
    raw per-chunk widths — it is the engine's jit-cache key, so two
    different topologies with the same bucketed profile share one
    compiled program (tests/test_fastpath.py proves the cache hit)."""
    profile: Tuple[tuple, ...]  # segment tuple: the jit key
    base: jax.Array             # [n_chunks] int32: first arena row written
    lidx: jax.Array             # [P] packed left-child arena rows
    ridx: jax.Array             # [P]
    lcode: jax.Array            # [P] packed 0-based tip indices
    rcode: jax.Array            # [P]
    row_of: np.ndarray          # [2*ntips-1] node number -> row (-1 tips)
    z_src: np.ndarray           # [P] flat-entry index per slot (-1 pad;
                                #     replay slots repeat their source)
    z_swap: np.ndarray          # [P] slot's children were canonicalized
    num_rows: int
    max_write: int


class FastSchedule:
    """Entry-list twin of `FastStructure` (legacy per-entry builder):
    the same packed layout plus the packed z arrays, and a lazily
    materialized per-chunk `FastChunk` list for harnesses that unroll
    chunks themselves (bench tiers, the Pallas equivalence tests).
    `profile` is the bucketed segment tuple — identical to
    `build_structure`'s for the same traversal (equivalence contract).
    """

    __slots__ = ("profile", "row_of", "num_rows", "max_write",
                 "base", "lidx", "ridx", "lcode", "rcode", "zl", "zr",
                 "_host", "_chunks")

    def __init__(self, profile, row_of, num_rows, max_write, dev, host):
        self.profile = profile
        self.row_of: Dict[int, int] = row_of
        self.num_rows = num_rows
        self.max_write = max_write
        (self.base, self.lidx, self.ridx, self.lcode, self.rcode,
         self.zl, self.zr) = dev
        self._host = host
        self._chunks: Optional[Tuple[FastChunk, ...]] = None

    @property
    def chunks(self) -> Tuple[FastChunk, ...]:
        """Materialized per-chunk list in execution order (includes the
        replay/padding chunks of scan groups, so running it unrolled is
        bit-identical to the segment program).  Built lazily — the
        engine's jitted programs use the packed arrays instead."""
        if self._chunks is None:
            base_h, li, ri, lc, rc, zl, zr = self._host
            views = []
            metas = []
            off = cidx = 0
            for kind, W in iter_profile_chunks(self.profile):
                views += [li[off:off + W], ri[off:off + W],
                          lc[off:off + W], rc[off:off + W],
                          zl[off:off + W], zr[off:off + W]]
                metas.append((kind, W, np.int32(base_h[cidx])))
                off += W
                cidx += 1
            dev = iter(jax.device_put(
                [m[2] for m in metas] + views))
            bases = [next(dev) for _ in metas]
            self._chunks = tuple(
                FastChunk(kind, W, b, next(dev), next(dev), next(dev),
                          next(dev), next(dev), next(dev))
                for (kind, W, _), b in zip(metas, bases))
        return self._chunks


# -- profile helpers ---------------------------------------------------------


def iter_profile_chunks(profile):
    """Yield (kind, width) for every chunk in execution order, scan
    groups expanded step-major (incl. replay steps)."""
    for seg in profile:
        if seg[0] == "u":
            yield seg[1], seg[2]
        else:
            _, glen, subs = seg
            for _ in range(glen):
                for k, w in subs:
                    yield k, w


def profile_stats(profile) -> Tuple[int, int, int]:
    """(unrolled_blocks, scan_groups, total_chunks) of a profile —
    unrolled_blocks + scan_groups is the program's operation count (the
    launch-latency floor per traversal); total_chunks counts every
    chunk incl. scan steps (the raw work-unit count)."""
    un = sum(1 for s in profile if s[0] == "u")
    sc = sum(1 for s in profile if s[0] == "s")
    total = sum(1 for _ in iter_profile_chunks(profile))
    return un, sc, total


def profile_slots(profile) -> int:
    """Total packed slot count P of a profile."""
    return sum(w for _, w in iter_profile_chunks(profile))


# -- layout planning ---------------------------------------------------------


class _Chunk:
    """Planner-internal chunk record (host only)."""

    __slots__ = ("kind", "W", "spans", "real", "pad", "replay_of",
                 "base", "slot")

    def __init__(self, kind, W, spans, pad=False, replay_of=None):
        self.kind = kind
        self.W = W
        self.spans = spans          # [(lo, hi)] into sorted-entry order
        self.real = sum(hi - lo for lo, hi in spans)
        self.pad = pad              # writes only slack rows
        self.replay_of = replay_of  # index into the final chunk list
        self.base = -1
        self.slot = -1


class _Layout(NamedTuple):
    profile: Tuple[tuple, ...]
    chunks: List[_Chunk]        # final execution order (incl. pads/replays)
    P: int                      # total packed slots
    max_write: int


def _bucket_w(s: int, mw: int) -> int:
    return max(mw, next_pow2(s))


def _plan_layout(kinds: np.ndarray, sizes: np.ndarray, gwave: np.ndarray,
                 starts: np.ndarray, child_key: np.ndarray, n: int,
                 bounded: bool) -> _Layout:
    """Plan the chunk/segment layout from the (wave, kind)-sorted group
    table.  `child_key[g]` is the max (wave*3+kind) sort key over group
    g's inner children's defining entries (-1 when all children are
    tips/external) — the vectorized dependency oracle for coalescing.

    Unbounded (legacy) mode: one unrolled chunk per group, width
    pow2(size) with no floor — byte-for-byte the historical layout."""
    G = len(kinds)
    if not bounded:
        chunks = [_Chunk(int(kinds[g]), next_pow2(int(sizes[g])),
                         [(int(starts[g]), int(starts[g] + sizes[g]))])
                  for g in range(G)]
        profile = tuple(("u", c.kind, c.W) for c in chunks)
        return _finish_layout(profile, chunks, n)

    mw, cap, tailw = _knobs()

    # -- 1. coalescing: merge a small group into the newest earlier
    # same-kind group when every inner child of the candidate was
    # computed strictly before the target's position (original sort
    # keys upper-bound post-merge positions, so the check is
    # conservative-safe) and the merged chunk stays small.
    class _Rec:
        __slots__ = ("kind", "wave", "size", "spans", "key")

        def __init__(self, g):
            self.kind = int(kinds[g])
            self.wave = int(gwave[g])
            self.size = int(sizes[g])
            self.spans = [(int(starts[g]), int(starts[g] + sizes[g]))]
            self.key = self.wave * 3 + self.kind

    recs: List[_Rec] = []
    open_of: Dict[int, _Rec] = {}
    for g in range(G):
        k = int(kinds[g])
        t = open_of.get(k)
        if (t is not None and t.size + int(sizes[g]) <= tailw
                and int(child_key[g]) < t.key):
            t.size += int(sizes[g])
            t.spans.append((int(starts[g]), int(starts[g] + sizes[g])))
            continue
        r = _Rec(g)
        recs.append(r)
        open_of[k] = r

    # -- 2. per-wave emission: head waves cap-split into ladder pieces,
    # tail waves normalize to a per-wave signature with width-mw padding
    # sub-chunks for absent (previously seen) kinds.
    by_wave: Dict[int, List[_Rec]] = {}
    for r in recs:
        by_wave.setdefault(r.wave, []).append(r)

    def split_spans(spans, take):
        """Cut `take` entries off the front of a span list."""
        out, rest = [], []
        need = take
        for lo, hi in spans:
            if need <= 0:
                rest.append((lo, hi))
            elif hi - lo <= need:
                out.append((lo, hi))
                need -= hi - lo
            else:
                out.append((lo, lo + need))
                rest.append((lo + need, hi))
                need = 0
        return out, rest

    stream: List[tuple] = []    # ("h", [chunk]) | ("t", sig, [chunks])
    seen = set()
    for wave in sorted(by_wave):
        wrecs = sorted(by_wave[wave], key=lambda r: r.kind)
        tail = all(_bucket_w(r.size, mw) <= tailw for r in wrecs)
        if tail:
            step = []
            have = {r.kind: r for r in wrecs}
            for k in (0, 1, 2):
                r = have.get(k)
                if r is not None:
                    step.append(_Chunk(k, _bucket_w(r.size, mw), r.spans))
                elif k in (1, 2) and k in seen:
                    step.append(_Chunk(k, mw, [], pad=True))
            seen.update(have)
            sig = tuple((c.kind, c.W) for c in step)
            stream.append(("t", sig, step))
        else:
            out = []
            for r in wrecs:
                seen.add(r.kind)
                spans, size = r.spans, r.size
                while size > cap:
                    head, spans = split_spans(spans, cap)
                    out.append(_Chunk(r.kind, cap, head))
                    size -= cap
                out.append(_Chunk(r.kind, _bucket_w(size, mw), spans))
            stream.append(("h", out))

    # -- 3. segmentation: maximal runs of an identical step shape become
    # one lax.scan; scan lengths bucket geometrically with replay
    # padding (idempotent re-execution of the final step).
    profile: List[tuple] = []
    chunks: List[_Chunk] = []

    def emit_run(sig, steps):
        glen = len(steps)
        if glen < MIN_SCAN:
            for step in steps:
                for c in step:
                    if not c.pad:       # unrolled pads are pure waste
                        profile.append(("u", c.kind, c.W))
                        chunks.append(c)
            return
        blen = bucket_len(glen)
        profile.append(("s", blen, sig))
        for step in steps:
            chunks.extend(step)
        ns = len(sig)
        last = len(chunks) - ns
        for _ in range(blen - glen):
            for j in range(ns):
                src = last + j
                chunks.append(_Chunk(chunks[src].kind, chunks[src].W,
                                     chunks[src].spans,
                                     pad=chunks[src].pad,
                                     replay_of=src))

    run_sig: Optional[tuple] = None
    run_steps: List[List[_Chunk]] = []

    def flush():
        nonlocal run_sig, run_steps
        if run_steps:
            emit_run(run_sig, run_steps)
        run_sig, run_steps = None, []

    for item in stream:
        if item[0] == "h":
            for c in item[1]:
                sig = ((c.kind, c.W),)
                if sig != run_sig:
                    flush()
                    run_sig = sig
                run_steps.append([c])
        else:
            _, sig, step = item
            if sig != run_sig:
                flush()
                run_sig = sig
            run_steps.append(step)
    flush()

    return _finish_layout(tuple(profile), chunks, n)


def _finish_layout(profile, chunks, n: int) -> _Layout:
    """Assign arena rows (final emission order; pads write the slack
    region at row n, replays rewrite their source rows) and packed slot
    offsets; compute max_write for the engine's arena-capacity check."""
    row = 0
    slot = 0
    max_write = 0
    for c in chunks:
        c.slot = slot
        slot += c.W
        if c.replay_of is not None:
            c.base = chunks[c.replay_of].base
        elif c.pad:
            c.base = n
        else:
            c.base = row
            row += c.real
        max_write = max(max_write, c.base + c.W)
    assert row == n, (row, n)
    return _Layout(profile=profile, chunks=chunks, P=slot,
                   max_write=max_write)


def _layout_from_arrays(wave_id, el, er, lt, rt, child_nodes_key, n,
                        bounded):
    """Shared planner front-end: group the (wave, kind)-sorted entries
    and plan.  Returns (layout, order, skey-derived group table pieces)
    where `order` is the (wave, kind) stable sort permutation."""
    if n == 0:
        return (_Layout(profile=(), chunks=[], P=0, max_write=0),
                np.empty(0, np.int64))
    kind = 2 - (lt.astype(np.int64) + rt.astype(np.int64))
    skey_all = wave_id * 3 + kind
    order = np.argsort(skey_all, kind="stable")
    skey = skey_all[order]
    starts = np.flatnonzero(np.r_[True, skey[1:] != skey[:-1]])
    sizes = np.diff(np.r_[starts, n])
    kinds = (skey[starts] % 3).astype(np.int64)
    gwave = (skey[starts] // 3).astype(np.int64)
    # Dependency oracle: per sorted entry, the max sort key over its
    # inner children's defining entries (tips/external -> -1), reduced
    # per group.
    ck = np.maximum(child_nodes_key[el[order]],
                    child_nodes_key[er[order]])
    child_key = (np.maximum.reduceat(ck, starts) if n
                 else np.empty(0, np.int64))
    layout = _plan_layout(kinds, sizes, gwave, starts, child_key, n,
                          bounded)
    return layout, order


def _pack_structure(layout: _Layout, order, el, er, lt, rt, swap, parent,
                    row_map_size: int):
    """Fill the packed per-slot arrays from a layout: a scatter per real
    chunk span (vectorized over entries), then slot-window copies for
    the replay chunks.  Returns host arrays."""
    n = order.shape[0]
    P = layout.P
    # Final entry order: concatenation of real-chunk spans (emission
    # order) — rows 0..n-1 in exactly this order.
    spans = [(lo, hi) for c in layout.chunks if c.replay_of is None
             for (lo, hi) in c.spans]
    if spans:
        pos = np.concatenate([np.arange(lo, hi) for lo, hi in spans])
    else:
        pos = np.empty(0, np.int64)
    assert pos.shape[0] == n
    final = order[pos]                  # indices into the ORIGINAL entries
    row_of = np.full(row_map_size, -1, dtype=np.int64)
    row_of[parent[final]] = np.arange(n)
    # Destination slot of each final-order entry.
    dst = np.empty(n, np.int64)
    off = 0
    for c in layout.chunks:
        if c.replay_of is not None:
            continue
        dst[off:off + c.real] = c.slot + np.arange(c.real)
        off += c.real
    el_f = el[final]
    er_f = er[final]
    lt_f = lt[final] | rt[final]        # post-swap: left is tip (kind 0/1)
    rt_f = lt[final] & rt[final]        # post-swap: right is tip (kind 0)
    # Every inner child must be defined by some entry in the traversal:
    # a -1 row would silently gather the scratch row (the loud
    # replacement for the old per-entry builder's KeyError on partial
    # entry lists, which the fast builders do not support).
    if (((~lt_f) & (row_of[el_f] < 0))
            | ((~rt_f) & (row_of[er_f] < 0))).any():
        raise KeyError("traversal entries reference inner children no "
                       "entry computes (partial entry lists are not "
                       "supported by the fast-path schedule builders)")
    lidx = np.zeros(P, np.int32)
    ridx = np.zeros(P, np.int32)
    lcode = np.zeros(P, np.int32)
    rcode = np.zeros(P, np.int32)
    z_src = np.full(P, -1, np.int64)
    z_swap = np.zeros(P, bool)
    lidx[dst] = np.where(lt_f, 0, row_of[el_f])
    ridx[dst] = np.where(rt_f, 0, row_of[er_f])
    lcode[dst] = np.where(lt_f, el_f - 1, 0)
    rcode[dst] = np.where(rt_f, er_f - 1, 0)
    z_src[dst] = final
    z_swap[dst] = swap[final]
    for c in layout.chunks:             # replay steps copy their source
        if c.replay_of is None:
            continue
        s = layout.chunks[c.replay_of].slot
        for arr in (lidx, ridx, lcode, rcode, z_src, z_swap):
            arr[c.slot:c.slot + c.W] = arr[s:s + c.W]
    base = np.asarray([c.base for c in layout.chunks], np.int32)
    return row_of, base, lidx, ridx, lcode, rcode, z_src, z_swap, dst


def build_structure(flat, ntips: int,
                    bounded: Optional[bool] = None) -> FastStructure:
    """Vectorized schedule-structure build from a FlatTraversal: the
    per-entry Python loop of `build_schedule` replaced by numpy sort/
    scatter over the whole traversal (this is what makes a 120k-taxon
    schedule build array-rate).  Produces the identical bounded chunk
    layout — same bucketing, coalescing, scan grouping, same row
    assignment discipline — as `build_schedule` on the same wave order
    (the equivalence contract both builders must keep)."""
    if bounded is None:
        bounded = bounded_default()
    n = flat.n
    left = flat.left
    right = flat.right
    wave_id = np.repeat(np.arange(flat.wave_sizes.shape[0], dtype=np.int64),
                        flat.wave_sizes)
    lt = left <= ntips
    rt = right <= ntips
    swap = (~lt) & rt                     # canonicalize: tip child left
    el = np.where(swap, right, left)
    er = np.where(swap, left, right)
    kind = 2 - ((left <= ntips).astype(np.int64)
                + (right <= ntips).astype(np.int64))
    node_key = np.full(2 * ntips - 1, -1, dtype=np.int64)
    node_key[flat.parent] = wave_id * 3 + kind
    layout, order = _layout_from_arrays(
        wave_id, el, er, lt, rt, node_key, n, bounded)
    (row_of, base, lidx, ridx, lcode, rcode, z_src, z_swap,
     _dst) = _pack_structure(layout, order, el, er, lt, rt, swap,
                             flat.parent, 2 * ntips - 1)
    dev = jax.device_put([base, lidx, ridx, lcode, rcode])
    return FastStructure(profile=layout.profile, base=dev[0], lidx=dev[1],
                         ridx=dev[2], lcode=dev[3], rcode=dev[4],
                         row_of=row_of, z_src=z_src, z_swap=z_swap,
                         num_rows=n, max_write=layout.max_write)


def refresh_z(st: FastStructure, flat, num_slots: int, dtype,
              total_slots: Optional[int] = None):
    """The DYNAMIC half of a cached schedule: permute the traversal's
    branch-length vectors into packed chunk-slot order (canonical swap
    applied; padding slots at z=1, replay slots repeating their source
    entry's z) — pure numpy fancy indexing, the only per-call host work
    on a schedule-cache hit.  `total_slots` (>= the structure's packed
    slot count) pads the result with z=1 rows for the universal
    interpreter's bucketed slot axis (ops/universal.py); the padding
    rows are never read."""
    zl_f = flat.zl
    zr_f = flat.zr
    if zl_f.shape[1] != num_slots:
        from examl_tpu.utils import z_slots
        zl_f = np.stack([z_slots(z, num_slots) for z in zl_f])
        zr_f = np.stack([z_slots(z, num_slots) for z in zr_f])
    P = st.z_src.shape[0]
    Pout = P if total_slots is None else total_slots
    assert Pout >= P, (Pout, P)
    ok = st.z_src >= 0
    src = st.z_src[ok]
    sw = st.z_swap[ok, None]
    zl = np.ones((Pout, num_slots))
    zr = np.ones((Pout, num_slots))
    zl[:P][ok] = np.where(sw, zr_f[src], zl_f[src])
    zr[:P][ok] = np.where(sw, zl_f[src], zr_f[src])
    return jax.device_put([np.asarray(zl, dtype), np.asarray(zr, dtype)])


def _z_matrix(zs: List[tuple], num_slots: int) -> np.ndarray:
    """[n, num_slots] branch-length matrix from per-entry z tuples
    (vectorized for the uniform-length cases that dominate)."""
    from examl_tpu.utils import z_slots
    n = len(zs)
    if n == 0:
        return np.ones((0, num_slots))
    ln = len(zs[0])
    if all(len(z) == ln for z in zs):
        arr = np.asarray(zs, dtype=np.float64)
        if ln == num_slots:
            return arr
        if ln == 1:
            return np.broadcast_to(arr, (n, num_slots)).copy()
        if ln > num_slots:
            return arr[:, :num_slots].copy()
    return np.stack([z_slots(z, num_slots) for z in zs])


def build_schedule(entries: List[TraversalEntry], ntips: int,
                   num_slots: int, dtype,
                   bounded: Optional[bool] = None) -> FastSchedule:
    """Wave-schedule entries into the bounded chunk layout (see module
    docstring), packed along one slot axis.  The uncached reference
    builder: equivalence-tested against `build_structure`, and still
    used by entry-list callers (bench tiers, bank warming)."""
    if bounded is None:
        bounded = bounded_default()
    waves = Tree.schedule_waves(entries)
    n = len(entries)
    wave_entries = [e for w in waves for e in w]
    wave_id = np.repeat(np.arange(len(waves), dtype=np.int64),
                        [len(w) for w in waves])
    parent = np.fromiter((e.parent for e in wave_entries), np.int64, n)
    left = np.fromiter((e.left for e in wave_entries), np.int64, n)
    right = np.fromiter((e.right for e in wave_entries), np.int64, n)
    zl_e = _z_matrix([e.zl for e in wave_entries], num_slots)
    zr_e = _z_matrix([e.zr for e in wave_entries], num_slots)
    lt = left <= ntips
    rt = right <= ntips
    swap = (~lt) & rt
    el = np.where(swap, right, left)
    er = np.where(swap, left, right)
    kind = 2 - (lt.astype(np.int64) + rt.astype(np.int64))
    nk = max(2 * ntips - 1, int(max(el.max(), er.max())) + 1) if n else 1
    node_key = np.full(nk, -1, dtype=np.int64)
    node_key[parent] = wave_id * 3 + kind
    layout, order = _layout_from_arrays(
        wave_id, el, er, lt, rt, node_key, n, bounded)
    (row_arr, base, lidx, ridx, lcode, rcode, z_src, z_swap,
     dst) = _pack_structure(layout, order, el, er, lt, rt, swap,
                            parent, nk)
    P = layout.P
    zl = np.ones((P, num_slots))
    zr = np.ones((P, num_slots))
    ok = z_src >= 0
    src = z_src[ok]
    sw = z_swap[ok, None]
    zl[ok] = np.where(sw, zr_e[src], zl_e[src])
    zr[ok] = np.where(sw, zl_e[src], zr_e[src])
    zl = np.asarray(zl, dtype)
    zr = np.asarray(zr, dtype)
    row_of = {int(num): int(r) for num, r in enumerate(row_arr)
              if r >= 0}
    host = (base, lidx, ridx, lcode, rcode, zl, zr)
    # ONE batched host->device transfer for the whole packed layout
    # (per-array device_puts dominated the 50k schedule build).
    dev = jax.device_put(list(host))
    return FastSchedule(profile=layout.profile, row_of=row_of,
                        num_rows=n, max_write=layout.max_write,
                        dev=dev, host=host)


# -- execution ---------------------------------------------------------------


def chunk_applier(models: kernels.DeviceModels, block_part: jax.Array,
                  tips: kernels.TipState, scale_exp: int, precision):
    """The single-chunk kernel body (traced): P-build + child
    contractions + product + rescale + contiguous arena write.  Shared
    by the unrolled blocks, the lax.scan group bodies, and the
    reference `run_chunks` loop, so every execution strategy performs
    the identical arithmetic."""
    M = models.eign.shape[0]
    C = tips.table.shape[0]
    cdt = tips.table.dtype        # COMPUTE dtype; the arena may store
    R = models.gamma_rates.shape[1]
    eyeR = jnp.eye(R, dtype=cdt)  # narrower (bf16 tier, EXAML_CLV_DTYPE)
    HI = jax.lax.Precision.HIGHEST
    minlik, two_e, _ = kernels.scale_constants(cdt, scale_exp)

    def tip_child(p, code, B, RK):
        # ump[w,m,c,(r a)] = sum_k tipvec[c,k] P[w,m,r,a,k]; contracted
        # against exact one-hot code vectors (MIC umpX generalization).
        W = code.shape[0]
        ump = jnp.einsum("ck,wmrak->wmcra", tips.table, p, precision=HI)
        ump = ump.reshape(W, M, C, RK)[:, block_part]       # [W,B,C,RK]
        oh = jax.nn.one_hot(tips.codes[code], C, dtype=cdt)
        return jax.lax.dot_general(oh, ump,
                                   (((3,), (2,)), ((0, 1), (0, 1))),
                                   precision=precision)

    def inner_child(p, idx, clv, B, lane, RK):
        # block-diagonal (r,k)->(r,a) contraction: exact same arithmetic
        # as per-rate P application, one MXU-friendly [RK,RK] dot.
        W = idx.shape[0]
        pb = jnp.einsum("wmrak,rs->wmrksa", p, eyeR).reshape(W, M, RK, RK)
        pb = pb[:, block_part]                              # [W,B,RK,RK]
        x = clv[idx].astype(cdt).reshape(W, B, lane, RK)
        return jax.lax.dot_general(x, pb,
                                   (((3,), (2,)), ((0, 1), (0, 1))),
                                   precision=precision)

    def values(clv, scaler, ch: FastChunk):
        """The chunk's COMPUTED rows, no write: (v [W, B, lane, R, K]
        in the compute dtype, sc [W, B, lane]).  Split out of `apply`
        so the universal interpreter (ops/universal.py) can run the
        identical arithmetic inside a `lax.switch` branch while the
        arena write stays OUTSIDE the conditional — XLA copies carry
        buffers that are written inside cond branches (measured 7.6x
        on CPU), but read-only operands flow through for free."""
        rows, B, lane, R_, K = clv.shape
        RK = R_ * K
        pl = kernels.p_matrices_wave(models, ch.zl)         # [W,M,R,K,K]
        pr = kernels.p_matrices_wave(models, ch.zr)
        W = ch.width
        if ch.kind == 0:
            yl = tip_child(pl, ch.lcode, B, RK)
            yr = tip_child(pr, ch.rcode, B, RK)
            sc = jnp.zeros((W, B, lane), jnp.int32)
        elif ch.kind == 1:
            yl = tip_child(pl, ch.lcode, B, RK)
            yr = inner_child(pr, ch.ridx, clv, B, lane, RK)
            sc = scaler[ch.ridx]
        else:
            yl = inner_child(pl, ch.lidx, clv, B, lane, RK)
            yr = inner_child(pr, ch.ridx, clv, B, lane, RK)
            sc = scaler[ch.lidx] + scaler[ch.ridx]
        v = yl * yr                                         # [W,B,lane,RK]
        needs = jnp.max(jnp.abs(v), axis=3) < minlik
        v = jnp.where(needs[..., None], v * two_e, v)
        sc = sc + needs.astype(jnp.int32)
        return v.reshape(W, B, lane, R_, K), sc

    def apply(clv, scaler, ch: FastChunk):
        v, sc = values(clv, scaler, ch)
        z0 = jnp.zeros((), ch.base.dtype if hasattr(ch.base, "dtype")
                       else jnp.int32)
        clv = jax.lax.dynamic_update_slice(
            clv, v.astype(clv.dtype), (ch.base, z0, z0, z0, z0))
        scaler = jax.lax.dynamic_update_slice(scaler, sc,
                                              (ch.base, z0, z0))
        return clv, scaler

    apply.values = values
    return apply


def run_chunks(models: kernels.DeviceModels, block_part: jax.Array,
               tips: kernels.TipState, clv: jax.Array, scaler: jax.Array,
               chunks, scale_exp: int, precision) -> Tuple[jax.Array, jax.Array]:
    """Execute an explicit chunk list unrolled, in order (traced; shapes
    static).  The REFERENCE execution strategy: the segment program
    (`run_segments`) must match it bit for bit.

    clv is [rows, B, lane, R, K]; writes spill up to width-1 junk rows
    past each chunk's real entries — the arena reserves slack for the
    final chunk and intermediate spill is overwritten by later chunks
    before anything reads it.
    """
    apply = chunk_applier(models, block_part, tips, scale_exp, precision)
    for ch in chunks:
        clv, scaler = apply(clv, scaler, ch)
    return clv, scaler


def run_segments(profile, base, lidx, ridx, lcode, rcode, zl, zr,
                 clv, scaler, apply) -> Tuple[jax.Array, jax.Array]:
    """Execute the bounded program over the PACKED 7-leaf layout:
    unrolled segments slice their windows statically; scan segments
    reshape theirs to [glen, step] and run one `lax.scan` whose body
    executes the step's sub-chunks with the same `apply` kernel, so the
    program length is O(#segments) while the arithmetic — and execution
    order — is chunk-for-chunk identical to `run_chunks`."""
    off = 0
    coff = 0

    def window(a, o, w):
        return jax.lax.slice_in_dim(a, o, o + w)

    for seg in profile:
        if seg[0] == "u":
            _, k, W = seg
            ch = FastChunk(k, W, base[coff], window(lidx, off, W),
                           window(ridx, off, W), window(lcode, off, W),
                           window(rcode, off, W), window(zl, off, W),
                           window(zr, off, W))
            clv, scaler = apply(clv, scaler, ch)
            off += W
            coff += 1
            continue
        _, glen, subs = seg
        SW = sum(w for _, w in subs)
        ns = len(subs)
        span = glen * SW

        def reshape_xs(a):
            w = window(a, off, span)
            return w.reshape((glen, SW) + w.shape[1:])

        xs = (window(base, coff, glen * ns).reshape(glen, ns),
              reshape_xs(lidx), reshape_xs(ridx), reshape_xs(lcode),
              reshape_xs(rcode), reshape_xs(zl), reshape_xs(zr))

        def body(carry, x, subs=subs):
            c, s = carry
            b, li, ri, lc, rc, zl_, zr_ = x
            o = 0
            for j, (k, W) in enumerate(subs):
                ch = FastChunk(k, W, b[j], window(li, o, W),
                               window(ri, o, W), window(lc, o, W),
                               window(rc, o, W), window(zl_, o, W),
                               window(zr_, o, W))
                c, s = apply(c, s, ch)
                o += W
            return (c, s), None

        (clv, scaler), _ = jax.lax.scan(body, (clv, scaler), xs)
        off += span
        coff += glen * ns
    return clv, scaler
