"""Pallas TPU kernels for the fast-traversal chunk pipeline.

One fused kernel per case-split chunk (see ops/fastpath.py for the
schedule): the two child P-applications, the elementwise product, the
scaling check, and the arena write happen in ONE Mosaic program per wave
chunk, so the intermediate child products never round-trip through HBM
and no XLA fusion boundary can reintroduce layout copies.  This is the
SURVEY §7.2(9) Pallas step over the reference's newview inner loops
(ExaML `newviewGenericSpecial.c:1263-1497`; MIC tip-product analogue
`mic_native_dna.c:132-165`).

Memory plan per grid step w (one wave-chunk entry):

* child CLV rows are fetched by MANUAL async DMA from the arena with
  scalar-prefetched row numbers (`lidx`/`ridx`) — the arena is passed
  ONCE in `pl.ANY` space and aliased to the output, so XLA updates it in
  place (the arena is donated by the engine; a second blocked operand on
  the same buffer would force a defensive copy of the whole arena, the
  exact failure the fast path exists to avoid);
* P-matrix blocks (`pb*`, block-diagonal over rates) and tip-product
  tables (`um*`, MIC-style) are tiny, built in XLA per chunk, and stream
  through the automatic VMEM pipeline;
* results are DMA'd to arena row `base + w`.  Within one chunk no
  written row is ever read (children live in strictly earlier waves), so
  the in-place alias is race-free; across chunks the XLA data dependence
  serializes.

Only f32 is supported (TPU Pallas has no f64); the engine keeps the
plain-XLA fast path for CPU/f64 parity runs.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from examl_tpu.ops import kernels

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# tier (and its interpret-mode tests) runs across jax versions.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

HIGHEST = jax.lax.Precision.HIGHEST


def _dot_b(x, p, precision):
    """[B, L, K] x [B, K, N] -> [B, L, N], batched over B on the MXU."""
    return jax.lax.dot_general(
        x, p, (((2,), (1,)), ((0,), (0,))), precision=precision,
        preferred_element_type=jnp.float32)


def _one_hot_apply(codes, um, C, precision):
    """Tip-child P application: one-hot(code) @ um, [B,L] -> [B,L,RK]."""
    oh = (codes[:, :, None] ==
          jax.lax.broadcasted_iota(jnp.int32, (1, 1, C), 2))
    return _dot_b(oh.astype(um.dtype), um, precision)


def _chunk_kernel(lidx_ref, ridx_ref, base_ref, clv_hbm, scaler_hbm,
                  opl_ref, opr_ref, lcode_ref, rcode_ref, scsum_ref,
                  clv_out, scaler_out,
                  xl_s, xr_s, v_s, sc_s, sem_l, sem_r, sem_v, sem_s,
                  *, kind: int, C: int, minlik: float, two_e: float,
                  precision):
    w = pl.program_id(0)
    b0 = base_ref[0]

    # Start child-row DMAs first so they overlap the tip-side compute.
    if kind == 2:
        cl = pltpu.make_async_copy(clv_hbm.at[lidx_ref[w]], xl_s, sem_l)
        cl.start()
    if kind >= 1:
        cr = pltpu.make_async_copy(clv_hbm.at[ridx_ref[w]], xr_s, sem_r)
        cr.start()

    if kind == 2:
        cl.wait()
        yl = _dot_b(xl_s[:], opl_ref[0], precision)
    else:
        yl = _one_hot_apply(lcode_ref[0], opl_ref[0], C, precision)
    if kind >= 1:
        cr.wait()
        yr = _dot_b(xr_s[:], opr_ref[0], precision)
    else:
        yr = _one_hot_apply(rcode_ref[0], opr_ref[0], C, precision)

    v = yl * yr
    needs = jnp.max(jnp.abs(v), axis=2) < minlik          # [B, L]
    v = jnp.where(needs[:, :, None], v * two_e, v)
    v_s[:] = v
    sc_s[:] = scsum_ref[0] + needs.astype(jnp.int32)

    cv = pltpu.make_async_copy(v_s, clv_out.at[b0 + w], sem_v)
    cs = pltpu.make_async_copy(sc_s, scaler_out.at[b0 + w], sem_s)
    cv.start()
    cs.start()
    cv.wait()
    cs.wait()


def _run_chunk(clv, scaler, lidx, ridx, base, opl, opr, lcodes, rcodes,
               scsum, *, kind: int, W: int, C: int, scale_exp: int,
               precision, interpret: bool):
    """One chunk: clv [rows,B,L,RK] f32, scaler [rows,B,L] int32.

    Traced inline under the caller's jit (the engine's fast-path program
    or the bench harness); the pallas_call's input_output_aliases keeps
    the arena update in place chunk to chunk.
    """
    rows, B, L, RK = clv.shape
    minlik = float(np.asarray(2.0, np.float64) ** (-scale_exp))
    two_e = float(np.asarray(2.0, np.float64) ** scale_exp)
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    row3 = pl.BlockSpec((1, B, L), lambda w, *_: (w, 0, 0))

    in_specs = [
        any_spec,                                          # clv arena
        any_spec,                                          # scaler arena
        pl.BlockSpec((1,) + opl.shape[1:],
                     lambda w, *_: (w,) + (0,) * (opl.ndim - 1)),
        pl.BlockSpec((1,) + opr.shape[1:],
                     lambda w, *_: (w,) + (0,) * (opr.ndim - 1)),
        row3,                                              # lcodes
        row3,                                              # rcodes
        row3,                                              # scsum
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(W,),
        in_specs=in_specs,
        out_specs=[any_spec, any_spec],
        scratch_shapes=[
            pltpu.VMEM((B, L, RK), clv.dtype),             # xl
            pltpu.VMEM((B, L, RK), clv.dtype),             # xr
            pltpu.VMEM((B, L, RK), clv.dtype),             # v
            pltpu.VMEM((B, L), jnp.int32),                 # sc
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(
        _chunk_kernel, kind=kind, C=C, minlik=minlik, two_e=two_e,
        precision=precision)
    flops_dot = 2 * W * B * L * RK * (RK if kind == 2 else C) * 2
    clv, scaler = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(clv.shape, clv.dtype),
                   jax.ShapeDtypeStruct(scaler.shape, scaler.dtype)],
        # inputs: 0 lidx, 1 ridx, 2 base, 3 clv, 4 scaler, 5 opl, 6 opr,
        # 7 lcodes, 8 rcodes, 9 scsum
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        cost_estimate=pl.CostEstimate(
            flops=flops_dot, transcendentals=0,
            bytes_accessed=3 * W * B * L * RK * 4),
        interpret=interpret,
    )(lidx, ridx, base, clv, scaler, opl, opr, lcodes, rcodes, scsum)
    return clv, scaler


def _block_diag_p(p, block_part, eyeR):
    """[W,M,R,A,K] -> [W,B,RK,RK] block-diagonal over rates (exact)."""
    W, M, R, A, K = p.shape
    pb = jnp.einsum("wmrak,rs->wmrksa", p, eyeR).reshape(W, M, R * K, R * A)
    return pb[:, block_part]


def _ump(p, table, block_part):
    """MIC-style tip-product table: [W,B,C,RK]."""
    W, M, R, A, K = p.shape
    um = jnp.einsum("ck,wmrak->wmcra", table, p, precision=HIGHEST)
    return um.reshape(W, M, table.shape[0], R * A)[:, block_part]


def chunk_applier(models, block_part, tips, scale_exp: int,
                  precision=None, interpret: bool = False):
    """Per-chunk Pallas kernel body (f32 only): the fused-kernel twin of
    fastpath.chunk_applier, shared by the unrolled chunk loop and the
    bounded program's lax.scan group bodies (ops/fastpath.run_segments).
    The [rows,B,lane,R,K]<->[rows,B,lane,RK] reshapes around each call
    are layout metadata XLA elides.

    `precision` applies to the child CLV contractions only (all-positive
    sums; HIGH is within the NUMERICS.md budget); the ump/block-diagonal
    operand construction in XLA stays at HIGHEST.
    """
    if precision is None:
        precision = HIGHEST
    # NOTE: Mosaic rejects HIGH ("Unsupported dot precision: HIGH" on
    # v5e); only DEFAULT and HIGHEST lower.  An explicit HIGH is passed
    # through so harnesses sweeping precisions fail loudly rather than
    # silently measuring a duplicate HIGHEST row; the engine maps its
    # HIGH default to HIGHEST before dispatching here (engine.py
    # `pallas_precision`).
    C = tips.table.shape[0]

    def apply(clv, scaler, ch):
        rows, B, lane, R, K = clv.shape
        RK = R * K
        eyeR = jnp.eye(R, dtype=clv.dtype)
        clvf = clv.reshape(rows, B, lane, RK)
        pml = kernels.p_matrices_wave(models, ch.zl)       # [W,M,R,A,K]
        pmr = kernels.p_matrices_wave(models, ch.zr)
        W = ch.width
        if ch.kind == 0:
            opl = _ump(pml, tips.table, block_part)
            opr = _ump(pmr, tips.table, block_part)
            scsum = jnp.zeros((W, B, lane), jnp.int32)
        elif ch.kind == 1:
            opl = _ump(pml, tips.table, block_part)
            opr = _block_diag_p(pmr, block_part, eyeR)
            scsum = scaler[ch.ridx]
        else:
            opl = _block_diag_p(pml, block_part, eyeR)
            opr = _block_diag_p(pmr, block_part, eyeR)
            scsum = scaler[ch.lidx] + scaler[ch.ridx]
        # tip codes as int32 rows [W,B,lane] (uint8 gather done in XLA)
        lcodes = tips.codes[ch.lcode].astype(jnp.int32)
        rcodes = tips.codes[ch.rcode].astype(jnp.int32)
        base = (ch.base[None] if getattr(ch.base, "ndim", 0) == 0
                else ch.base)
        clvf, scaler = _run_chunk(
            clvf, scaler, ch.lidx, ch.ridx, base, opl, opr,
            lcodes, rcodes, scsum, kind=ch.kind, W=W, C=C,
            scale_exp=scale_exp, precision=precision, interpret=interpret)
        return clvf.reshape(rows, B, lane, R, K), scaler

    return apply


def run_chunks(models, block_part, tips, clv, scaler, chunks,
               scale_exp: int, precision=None,
               interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Drop-in Pallas equivalent of fastpath.run_chunks (f32 only).

    Per-chunk host loop: each chunk is one pallas_call whose donated
    arena threads through, so the XLA data dependence serializes chunks
    while everything inside a chunk stays fused in VMEM.
    """
    apply = chunk_applier(models, block_part, tips, scale_exp,
                          precision=precision, interpret=interpret)
    for ch in chunks:
        clv, scaler = apply(clv, scaler, ch)
    return clv, scaler
