"""PSR (per-site rate / CAT) model optimization.

Reference: `optimizeRateCategories` and its pipeline
(`optimizeModel.c:1792-2507`): per-site rate hill scan
(`optRateCatPthreads` via `evaluatePartialGeneric`), master-side
categorization into <=`-c` categories (`categorizeTheRates` /
`categorizePartition`), weighted mean-rate-1 normalization
(`updatePerSiteRates`), and accept-only-if-better semantics.

TPU-native redesign (SURVEY §7.3(5)): instead of one tiny host traversal
per (site, trial rate), ALL sites' likelihoods under a whole grid of
candidate rates are computed by a single full traversal per grid chunk
with a per-site-rate axis (`LikelihoodEngine.rate_scan`).  The candidate
grid reproduces the reference's hill-scan probes: current rate +- k
spacings, with the spacing schedule shrinking per invocation.
"""

from __future__ import annotations

from typing import List

import numpy as np

from examl_tpu.instance import PhyloInstance
from examl_tpu.tree.topology import Tree

MIN_RATE = 0.0001          # reference lower bound on trial rates
RATE_STEPS = 64            # +-k steps covering the reference's open-ended
                           # scan reach (its crawl stops at the first
                           # non-improving step; 64 steps of the same
                           # spacing covers every realistic optimum)
CAT_MERGE_TOL = 0.001      # rates closer than this share a category
MAX_CAT_ROUNDS = 3         # catOpt < 3 in modOpt (optimizeModel.c:3100)


def _spacings(invocations: int) -> tuple[float, float]:
    """Shrinking scan spacings (reference `optimizeRateCategories`,
    `optimizeModel.c:2430-2444`)."""
    n = max(invocations, 1)
    if n == 1:
        lower, upper = 0.5, 1.0
    else:
        lower, upper = 0.05 / n, 0.1 / n
    return max(lower, 0.001), max(upper, 0.001)


def _scan_partition_rates(inst: PhyloInstance, tree: Tree,
                          lower: float, upper: float,
                          grid_chunk: int = 8) -> None:
    """Update inst.patrat / inst.site_lhs with the best rate per site.

    The batched replacement for the reference's per-site open-ended hill
    climb (`optRateCatPthreads`): every site's lnL under a +-RATE_STEPS
    candidate grid is computed by shared full traversals.  The grid is
    deliberately the SAME arithmetic lattice (current rate + k*spacing)
    the reference's crawl walks: sites landing on shared lattice values is
    what lets `categorizeTheRates`-style mass-ranked category selection
    find good representatives — re-centering per site was measured to
    smear the lattice and cost ~300 lnL after categorization."""
    p, entries = tree.full_traversal()
    up = upper * np.arange(1, RATE_STEPS + 1)
    down = -lower * np.arange(1, RATE_STEPS + 1)
    if inst.save_memory:
        # The rate scan's scratch CLV is DENSE [rows, B, lane, G, K]
        # inside its program (engine._rate_scan_impl) — G x a
        # single-rate dense arena.  -S runs exist because dense does
        # not fit; keep the transient peak at ~2 dense arenas.
        grid_chunk = min(grid_chunk, 2)

    for states, bucket in inst.buckets.items():
        eng = inst.engines[states]
        packed_r0 = np.ones(bucket.num_sites)
        for li, gid in enumerate(bucket.part_ids):
            packed_r0[bucket.site_indices(li)] = inst.patrat[gid]
        r0 = packed_r0.reshape(bucket.num_blocks, bucket.lane)
        # Pattern weights: per-site lnls are WEIGHT-MULTIPLIED exactly as
        # the reference's `term * w` (`evaluatePartialGenericSpecial.c:
        # 1049`).  This is load-bearing twice: high-weight (conserved)
        # patterns crawl further before the epsilon stop, and the
        # categorization ranks rate groups by weighted mass — without it
        # the near-zero-rate category never wins a slot and PSR lands
        # ~400 lnL short on testData/49.  GLOBAL view (one allgather of
        # the per-process windows under selective loading) because the
        # crawl and categorization run on global arrays everywhere.
        w = inst.psr_packed_weights(bucket)

        def eval_offsets(offs):
            grid = r0[:, :, None] + offs[None, None, :]
            valid = grid > MIN_RATE
            grid = np.maximum(grid, MIN_RATE)
            lnls = eng.rate_scan(entries, p.number, p.back.number, p.z,
                                 grid) * w[:, :, None]       # [B, lane, Gc]
            return np.where(valid, lnls, -np.inf)

        cur_lnl = eval_offsets(np.zeros(1))[:, :, 0]

        def crawl(dir_offsets):
            """Directional crawl with the reference's stop rule: continue
            only while the next step improves by more than epsilon=1e-5
            (`optRateCatPthreads` while conditions) — the early stop keeps
            sites clustered on few shared lattice rates, which the
            mass-ranked categorization depends on.  Grid chunks are
            evaluated lazily in walk order and the scan stops fetching
            once every site's crawl has died, so the typical cost is a
            couple of chunks, not the full RATE_STEPS reach."""
            best = cur_lnl.copy()
            best_r = r0.copy()
            alive = np.ones_like(best, dtype=bool)
            for start in range(0, len(dir_offsets), grid_chunk):
                offs = dir_offsets[start:start + grid_chunk]
                lnls = eval_offsets(offs)
                for k in range(len(offs)):
                    v = lnls[:, :, k]
                    step = alive & (v > best) & (np.abs(best - v) > 1e-5)
                    rate_k = np.maximum(r0 + offs[k], MIN_RATE)
                    best = np.where(step, v, best)
                    best_r = np.where(step, rate_k, best_r)
                    alive = step
                if not alive.any():
                    break
            return best, best_r

        up_lnl, up_rate = crawl(up)
        dn_lnl, dn_rate = crawl(down)
        # Pick the better crawl end if it strictly beats the current
        # rate; on an exact up-vs-down tie the DOWN rate wins, as the
        # reference's `if(rightLH > leftLH) right else left`
        # (`optimizeModel.c:1905-1917`).
        best_lnl = cur_lnl.copy()
        best_rate = r0.copy()
        use_up = (up_lnl > cur_lnl) & (up_lnl > dn_lnl)
        use_dn = (dn_lnl > cur_lnl) & ~use_up
        best_lnl = np.where(use_up, up_lnl, np.where(use_dn, dn_lnl,
                                                     best_lnl))
        best_rate = np.where(use_up, up_rate, np.where(use_dn, dn_rate,
                                                       best_rate))

        flat_rate = best_rate.reshape(-1)
        flat_lnl = best_lnl.reshape(-1)
        for li, gid in enumerate(bucket.part_ids):
            idx = bucket.site_indices(li)
            inst.patrat[gid] = flat_rate[idx].copy()
            inst.site_lhs[gid] = flat_lnl[idx].copy()


def _categorize_partition(patrat: np.ndarray, lhs: np.ndarray,
                          max_categories: int):
    """Bucket a partition's site rates into <= max_categories categories —
    the reference's EXACT algorithm (`categorizeTheRates`
    `optimizeModel.c:2171-2252`, `categorizePartition` :1734-1790):

    1. FIRST-COME tolerance merge in site order: a site joins the
       EARLIEST-CREATED category whose representative (first-seen) rate
       is within 0.001 absolute; otherwise it founds a new category with
       itself as representative.  (Chained drift is intentional: 1.0009
       joins 1.0000's category but 1.0018 founds its own.)
    2. Categories sorted ASCENDING by accumulated site lnL (sums of
       negative values: biggest lnL mass first); the first
       max_categories survive.
    3. Each site takes the FIRST surviving category (in mass order)
       within tolerance of its rate, else the nearest representative.

    Returns (category_per_site [W] int32, category_rates [ncat]).

    The merge is O(W log C) — representatives kept in a sorted list,
    candidates found by bisection, the first-come rule resolved by
    minimum creation index among in-tolerance candidates — so it stays
    viable at the reference's 12,000-16,000 patterns/core PSR loads
    (BASELINE.md) where the reference's own O(W*C) scan is the model.
    Replacing the earlier quantized-grid approximation with this exact
    form moved the testData/49 PSR endpoint from -14763.8 to within a
    few lnL of the reference's -14702.97."""
    import bisect

    rep_rates: list = []      # sorted representative rates
    rep_created: list = []    # parallel creation indices
    cat_rate: list = []       # creation-order representatives
    cat_lnl: list = []        # accumulated site lnL per category
    tol = CAT_MERGE_TOL
    for r, l in zip(patrat.tolist(), lhs.tolist()):
        lo = bisect.bisect_left(rep_rates, r - tol)
        hi = bisect.bisect_right(rep_rates, r + tol)
        best = -1
        for j in range(lo, hi):
            if (r == rep_rates[j] or abs(r - rep_rates[j]) < tol) \
                    and (best == -1 or rep_created[j] < best):
                best = rep_created[j]
        if best == -1:
            best = len(cat_rate)
            cat_rate.append(r)
            cat_lnl.append(l)
            ins = bisect.bisect_left(rep_rates, r)
            rep_rates.insert(ins, r)
            rep_created.insert(ins, best)
        else:
            cat_lnl[best] += l

    order = np.argsort(np.asarray(cat_lnl), kind="stable")  # ascending
    kept = np.asarray(cat_rate)[order[:max_categories]]
    diff = np.abs(patrat[:, None] - kept[None, :])
    in_tol = (diff < tol) | (patrat[:, None] == kept[None, :])
    first_tol = np.argmax(in_tol, axis=1)
    nearest = np.argmin(diff, axis=1)
    category = np.where(in_tol.any(axis=1), first_tol, nearest)
    return category.astype(np.int32), kept


def _normalize_mean_rate(inst: PhyloInstance) -> None:
    """Scale category rates so the weighted mean site rate is 1 — per
    partition under per-partition branch lengths, globally otherwise
    (reference `updatePerSiteRates`, `optimizeModel.c:2060-2120`)."""
    parts = inst.alignment.partitions
    if inst.num_branch_slots > 1:
        for gid in range(len(parts)):
            w = inst.psr_pattern_weights(gid)   # GLOBAL under slicing
            rates = inst.per_site_rates[gid][inst.rate_category[gid]]
            mean = float(w @ rates) / float(w.sum())
            inst.per_site_rates[gid] = inst.per_site_rates[gid] / mean
    else:
        num = den = 0.0
        for gid in range(len(parts)):
            w = inst.psr_pattern_weights(gid)   # GLOBAL under slicing
            rates = inst.per_site_rates[gid][inst.rate_category[gid]]
            num += float(w @ rates)
            den += float(w.sum())
        scale = num / den
        for gid in range(len(parts)):
            inst.per_site_rates[gid] = inst.per_site_rates[gid] / scale
    # NOTE: patrat deliberately keeps the UN-snapped per-site scan optima —
    # the reference likewise scales only perSiteRates (the category
    # representatives used for evaluation) and leaves patrat as each
    # site's own running optimum, which seeds the next scan invocation
    # (`updatePerSiteRates` touches only perSiteRates,
    # `optimizeModel.c:2060-2120`; categorizePartition never writes
    # patrat).  Snapping patrat to category rates each round collapses the
    # per-site resolution and was measured to cost ~800 lnL on
    # testData/49 PSR.


def refine_category_rates(inst: PhyloInstance, tree: Tree,
                          tol: float = 0.0001) -> float:
    """Continuous polish of a frozen categorization — an extension
    beyond the reference, run in mod_opt rounds after the reference's 3
    scan/categorize rounds are exhausted (where its CAT branch does
    nothing further for rate heterogeneity).

    The reference pins each category's rate to the lattice value the
    per-site crawl happened to land on (`categorizePartition` copies
    `rc[k].rate`, `optimizeModel.c:1784-1788`); the lattice resolution
    then bounds the reachable (GTR rates x branch lengths) basin.  Here
    each representative rate is a free continuous parameter: Brent each
    category index across partitions (batched, accept-if-better per
    partition), then restore the weighted-mean-rate-1 convention
    EXACTLY via rates /= m and z -> z**m — lnL depends on the product
    rate*log(z) only (`makeP`'s EIGN*r*log z), so the joint rescale is
    invariant, not just approximate.

    Measured on testData/49 PSR -f e: endpoint -14710.8 -> -14662.5 vs
    the reference's -14702.97 (the lattice-frozen optimizers stall ~8
    lnL apart; the continuous polish beats both).  EXAML_PSR_REFINE=0
    restores the reference's exact stop-at-the-lattice behavior.
    """
    import os

    from examl_tpu.optimize.brent import minimize_vector
    from examl_tpu.constants import ZMAX, ZMIN
    from examl_tpu.tree.topology import hookup

    assert inst.psr
    if os.environ.get("EXAML_PSR_REFINE") == "0":
        return inst.evaluate(tree, full=True)
    inst.evaluate(tree, full=True)
    # Accepted-state lnL per partition, maintained incrementally: after
    # each category's accept/restore the accepted value is known from
    # the Brent result, so no re-evaluate per category is needed (the
    # next category's bracket starts from the accepted state anyway).
    cur = [float(v) for v in inst.per_partition_lnl]
    ncat_max = max(len(r) for r in inst.per_site_rates)
    for k in range(ncat_max):
        gids = [g for g in range(inst.num_parts)
                if len(inst.per_site_rates[g]) > k]
        if not gids:
            continue
        x0 = np.array([float(inst.per_site_rates[g][k]) for g in gids])
        start = np.array([cur[g] for g in gids])

        def fn(xs: np.ndarray) -> np.ndarray:
            for g, v in zip(gids, xs):
                inst.per_site_rates[g][k] = float(v)
            inst.push_site_rates()
            inst.evaluate(tree, full=True)
            return -np.array([float(inst.per_partition_lnl[g])
                              for g in gids])

        xb, fb = minimize_vector(x0, np.full(len(gids), MIN_RATE),
                                 np.full(len(gids), 32.0), fn, tol)
        for g, v0, v1, f1, l0 in zip(gids, x0, xb, fb, start):
            accept = -f1 > l0
            inst.per_site_rates[g][k] = float(v1 if accept else v0)
            cur[g] = float(-f1) if accept else float(l0)
        inst.push_site_rates()
    # Exact mean-rate-1 restoration (see docstring): globally with one
    # exponent, or per partition under -M (each partition's branch
    # slot compensates with its own partition's exponent, preserving
    # the reference's per-partition convention, `updatePerSiteRates`
    # numBranches>1 arm).  Clipping at ZMIN/ZMAX breaks exactness only
    # for branches already pinned at the bounds, where the reference
    # clips identically.
    parts = inst.alignment.partitions
    C = inst.num_branch_slots
    if C > 1:
        mexp = np.ones(C)
        for gid in range(len(parts)):
            w = inst.psr_pattern_weights(gid)   # GLOBAL under slicing
            rates = inst.per_site_rates[gid][inst.rate_category[gid]]
            m = float(w @ rates) / float(w.sum())
            inst.per_site_rates[gid] = inst.per_site_rates[gid] / m
            mexp[gid] = m
    else:
        num = den = 0.0
        for gid in range(len(parts)):
            w = inst.psr_pattern_weights(gid)   # GLOBAL under slicing
            rates = inst.per_site_rates[gid][inst.rate_category[gid]]
            num += float(w @ rates)
            den += float(w.sum())
        mexp = np.full(1, num / den)
        for gid in range(inst.num_parts):
            inst.per_site_rates[gid] = inst.per_site_rates[gid] / mexp[0]
    inst.push_site_rates()
    for a, b in tree.all_branches():
        z = np.clip(np.power(np.asarray(a.z, np.float64), mexp),
                    ZMIN, ZMAX)
        hookup(a, b, z.tolist())
    return inst.evaluate(tree, full=True)


def optimize_rate_categories(inst: PhyloInstance, tree: Tree,
                             max_categories: int | None = None) -> float:
    """One CAT optimization round: scan, categorize, normalize, accept if
    the full lnL improved (reference `optimizeRateCategories`)."""
    assert inst.psr
    max_categories = max_categories or inst.psr_categories
    if max_categories == 1:
        return inst.evaluate(tree, full=True)

    initial_lnl = inst.evaluate(tree, full=True)
    backup = ([r.copy() for r in inst.patrat],
              [c.copy() for c in inst.rate_category],
              [p.copy() for p in inst.per_site_rates])

    inst.psr_invocations += 1
    lower, upper = _spacings(inst.psr_invocations)
    _scan_partition_rates(inst, tree, lower, upper)

    for gid in range(inst.num_parts):
        cat, kept = _categorize_partition(
            inst.patrat[gid], inst.site_lhs[gid], max_categories)
        inst.rate_category[gid] = cat
        inst.per_site_rates[gid] = kept
    _normalize_mean_rate(inst)
    inst.push_site_rates()

    lnl = inst.evaluate(tree, full=True)
    if lnl < initial_lnl:
        inst.patrat, inst.rate_category, inst.per_site_rates = backup
        inst.push_site_rates()
        lnl = inst.evaluate(tree, full=True)
        assert abs(lnl - initial_lnl) < 1e-6, (lnl, initial_lnl)
    return lnl
