"""Vectorized 1-D bracketing + Brent minimization over parameter groups.

The TPU-shaped equivalent of the reference's `brakGeneric`/`brentGeneric`
(ExaML `optimizeModel.c:582-1114`): instead of masking converged linkage
groups out of a replicated scalar loop, all groups' trial parameters advance
together as vectors and every objective call evaluates the whole batch at
once (one device dispatch per Brent step for all partitions).

The objective `fn(x[G]) -> f[G]` must accept a full vector; frozen groups'
entries are simply ignored.  Minimization; callers pass f = -lnL.
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np

from examl_tpu.constants import (BRAK_GOLD as GOLD, BRENT_ITMAX,
                                 BRENT_ZEPS as ZEPS)

CGOLD = 0.3819660               # golden-section fallback ratio
BRAK_MAXITER = 50


def _clamp(x, lo, hi):
    return np.minimum(np.maximum(x, lo), hi)


def bracket(x0: np.ndarray, lim_inf: np.ndarray, lim_sup: np.ndarray,
            fn: Callable[[np.ndarray], np.ndarray]):
    """Find per-group (a, b, c) with f(b) <= min(f(a), f(c)), clamped.

    Starts from (x0+0.1, x0-0.1) like the reference's optParamGeneric
    (`optimizeModel.c:1385-1407`) and expands downhill by golden steps.
    Groups whose minimum runs into a bound get a degenerate bracket at the
    bound (Brent then stays there).
    """
    a = _clamp(x0 + 0.1, lim_inf, lim_sup)
    b = _clamp(x0 - 0.1, lim_inf, lim_sup)
    # Degenerate start (x0 at/outside a bound clamps both probes together):
    # nudge b inward so the bracket search has a direction.
    degenerate = a == b
    b = np.where(degenerate, _clamp(b + 0.2, lim_inf, lim_sup), b)
    b = np.where(a == b, _clamp(a - 0.2, lim_inf, lim_sup), b)
    fa = fn(a)
    fb = fn(b)
    # Ensure downhill direction a -> b.
    swap = fb > fa
    a2 = np.where(swap, b, a)
    fa2 = np.where(swap, fb, fa)
    b = np.where(swap, a, b)
    fb = np.where(swap, fa, fb)
    a, fa = a2, fa2

    c = _clamp(b + GOLD * (b - a), lim_inf, lim_sup)
    fc = fn(c)
    done = fb <= fc
    for _ in range(BRAK_MAXITER):
        if done.all():
            break
        # Golden expansion past c for still-descending groups.
        u = _clamp(c + GOLD * (c - b), lim_inf, lim_sup)
        stuck = (u == c)                    # hit the bound
        fu = fn(u)
        a = np.where(done, a, b)
        fa = np.where(done, fa, fb)
        b = np.where(done, b, c)
        fb = np.where(done, fb, fc)
        c = np.where(done, c, u)
        fc = np.where(done, fc, fu)
        done = done | (fb <= fc) | stuck
    return a, b, c, fb


def brent(a: np.ndarray, b: np.ndarray, c: np.ndarray, fb: np.ndarray,
          tol: float, fn: Callable[[np.ndarray], np.ndarray]
          ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized Brent line minimization inside brackets (a, b, c)."""
    lo = np.minimum(a, c)
    hi = np.maximum(a, c)
    x = w = v = b.copy()
    fx = fw = fv = fb.copy()
    d = np.zeros_like(x)
    e = np.zeros_like(x)
    done = np.zeros(x.shape, dtype=bool)

    for _ in range(BRENT_ITMAX):
        xm = 0.5 * (lo + hi)
        tol1 = tol * np.abs(x) + ZEPS
        tol2 = 2.0 * tol1
        done = done | (np.abs(x - xm) <= tol2 - 0.5 * (hi - lo))
        if done.all():
            break
        # Parabolic fit through (x, fx), (w, fw), (v, fv).
        r = (x - w) * (fx - fv)
        q = (x - v) * (fx - fw)
        p = (x - v) * q - (x - w) * r
        q2 = 2.0 * (q - r)
        p = np.where(q2 > 0, -p, p)
        q2 = np.abs(q2)
        use_para = ((np.abs(p) < np.abs(0.5 * q2 * e))
                    & (p > q2 * (lo - x)) & (p < q2 * (hi - x)))
        with np.errstate(divide="ignore", invalid="ignore"):
            d_para = np.where(q2 != 0, p / np.where(q2 == 0, 1.0, q2), 0.0)
        e_gold = np.where(x >= xm, lo - x, hi - x)
        d_gold = CGOLD * e_gold
        e = np.where(use_para, d, e_gold)
        d = np.where(use_para, d_para, d_gold)
        u = np.where(np.abs(d) >= tol1, x + d,
                     x + np.where(d >= 0, tol1, -tol1))
        u = _clamp(u, lo, hi)
        fu = fn(np.where(done, x, u))
        fu = np.where(done, fx, fu)

        better = fu <= fx
        # Update bracket bounds.
        lo = np.where(done, lo, np.where(better, np.where(u >= x, x, lo),
                                         np.where(u < x, u, lo)))
        hi = np.where(done, hi, np.where(better, np.where(u >= x, hi, x),
                                         np.where(u < x, hi, u)))
        # Shift (v, w, x) bookkeeping.
        shift_vw = better
        v = np.where(done, v, np.where(shift_vw, w, np.where(
            (fu <= fw) | (w == x), w, np.where((fu <= fv) | (v == x) | (v == w),
                                               u, v))))
        fv = np.where(done, fv, np.where(shift_vw, fw, np.where(
            (fu <= fw) | (w == x), fw,
            np.where((fu <= fv) | (v == x) | (v == w), fu, fv))))
        w = np.where(done, w, np.where(shift_vw, x,
                                       np.where((fu <= fw) | (w == x), u, w)))
        fw = np.where(done, fw, np.where(shift_vw, fx,
                                         np.where((fu <= fw) | (w == x), fu, fw)))
        x = np.where(done, x, np.where(better, u, x))
        fx = np.where(done, fx, np.where(better, fu, fx))
    return x, fx


def minimize_vector(x0: np.ndarray, lim_inf: np.ndarray, lim_sup: np.ndarray,
                    fn: Callable[[np.ndarray], np.ndarray],
                    tol: float) -> Tuple[np.ndarray, np.ndarray]:
    """bracket + brent; returns (x_best[G], f_best[G])."""
    a, b, c, fb = bracket(x0, lim_inf, lim_sup, fn)
    return brent(a, b, c, fb, tol, fn)
