"""Model-parameter optimization: GTR rates, alpha, base frequencies, modOpt.

Semantics of the reference's `optimizeModel.c` (`optRatesGeneric` :1634,
`optAlphasGeneric` :1136, `optBaseFreqs` :1501, `modOpt` :2963-3133): each
parameter is optimized by 1-D Brent over linkage groups (default: every
partition its own group; amino-acid GTR partitions share one rate group,
ref `initLinkageListGTR` :260), with base frequencies parameterized as
softmax exponents, and the whole cycle repeated until the lnL gain drops
below the caller's epsilon.  All groups' Brent probes are batched into one
device evaluation per step (see optimize/brent.py).
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

from examl_tpu import obs
from examl_tpu.constants import ALPHA_MAX, ALPHA_MIN, RATE_MAX, RATE_MIN
from examl_tpu.instance import PhyloInstance
from examl_tpu.models.gtr import (ModelParams, n_exchange, with_alpha,
                                  with_freqs, with_rates)
from examl_tpu.optimize.branch import tree_evaluate
from examl_tpu.optimize.brent import minimize_vector
from examl_tpu.tree.topology import Tree

MODEL_EPSILON = 0.0001
FREQ_EXP_MIN = -1.0e6
FREQ_EXP_MAX = 200.0


def _group_lnl(inst: PhyloInstance, groups: Sequence[List[int]]) -> np.ndarray:
    return np.array([sum(inst.per_partition_lnl[g] for g in grp)
                     for grp in groups])


def _opt_param(inst: PhyloInstance, tree: Tree, groups: Sequence[List[int]],
               get0: Callable[[int], float],
               setv: Callable[[int, float], None],
               lim_inf: float, lim_sup: float,
               tol: float = MODEL_EPSILON, only_states=None,
               coherent: bool = False) -> None:
    """Optimize one scalar parameter per linkage group by batched Brent.

    get0(gid) reads the current value from partition gid; setv(gid, v)
    installs a trial value into inst.models[gid] (without device push).
    Accept-if-improved per group, as the reference's optParamGeneric.
    Brent probes touch only the affected state buckets (only_states);
    the final evaluate is unrestricted so all engines end coherent.
    coherent=True promises per_partition_lnl already matches the current
    models+tree (skips the leading full evaluate).
    """
    if not groups:
        return
    if not coherent:
        inst.evaluate(tree, full=True)
    start_lnl = _group_lnl(inst, groups)
    x0 = np.array([get0(grp[0]) for grp in groups])

    def fn(xs: np.ndarray) -> np.ndarray:
        for grp, v in zip(groups, xs):
            for gid in grp:
                setv(gid, float(v))
        inst.push_models(only_states)
        inst.evaluate(tree, full=True, only_states=only_states)
        return -_group_lnl(inst, groups)

    xb, fb = minimize_vector(x0, np.full(len(groups), lim_inf),
                             np.full(len(groups), lim_sup), fn, tol)
    # Accept per group only if improved; otherwise restore.
    for grp, v0, v1, f1, l0 in zip(groups, x0, xb, fb, start_lnl):
        v = v1 if -f1 > l0 else v0
        for gid in grp:
            setv(gid, float(v))
    inst.push_models()
    inst.evaluate(tree, full=True)


def _rate_groups(inst: PhyloInstance, states: int) -> List[List[int]]:
    """Linkage groups for rate optimization within one state bucket:
    unlinked, except all amino-acid GTR partitions share one group."""
    groups: List[List[int]] = []
    gtr_group: List[int] = []
    for gid, part in enumerate(inst.alignment.partitions):
        if part.states != states:
            continue
        if part.datatype.name == "AA" and part.model_name != "GTR":
            continue                      # empirical matrix: rates fixed
        if part.datatype.name == "AA":
            gtr_group.append(gid)
        else:
            groups.append([gid])
    if gtr_group:
        groups.append(gtr_group)
    return groups


def opt_rates(inst: PhyloInstance, tree: Tree,
              tol: float = MODEL_EPSILON) -> None:
    """Brent over every free exchangeability (last one fixed at 1.0)."""
    for states in sorted(inst.buckets):
        groups = _rate_groups(inst, states)
        if not groups:
            continue
        nrates = n_exchange(states) - 1   # last exchangeability pinned
        for k in range(nrates):
            def get0(gid, k=k):
                return float(inst.models[gid].rates[k])

            def setv(gid, v, k=k):
                m = inst.models[gid]
                rates = m.rates.copy()
                rates[k] = v
                inst.models[gid] = with_rates(m, rates)

            _opt_param(inst, tree, groups, get0, setv, RATE_MIN, RATE_MAX,
                       tol, only_states={states}, coherent=k > 0)


def opt_alphas(inst: PhyloInstance, tree: Tree,
               tol: float = MODEL_EPSILON) -> None:
    """Gamma-shape Brent for every partition except LG4X (whose category
    rates are free parameters optimized by opt_lg4x instead)."""
    from examl_tpu.models.lg4 import LG4Params, lg4_with_alpha

    groups = [[gid] for gid in range(inst.num_parts)
              if not (isinstance(inst.models[gid], LG4Params)
                      and inst.models[gid].is_lg4x)]
    if not groups:
        return

    def get0(gid):
        return float(inst.models[gid].alpha)

    def setv(gid, v):
        m = inst.models[gid]
        inst.models[gid] = (lg4_with_alpha(m, v)
                            if isinstance(m, LG4Params) else with_alpha(m, v))

    _opt_param(inst, tree, groups, get0, setv, ALPHA_MIN, ALPHA_MAX, tol)


def opt_lg4x(inst: PhyloInstance, tree: Tree,
             tol: float = MODEL_EPSILON) -> None:
    """LG4X free category rates + weights (reference `optLG4X` +
    `optimizeWeights`, `optimizeModel.c:1114-1132`): per round, Brent each
    of the 4 rates then each of the 4 weight exponents."""
    from examl_tpu.models.lg4 import (LG4X_RATE_MAX, LG4X_RATE_MIN,
                                      LG4Params, lg4x_with_rates,
                                      lg4x_with_weights)

    gids = [gid for gid in range(inst.num_parts)
            if isinstance(inst.models[gid], LG4Params)
            and inst.models[gid].is_lg4x]
    if not gids:
        return
    groups = [[g] for g in gids]

    # Trial rate vectors derive from a per-k base snapshot, not from the
    # trial-mutated model: normalization rescales all four rates, so the
    # objective must be a pure function of the Brent variable and the
    # reject-restore (setv(v0)) must reproduce the base exactly.
    for k in range(4):
        base = {g: np.asarray(inst.models[g].gamma_rates).copy()
                for g in gids}

        def get0(gid, k=k):
            return float(base[gid][k])

        def setv(gid, v, k=k):
            rates = base[gid].copy()
            rates[k] = v
            inst.models[gid] = lg4x_with_rates(inst.models[gid], rates)

        _opt_param(inst, tree, groups, get0, setv, LG4X_RATE_MIN,
                   LG4X_RATE_MAX, tol, only_states={20}, coherent=k > 0)

    exponents = {g: np.log(np.maximum(inst.models[g].rate_weights, 1e-12))
                 for g in gids}
    for k in range(4):
        def get0(gid, k=k):
            return float(exponents[gid][k])

        def setv(gid, v, k=k):
            exponents[gid][k] = v
            e = exponents[gid] - exponents[gid].max()
            inst.models[gid] = lg4x_with_weights(inst.models[gid],
                                                 np.exp(e))

        _opt_param(inst, tree, groups, get0, setv, FREQ_EXP_MIN,
                   FREQ_EXP_MAX, tol, only_states={20}, coherent=True)


def opt_freqs(inst: PhyloInstance, tree: Tree,
              tol: float = MODEL_EPSILON) -> None:
    """Softmax-exponent frequency optimization for X-flagged partitions."""
    for states in sorted(inst.buckets):
        gids = [gid for gid, p in enumerate(inst.alignment.partitions)
                if p.states == states and p.optimize_freqs]
        if not gids:
            continue
        groups = [[g] for g in gids]
        exponents = {g: np.log(np.maximum(inst.models[g].freqs, 1e-12))
                     for g in gids}
        for k in range(states):
            def get0(gid, k=k):
                return float(exponents[gid][k])

            def setv(gid, v, k=k):
                exponents[gid][k] = v
                e = exponents[gid] - exponents[gid].max()
                freqs = np.exp(e) / np.exp(e).sum()
                inst.models[gid] = with_freqs(inst.models[gid], freqs)

            _opt_param(inst, tree, groups, get0, setv,
                       FREQ_EXP_MIN, FREQ_EXP_MAX, tol, only_states={states},
                       coherent=k > 0)


def mod_opt(inst: PhyloInstance, tree: Tree, likelihood_epsilon: float,
            max_rounds: int = 100, auto_protein_fn=None,
            checkpoint_cb=None) -> float:
    """Round-robin parameter optimization until Delta lnL < epsilon
    (reference `modOpt`, `optimizeModel.c:2963-3133`).  Under GAMMA the
    rate-heterogeneity step is the alpha Brent; under PSR it is a rate
    categorization round, capped at 3 per search as the reference's
    `catOpt < 3` (`optimizeModel.c:3100-3110`).

    checkpoint_cb(state, extras), when given, is invoked after every
    optimization round — the reference's MOD_OPT checkpoint cadence in
    tree-evaluation mode (`optimizeModel.c:2995-3010`, `axml.h:655-659`)."""
    inst.evaluate(tree, full=True)
    if getattr(inst, "psr", False):
        inst.cat_opt_rounds = 0
    if auto_protein_fn is None and any(
            p.auto for p in inst.alignment.partitions):
        from functools import partial

        from examl_tpu.optimize.auto_protein import auto_protein
        auto_protein_fn = partial(
            auto_protein,
            criterion=getattr(inst, "auto_prot_criterion", "ml"))
    import os

    def dbg(tag: str) -> None:
        # EXAML_DEBUG_MODOPT=1: per-phase lnL trace, the mirror of the
        # reference's -D_DEBUG_MOD_OPT printf trail — phase-by-phase
        # diffable against an instrumented reference build.
        if os.environ.get("EXAML_DEBUG_MODOPT"):
            print(f"modopt {tag}: {inst.likelihood:.6f}", flush=True)

    rounds = 0
    while max_rounds > 0:
        max_rounds -= 1
        current = inst.likelihood
        rounds += 1
        obs.inc("search.model_opt_rounds")
        # Optimizer rounds are search-loop iterations too: model
        # optimization between SPR phases can run minutes on large
        # data, and a wedge inside it must freeze the liveness clock
        # the supervisor watches (resilience/heartbeat.py).
        from examl_tpu.resilience import heartbeat
        heartbeat.beat("MOD_OPT")
        with obs.span("opt:model_opt_round", args={"round": rounds}):
            dbg("start")
            opt_rates(inst, tree)
            dbg("after rates")
            if auto_protein_fn is not None:
                auto_protein_fn(inst, tree)
            tree_evaluate(inst, tree, 0.0625)
            dbg("after br-len 1")
            opt_freqs(inst, tree)
            tree_evaluate(inst, tree, 0.0625)
            dbg("after freqs")
            if getattr(inst, "psr", False):
                if inst.cat_opt_rounds < 3:
                    from examl_tpu.optimize.psr import (
                        optimize_rate_categories)
                    optimize_rate_categories(inst, tree)
                    inst.cat_opt_rounds += 1
                    dbg("after cat-opt")
                else:
                    # Rounds beyond the reference's 3: its CAT branch does
                    # nothing more for rate heterogeneity; we polish the
                    # frozen categorization's representative rates as free
                    # continuous parameters (accept-if-better; the PSR
                    # analogue of the GAMMA branch's alpha Brent).
                    from examl_tpu.optimize.psr import refine_category_rates
                    refine_category_rates(inst, tree)
                    dbg("after cat-refine")
            else:
                opt_alphas(inst, tree)
                opt_lg4x(inst, tree)
                tree_evaluate(inst, tree, 0.1)
                dbg("after alphas + br-len 2")
        if checkpoint_cb is not None:
            checkpoint_cb("MOD_OPT", {})
        if abs(current - inst.likelihood) <= likelihood_epsilon:
            break
    return inst.likelihood
