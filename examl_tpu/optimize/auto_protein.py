"""Automatic protein model selection for AUTO partitions.

Reference `autoProtein` + `optModel` (`optimizeModel.c:2606-2900`): every
candidate empirical matrix is scored on all AUTO partitions at once —
branches reset to default, one smoothing pass, per-partition lnL recorded —
under both the matrix's own frequencies and the partition's empirical
frequencies; the winner per partition is picked by ML / BIC / AIC / AICc
(empirical frequencies cost 19 extra free parameters), and the whole
selection is reverted if the final smoothed likelihood got worse.
"""

from __future__ import annotations

import numpy as np

from examl_tpu.instance import PhyloInstance
from examl_tpu.models import protein as protein_mod
from examl_tpu.models.gtr import build_model
from examl_tpu.optimize.branch import tree_evaluate
from examl_tpu.search.snapshots import TreeSnapshot
from examl_tpu.tree.topology import Tree

CRITERIA = ("ml", "bic", "aic", "aicc")


def _install(inst: PhyloInstance, gid: int, name: str,
             empirical: bool) -> None:
    part = inst.alignment.partitions[gid]
    rates, model_freqs = protein_mod.get_matrix(name)
    freqs = part.empirical_freqs if empirical else model_freqs
    inst.models[gid] = build_model(part.datatype, freqs, rates=rates,
                                   alpha=inst.models[gid].alpha,
                                   ncat=inst.ncat,
                                   use_median=inst.use_median)


def _scan(inst: PhyloInstance, tree: Tree, autos, empirical: bool):
    """Best (matrix index, lnL) per AUTO partition across all candidates
    (reference `optModel`)."""
    best_idx = {gid: -1 for gid in autos}
    best_lnl = {gid: -np.inf for gid in autos}
    for i, name in enumerate(protein_mod.AUTO_CANDIDATES):
        for gid in autos:
            _install(inst, gid, name, empirical)
        inst.push_models()
        tree.reset_branches()
        inst.evaluate(tree, full=True)
        tree_evaluate(inst, tree, 0.5)
        for gid in autos:
            lnl = float(inst.per_partition_lnl[gid])
            if lnl > best_lnl[gid]:
                best_lnl[gid] = lnl
                best_idx[gid] = i
    return best_idx, best_lnl


def _criterion_score(criterion: str, lnl: float, k: float,
                     n: float) -> float:
    """Lower is better for BIC/AIC/AICc; ML handled by the caller."""
    if criterion == "bic":
        return -2.0 * lnl + k * np.log(n)
    if criterion == "aic":
        return 2.0 * (k - lnl)
    if criterion == "aicc":
        if n - k - 1.0 < 0.5:
            # Sample size too small for the correction term: this model
            # cannot be ranked — score it worst (the reference's 0.0 here
            # would make it win unconditionally, which is backwards).
            return float("inf")
        return 2.0 * (k - lnl) + (2.0 * k * (k + 1.0)) / (n - k - 1.0)
    raise ValueError(criterion)


def auto_protein(inst: PhyloInstance, tree: Tree, criterion: str = "ml",
                 log=lambda m: None) -> None:
    """Select and install the best matrix for every AUTO partition
    (reference `autoProtein`)."""
    autos = [gid for gid, p in enumerate(inst.alignment.partitions)
             if p.auto]
    if not autos:
        return
    assert criterion in CRITERIA

    start_lnl = inst.evaluate(tree, full=True)
    snap = TreeSnapshot.capture(tree, start_lnl, with_key=False)
    old = {gid: (inst.auto_prot_models.get(gid, "WAG"),
                 inst.auto_prot_freqs.get(gid, "fixed")) for gid in autos}

    fixed_idx, fixed_lnl = _scan(inst, tree, autos, empirical=False)
    emp_idx, emp_lnl = _scan(inst, tree, autos, empirical=True)

    ntips = inst.alignment.ntaxa
    for gid in autos:
        part = inst.alignment.partitions[gid]
        n = float(part.weights.sum())
        k_fixed = float(2 * ntips - 3)
        if inst.psr:
            k_fixed += len(inst.per_site_rates[gid])
        else:
            k_fixed += 1.0                       # alpha
        k_emp = k_fixed + 19.0
        if criterion == "ml":
            use_emp = emp_lnl[gid] > fixed_lnl[gid]
        else:
            use_emp = (_criterion_score(criterion, emp_lnl[gid], k_emp, n)
                       < _criterion_score(criterion, fixed_lnl[gid],
                                          k_fixed, n))
        idx = emp_idx[gid] if use_emp else fixed_idx[gid]
        name = protein_mod.AUTO_CANDIDATES[idx]
        inst.auto_prot_models[gid] = name
        inst.auto_prot_freqs[gid] = "empirical" if use_emp else "fixed"
        _install(inst, gid, name, use_emp)
        log(f"partition {gid} best-scoring AA model: {name} "
            f"(lnL {emp_lnl[gid] if use_emp else fixed_lnl[gid]:.4f}, "
            f"{'empirical' if use_emp else 'fixed'} frequencies, "
            f"{criterion.upper()})")
    inst.push_models()

    tree.reset_branches()
    inst.evaluate(tree, full=True)
    tree_evaluate(inst, tree, 2.0)
    if inst.likelihood < start_lnl:
        for gid in autos:
            name, fr = old[gid]
            inst.auto_prot_models[gid] = name
            inst.auto_prot_freqs[gid] = fr
            _install(inst, gid, name, fr == "empirical")
        inst.push_models()
        snap.restore_into(tree)
        inst.evaluate(tree, full=True)
    assert inst.likelihood >= start_lnl - 1e-6
