"""Branch-length smoothing passes over the tree.

Semantics of the reference's `update`/`smooth`/`smoothTree`/`localSmooth`/
`treeEvaluate` (ExaML `searchAlgo.c:127-436, 2635-2650`): repeated
Newton-Raphson passes over every branch until no branch moves by more than
`deltaz`, tracked per branch slot through the instance's
`partition_smoothed` / `partition_converged` flags.
"""

from __future__ import annotations

import numpy as np

from examl_tpu.constants import DELTAZ, SMOOTHINGS
from examl_tpu.instance import PhyloInstance
from examl_tpu.tree.topology import Node, Tree


def update_branch(inst: PhyloInstance, tree: Tree, p: Node) -> None:
    """One-branch NR update + smoothed-flag bookkeeping (ref `update`)."""
    from examl_tpu.utils import z_slots
    q = p.back
    z0 = z_slots(q.z, inst.num_branch_slots)
    z = inst.makenewz(tree, p, q, z0, maxiter=1,
                      mask_converged=inst.num_branch_slots > 1)
    moved = np.abs(z - z0) > DELTAZ
    upd = ~inst.partition_converged
    inst.partition_smoothed &= ~(upd & moved)
    znew = np.where(upd, z, z0)
    p.z[:] = znew.tolist()
    q.z[:] = znew.tolist()


def smooth_subtree(inst: PhyloInstance, tree: Tree, p: Node) -> None:
    """Adjust branch (p, p.back) then recurse below p (ref `smooth`)."""
    update_branch(inst, tree, p)
    if not tree.is_tip(p.number):
        for s in (p.next, p.next.next):
            smooth_subtree(inst, tree, s.back)
        inst.new_view(tree, p)


def _all_smoothed(inst: PhyloInstance) -> bool:
    result = True
    for i in range(inst.num_branch_slots):
        if not inst.partition_smoothed[i]:
            result = False
        else:
            inst.partition_converged[i] = True
    return result


def smooth_tree(inst: PhyloInstance, tree: Tree, maxtimes: int) -> None:
    """Smoothing passes over every branch (ref `smoothTree`).

    tree.start is always tip 1, so one recursion from start.back covers
    every branch (the reference's extra non-tip start case is unreachable
    here)."""
    p = tree.start
    inst.partition_converged[:] = False
    while maxtimes > 0:
        maxtimes -= 1
        inst.partition_smoothed[:] = True
        smooth_subtree(inst, tree, p.back)
        if _all_smoothed(inst):
            break
    inst.partition_converged[:] = False


def local_smooth(inst: PhyloInstance, tree: Tree, p: Node,
                 maxtimes: int) -> bool:
    """Smooth only the three branches of inner node p (ref `localSmooth`)."""
    if tree.is_tip(p.number):
        return False
    inst.partition_converged[:] = False
    while maxtimes > 0:
        maxtimes -= 1
        inst.partition_smoothed[:] = True
        for s in (p, p.next, p.next.next):
            update_branch(inst, tree, s)
        if _all_smoothed(inst):
            break
    inst.partition_smoothed[:] = False
    inst.partition_converged[:] = False
    return True


def region_smooth(inst: PhyloInstance, tree: Tree, p: Node, region: int,
                  maxtimes: int) -> bool:
    """Smooth branches within `region` hops of branch (p, p.back)
    (ref `regionalSmooth`, `searchAlgo.c:368-436`)."""
    def smooth_region(s: Node, depth: int) -> None:
        update_branch(inst, tree, s)
        if depth > 0 and not tree.is_tip(s.number):
            for t in (s.next, s.next.next):
                smooth_region(t.back, depth - 1)
            inst.new_view(tree, s)

    if tree.is_tip(p.number) and tree.is_tip(p.back.number):
        return False
    inst.partition_converged[:] = False
    while maxtimes > 0:
        maxtimes -= 1
        inst.partition_smoothed[:] = True
        smooth_region(p, region)
        smooth_region(p.back, region)
        if _all_smoothed(inst):
            break
    inst.partition_smoothed[:] = False
    inst.partition_converged[:] = False
    return True


def tree_evaluate(inst: PhyloInstance, tree: Tree,
                  smooth_factor: float = 1.0) -> float:
    """Smooth all branches then evaluate (ref `treeEvaluate`)."""
    smooth_tree(inst, tree, int(SMOOTHINGS * smooth_factor))
    return inst.evaluate(tree, tree.start, full=True)
