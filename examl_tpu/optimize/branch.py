"""Branch-length smoothing passes over the tree.

Semantics of the reference's `update`/`smooth`/`smoothTree`/`localSmooth`/
`treeEvaluate` (ExaML `searchAlgo.c:127-436, 2635-2650`): repeated
Newton-Raphson passes over every branch until no branch moves by more than
`deltaz`, tracked per branch slot through the instance's
`partition_smoothed` / `partition_converged` flags.

Two execution modes for the FULL-tree pass (`smooth_tree`):

* PER-BRANCH (the reference's): one fused traversal+sumtable+Newton
  dispatch per branch per sweep — O(n) sequential dispatches per sweep,
  the dispatch storm BENCH r03/r04 measured at `newton_branch_ms` ~10x
  `evaluate_ms`.  Retained verbatim for `local_smooth`/`region_smooth`
  (a handful of branches), for -S/sharded instances, and as the
  fallback ladder rung (`EXAML_GRAD_SMOOTH=0` restores it exactly).
* WHOLE-TREE GRADIENT (default where eligible): per sweep, ONE
  post-order traversal dispatch plus ONE analytic gradient dispatch
  per engine yield (d1, d2) for all 2n-3 branches at once
  (ops/gradient.py — the pre-order/outroot pass of Ji et al.
  2303.04390), followed by a batched damped-Newton update applied to
  every branch simultaneously; sweeps repeat to the same DELTAZ
  movement criterion.  O(1) dispatches per sweep — the
  `engine.dispatches_per_smoothing_round` gauge is the acceptance
  evidence (ROADMAP §5).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from examl_tpu import obs
from examl_tpu.constants import DELTAZ, SMOOTHINGS
from examl_tpu.instance import PhyloInstance
from examl_tpu.tree.topology import Node, Tree


def update_branch(inst: PhyloInstance, tree: Tree, p: Node) -> None:
    """One-branch NR update + smoothed-flag bookkeeping (ref `update`)."""
    from examl_tpu.utils import z_slots
    q = p.back
    z0 = z_slots(q.z, inst.num_branch_slots)
    z = inst.makenewz(tree, p, q, z0, maxiter=1,
                      mask_converged=inst.num_branch_slots > 1)
    moved = np.abs(z - z0) > DELTAZ
    upd = ~inst.partition_converged
    inst.partition_smoothed &= ~(upd & moved)
    znew = np.where(upd, z, z0)
    p.z[:] = znew.tolist()
    q.z[:] = znew.tolist()


def smooth_subtree(inst: PhyloInstance, tree: Tree, p: Node) -> None:
    """Adjust branch (p, p.back) then descend below p (ref `smooth`).

    Iterative two-visit stack: the reference recursed per node, which
    blows Python's recursion limit on a deep (caterpillar-shaped) tree
    of a few thousand taxa — long before the 50k-taxon host path does
    (pinned by tests/test_gradients.py's deep-tree smoke)."""
    stack: List[Tuple[Node, bool]] = [(p, False)]
    while stack:
        s, expanded = stack.pop()
        if expanded:
            inst.new_view(tree, s)
            continue
        update_branch(inst, tree, s)
        if not tree.is_tip(s.number):
            stack.append((s, True))
            stack.append((s.next.next.back, False))
            stack.append((s.next.back, False))


def _all_smoothed(inst: PhyloInstance) -> bool:
    result = True
    for i in range(inst.num_branch_slots):
        if not inst.partition_smoothed[i]:
            result = False
        else:
            inst.partition_converged[i] = True
    return result


# -- whole-tree gradient smoothing (ops/gradient.py) -------------------------


def grad_smooth_enabled() -> bool:
    """Gradient smoothing unless EXAML_GRAD_SMOOTH=0 (escape hatch and
    the bit-identical-to-HEAD reference mode)."""
    return os.environ.get("EXAML_GRAD_SMOOTH", "") != "0"


def grad_smooth_ineligible(inst: PhyloInstance) -> Optional[str]:
    """None when the whole-tree gradient pass can serve this instance,
    else the reason the per-branch path is kept."""
    if inst.save_memory:
        return "-S SEV pools keep the per-branch Newton path"
    for eng in inst.engines.values():
        if eng.sharding is not None:
            return "sharded arenas keep the per-branch Newton path"
    return None


def _slot_facing(tree: Tree, child: int, parent: int) -> Node:
    """The slot at `child` whose back is `parent` — the Node owning the
    branch's shared z list (hookup aliases both endpoints' z to ONE
    list, so writing through either slot updates the branch)."""
    if tree.is_tip(child):
        return tree.nodep[child]
    for sl in tree.slots(child):
        if sl.back is not None and sl.back.number == parent:
            return sl
    raise KeyError(f"no slot at node {child} faces node {parent}")


def _edge_slots(tree: Tree, flat, p: Node) -> List[Node]:
    """Node slots in the engine's edge order (ops/gradient.py): edge 0
    the traversal's root edge, then each entry's (left, right) child
    branches in flat order."""
    slots = [p]
    for v, l, r in zip(flat.parent.tolist(), flat.left.tolist(),
                       flat.right.tolist()):
        slots.append(_slot_facing(tree, l, v))
        slots.append(_slot_facing(tree, r, v))
    return slots


def tree_gradients(inst: PhyloInstance, tree: Tree):
    """Analytic (d1, d2) w.r.t. lz for EVERY branch, plus the Node
    slots owning them, in O(1) dispatches per engine: one post-order
    full traversal + one fused pre-order/edge-derivative dispatch.
    Mixed state buckets sum their per-engine derivatives (the same
    cross-engine reduction `makenewz` performs per NR iteration)."""
    from examl_tpu.utils import z_slots
    p = tree.centroid_branch()
    with obs.timer("host_schedule"):
        flat = tree.flat_full_traversal(p)
    C = inst.num_branch_slots
    root_z = z_slots(p.z, C)
    d1 = d2 = None
    for eng in inst.engines.values():
        eng.run_traversal(flat, full=True)
        e1, e2 = eng.whole_tree_gradients(flat, root_z)
        d1 = e1 if d1 is None else d1 + e1
        d2 = e2 if d2 is None else d2 + e2
    slots = _edge_slots(tree, flat, p)
    assert len(slots) == d1.shape[0], (len(slots), d1.shape)
    return slots, d1, d2


def gradient_smooth_tree(inst: PhyloInstance, tree: Tree,
                         maxtimes: int) -> bool:
    """Simultaneous whole-tree branch-length optimization: per sweep,
    one analytic gradient pass (all branches at once) and one batched
    damped-Newton update (`gradient.newton_step` — the reference NR
    body's single iteration, vectorized over edges), converging to the
    same DELTAZ movement criterion as the per-branch path.

    Simultaneous (Jacobi-style) Newton updates can make an adjacent
    branch pair overshoot in antiphase where the sequential per-branch
    solve would damp through the coupling, so each branch carries an
    Rprop-style step scale in lz space: a direction flip between
    sweeps halves it, a consistent direction grows it back (x1.2,
    capped at the EXAML_GRAD_DAMPING base, default 1).  Sweeps are
    O(1) dispatches each, so the budget is 4x `maxtimes` single-step
    sweeps against the per-branch path's `maxtimes` full-solve sweeps;
    returns False if branches still moved at the end (caller falls
    back to the per-branch ladder rung)."""
    from examl_tpu.constants import ZMAX, ZMIN
    from examl_tpu.ops import gradient
    from examl_tpu.utils import z_slots
    try:
        damping = float(os.environ.get("EXAML_GRAD_DAMPING", "") or 1.0)
    except ValueError:
        damping = 1.0
    C = inst.num_branch_slots
    scale = prev_step = None
    for _ in range(max(1, 4 * maxtimes)):
        d0 = obs.counter("engine.dispatch_count")
        inst.partition_smoothed[:] = True
        slots, d1, d2 = tree_gradients(inst, tree)
        z0 = np.clip(np.stack([z_slots(s.z, C) for s in slots]),
                     ZMIN, ZMAX)
        znew = gradient.newton_step(z0, d1, d2)
        step = np.log(znew) - np.log(z0)
        if scale is None:
            scale = np.full_like(step, damping)
        else:
            flip = prev_step * step < 0.0
            scale = np.maximum(
                np.where(flip, scale * 0.5,
                         np.minimum(scale * 1.2, damping)), 1.0 / 64)
        prev_step = step
        zapp = np.clip(z0 * np.exp(step * scale), ZMIN, ZMAX)
        upd = ~inst.partition_converged
        zapp = np.where(upd[None, :], zapp, z0)
        moved = np.abs(zapp - z0) > DELTAZ
        inst.partition_smoothed &= ~(upd & moved.any(axis=0))
        for i, s in enumerate(slots):
            s.z[:] = zapp[i].tolist()
        # The ROADMAP §5 acceptance gauge: device dispatches this sweep
        # cost — O(1) per engine here vs O(n) on the per-branch path
        # (which publishes the same gauge from its own loop).
        obs.gauge("engine.dispatches_per_smoothing_round",
                  obs.counter("engine.dispatch_count") - d0)
        obs.inc("optimize.grad_smooth_sweeps")
        if _all_smoothed(inst):
            return True
    return False


def smooth_tree(inst: PhyloInstance, tree: Tree, maxtimes: int) -> None:
    """Smoothing passes over every branch (ref `smoothTree`).

    tree.start is always tip 1, so one recursion from start.back covers
    every branch (the reference's extra non-tip start case is unreachable
    here).  Full-tree smoothing routes through the whole-tree gradient
    mode where eligible (EXAML_GRAD_SMOOTH=0 pins the per-branch
    reference path); a gradient pass that fails to settle within its
    sweep budget falls back to the per-branch rung below."""
    inst.partition_converged[:] = False
    if grad_smooth_enabled() and grad_smooth_ineligible(inst) is None:
        try:
            converged = gradient_smooth_tree(inst, tree, maxtimes)
        except Exception:                      # noqa: BLE001 — the
            # per-branch rung below is the in-run fallback; the env pin
            # (EXAML_GRAD_SMOOTH=0, bank/supervisor ladder) is the
            # cross-run one.
            obs.inc("optimize.grad_smooth_fallbacks")
            converged = None
        inst.partition_converged[:] = False
        if converged is not None:
            # A budget-exhausted sweep set (converged=False) is
            # ACCEPTED, exactly as the per-branch path accepts its own
            # maxtimes exhaustion — rerunning the O(n) per-branch pass
            # on top would pay both costs (counted for visibility).
            if not converged:
                obs.inc("optimize.grad_smooth_unconverged")
            return
    p = tree.start
    while maxtimes > 0:
        maxtimes -= 1
        d0 = obs.counter("engine.dispatch_count")
        inst.partition_smoothed[:] = True
        smooth_subtree(inst, tree, p.back)
        obs.gauge("engine.dispatches_per_smoothing_round",
                  obs.counter("engine.dispatch_count") - d0)
        if _all_smoothed(inst):
            break
    inst.partition_converged[:] = False


def local_smooth(inst: PhyloInstance, tree: Tree, p: Node,
                 maxtimes: int) -> bool:
    """Smooth only the three branches of inner node p (ref `localSmooth`)."""
    if tree.is_tip(p.number):
        return False
    inst.partition_converged[:] = False
    while maxtimes > 0:
        maxtimes -= 1
        inst.partition_smoothed[:] = True
        for s in (p, p.next, p.next.next):
            update_branch(inst, tree, s)
        if _all_smoothed(inst):
            break
    inst.partition_smoothed[:] = False
    inst.partition_converged[:] = False
    return True


def region_smooth(inst: PhyloInstance, tree: Tree, p: Node, region: int,
                  maxtimes: int) -> bool:
    """Smooth branches within `region` hops of branch (p, p.back)
    (ref `regionalSmooth`, `searchAlgo.c:368-436`).  Iterative like
    `smooth_subtree` — the same recursion-depth hazard, one level down."""
    def smooth_region(s0: Node, region: int) -> None:
        stack: List[Tuple[Node, int, bool]] = [(s0, region, False)]
        while stack:
            s, depth, expanded = stack.pop()
            if expanded:
                inst.new_view(tree, s)
                continue
            update_branch(inst, tree, s)
            if depth > 0 and not tree.is_tip(s.number):
                stack.append((s, depth, True))
                stack.append((s.next.next.back, depth - 1, False))
                stack.append((s.next.back, depth - 1, False))

    if tree.is_tip(p.number) and tree.is_tip(p.back.number):
        return False
    inst.partition_converged[:] = False
    while maxtimes > 0:
        maxtimes -= 1
        inst.partition_smoothed[:] = True
        smooth_region(p, region)
        smooth_region(p.back, region)
        if _all_smoothed(inst):
            break
    inst.partition_smoothed[:] = False
    inst.partition_converged[:] = False
    return True


def tree_evaluate(inst: PhyloInstance, tree: Tree,
                  smooth_factor: float = 1.0) -> float:
    """Smooth all branches then evaluate (ref `treeEvaluate`)."""
    smooth_tree(inst, tree, int(SMOOTHINGS * smooth_factor))
    return inst.evaluate(tree, tree.start, full=True)
