from examl_tpu.optimize.branch import (  # noqa: F401
    update_branch, smooth_subtree, smooth_tree, local_smooth, region_smooth,
    tree_evaluate)
