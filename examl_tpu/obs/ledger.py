"""Run ledger: an append-only per-rank JSONL event stream.

The r04 postmortem's missing artifact was a TIMELINE: compiles, tier
degradations, gang kills, checkpoint cycles and probe verdicts were
scattered across per-rank metrics snapshots, trace files and supervisor
logs with no single ordered record of what happened when.  This module
is that record.  Events are emitted from the real seams — CLI phase
transitions, compile start/end (engine._guard_first_call), tier
fallbacks, fault firings, supervisor kill/restart/elastic decisions,
coordinated checkpoint publish/GC, chip-probe verdicts — one JSON
object per line, flushed per event so a SIGKILLed process's last
decision is on disk.

Contract (mirrors obs/trace.py, same procid suffix convention as the
heartbeat/trace files):

* one file per process: `ledger.p<procid>.jsonl` (the jax-free
  supervisor writes `ledger.psup.jsonl` — it shares the directory with
  its rank-0 child and must never clobber its stream);
* every record carries a per-process monotone `seq` and an epoch-µs
  `ts`, so the exit-time MERGE — every rank re-merges, the last one
  out (or the supervisor, post-crash) completing
  `ledger.merged.jsonl`, ordered by (ts, proc, seq) — is one totally
  ordered gang timeline;
* stdlib-only BY CONTRACT: the supervisor (jax-free parent) and
  tools/top.py read and write ledgers with no backend anywhere on the
  import path;
* readers tolerate crash-truncated files (a torn final line is skipped,
  like obs.trace.read_events) — a killed rank's ledger must merge, not
  poison the timeline.

Off unless enabled (`--ledger DIR`, auto-on next to `--metrics`, or
`EXAML_LEDGER_DIR`, checked lazily so bank workers and gang ranks
inherit it for free).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import List, Optional, Union

ENV_VAR = "EXAML_LEDGER_DIR"
MERGED_NAME = "ledger.merged.jsonl"

_lock = threading.Lock()
_STATE = {"f": None, "path": None, "dir": None, "proc": None, "seq": 0,
          "env_checked": False}


def _now_us() -> int:
    return time.time_ns() // 1000


def _default_proc() -> Union[int, str]:
    """EXAML_PROCID when set (gang ranks, manual multi-host launches),
    else 0 — deliberately NOT consulting jax (stdlib-only contract;
    launches that join a process group export EXAML_PROCID anyway,
    cli/main.py canonicalizes it)."""
    env = os.environ.get("EXAML_PROCID")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return 0


def file_name(proc: Union[int, str]) -> str:
    return f"ledger.p{proc}.jsonl"


def default_dir(ledger_dir: Optional[str],
                metrics_file: Optional[str]) -> Optional[str]:
    """The ONE ledger-placement rule, shared by the CLI and the
    supervisor (which must derive the same directory to write its own
    `ledger.psup.jsonl` and run the final merge): an explicit --ledger
    DIR wins; otherwise the ledger lands next to the --metrics file —
    a run that asked for metrics gets the timeline artifact with it."""
    if ledger_dir:
        return ledger_dir
    if metrics_file:
        return os.path.dirname(os.path.abspath(metrics_file)) or "."
    return None


def enable(ledger_dir: str,
           proc: Optional[Union[int, str]] = None) -> Optional[str]:
    """Open this process's ledger file under `ledger_dir` (append mode:
    a supervised retry in the same rank slot extends the stream rather
    than erasing the failed attempt's evidence).  Idempotent; returns
    the path, or None when the directory cannot be created."""
    with _lock:
        _STATE["env_checked"] = True
        if _STATE["f"] is not None:
            return _STATE["path"]
        if proc is None:
            proc = _default_proc()
        try:
            os.makedirs(ledger_dir, exist_ok=True)
            path = os.path.join(ledger_dir, file_name(proc))
            f = open(path, "a")
        except OSError:
            return None
        _STATE.update(f=f, path=path, dir=ledger_dir, proc=proc)
        atexit.register(finalize)
        return path


def enabled() -> bool:
    return _STATE["f"] is not None


def reset() -> None:
    """Close without merging and forget the env check (tests; one
    in-process CLI run must not inherit a previous run's stream)."""
    with _lock:
        f = _STATE["f"]
        _STATE.update(f=None, path=None, dir=None, proc=None, seq=0,
                      env_checked=False)
    if f is not None:
        try:
            f.close()
        except OSError:
            pass


def active_dir() -> Optional[str]:
    return _STATE["dir"]


def _maybe_env_enable() -> bool:
    if _STATE["env_checked"]:
        return _STATE["f"] is not None
    with _lock:
        _STATE["env_checked"] = True
    env = os.environ.get(ENV_VAR)
    if env:
        enable(env)
    return _STATE["f"] is not None


def event(kind: str, **fields) -> None:
    """Append one event; no-op unless enabled (or EXAML_LEDGER_DIR is
    set).  Never raises — a full disk must not kill the run."""
    if _STATE["f"] is None and not _maybe_env_enable():
        return
    with _lock:
        f = _STATE["f"]
        if f is None or f.closed:
            return
        _STATE["seq"] += 1
        rec = {"ts": _now_us(), "seq": _STATE["seq"],
               "proc": _STATE["proc"], "pid": os.getpid(), "kind": kind}
        rec.update(fields)
        try:
            f.write(json.dumps(rec, separators=(",", ":"),
                               default=str) + "\n")
            f.flush()                 # crash-robust: the last event lands
        except (OSError, ValueError):
            pass


def finalize() -> Optional[str]:
    """Close this process's ledger and merge the directory into one
    ordered timeline.  EVERY rank merges (merge() is idempotent and
    publishes via atomic rename), so in an unsupervised multi-rank run
    the last rank to exit rewrites `ledger.merged.jsonl` with every
    peer's final events — a rank-0-only merge would race the peers'
    tails.  Supervised runs get a further post-crash re-merge from the
    supervisor.  Returns the merged path."""
    with _lock:
        f = _STATE["f"]
        d = _STATE["dir"]
        _STATE.update(f=None, path=None, dir=None, proc=None, seq=0)
    if f is None:
        return None
    try:
        f.close()
    except OSError:
        pass
    if d is not None:
        return merge(d)
    return None


# Per-event bookkeeping keys; everything else is the event's payload.
META_KEYS = frozenset({"ts", "seq", "pid", "kind", "proc"})


def format_fields(ev: dict) -> str:
    """The payload of one event as `k=v` pairs — the shared rendering
    both report tools (run_report.py, top.py) use, so a new metadata
    key is hidden (or shown) by both at once."""
    return " ".join(f"{k}={ev[k]}" for k in ev
                    if k not in META_KEYS and ev[k] is not None)


def read_events(path: str) -> List[dict]:
    """Parse one ledger file, tolerating a torn final line (the
    crash-truncation artifact of a SIGKILLed writer)."""
    events: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue          # torn final line of a killed writer
                if isinstance(ev, dict):
                    events.append(ev)
    except OSError:
        pass
    return events


def read_dir(ledger_dir: str) -> List[dict]:
    """Every per-process ledger in `ledger_dir`, merged IN MEMORY and
    totally ordered by (ts, proc, seq) — for viewers/report tools that
    must not write into a run's (possibly read-only, archived)
    artifact directory."""
    try:
        names = sorted(n for n in os.listdir(ledger_dir)
                       if n.startswith("ledger.p")
                       and n.endswith(".jsonl"))
    except OSError:
        return []
    events: List[dict] = []
    for name in names:
        events.extend(read_events(os.path.join(ledger_dir, name)))
    events.sort(key=lambda ev: (ev.get("ts", 0), str(ev.get("proc")),
                                ev.get("seq", 0)))
    return events


def merge(ledger_dir: str) -> Optional[str]:
    """Merge every per-process ledger in `ledger_dir` into
    `ledger.merged.jsonl`, totally ordered by (ts, proc, seq) — the
    single gang timeline the r04 postmortem lacked.  Best-effort and
    idempotent (re-merging after more events re-sorts the union)."""
    events = read_dir(ledger_dir)
    if not events:
        return None
    out = os.path.join(ledger_dir, MERGED_NAME)
    tmp = f"{out}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev, separators=(",", ":"),
                                   default=str) + "\n")
        # graftlint: disable=GL007 -- derived artifact: the merged view
        # re-merges from the per-rank streams at any time (read_dir),
        # so a torn merge costs a re-merge, not evidence.
        os.replace(tmp, out)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return out
