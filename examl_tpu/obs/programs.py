"""Program observatory: compiler-truth cost/memory accounting.

Every roofline number this runtime can state divides by the hand-written
analytic bytes model (obs/traffic.py) — without the compiler's own
accounting next to it, an `achieved_gbps` row cannot be distinguished
from a model bug, and the HBM budgets the bf16-arena and multi-tenant
items must prove have no telemetry to stand on.  This module is the
process-wide registry of every compiled or deserialized executable the
run dispatched: one row per program with its family, jit key, compile
source (fresh / xla-cache / exported), compile seconds, and — behind a
fallback-not-crash ladder, because some backends return empty analyses —
XLA's `cost_analysis()` flops / bytes-accessed / transcendentals and
`memory_analysis()` argument / output / temp / peak bytes.

Three consumers, all fed from the one registry:

* `program.*` gauges + the table embedded in every `--metrics`
  snapshot (obs.snapshot) and BENCH row — `tools/run_report.py`
  renders it as the "Programs" table;
* a `programs.p<procid>.jsonl` stream next to the run ledger (same
  per-rank suffix, append + flush-per-row, torn-line-tolerant readers)
  so a SIGKILLed process leaves its program evidence behind;
* the **drift gate**: `model_vs_xla()` reconciles the analytic
  bytes-per-traversal model against the serving program's XLA
  bytes-accessed per tier (`program.model_drift_pct.<tier>`), so the
  `achieved_gbps` gauges can carry a `source: model|xla` tag.  Scan-
  and chunk-tier programs on the CPU fixtures sit within tolerance;
  a tier past EXAML_DRIFT_TOL_PCT is *documented divergence* — it
  increments `program.model_drift_exceeded.<tier>` and keeps serving
  (the model stays the accounting denominator; the gate is evidence,
  never a crash).

Deep analysis needs a `Compiled`, and jax's jit path does not expose
the executable it cached — so the observatory AOT-compiles the traced
lowering once per first call (`lowered.compile()`, timed into
`program.analyze_seconds`; with a persistent XLA cache armed this is a
cache deserialize, not a second codegen).  `EXAML_PROGRAM_OBS=rows`
keeps registry rows but skips that compile; `0` disables the
observatory.  Exported-bank hits get their analyses free: a
deserialized executable answers `cost_analysis()` directly, which is
how a zero-compile cold start still populates the table.

Live HBM telemetry rides the same module: `sample_memory()` reads
`device.memory_stats()` (rate-limited by EXAML_MEM_SAMPLE_S) into
`mem.device.<k>.{in_use,peak,limit}` gauges — sampled at the engine's
traffic-window cadence, per fleet drain round, and at every metrics
snapshot, cross-checkable against `engine.clv_arena_bytes`.  CPU
backends return no memory stats; that is the
`program.analysis_missing.memory_stats` rung of the ladder, not an
error.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from examl_tpu.obs import ledger as _ledger
from examl_tpu.obs import metrics as _metrics

ENV_VAR = "EXAML_PROGRAM_OBS"

# Which program families serve which traffic tier (engine._dispatch_tier
# labels): the drift gate compares a tier's modeled dispatch bytes with
# the newest registry row of the family that actually moved them.
TIER_FAMILIES: Dict[str, Tuple[str, ...]] = {
    "scan": ("trav_eval", "traverse", "newton", "scan", "thscan",
             "sumtable", "derivs"),
    "chunk": ("fast",),
    "pallas": ("fast",),
    "whole": ("whole", "fast"),
    "universal": ("universal",),
    "grad": ("grad",),
}

_lock = threading.Lock()
_STATE: Dict[str, object] = {
    "rows": {},            # (family, key) -> row dict, insertion-ordered
    "by_family": {},       # family -> newest row with analyses
    "stream": None,        # open programs.p<proc>.jsonl handle
    "stream_dir": None,
    "mem_last": None,      # monotonic of the last memory sample
    "collector": False,
    "listener": False,
}
_XLA_CACHE_HITS = [0]


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name) or default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


def mode() -> str:
    """"deep" (default: rows + AOT analyses), "rows" (registry only,
    no analysis compile), or "off"."""
    m = _env_str(ENV_VAR, "deep").strip().lower()
    if m in ("0", "off", "false"):
        return "off"
    if m == "rows":
        return "rows"
    return "deep"


def enabled() -> bool:
    return mode() != "off"


def drift_tolerance_pct() -> float:
    return _env_float("EXAML_DRIFT_TOL_PCT", 25.0)


def reset() -> None:
    """Forget rows and close the stream (tests; one in-process run must
    not inherit a previous run's table)."""
    with _lock:
        f = _STATE["stream"]
        _STATE.update(rows={}, by_family={}, stream=None,
                      stream_dir=None, mem_last=None)
    if f is not None:
        try:
            f.close()
        except OSError:
            pass


# -- compile-source attribution ----------------------------------------------
# jax's persistent compilation cache announces hits through the
# monitoring event '/jax/compilation_cache/cache_hits'; counting them
# around a first call is the only non-invasive way to tell a fresh
# codegen from a cache deserialize.  Registration is best-effort: a
# jax without the hook just reports every in-process compile as
# "fresh".

def _install_listener() -> None:
    if _STATE["listener"]:
        return
    _STATE["listener"] = True
    try:
        import jax.monitoring as _mon

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                _XLA_CACHE_HITS[0] += 1

        _mon.register_event_listener(_on_event)
    except Exception:                        # noqa: BLE001 — optional hook
        pass


def xla_cache_hits() -> int:
    """Monotone count of persistent-cache hits seen so far (installs
    the monitoring listener on first use)."""
    _install_listener()
    return _XLA_CACHE_HITS[0]


# -- the fallback-not-crash analysis ladder ----------------------------------


def _missing(field: str, row: dict) -> None:
    _metrics.registry().inc(f"program.analysis_missing.{field}")
    row.setdefault("missing", []).append(field)


def prelower(fn, args, family: str):
    """Trace `fn` to a Lowered BEFORE the dispatch donates its buffers
    (lowering reads only avals).  Returns None — counting, never
    raising — when the callable cannot lower (non-jit wrappers,
    backend refusals) or deep analysis is off."""
    if mode() != "deep":
        return None
    try:
        return fn.lower(*args)
    except Exception:                        # noqa: BLE001 — ladder rung
        _metrics.registry().inc("program.analysis_missing.lower")
        return None


def _cost_analysis(compiled, row: dict) -> None:
    try:
        cost = compiled.cost_analysis()
    except Exception:                        # noqa: BLE001 — ladder rung
        cost = None
    if isinstance(cost, (list, tuple)):      # jaxlib returns [dict]
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        _missing("cost_analysis", row)
        return
    for field, keys in (("flops", ("flops",)),
                        ("bytes_accessed", ("bytes accessed",
                                            "bytes_accessed")),
                        ("transcendentals", ("transcendentals",))):
        for k in keys:
            if k in cost:
                row[field] = float(cost[k])
                break
        else:
            _missing(field, row)


def _memory_analysis(compiled, row: dict) -> None:
    try:
        ma = compiled.memory_analysis()
    except Exception:                        # noqa: BLE001 — ladder rung
        ma = None
    if ma is None:
        _missing("memory_analysis", row)
        return
    for field, attr in (("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("temp_bytes", "temp_size_in_bytes")):
        v = getattr(ma, attr, None)
        if v is None:
            _missing(field, row)
        else:
            row[field] = int(v)
    # No jaxlib to date reports a live peak; the structural peak is
    # what the executable can address at once.  An explicit attribute
    # (future backends) wins when present.
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        parts = [row.get(f) for f in ("argument_bytes", "output_bytes",
                                      "temp_bytes")]
        if any(p is not None for p in parts):
            peak = sum(p or 0 for p in parts)
        else:
            _missing("peak_bytes", row)
    if peak is not None:
        row["peak_bytes"] = int(peak)


# Collective kinds GSPMD can insert; the fabric's contract (ISSUE 17)
# is that a compiled mesh program carries EXACTLY ONE all-reduce (the
# root lnL segment-sum over `sites` — ExaML's single Allreduce) and
# zero of every other kind.  tests/test_mesh.py pins this census.
_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all")


def collective_census(compiled) -> Optional[Dict[str, int]]:
    """Count the collective ops in a Compiled's optimized HLO text
    ({kind: n}, kinds with zero occurrences omitted), or None when the
    backend will not serve the text.  Async pairs count once (the
    `-start` op; `-done` is its completion, not a second collective)."""
    import re
    try:
        text = compiled.as_text()
    except Exception:                        # noqa: BLE001 — ladder rung
        return None
    if not text:
        return None
    census: Dict[str, int] = {}
    for kind in _COLLECTIVE_KINDS:
        n = len(re.findall(rf"\b{kind}(?:-start)?\(", text))
        if n:
            census[kind] = n
    return census


def _collectives(compiled, row: dict) -> None:
    census = collective_census(compiled)
    if census is None:
        _missing("collectives", row)
        return
    row["collectives"] = census
    row["collective_total"] = sum(census.values())


def _analyze(compiled, row: dict) -> None:
    _cost_analysis(compiled, row)
    _memory_analysis(compiled, row)
    _collectives(compiled, row)


# -- the registry ------------------------------------------------------------


def record(family: str, key, source: str, compile_s: float,
           lowered=None, compiled=None) -> Optional[dict]:
    """One registry row per (family, jit key): called by the engine's
    first-call guard (lowered: the pre-dispatch trace; the analysis
    compile runs here, timed) and by the export bank's load ladder
    (compiled: the deserialized executable — analyses are free).
    Never raises; returns the row (or None when disabled)."""
    if not enabled():
        return None
    try:
        return _record(family, key, source, compile_s, lowered, compiled)
    except Exception:                        # noqa: BLE001 — observability
        _metrics.registry().inc("program.analysis_missing.record")
        return None


def _record(family, key, source, compile_s, lowered, compiled):
    reg = _metrics.registry()
    row = {"ts": round(time.time(), 3), "family": family,
           "key": str(key)[:200], "source": source,
           "compile_s": round(float(compile_s), 4)}
    if compiled is None and lowered is not None and mode() == "deep":
        t0 = time.perf_counter()
        try:
            compiled = lowered.compile()
        except Exception:                    # noqa: BLE001 — ladder rung
            _missing("compile", row)
        reg.observe("program.analyze_seconds",
                    time.perf_counter() - t0)
    if compiled is not None:
        _analyze(compiled, row)
    with _lock:
        rows = _STATE["rows"]
        rows[(family, row["key"])] = row
        if row.get("bytes_accessed") is not None:
            _STATE["by_family"][family] = row
        n = len(rows)
    reg.inc(f"program.records.{source}")
    reg.gauge("program.count", n)
    if row.get("bytes_accessed") is not None:
        reg.gauge(f"program.bytes_accessed.{family}",
                  row["bytes_accessed"])
    if row.get("flops") is not None:
        reg.gauge(f"program.flops.{family}", row["flops"])
    if row.get("peak_bytes") is not None:
        reg.gauge(f"program.peak_bytes.{family}", row["peak_bytes"])
    if row.get("collective_total") is not None:
        reg.gauge(f"program.collectives.{family}",
                  row["collective_total"])
    _stream_write(row)
    _ensure_collector()
    return row


def record_loaded(family: str, sig: str, loaded) -> Optional[dict]:
    """A deserialized exported-bank executable: zero compile seconds,
    analyses straight off the loaded Compiled — the row that keeps an
    `engine.compile_count == 0` cold start observable."""
    return record(family, sig, "exported", 0.0, compiled=loaded)


def table() -> List[dict]:
    """Every registry row (copies), oldest first — the list embedded
    under "programs" in metrics snapshots and BENCH artifacts."""
    with _lock:
        return [dict(r) for r in _STATE["rows"].values()]


def xla_bytes_for(tier: str, family: Optional[str] = None):
    """(family, bytes_accessed) of the newest analyzed program that
    serves `tier` (engine tier labels; an explicit family wins), or
    None when no compiler figure exists yet."""
    fams = (family,) if family else \
        TIER_FAMILIES.get(tier.split(".", 1)[0], ())
    with _lock:
        by = _STATE["by_family"]
        for f in fams:
            row = by.get(f)
            if row is not None:
                return f, row["bytes_accessed"]
    return None


def model_vs_xla(tier: str, model_bytes: int,
                 family: Optional[str] = None) -> str:
    """The drift gate: reconcile one dispatch's analytic bytes with
    the serving program's XLA bytes-accessed.  Publishes
    `program.model_drift_pct.<tier>` and counts
    `program.model_drift_exceeded.<tier>` past the pinned tolerance
    (documented divergence — the run keeps serving).  Returns the
    source tag for the tier's achieved-GB/s row: "xla" when a
    compiler figure backs the number, "model" otherwise."""
    if not enabled() or model_bytes <= 0:
        return "model"
    hit = xla_bytes_for(tier, family)
    if hit is None or not hit[1]:
        return "model"
    _, xla = hit
    drift = abs(float(model_bytes) - xla) / xla * 100.0
    reg = _metrics.registry()
    reg.gauge(f"program.model_drift_pct.{tier}", round(drift, 2))
    if drift > drift_tolerance_pct():
        reg.inc(f"program.model_drift_exceeded.{tier}")
    return "xla"


# -- live HBM telemetry ------------------------------------------------------


def host_rss_bytes() -> Optional[int]:
    """This process's resident set size: psutil when the container has
    it, else `/proc/self/statm` (field 1 × page size).  None on
    platforms with neither — the caller counts the missing rung."""
    try:
        import psutil                            # type: ignore
        return int(psutil.Process().memory_info().rss)
    except Exception:                            # noqa: BLE001 — optional
        pass
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:                            # noqa: BLE001 — non-Linux
        return None


def sample_memory(devices=None, force: bool = False) -> bool:
    """`device.memory_stats()` -> `mem.device.<k>.{in_use,peak,limit}`
    gauges, rate-limited by EXAML_MEM_SAMPLE_S (0 samples every call).
    Backends without allocator stats (CPU) fall back to the HOST
    resident set (`mem.host.rss` via psutil or /proc/self/statm) so CPU
    runs still carry real memory telemetry; only when even that rung is
    missing does `program.analysis_missing.memory_stats` count a truly
    absent sample.  Returns True when a sample was taken."""
    if not enabled():
        return False
    now = time.monotonic()
    interval = _env_float("EXAML_MEM_SAMPLE_S", 5.0)
    with _lock:
        last = _STATE["mem_last"]
        if not force and last is not None and now - last < interval:
            return False
        _STATE["mem_last"] = now
    reg = _metrics.registry()
    try:
        if devices is None:
            import jax
            devices = jax.local_devices()
        for d in devices:
            stats = d.memory_stats()
            if not stats:
                rss = host_rss_bytes()
                if rss is None:
                    reg.inc("program.analysis_missing.memory_stats")
                else:
                    reg.gauge("mem.host.rss", int(rss))
                continue
            k = getattr(d, "id", 0)
            for field, src in (("in_use", "bytes_in_use"),
                               ("peak", "peak_bytes_in_use"),
                               ("limit", "bytes_limit")):
                if src in stats:
                    reg.gauge(f"mem.device.{k}.{field}",
                              int(stats[src]))
                else:
                    reg.inc("program.analysis_missing.memory_stats")
    except Exception:                        # noqa: BLE001 — telemetry
        reg.inc("program.analysis_missing.memory_stats")
        return False
    return True


def _ensure_collector() -> None:
    """Every metrics snapshot carries a fresh memory sample (snapshot
    collectors are the designed place for device-touching gauges;
    `snapshot_light` flushes skip them by contract)."""
    if _STATE["collector"]:
        return
    _STATE["collector"] = True

    def _collect() -> bool:
        sample_memory()
        return True

    _metrics.registry().add_collector(_collect)


# -- the programs.p<procid>.jsonl stream -------------------------------------
# PR7 ledger discipline (obs/ledger.py): per-rank file next to the run
# ledger, append mode, flush per row, readers tolerate a torn final
# line.  The stream is the crash-durable form of the table; the
# metrics-snapshot embed is the queryable one.


def stream_name(proc) -> str:
    return f"programs.p{proc}.jsonl"


def _stream_write(row: dict) -> None:
    d = _ledger.active_dir() or os.environ.get(_ledger.ENV_VAR)
    if not d:
        return
    with _lock:
        f = _STATE["stream"]
        if f is None or _STATE["stream_dir"] != d:
            try:
                os.makedirs(d, exist_ok=True)
                f = open(os.path.join(
                    d, stream_name(_ledger._default_proc())), "a")
            except OSError:
                return
            _STATE.update(stream=f, stream_dir=d)
        try:
            f.write(json.dumps(row, separators=(",", ":"),
                               default=str) + "\n")
            f.flush()             # crash-robust: the last row lands
        except (OSError, ValueError):
            pass


def read_stream(path: str) -> List[dict]:
    """Rows of one programs stream, torn-final-line tolerant (same
    reader contract as ledger.read_events)."""
    rows: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue      # torn final line of a killed writer
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def read_dir(stream_dir: str) -> List[dict]:
    """Every rank's programs stream in `stream_dir`, merged in memory
    (viewers must not write into a run's artifact directory)."""
    try:
        names = sorted(n for n in os.listdir(stream_dir)
                       if n.startswith("programs.p")
                       and n.endswith(".jsonl"))
    except OSError:
        return []
    rows: List[dict] = []
    for name in names:
        rows.extend(read_stream(os.path.join(stream_dir, name)))
    rows.sort(key=lambda r: r.get("ts", 0))
    return rows
