"""Span tracer: Chrome-trace / Perfetto-compatible JSONL event files.

Off by default and zero-cost when off: `span()` returns a shared no-op
context unless tracing was enabled (by `enable(dir, procid)`, the CLI's
`--trace-events`, or the `EXAML_TRACE_DIR` environment variable, checked
lazily on the first span so subprocesses inherit tracing for free).

Design constraints, all from the round-4 postmortem (a compile wedged in
`recv` with no visibility into which program or what had completed):

* spans are B/E *pairs*, flushed per event — a wedged compile leaves an
  unmatched "B" naming the guilty program family as the file's last
  line, exactly the artifact the postmortem lacked;
* one file per process, named by procid (`trace.p<procid>.jsonl`), so
  multi-host runs never interleave writers; process 0 merges a
  cross-process `summary.json` at exit;
* the file is a streaming Chrome-trace JSON array: a `[` header, one
  event object per line each terminated by a comma, closed with a
  metadata event + `]` at finalize.  Perfetto and chrome://tracing load
  both the finalized file and a crash-truncated one (the format is
  specified to tolerate a missing terminator).

Timestamps are epoch microseconds (`time.time_ns() // 1000`) so traces
from different processes of one job line up on a shared axis.

`device_span()` additionally enters a `jax.profiler.TraceAnnotation`
named scope (when annotations are on: tracing enabled or `--profile`
active) so host spans line up with device activity in xprof profiles.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullContext()

_lock = threading.Lock()
_writer: Optional["TraceWriter"] = None
_env_checked = False
_annotate = False


def _now_us() -> int:
    return time.time_ns() // 1000


class TraceWriter:
    def __init__(self, path: str, procid: int) -> None:
        self.path = path
        self.procid = procid
        self._lock = threading.Lock()
        self._tids: dict = {}
        self._f = open(path, "w")
        self._f.write("[\n")
        self.event({"ph": "M", "name": "process_name", "pid": procid,
                    "tid": 0, "ts": _now_us(),
                    "args": {"name": f"examl-tpu proc {procid}"}})

    def tid(self) -> int:
        ident = threading.get_ident()
        t = self._tids.get(ident)
        if t is None:
            with self._lock:
                t = self._tids.setdefault(ident, len(self._tids))
        return t

    def event(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + ",\n")
            self._f.flush()           # crash-robust: the last span survives

    def close(self) -> None:
        with self._lock:
            if self._f.closed:
                return
            # Final metadata event carries no trailing comma so the file
            # closes as strictly valid JSON.
            self._f.write(json.dumps(
                {"ph": "M", "name": "trace_shutdown", "pid": self.procid,
                 "tid": 0, "ts": _now_us(), "args": {}},
                separators=(",", ":")) + "\n]\n")
            self._f.close()


class _Span:
    __slots__ = ("_name", "_cat", "_args", "_writer")

    def __init__(self, writer: TraceWriter, name: str, cat: str, args):
        self._writer = writer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        w = self._writer
        ev = {"ph": "B", "name": self._name, "cat": self._cat,
              "pid": w.procid, "tid": w.tid(), "ts": _now_us()}
        if self._args:
            ev["args"] = self._args
        w.event(ev)
        return self

    def __exit__(self, *exc):
        w = self._writer
        w.event({"ph": "E", "name": self._name, "cat": self._cat,
                 "pid": w.procid, "tid": w.tid(), "ts": _now_us()})
        return False


class _DeviceSpan(_Span):
    """Host span + jax.profiler.TraceAnnotation named scope, so the host
    trace and an xprof device profile share span names."""

    __slots__ = ("_tm",)

    def __enter__(self):
        self._tm = None
        if _annotate:
            try:
                import jax
                self._tm = jax.profiler.TraceAnnotation(self._name)
                self._tm.__enter__()
            except Exception:
                self._tm = None
        if self._writer is not None:
            super().__enter__()
        return self

    def __exit__(self, *exc):
        if self._writer is not None:
            super().__exit__(*exc)
        if self._tm is not None:
            try:
                self._tm.__exit__(*exc)
            except Exception:
                pass
        return False


def _default_procid() -> int:
    env = os.environ.get("EXAML_PROCID")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        # Only consult jax when a distributed client already exists:
        # jax.process_index() initializes backends, which tracing setup
        # must never force on its own.
        from jax._src import distributed
        if getattr(distributed.global_state, "client", None) is not None:
            import jax
            return jax.process_index()
    except Exception:
        pass
    return 0


def enable(trace_dir: str, procid: Optional[int] = None) -> str:
    """Open this process's trace file under `trace_dir`; returns its
    path.  Idempotent: re-enabling returns the existing file."""
    global _writer, _env_checked, _annotate
    with _lock:
        _env_checked = True
        if _writer is not None:
            return _writer.path
        if procid is None:
            procid = _default_procid()
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, f"trace.p{procid}.jsonl")
        _writer = TraceWriter(path, procid)
        _annotate = True
        atexit.register(finalize)
        return path


def enabled() -> bool:
    return _writer is not None


def set_annotations(on: bool) -> None:
    """Turn jax.profiler.TraceAnnotation scopes on/off independently of
    the JSONL writer (the CLI sets this under --profile so xprof traces
    get named scopes even without --trace-events)."""
    global _annotate
    _annotate = on


def _maybe_env_enable() -> bool:
    global _env_checked
    if _env_checked:
        return _writer is not None
    with _lock:
        _env_checked = True
    env = os.environ.get("EXAML_TRACE_DIR")
    if env:
        try:
            enable(env)
        except OSError:
            pass
    return _writer is not None


def span(name: str, cat: str = "host", args: Optional[dict] = None):
    """A host-side span context manager; no-op unless tracing is on."""
    if _writer is None and not _maybe_env_enable():
        return _NULL
    return _Span(_writer, name, cat, args)


def device_span(name: str, args: Optional[dict] = None):
    """A span around a device dispatch: host trace event + TraceAnnotation
    (annotations may be on without the JSONL writer, under --profile)."""
    if _writer is None and not _maybe_env_enable() and not _annotate:
        return _NULL
    return _DeviceSpan(_writer, name, "dispatch", args)


def instant(name: str, args: Optional[dict] = None) -> None:
    """A zero-duration marker event (Pallas fallback, watchdog bark)."""
    if _writer is None and not _maybe_env_enable():
        return
    ev = {"ph": "i", "s": "p", "name": name, "cat": "event",
          "pid": _writer.procid, "tid": _writer.tid(), "ts": _now_us()}
    if args:
        ev["args"] = args
    _writer.event(ev)


def read_events(path: str) -> list:
    """Parse a trace file (finalized or crash-truncated) into a list of
    event dicts — the shared reader for the summary merge and the tests."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue              # torn final line of a crashed writer
    return events


def merge_summary(trace_dir: str) -> Optional[str]:
    """Merge every per-process trace file in `trace_dir` into
    summary.json: per-file event counts plus aggregate span wall time by
    name.  Best-effort — files from still-running processes are summed
    as far as they have been written."""
    try:
        names = sorted(n for n in os.listdir(trace_dir)
                       if n.startswith("trace.p") and n.endswith(".jsonl"))
    except OSError:
        return None
    files = {}
    spans: dict = {}
    for name in names:
        events = read_events(os.path.join(trace_dir, name))
        files[name] = {"events": len(events)}
        open_spans: dict = {}
        for ev in events:
            key = (ev.get("pid"), ev.get("tid"), ev.get("name"))
            if ev.get("ph") == "B":
                open_spans.setdefault(key, []).append(ev.get("ts", 0))
            elif ev.get("ph") == "E" and open_spans.get(key):
                t0 = open_spans[key].pop()
                agg = spans.setdefault(
                    ev.get("name"), {"count": 0, "total_us": 0})
                agg["count"] += 1
                agg["total_us"] += max(0, ev.get("ts", t0) - t0)
        for key, starts in open_spans.items():
            if starts:
                agg = spans.setdefault(key[2], {"count": 0, "total_us": 0})
                agg["unfinished"] = agg.get("unfinished", 0) + len(starts)
    # Top spans by wall time — but unfinished spans (the wedged-compile
    # evidence this file exists to preserve) are ALWAYS included, even
    # with zero completed time.
    top = dict(sorted(spans.items(),
                      key=lambda kv: -kv[1].get("total_us", 0))[:50])
    top.update({n: s for n, s in spans.items() if s.get("unfinished")})
    out = os.path.join(trace_dir, "summary.json")
    try:
        with open(out, "w") as f:
            json.dump({"files": files, "spans": top}, f, indent=2,
                      sort_keys=True)
    except OSError:
        return None
    return out


def finalize() -> None:
    """Close this process's trace file; process 0 merges the summary."""
    global _writer
    with _lock:
        w = _writer
        _writer = None
    if w is None:
        return
    w.close()
    if w.procid == 0:
        merge_summary(os.path.dirname(w.path) or ".")
