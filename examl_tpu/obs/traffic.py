"""The ONE bytes-per-traversal HBM-traffic model + regime classifier.

Roofline accounting (ROOFLINE.md) lived only in `bench.py`
(`_bytes_per_traversal`), so a CLI or supervised run could never state
its own achieved GB/s against the 306 GB/s target — and a bench row's
number could silently drift from any in-engine estimate.  This module
is the single shared definition: bench.py delegates here verbatim and
`ops/engine.py` uses the same model for its per-dispatch
`engine.traffic_bytes` counter and windowed `engine.achieved_gbps.<tier>`
gauges, so the two agree bit-for-bit by construction
(tests/test_flightrec.py pins it).

Model (unchanged from the r05 bench): per traversal entry one CLV row
written, each non-tip child's CLV row read, scaler rows alongside
(int32/lane), tip children read 1-byte code rows; P matrices / tip
tables are O(states^2) noise.

Regime classification (ROOFLINE.md "Program size & launch floor"): a
traversal whose wall time sits at `program_ops x launch-latency` is
DISPATCH-BOUND — its GB/s is a launch-floor artifact, not a bandwidth
measurement (r02's 23 GB/s on testData/140 was exactly this).  Every
achieved_gbps this runtime reports carries the verdict so a chip round
can never mistake a floor for a roofline.

stdlib+numpy only — the bench parent and report tools import this with
no backend on the path.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

# The ≥10x target expressed as sustained HBM bandwidth (ROOFLINE.md:
# 2.55e10 updates/s x 12 B/update).
ROOFLINE_TARGET_GBPS = 306.0

# Per-op launch-latency estimate for the dependent-kernel floor.  r02:
# 138 dependent launches took 6.2 ms on the axon tunnel -> ~45 us/op.
# Override with EXAML_LAUNCH_LATENCY_S when a measured per-backend
# number exists.
DEFAULT_LAUNCH_LATENCY_S = 45e-6

# Minimum seconds between `traffic.window` ledger events per tier: the
# gauges always carry the latest verdict; the ledger gets periodic
# samples, not one line per window.
LEDGER_EVENT_INTERVAL_S = 30.0

# wall / launch-floor ratio below which a measurement is called
# dispatch-bound.  3x: r02's small config sits at ~1 (floor), the
# bandwidth-meaningful LARGE_CONFIGS at >6 (ROOFLINE.md numbers).
DISPATCH_BOUND_RATIO = 3.0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name) or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


def launch_latency_s() -> float:
    return _env_float("EXAML_LAUNCH_LATENCY_S",
                      DEFAULT_LAUNCH_LATENCY_S)


def bytes_per_traversal_counts(n_entries: int, n_tip_children: int,
                               patterns: int, R: int, K: int,
                               itemsize: int) -> int:
    """Closed-form core of the model: `n_entries` CLV rows written,
    `2*n_entries - n_tip_children` inner-child CLV rows read (each with
    its scaler row), `n_tip_children` 1-byte tip code rows read."""
    clv_row = patterns * R * K * itemsize
    sc_row = patterns * 4
    inner_children = 2 * n_entries - n_tip_children
    return ((n_entries + inner_children) * (clv_row + sc_row)
            + n_tip_children * patterns)


def count_tip_children(entries, ntips: int) -> int:
    """Tip children of a TraversalEntry list (node numbers 1..ntips are
    tips — the `ch <= ntips` test bench.py has always used)."""
    n = 0
    for e in entries:
        for ch in (e.left, e.right):
            if isinstance(ch, (int, np.integer)) and ch <= ntips:
                n += 1
    return n


def bytes_per_grad_pass(n_entries: int, n_tip_children: int,
                        n_edges: int, patterns: int, R: int, K: int,
                        itemsize: int) -> int:
    """Closed-form model of one whole-tree gradient dispatch
    (ops/gradient.py): the PRE-ORDER pass reads one outroot row and
    two child partials per entry (tip children read 1-byte code rows,
    like the post-order model) and writes two outroot rows; the
    EDGE-DERIVATIVE contraction then reads one outroot row and one
    down partial per edge (d1/d2 outputs are O(edges) scalars —
    noise).  Shares the post-order model's per-row cost so the "grad"
    tier's achieved-GB/s gauge is comparable with the traversal
    tiers'."""
    clv_row = patterns * R * K * itemsize
    sc_row = patterns * 4
    inner_children = 2 * n_entries - n_tip_children
    pre = ((n_entries + 2 * n_entries) * clv_row      # up reads + writes
           + inner_children * (clv_row + sc_row)      # child CLV reads
           + n_tip_children * patterns)               # child code reads
    edges = n_edges * (2 * clv_row + sc_row)
    return pre + edges


def bytes_per_traversal(entries, ntips: int, patterns: int, R: int,
                        K: int, itemsize: int) -> int:
    """Entry-list form — the exact historical bench.py signature, now a
    thin wrapper over the shared closed form."""
    return bytes_per_traversal_counts(
        len(entries), count_tip_children(entries, ntips), patterns, R,
        K, itemsize)


def classify_regime(wall_s: float, program_ops: int,
                    launch_latency: Optional[float] = None) -> dict:
    """Verdict for one traversal measurement: where does `wall_s` sit
    against the `program_ops x launch-latency` floor?

    Returns {"regime": "dispatch-bound" | "bandwidth-meaningful",
    "launch_floor_s", "floor_ratio"} — floor_ratio is wall/floor, so a
    ratio near 1 means the number measures launch latency, not HBM."""
    lat = launch_latency_s() if launch_latency is None else launch_latency
    floor = max(1, int(program_ops)) * lat
    ratio = (wall_s / floor) if floor > 0 else float("inf")
    regime = ("dispatch-bound" if ratio < DISPATCH_BOUND_RATIO
              else "bandwidth-meaningful")
    return {"regime": regime, "launch_floor_s": floor,
            "floor_ratio": round(ratio, 3)}


class TrafficWindow:
    """Windowed achieved-GB/s accumulator for the engine's timed
    (blocking) dispatch path: per blocked dispatch `add()` records
    (bytes, wall seconds, program ops); once `min_dispatches` have
    accumulated or `min_wall_s` has been spanned, `add()` returns the
    window verdict — (gbps, regime dict, dispatches) — and resets.
    Windowing keeps the gauge honest (a single warm dispatch after a
    compile would otherwise swing it) and cheap (one division per
    window, not per dispatch)."""

    __slots__ = ("min_dispatches", "min_wall_s", "bytes", "wall",
                 "ops", "n")

    def __init__(self, min_dispatches: Optional[int] = None,
                 min_wall_s: Optional[float] = None) -> None:
        # Env-tunable so a tiny CI smoke run (a handful of blocking
        # dispatches, milliseconds of wall) can force the gauge out
        # without waiting for a production-sized window.
        if min_dispatches is None:
            min_dispatches = _env_int("EXAML_TRAFFIC_WINDOW_DISPATCHES", 8)
        if min_wall_s is None:
            min_wall_s = _env_float("EXAML_TRAFFIC_WINDOW_WALL_S", 2.0)
        self.min_dispatches = min_dispatches
        self.min_wall_s = min_wall_s
        self.bytes = 0
        self.wall = 0.0
        self.ops = 0
        self.n = 0

    def add(self, nbytes: int, wall_s: float,
            program_ops: int) -> Optional[tuple]:
        self.bytes += int(nbytes)
        self.wall += float(wall_s)
        self.ops += int(program_ops)
        self.n += 1
        if self.n < self.min_dispatches and self.wall < self.min_wall_s:
            return None
        if self.wall <= 0:
            self.__init__(self.min_dispatches, self.min_wall_s)
            return None
        gbps = self.bytes / self.wall / 1e9
        regime = classify_regime(self.wall / self.n,
                                 max(1, self.ops // self.n))
        n = self.n
        self.__init__(self.min_dispatches, self.min_wall_s)
        return gbps, regime, n
