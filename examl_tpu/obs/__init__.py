"""examl_tpu.obs — unified runtime observability.

Dependency-free pieces (SURVEY §5.1/§5.5: the reference's only
instruments are gettime() deltas and ExaML_info prints):

* a process-wide **metrics registry** (`obs.metrics`): counters, gauges,
  timers with log-bucketed latency histograms (`obs.hist`) — always on,
  dict-update cheap — plus a heartbeat-ticked periodic snapshot flush
  so a killed process leaves its last-known counters behind;
* a **span tracer** (`obs.trace`): Chrome-trace/Perfetto-compatible
  per-process JSONL files, off unless `--trace-events` /
  `EXAML_TRACE_DIR` enables it, with `jax.profiler.TraceAnnotation`
  scopes so host spans line up with device profiles;
* a **run ledger** (`obs.ledger`): append-only per-rank JSONL event
  stream (compiles, phases, faults, checkpoint cycles, supervisor
  decisions, probe verdicts), merged by rank 0 into one ordered gang
  timeline at exit;
* the shared **roofline traffic model** (`obs.traffic`): the one
  bytes-per-traversal definition bench.py and the engine both use,
  plus the dispatch-bound vs bandwidth-meaningful regime classifier;
* a shared **dispatch-timing helper** (`obs.timing`) so bench.py and
  tools/perf_lab.py measure "dispatch time" identically (every rep
  lands in the histogram; windows are ledger-audited).

This module is the flat facade the rest of the runtime imports:

    from examl_tpu import obs
    obs.inc("engine.dispatch_count")
    with obs.device_span("engine:traverse", args={"entries": n}):
        ...
"""

from __future__ import annotations

import sys
from typing import Callable, Optional

from examl_tpu.obs import ledger as _ledger
from examl_tpu.obs import metrics as _metrics
from examl_tpu.obs import trace as _trace
from examl_tpu.obs import traffic  # noqa: F401  (shared roofline model)
from examl_tpu.obs.ledger import (  # noqa: F401
    enable as enable_ledger, enabled as ledger_enabled,
    event as ledger_event, finalize as finalize_ledger,
    merge as merge_ledger, read_events as read_ledger)
from examl_tpu.obs.metrics import (  # noqa: F401
    maybe_autoflush, set_autoflush)
from examl_tpu.obs.timing import time_dispatch  # noqa: F401
from examl_tpu.obs.trace import (  # noqa: F401
    device_span, enable as enable_tracing, enabled as tracing_enabled,
    finalize as finalize_tracing, instant, merge_summary, read_events,
    set_annotations, span)

# -- metrics facade ---------------------------------------------------------


def registry() -> _metrics.MetricsRegistry:
    return _metrics.registry()


def inc(name: str, value: float = 1) -> None:
    _metrics.registry().inc(name, value)


def counter(name: str) -> float:
    return _metrics.registry().counter(name)


def gauge(name: str, value: float) -> None:
    _metrics.registry().gauge(name, value)


def observe(name: str, seconds: float) -> None:
    _metrics.registry().observe(name, seconds)


def timer(name: str):
    return _metrics.registry().timer(name)


def add_collector(fn: Callable[[], bool]) -> None:
    _metrics.registry().add_collector(fn)


def snapshot() -> dict:
    snap = _metrics.registry().snapshot()
    # The program observatory's registry rows ride in every snapshot
    # (and, via bench worker merging, every BENCH artifact) so
    # tools/run_report.py can render the Programs table from the same
    # artifact that carries the gauges.
    from examl_tpu.obs import programs as _programs
    rows = _programs.table()
    if rows:
        snap["programs"] = rows
    return snap


def snapshot_counters() -> dict:
    """Counters only, no collectors — safe on hot loops (heartbeat)."""
    return _metrics.registry().snapshot_counters()


def reset() -> None:
    _metrics.registry().reset()


# -- operator log sink ------------------------------------------------------
# Runtime components that must reach the operator (the compile watchdog)
# write through here: always stderr, plus whatever sink the driver
# installed (the CLI points this at the ExaML_info file so a wedged run's
# info file names the guilty program family).

_log_sink: Optional[Callable[[str], None]] = None


def set_log_sink(fn: Optional[Callable[[str], None]]) -> None:
    global _log_sink
    _log_sink = fn


def log(msg: str) -> None:
    sys.stderr.write(msg + "\n")
    sink = _log_sink
    if sink is not None:
        try:
            sink(msg)
        except Exception:
            pass
