"""Log-bucketed latency histograms (stdlib-only).

A `TimerStat`'s count/total/min/max cannot show a tail: one 20 s
recompile inside 10,000 sub-millisecond dispatches vanishes into
`total_s`, which is exactly how the r04 launch-floor stall stayed
invisible.  Every timer therefore carries one of these: durations land
in geometrically-spaced buckets (20 per decade, ~12% relative width)
spanning 100 ns .. ~10^4 s, so p50/p95/p99 are readable from any
`--metrics` snapshot and two snapshots MERGE exactly (bucket counts
add; quantiles recompute) — the property bench.py's worker-snapshot
accumulation and the supervisor's attempt merging rely on, and the one
min/max/avg fundamentally lacks.

Representation: a sparse `{bucket_index: count}` dict.  Bucket i covers
seconds in `[FLOOR * BASE**i, FLOOR * BASE**(i+1))`; a quantile reports
the geometric midpoint of its bucket, so the relative error is bounded
by half the bucket width (~6%).  Serialized as string-keyed dicts
(JSON round-trip safe).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional

# 20 buckets per decade over [1e-7 s, 1e4 s): index range [0, 220).
FLOOR = 1e-7
DECADE_BUCKETS = 20
BASE = 10.0 ** (1.0 / DECADE_BUCKETS)
_LOG_BASE = math.log(BASE)
MAX_INDEX = 11 * DECADE_BUCKETS - 1        # 1e-7 .. 1e4: 11 decades

QUANTILES = (0.5, 0.95, 0.99)


def bucket_index(seconds: float) -> int:
    """The bucket holding `seconds`; durations at or below FLOOR share
    bucket 0 and absurdly long ones clamp to MAX_INDEX (an observation
    must never be droppable)."""
    if seconds <= FLOOR:
        return 0
    i = int(math.log(seconds / FLOOR) / _LOG_BASE)
    return min(max(i, 0), MAX_INDEX)


def bucket_bounds(index: int) -> tuple:
    """[lo, hi) seconds covered by bucket `index`."""
    return (FLOOR * BASE ** index, FLOOR * BASE ** (index + 1))


def bucket_mid(index: int) -> float:
    """Geometric midpoint — the value a quantile inside this bucket
    reports."""
    return FLOOR * BASE ** (index + 0.5)


class Histogram:
    """Sparse log-bucketed histogram of seconds."""

    __slots__ = ("buckets", "count")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0

    def observe(self, seconds: float) -> None:
        i = bucket_index(seconds)
        self.buckets[i] = self.buckets.get(i, 0) + 1
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        return quantile_from_buckets(self.buckets, q)

    def quantiles(self, qs: Iterable[float] = QUANTILES) -> dict:
        return {f"p{int(q * 100)}_s": self.quantile(q) for q in qs}

    def to_dict(self) -> Dict[str, int]:
        """JSON-safe sparse form ({str(index): count})."""
        return {str(i): c for i, c in sorted(self.buckets.items())}

    def merge_dict(self, buckets: Dict) -> None:
        """Fold a serialized bucket dict in (snapshot accumulation)."""
        for k, c in (buckets or {}).items():
            i = int(k)
            self.buckets[i] = self.buckets.get(i, 0) + int(c)
            self.count += int(c)


def quantile_from_buckets(buckets: Dict, q: float) -> Optional[float]:
    """The q-quantile of a (possibly serialized, string-keyed) bucket
    dict, or None when empty.  Reports the geometric midpoint of the
    bucket holding the q-th observation."""
    items: List[tuple] = sorted((int(k), int(c))
                                for k, c in (buckets or {}).items())
    total = sum(c for _, c in items)
    if total <= 0:
        return None
    # rank of the target observation, 1-based, ceil(q * total) clamped
    rank = min(total, max(1, math.ceil(q * total)))
    seen = 0
    for i, c in items:
        seen += c
        if seen >= rank:
            return bucket_mid(i)
    return bucket_mid(items[-1][0])


def merge_bucket_dicts(*dicts: Dict) -> Dict[str, int]:
    """Sum serialized bucket dicts (the snapshot-merge primitive used by
    bench.py's worker accumulation)."""
    out: Dict[int, int] = {}
    for d in dicts:
        for k, c in (d or {}).items():
            i = int(k)
            out[i] = out.get(i, 0) + int(c)
    return {str(i): c for i, c in sorted(out.items())}
