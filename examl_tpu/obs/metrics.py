"""Process-wide metrics registry: counters, gauges, timers.

The runtime's single source of numeric truth (SURVEY §5.5: the reference
has only ExaML_info prints and gettime() deltas).  Everything here is
stdlib-only and always on — a counter increment is a dict update under a
lock, negligible against the millisecond-scale device dispatches it
counts — while the *expensive* instruments (trace events, device-array
gauges) stay behind explicit opt-ins (`obs.trace`, snapshot collectors).

Naming convention (dotted, lowercase):

  engine.dispatch_count        device program invocations
  engine.traversal_entries     newview entries submitted (retraversal size)
  engine.cache_hits/misses/evictions   shared fast-program LRU
  engine.sched_cache.hit/miss          topology-keyed schedule-structure
  engine.sched_cache.invalidate/evictions   cache (ops/engine.py)
  host_schedule                timer: host-side schedule building
                               (flat traversal + structure/z assembly,
                               scan-tier packing) — the host floor,
                               split from device dispatch
  engine.compile_count, engine.compile_seconds[.family]
  engine.compile_count.bank_phase      first calls inside the bank phase
  engine.first_calls.banked/unbanked[.family]   post-bank first calls
  engine.first_calls.degraded_inprocess[.family]   deadline-degraded
                               scan-tier family compiled in-process
                               (watchdogged; expected, not a gap)
  engine.pallas_fallbacks      Mosaic -> XLA demotions
  engine.watchdog_barks        compile-deadline watchdog firings
  engine.nonfinite_retries/.nonfinite_recovered   NaN-lnL scan-tier retries
  bank.families/banked/timeouts/errors/skipped/fallbacks   AOT banking
  bank.compile.<family>        per-family subprocess compile (timers)
  bank.engine.*                worker-side compile counters, merged
  resilience.heartbeats        published search-loop liveness beats
  resilience.restarts/heartbeat_stalls/preempts   supervisor (merged
                               into the --metrics snapshot at exit)
  resilience.preempt_checkpoints   emergency checkpoints before exit 75
  checkpoint.corrupt_skipped   unreadable checkpoints skipped at restore
  faults.fired.<point>         injected faults that fired (chaos tests)
  search.spr_cycles, search.fast_cycles, search.thorough_cycles
  search.scan_dispatches, search.scan_candidates
  phase.<name>                 CLI wall-clock phases (timers)

Counters accept float increments (compile_seconds accumulates wall
seconds); timers record count/total/min/max of observed durations.
Snapshot collectors let owners of live state (engines) publish gauges
lazily — they run only when `snapshot()` is taken, so per-call cost is
zero, and they hold weak references so a registry never keeps a CLV
arena alive.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class TimerStat:
    __slots__ = ("count", "total", "min", "max")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = seconds if self.min is None else min(self.min, seconds)
        self.max = seconds if self.max is None else max(self.max, seconds)

    def as_dict(self) -> dict:
        return {"count": self.count, "total_s": self.total,
                "min_s": self.min, "max_s": self.max}


class _TimerContext:
    """Context manager that observes its own wall duration into a timer;
    exposes `.elapsed` (seconds) after exit so callers can reuse the one
    measurement instead of re-bracketing with perf_counter."""

    __slots__ = ("_registry", "_name", "_t0", "elapsed")

    def __init__(self, registry: "MetricsRegistry", name: str) -> None:
        self._registry = registry
        self._name = name
        self.elapsed = 0.0

    def __enter__(self) -> "_TimerContext":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self._t0
        self._registry.observe(self._name, self.elapsed)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, TimerStat] = {}
        self._collectors: list = []

    # -- counters / gauges / timers ----------------------------------------

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = TimerStat()
            stat.observe(seconds)

    def timer(self, name: str) -> _TimerContext:
        return _TimerContext(self, name)

    # -- collectors ---------------------------------------------------------

    def add_collector(self, fn: Callable[[], bool]) -> None:
        """Register a zero-arg callable run at every snapshot().  It may
        set gauges; returning False (or raising) unregisters it — the
        idiom for weakref-bound owners that have been collected."""
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        dead = []
        for fn in collectors:
            try:
                if fn() is False:
                    dead.append(fn)
            except Exception:
                dead.append(fn)
        if dead:
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]

    # -- snapshot / reset ---------------------------------------------------

    def snapshot(self) -> dict:
        self._run_collectors()
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {n: t.as_dict() for n, t in self._timers.items()},
            }

    def snapshot_counters(self) -> Dict[str, float]:
        """Counters only, WITHOUT running collectors: the cheap form for
        high-frequency consumers (the resilience heartbeat embeds this
        in every published beat — collectors may touch device state and
        must not run on the search loop's iteration clock)."""
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        """Clear counters/gauges/timers (collectors stay registered —
        their owners are still live)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY
