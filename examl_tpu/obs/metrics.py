"""Process-wide metrics registry: counters, gauges, timers.

The runtime's single source of numeric truth (SURVEY §5.5: the reference
has only ExaML_info prints and gettime() deltas).  Everything here is
stdlib-only and always on — a counter increment is a dict update under a
lock, negligible against the millisecond-scale device dispatches it
counts — while the *expensive* instruments (trace events, device-array
gauges) stay behind explicit opt-ins (`obs.trace`, snapshot collectors).

Naming convention (dotted, lowercase):

  engine.dispatch_count        device program invocations
  engine.traversal_entries     newview entries submitted (retraversal size)
  engine.cache_hits/misses/evictions   shared fast-program LRU
  engine.sched_cache.hit/miss          topology-keyed schedule-structure
  engine.sched_cache.invalidate/evictions   cache (ops/engine.py)
  host_schedule                timer: host-side schedule building
                               (flat traversal + structure/z assembly,
                               scan-tier packing) — the host floor,
                               split from device dispatch
  engine.compile_count, engine.compile_seconds[.family]
  engine.compile_count.bank_phase      first calls inside the bank phase
  engine.first_calls.banked/unbanked[.family]   post-bank first calls
  engine.first_calls.degraded_inprocess[.family]   deadline-degraded
                               scan-tier family compiled in-process
                               (watchdogged; expected, not a gap)
  engine.pallas_fallbacks      Mosaic -> XLA demotions
  engine.watchdog_barks        compile-deadline watchdog firings
  engine.nonfinite_retries/.nonfinite_recovered   NaN-lnL scan-tier retries
  bank.families/banked/timeouts/errors/skipped/fallbacks   AOT banking
  bank.compile.<family>        per-family subprocess compile (timers)
  bank.engine.*                worker-side compile counters, merged
  resilience.heartbeats        published search-loop liveness beats
  resilience.restarts/heartbeat_stalls/preempts   supervisor (merged
                               into the --metrics snapshot at exit)
  resilience.preempt_checkpoints   emergency checkpoints before exit 75
  checkpoint.corrupt_skipped   unreadable checkpoints skipped at restore
  engine.traffic_bytes         modeled HBM bytes moved by traversal
                               dispatches (obs/traffic.py — the ONE
                               bytes-per-traversal model bench.py uses)
  engine.achieved_gbps.<tier>.<engine-tag>   windowed achieved GB/s
                               gauge per tier (scan/chunk/pallas/
                               whole) and engine, from the timed
                               blocking dispatch path
  engine.regime_dispatch_bound.<tier>.<engine-tag>   1.0 = the
                               window's wall time sits at the
                               launch-latency floor (dispatch-bound),
                               0.0 = bandwidth-meaningful
                               (obs/traffic.classify_regime)
  chip.probe.<verdict>         chip_probe answer/no-answer/hang tallies
  faults.fired.<point>         injected faults that fired (chaos tests)
  search.spr_cycles, search.fast_cycles, search.thorough_cycles
  search.scan_dispatches, search.scan_candidates
  phase.<name>                 CLI wall-clock phases (timers)

Counters accept float increments (compile_seconds accumulates wall
seconds); timers record count/total/min/max of observed durations PLUS
a log-bucketed latency histogram (obs/hist.py), so every snapshot
carries p50/p95/p99 per timer — one slow outlier (a launch-floor
stall, a recompile) is visible instead of vanishing into a `total_s`
sum.  Snapshot collectors let owners of live state (engines) publish
gauges lazily — they run only when `snapshot()` is taken, so per-call
cost is zero, and they hold weak references so a registry never keeps
a CLV arena alive.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from examl_tpu.obs import hist as _hist


class TimerStat:
    __slots__ = ("count", "total", "min", "max", "hist")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.hist = _hist.Histogram()

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = seconds if self.min is None else min(self.min, seconds)
        self.max = seconds if self.max is None else max(self.max, seconds)
        self.hist.observe(seconds)

    def as_dict(self) -> dict:
        d = {"count": self.count, "total_s": self.total,
             "min_s": self.min, "max_s": self.max}
        # Quantiles + the raw sparse buckets: the buckets are what lets
        # two snapshots MERGE exactly (bench worker accumulation,
        # supervisor attempt merging) — merged quantiles recompute from
        # summed buckets, never from quantiles.
        d.update(self.hist.quantiles())
        d["buckets"] = self.hist.to_dict()
        return d


class _TimerContext:
    """Context manager that observes its own wall duration into a timer;
    exposes `.elapsed` (seconds) after exit so callers can reuse the one
    measurement instead of re-bracketing with perf_counter."""

    __slots__ = ("_registry", "_name", "_t0", "elapsed")

    def __init__(self, registry: "MetricsRegistry", name: str) -> None:
        self._registry = registry
        self._name = name
        self.elapsed = 0.0

    def __enter__(self) -> "_TimerContext":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self._t0
        self._registry.observe(self._name, self.elapsed)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, TimerStat] = {}
        self._collectors: list = []

    # -- counters / gauges / timers ----------------------------------------

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = TimerStat()
            stat.observe(seconds)

    def timer(self, name: str) -> _TimerContext:
        return _TimerContext(self, name)

    # -- collectors ---------------------------------------------------------

    def add_collector(self, fn: Callable[[], bool]) -> None:
        """Register a zero-arg callable run at every snapshot().  It may
        set gauges; returning False (or raising) unregisters it — the
        idiom for weakref-bound owners that have been collected."""
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        dead = []
        for fn in collectors:
            try:
                if fn() is False:
                    dead.append(fn)
            except Exception:
                dead.append(fn)
        if dead:
            with self._lock:
                self._collectors = [c for c in self._collectors
                                    if c not in dead]

    # -- snapshot / reset ---------------------------------------------------

    def snapshot(self) -> dict:
        self._run_collectors()
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {n: t.as_dict() for n, t in self._timers.items()},
            }

    def snapshot_counters(self) -> Dict[str, float]:
        """Counters only, WITHOUT running collectors: the cheap form for
        high-frequency consumers (the resilience heartbeat embeds this
        in every published beat — collectors may touch device state and
        must not run on the search loop's iteration clock)."""
        with self._lock:
            return dict(self._counters)

    def snapshot_light(self) -> dict:
        """Full snapshot shape WITHOUT running collectors: counters,
        last-set gauges, timers.  The periodic-flush form — safe on the
        search loop's clock for the same reason as snapshot_counters
        (collectors may touch device state)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {n: t.as_dict() for n, t in self._timers.items()},
            }

    def reset(self) -> None:
        """Clear counters/gauges/timers (collectors stay registered —
        their owners are still live)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# -- periodic snapshot flush -------------------------------------------------
# `--metrics` snapshots used to be written only at exit (try/finally),
# so a SIGKILLed / hang-killed child left NOTHING — the supervisor had
# no last-known counters to merge for the killed attempt.  The CLI arms
# this and the resilience heartbeat ticks it on every published beat:
# a cheap collector-free snapshot lands on disk on a rate-limited
# cadence (atomic tmp+rename, so the supervisor never reads torn JSON),
# marked `"partial": true` so consumers can tell a mid-run flush from
# the final at-exit snapshot that overwrites it.

_FLUSH = {"path": None, "interval": 5.0, "last": 0.0}

DEFAULT_FLUSH_INTERVAL_S = 5.0


def set_autoflush(path: Optional[str],
                  interval: Optional[float] = None) -> None:
    """Arm (or, with None, disarm) the periodic snapshot flush.
    `interval` defaults to EXAML_METRICS_FLUSH_S (else 5 s) — chaos
    tests pin it to 0 so a warm-cache attempt killed seconds in still
    leaves counter-bearing evidence, not just the startup flush."""
    if interval is None:
        try:
            interval = float(os.environ.get("EXAML_METRICS_FLUSH_S")
                             or DEFAULT_FLUSH_INTERVAL_S)
        except ValueError:
            interval = DEFAULT_FLUSH_INTERVAL_S
    _FLUSH.update(path=path, interval=float(interval), last=0.0)


def maybe_autoflush(force: bool = False) -> bool:
    """Write the collector-free snapshot if armed and the cadence is
    due; returns True when a flush happened.  Never raises: a full or
    read-only disk must not kill the run it observes."""
    path = _FLUSH["path"]
    if path is None:
        return False
    now = time.time()
    if not force and now - _FLUSH["last"] < _FLUSH["interval"]:
        return False
    _FLUSH["last"] = now
    snap = _REGISTRY.snapshot_light()
    snap["partial"] = True
    snap["flushed_at"] = now
    try:
        # Collector-free by design, but the program table is plain
        # host data — a killed run's last flush should still name the
        # programs it had compiled (obs/programs.py).
        from examl_tpu.obs import programs as _programs
        rows = _programs.table()
        if rows:
            snap["programs"] = rows
    except Exception:                        # noqa: BLE001 — never-raise
        pass
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(snap, f, sort_keys=True, default=str)
        # graftlint: disable=GL007 -- best-effort mid-run flush on the
        # heartbeat clock (never-raise contract); the exit snapshot
        # overwrites it, and a lost flush costs one cadence of counters.
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True
