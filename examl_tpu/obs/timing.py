"""The one definition of "dispatch time" shared by bench.py and the perf
lab: warm-up calls, then best-of-N wall seconds around a blocking call,
every repetition observed into the metrics registry so lab and bench
numbers are the same measurement with different report formats.

The callable must itself block until the device work is done (wrap the
dispatch in `jax.block_until_ready`); this module stays jax-free so the
obs package imports without a backend.
"""

from __future__ import annotations

import time
from typing import Callable

from examl_tpu.obs import ledger as _ledger
from examl_tpu.obs import metrics as _metrics


def time_dispatch(call: Callable[[], object], *, reps: int = 1,
                  warmup: int = 1, name: str = "dispatch") -> float:
    """Best wall seconds of `reps` timed invocations of `call()` after
    `warmup` untimed ones.  EVERY timed repetition is observed into the
    registry timer `name` — with the timer's log-bucketed histogram
    that means the full rep distribution survives, not just the
    best-of-N headline — and the window's parameters land as one
    `dispatch.window` ledger event (reps/warmup/best/total) so a bench
    measurement is auditable from the run artifacts alone."""
    reg = _metrics.registry()
    for _ in range(warmup):
        call()
    best = None
    total = 0.0
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        call()
        dt = time.perf_counter() - t0
        reg.observe(name, dt)
        total += dt
        if best is None or dt < best:
            best = dt
    _ledger.event("dispatch.window", name=name, reps=max(1, reps),
                  warmup=warmup, best_s=round(best, 6),
                  total_s=round(total, 6))
    return best
