"""The one definition of "dispatch time" shared by bench.py and the perf
lab: warm-up calls, then best-of-N wall seconds around a blocking call,
every repetition observed into the metrics registry so lab and bench
numbers are the same measurement with different report formats.

The callable must itself block until the device work is done (wrap the
dispatch in `jax.block_until_ready`); this module stays jax-free so the
obs package imports without a backend.
"""

from __future__ import annotations

import time
from typing import Callable

from examl_tpu.obs import metrics as _metrics


def time_dispatch(call: Callable[[], object], *, reps: int = 1,
                  warmup: int = 1, name: str = "dispatch") -> float:
    """Best wall seconds of `reps` timed invocations of `call()` after
    `warmup` untimed ones.  Each timed repetition is observed into the
    registry timer `name`."""
    reg = _metrics.registry()
    for _ in range(warmup):
        call()
    best = None
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        call()
        dt = time.perf_counter() - t0
        reg.observe(name, dt)
        if best is None or dt < best:
            best = dt
    return best
