"""Numeric constants of the likelihood engine and search.

Values mirror the reference's tuning constants (ExaML `axml.h:89-193`) so the
search dynamics and numerics are comparable; they are plain published
algorithmic constants, not code.
"""

# Branch lengths are parameterized as z = exp(-t) with t in expected
# substitutions per site (rate matrices are normalized to mean rate 1).
ZMIN = 1.0e-15          # max branch length ~ -log(zmin) ≈ 34.5
ZMAX = 1.0 - 1.0e-6     # min branch length 1e-6
DEFAULTZ = 0.9          # starting value for fresh branches
DELTAZ = 0.00001        # convergence test on z in branch-length updates

SMOOTHINGS = 32         # max smoothing passes through the tree
NEWTON_MAX_ITERS = 30   # max Newton-Raphson iterations per branch (ref `maxiter`)

# CLV underflow rescaling: multiply by 2^256 when all entries drop below
# 2^-256, and track the exponent in an integer scaler per (node, site).
TWO_TO_THE_256 = 1.15792089237316195423570985008687907853e77
MINLIKELIHOOD = 1.0 / TWO_TO_THE_256
LOG_MINLIKELIHOOD = -177.445678223345993274                     # log(2^-256)

UNLIKELY = -1.0e300     # lnL initializer

LIKELIHOOD_EPSILON = 1.0e-7

# Model-parameter bounds.
ALPHA_MIN = 0.02
ALPHA_MAX = 1000.0
RATE_MIN = 1.0e-7
RATE_MAX = 1.0e6
FREQ_MIN = 0.001

# Brent / bracketing (standard Numerical-Recipes-style constants).
BRENT_ITMAX = 100
BRENT_ZEPS = 1.0e-5
BRAK_GOLD = 1.618034
BRAK_GLIMIT = 100.0
BRAK_TINY = 1.0e-20

# Search tuning.
MAX_LOCAL_SMOOTHING_ITERATIONS = 10   # ref `iterations`
DEFAULT_RATEGORIES = 25               # PSR/CAT default category count
TPU_LANE = 128                        # site-block lane width (VPU lane count)
