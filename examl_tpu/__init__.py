"""examl_tpu — TPU-native maximum-likelihood phylogenetic inference.

A ground-up JAX/XLA re-design of the capabilities of stamatak/ExaML
(Felsenstein-pruning likelihood, RAxML SPR search, GTR-family models with
GAMMA / per-site-rate heterogeneity, model optimization, checkpointing).

Architecture (TPU-first, not a port):
  - Alignment sites are pattern-compressed, packed into 128-lane blocks and
    sharded over a `jax.sharding.Mesh` ("data parallelism over sites", the
    reference's one distributed strategy — ExaML `partitionAssignment.c`).
  - Conditional likelihood vectors (CLVs) live in one HBM-resident tensor
    `[nodes, blocks, lane, rates, states]`; tree traversals execute as a
    `lax.scan` over a fixed-size traversal descriptor.
  - The per-lnL MPI_Allreduce of the reference (ExaML
    `evaluateGenericSpecial.c:968`) becomes a `psum` over the mesh.
  - Tree topology bookkeeping, SPR moves and scalar optimizer control loops
    stay on the host, mirroring the reference's split.
"""

__version__ = "0.1.0"

from examl_tpu import constants  # noqa: F401
