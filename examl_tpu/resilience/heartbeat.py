"""Search-loop heartbeat: the liveness signal the compile watchdog
cannot provide.

The in-process compile monitor (`engine._guard_first_call`) and the
bank's killable workers cover COMPILE wedges; a dispatch/collective
wedge — the round-4/5 class where an already-compiled program blocks in
recv, or a multi-host peer stalls inside a psum — hangs the main thread
with no Python-level recourse, and only an outside watcher can act.

The search loop therefore calls `beat()` on every iteration (SPR slot,
optimizer round, evaluated tree), and the long HOST-SIDE setup phases
call `phase_beat()` (PARSE/PACK/SCHEDULE — tree build loops,
alignment packing, schedule assembly) so a legitimate 120k-taxon
setup never reads as a wedge.  When `EXAML_HEARTBEAT_FILE` is set
(the supervisor sets it; operators may too) each rate-limited beat
atomically publishes a small JSON record: timestamp, pid, sequence
number, loop state, and a snapshot of the obs registry's counters — so
a stall is not just detectable but *attributable* (the last record
names the state and the counter values where progress stopped).

The `search.kill` and `heartbeat.stall` fault points live here: beats
are the search loop's iteration clock, so `after=N` addresses "the Nth
search iteration" for chaos tests.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from examl_tpu.resilience import faults

ENV_VAR = "EXAML_HEARTBEAT_FILE"

# Gang contract (resilience/supervisor.py `--launch N`): the gang
# supervisor exports EXAML_GANG_RANKS=N and a per-rank EXAML_PROCID to
# every rank it spawns — in REAL multi-host mode alongside
# `--coordinator/--nprocs/--procid`, in EMULATED mode (CPU containers
# whose jaxlib has no multi-process collectives; chaos tests) on their
# own.  Rank 0 beats into the base path the supervisor watches; rank
# k>0 into `<base>.p<k>` (`rank_path`), so the gang watcher can tell a
# single straggler from a collective wedge.
PROCID_VAR = "EXAML_PROCID"
GANG_VAR = "EXAML_GANG_RANKS"

# Minimum seconds between file writes.  Beats are called per SPR slot
# (possibly hundreds/second on small trees); the file is for stall
# detection on the tens-of-seconds scale, so 0.5 s of write cadence
# costs nothing and bounds the I/O.
MIN_INTERVAL = 0.5

_STATE = {"path": None, "installed": False, "last": 0.0, "seq": 0,
          "stalled": False, "last_state": None}


def install(path: Optional[str] = None) -> Optional[str]:
    """Point beats at `path` (default: $EXAML_HEARTBEAT_FILE).  Returns
    the active path, or None when heartbeats stay disabled."""
    path = path or os.environ.get(ENV_VAR) or None
    _STATE.update(path=path, installed=True, last=0.0, seq=0,
                  stalled=False, last_state=None)
    return path


def reset() -> None:
    """Disable + clear (one CLI run = one heartbeat stream)."""
    _STATE.update(path=None, installed=False, last=0.0, seq=0,
                  stalled=False, last_state=None)


def beat(state: str = "", payload: Optional[dict] = None) -> None:
    """One search-loop iteration happened.  Cheap no-op when no
    heartbeat file is configured — except for the fault points, which
    must tick even unsupervised so chaos tests can address "the Nth
    iteration" without also requiring a supervisor.

    `payload` merges extra top-level fields into the published record
    and FORCES the publish past the rate limit — the fleet driver uses
    it to declare the in-flight batch (job ids + wall-clock deadline)
    so the supervisor can tell a job-stuck batch from an engine wedge;
    a stale in-flight declaration would misattribute the next wedge to
    innocent jobs, so a payload must never be skipped or reordered by
    the rate limiter."""
    # search.kill: a signal action never returns (SIGKILL) or sets the
    # preemption flag (TERM/INT with the handler installed).
    faults.fire("search.kill")
    if faults.fire("heartbeat.stall"):
        _STATE["stalled"] = True
    _publish(state, payload)


def phase_beat(state: str = "", payload: Optional[dict] = None) -> None:
    """Liveness from long HOST-SIDE setup phases (PARSE/PACK/SCHEDULE)
    and from fleet bookkeeping beats (retry-backoff waits, the
    in-flight-declaration clear after a batch): a legitimate
    120k-taxon tree build or schedule assembly must not read as a
    dispatch wedge to the `--supervise` stall detector, which
    until now only saw beats from the search loop.

    Publishes exactly like `beat()` (same file, same rate limit, same
    stall-injection suppression, same payload force-publish) but does
    NOT tick the `search.kill` / `heartbeat.stall` fault points —
    those count SEARCH iterations (one per fleet batch), and
    setup-phase liveness must not shift the `after=N` addressing chaos
    tests rely on."""
    _publish(state, payload)


def _publish(state: str, payload: Optional[dict] = None) -> None:
    # Loop-state transitions are ledger events (independent of the
    # heartbeat file and its rate limit): the merged timeline shows
    # FAST_SPRS -> SLOW_SPRS -> MOD_OPT with timestamps even for runs
    # nobody supervised.
    if state and state != _STATE["last_state"]:
        _STATE["last_state"] = state
        try:
            from examl_tpu import obs
            obs.ledger_event("search.state", state=state)
        except Exception:             # noqa: BLE001
            pass
    if _STATE["stalled"]:
        return
    # Piggybacked periodic --metrics flush: the beat cadence is the
    # liveness clock, so a killed process's snapshot is at most one
    # flush interval stale (collector-free — see snapshot_light).
    # Ticked BEFORE the heartbeat-file gate: an unsupervised run with
    # --metrics but no EXAML_HEARTBEAT_FILE must flush too.
    try:
        from examl_tpu import obs
        obs.maybe_autoflush()
    except Exception:                 # noqa: BLE001
        pass
    if not _STATE["installed"]:
        install()
    path = _STATE["path"]
    if path is None:
        return
    now = time.time()
    _STATE["seq"] += 1
    if payload is None and now - _STATE["last"] < MIN_INTERVAL:
        return
    _STATE["last"] = now
    try:
        from examl_tpu import obs
        counters = obs.snapshot_counters()
        obs.inc("resilience.heartbeats")
    except Exception:                 # noqa: BLE001
        counters = {}
    record = {"t": now, "pid": os.getpid(), "seq": _STATE["seq"],
              "state": state, "counters": counters}
    if payload:
        record.update(payload)
    # Atomic publish contract: write the full record to a pid-suffixed
    # tmp and rename.  The gang watcher polls these files at 4 Hz from
    # another process — a plain in-place write would hand it torn JSON
    # under exactly the load a stall decision matters most
    # (tests/test_gang.py interleaves reader and writer to pin this).
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f)
        # graftlint: disable=GL007 -- atomicity-only publish: a beat is
        # superseded within seconds and a lost one reads as one stall
        # tick; fsync per beat would put disk latency on the loop clock.
        os.replace(tmp, path)         # readers never see a partial record
    except OSError:
        # A full/readonly disk must not kill the search it monitors.
        try:
            os.unlink(tmp)
        except OSError:
            pass


def read(path: str) -> Optional[dict]:
    """The last published heartbeat record, or None (no file yet, or a
    transiently unreadable one — callers key stall decisions off file
    AGE, so a None here is simply 'no evidence')."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def age(path: str) -> Optional[float]:
    """Seconds since the last heartbeat PUBLISH (file mtime — immune to
    clock skew in the payload), or None when no heartbeat exists yet."""
    try:
        return max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None


# -- gang aggregation (stdlib-only: the jax-free gang supervisor reads
# these; parallel/launch.install_heartbeat uses the same naming) --------


def env_rank() -> int:
    """This process's gang rank (`EXAML_PROCID`; 0 when unset)."""
    try:
        return int(os.environ.get(PROCID_VAR, "0") or 0)
    except ValueError:
        return 0


def env_gang_size() -> Optional[int]:
    """The gang's world size (`EXAML_GANG_RANKS`), or None when this
    process was not spawned by the gang supervisor."""
    try:
        n = int(os.environ.get(GANG_VAR, "") or 0)
    except ValueError:
        return None
    return n if n > 0 else None


def rank_path(base: str, rank: int) -> str:
    """Rank `rank`'s heartbeat file for a gang watching `base` (rank 0
    keeps the base path — its watcher has always watched exactly that
    file; peers suffix `.p<rank>`)."""
    return base if rank == 0 else f"{base}.p{rank}"


def gang_paths(base: str, nranks: int) -> list:
    return [rank_path(base, k) for k in range(nranks)]


def gang_ages(base: str, nranks: int) -> list:
    """Per-rank beat ages for the gang watcher (None = that rank has
    never published a beat)."""
    return [age(p) for p in gang_paths(base, nranks)]
