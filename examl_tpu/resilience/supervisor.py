"""Self-healing run supervisor (`--supervise`): converts "lost window"
into "resumed run".

The supervisor is a thin, jax-free parent (IMPORT CONTRACT in the
package `__init__`: on exclusive-access accelerators the parent must
never take the device handle the child needs, and a hung accelerator
plugin must not be able to hang the watcher).  It:

* runs the search CLI as a KILLABLE child in its own process group
  (`python -m examl_tpu.cli.main`, `--supervise` stripped);
* exports `EXAML_HEARTBEAT_FILE` and watches it — once the search loop
  starts beating, a stall longer than `--supervise-stall` means a
  dispatch/collective wedge (the class the compile watchdog cannot
  see) and the whole child process group is SIGKILLed;
* classifies every death through the shared exit taxonomy
  (`resilience/exitcause.py`: SIGILL vs OOM vs hang-kill vs preempt);
* restarts from the newest checkpoint (`-R` once one exists) with
  capped retries, exponential backoff, and ESCALATING degradation pins
  mirroring the bank's escape hatches: retry 1 pins `EXAML_PALLAS=0`
  (pallas→chunk), retry 2+ pins the scan tier
  (`EXAML_FAST_TRAVERSAL=0`, `EXAML_BATCH_SCAN=0`,
  `EXAML_BATCH_THOROUGH=0`) — the one tier hardware-proven everywhere;
* treats a child exit of EXIT_PREEMPTED (75) as RESUMABLE: restarted
  immediately, no retry consumed (capped separately so a preemption
  storm still terminates);
* forwards its own SIGTERM/SIGINT to the child as SIGTERM, so
  preempting the supervisor preempts the run gracefully end-to-end;
* merges its `resilience.*` counters into the child's `--metrics`
  snapshot, so one artifact carries both sides' evidence
  (`resilience.restarts`, `resilience.heartbeat_stalls`,
  `resilience.preempts`, plus the child's `engine.nonfinite_retries`).

`EXAML_RESTART_COUNT` is exported to each attempt so fault-injection
specs (`resilience/faults.py`) can target a single attempt — the
mechanism that makes "crash once, then recover" chaos tests converge.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from examl_tpu.resilience import exitcause, heartbeat

# Degradation ladder, in escalation order (mirrors ops/bank.FALLBACK_ENV
# without importing it: bank pulls in obs/jax, this parent must not).
DEGRADE_LADDER = (
    {},
    {"EXAML_PALLAS": "0"},
    {"EXAML_PALLAS": "0", "EXAML_FAST_TRAVERSAL": "0",
     "EXAML_BATCH_SCAN": "0", "EXAML_BATCH_THOROUGH": "0"},
)

DEFAULT_RETRIES = 3
DEFAULT_STALL = 300.0
POLL_S = 0.25

# Supervisor flags stripped from the child's argv.  Values live with the
# flag (argparse two-token form) — single-token "--flag=value" is also
# handled by prefix match.
_SUPERVISOR_FLAGS = {"--supervise": 0, "--supervise-retries": 1,
                     "--supervise-stall": 1, "--supervise-backoff": 1}


def child_argv(argv: List[str]) -> List[str]:
    """The supervised child's argument list: the original CLI argv minus
    the supervisor-only flags (`--inject-fault` passes THROUGH — the
    child arms the registry; attempt gating keeps retries clean)."""
    out: List[str] = []
    skip = 0
    for tok in argv:
        if skip:
            skip -= 1
            continue
        flag = tok.split("=", 1)[0]
        if flag in _SUPERVISOR_FLAGS:
            if "=" not in tok:
                skip = _SUPERVISOR_FLAGS[flag]
            continue
        out.append(tok)
    return out


def checkpoint_glob(workdir: str, run_id: str) -> List[str]:
    """Checkpoint files for (workdir, run_id) — the same naming
    CheckpointManager publishes (search/checkpoint.py; that module
    imports jax via the instance, so the pattern is mirrored here and
    pinned by a cross-check test)."""
    return sorted(glob.glob(os.path.join(
        workdir, f"ExaML_binaryCheckpoint.{run_id}.ckpt_*.json.gz")))


def _repo_env() -> Dict[str, str]:
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if repo not in pp:
        env["PYTHONPATH"] = os.pathsep.join([repo] + pp)
    return env


class Supervisor:
    def __init__(self, argv: List[str], workdir: str, run_id: str,
                 max_retries: int = DEFAULT_RETRIES,
                 stall_timeout: float = DEFAULT_STALL,
                 backoff: float = 2.0,
                 metrics_file: Optional[str] = None,
                 log=print):
        self.base_argv = child_argv(argv)
        self.workdir = workdir
        self.run_id = run_id
        self.max_retries = max_retries
        self.stall_timeout = stall_timeout
        self.backoff = backoff
        self.metrics_file = metrics_file
        self.log = lambda msg: log(f"supervise: {msg}")
        os.makedirs(workdir, exist_ok=True)
        self.hb_path = os.path.join(workdir,
                                    f".heartbeat.{run_id}.json")
        # Counters mirrored into the metrics snapshot at the end — the
        # supervisor is jax/obs-free, so it keeps its own dict.
        self.counters: Dict[str, float] = {}
        self.attempts: List[dict] = []
        self.degrade_level = 0
        self._preempt_signal: Optional[str] = None
        self._child: Optional[subprocess.Popen] = None
        self._last_argv: List[str] = []

    # -- bookkeeping --------------------------------------------------------

    def _inc(self, name: str, v: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + v

    def _pins(self) -> Dict[str, str]:
        return dict(DEGRADE_LADDER[min(self.degrade_level,
                                       len(DEGRADE_LADDER) - 1)])

    def _attempt_argv(self) -> List[str]:
        argv = list(self.base_argv)
        if "-R" not in argv and checkpoint_glob(self.workdir, self.run_id):
            argv.append("-R")
        return argv

    # -- signal forwarding --------------------------------------------------

    def _install_signals(self):
        if not hasattr(signal, "SIGTERM"):
            return None

        def handler(signum, frame):
            self._preempt_signal = signal.Signals(signum).name
            child = self._child
            if child is not None and child.poll() is None:
                try:                        # graceful: the child
                    os.killpg(child.pid, signal.SIGTERM)  # checkpoints
                except (OSError, ProcessLookupError):
                    pass

        try:
            return (signal.signal(signal.SIGTERM, handler),
                    signal.signal(signal.SIGINT, handler))
        except ValueError:                  # non-main thread (tests)
            return None

    def _restore_signals(self, prior) -> None:
        if prior is not None:
            signal.signal(signal.SIGTERM, prior[0])
            signal.signal(signal.SIGINT, prior[1])

    # -- one attempt --------------------------------------------------------

    def _spawn(self, restarts_total: int) -> subprocess.Popen:
        env = _repo_env()
        env["EXAML_HEARTBEAT_FILE"] = self.hb_path
        env["EXAML_RESTART_COUNT"] = str(restarts_total)
        env.update(self._pins())
        argv = self._last_argv = self._attempt_argv()
        pins = self._pins()
        self.log(f"attempt {restarts_total}: starting "
                 + ("(resume -R) " if "-R" in argv else "")
                 + (f"[pins {pins}] " if pins else "")
                 + " ".join(argv))
        try:
            os.unlink(self.hb_path)         # stale beats must not mask
        except OSError:                     # a child that never starts
            pass
        return subprocess.Popen(
            [sys.executable, "-m", "examl_tpu.cli.main"] + argv,
            env=env, start_new_session=True)

    def _kill_group(self, child: subprocess.Popen) -> None:
        """SIGKILL the child's whole process group: bank workers and any
        other helpers must die with it, or the retry races them for the
        accelerator."""
        for target in (child.pid,):
            try:
                os.killpg(target, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    child.kill()
                except OSError:
                    pass
        child.wait()

    def _watch(self, child: subprocess.Popen) -> str:
        """Wait for exit or heartbeat stall; returns the exit cause."""
        spawned = time.time()
        # Startup (data load, banking, first compiles, the pre-search
        # model opt) legitimately produces no beats, so the deadline
        # for the FIRST beat is much more generous than the stall
        # window — but it must exist: a dispatch that wedges before the
        # first search iteration would otherwise hang the supervisor
        # forever.
        first_beat_deadline = max(4.0 * self.stall_timeout, 900.0)
        while True:
            rc = child.poll()
            if rc is not None:
                return exitcause.classify(rc)
            if self.stall_timeout:
                hb_age = heartbeat.age(self.hb_path)
                stalled = (hb_age > self.stall_timeout
                           if hb_age is not None else
                           time.time() - spawned > first_beat_deadline)
                if stalled:
                    # The search loop stopped beating (or never
                    # started): dispatch/collective wedge.  Kill the
                    # whole group and classify ourselves — our SIGKILL
                    # must not read as an OOM kill.
                    last = heartbeat.read(self.hb_path) or {}
                    self.log(
                        "heartbeat stalled ("
                        + (f"{hb_age:.0f}s > {self.stall_timeout:.0f}s"
                           if hb_age is not None else
                           f"no first beat within {first_beat_deadline:.0f}s")
                        + f"; last state {last.get('state')!r} seq "
                        f"{last.get('seq')}); killing the child process "
                        "group")
                    self._inc("resilience.heartbeat_stalls")
                    self._kill_group(child)
                    return exitcause.CAUSE_HANG_KILL
            time.sleep(POLL_S)

    # -- the supervision loop -----------------------------------------------

    def run(self) -> int:
        prior = self._install_signals()
        retries = 0
        preempts = 0
        restarts_total = 0
        rc = 1
        try:
            while True:
                if self._preempt_signal is not None:
                    # Preempted BETWEEN children (during the backoff
                    # sleep or before the first spawn): there is no
                    # child to forward to — exit resumable now instead
                    # of launching an attempt the grace window will
                    # just SIGKILL.
                    self.log(f"supervisor preempted "
                             f"({self._preempt_signal}) between "
                             "attempts; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                t0 = time.time()
                self._child = child = self._spawn(restarts_total)
                cause = self._watch(child)
                self._child = None
                rc = child.returncode
                self.attempts.append({
                    "attempt": restarts_total, "cause": cause,
                    "returncode": rc, "seconds": round(time.time() - t0, 2),
                    "pins": self._pins(),
                    "resumed": "-R" in self._last_argv})
                desc = exitcause.exit_desc(rc, none_desc="(hang-killed)")

                if cause == exitcause.CAUSE_OK:
                    self.log(f"run completed after {restarts_total} "
                             "restart(s)")
                    return 0
                if self._preempt_signal is not None:
                    # WE were preempted: the child checkpointed (or
                    # died); do not restart — exit resumable ourselves.
                    self.log(f"supervisor preempted ({self._preempt_signal})"
                             f"; child exited {desc}; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                if cause == exitcause.CAUSE_PREEMPT:
                    # The CHILD was preempted externally but we were
                    # not: resume immediately, no retry consumed.
                    preempts += 1
                    self._inc("resilience.preempts")
                    if preempts > max(10, 5 * self.max_retries):
                        self.log("preemption storm: giving up")
                        return exitcause.EXIT_PREEMPTED
                    restarts_total += 1
                    self._inc("resilience.restarts")
                    self.log(f"child preempted {desc}; resuming "
                             "(no retry consumed)")
                    continue
                if cause == exitcause.CAUSE_USAGE:
                    self.log(f"usage error {desc}: not retryable")
                    return rc
                # Failure: classify, maybe degrade, retry with backoff.
                retries += 1
                self._inc("resilience.restarts")
                self._inc(f"resilience.exits.{cause.replace('-', '_')}")
                if retries > self.max_retries:
                    self.log(f"child failed ({cause} {desc}); retry "
                             f"budget exhausted after {self.max_retries}")
                    # Signal deaths surface as the conventional
                    # 128+signum (a raw negative rc through sys.exit
                    # becomes an unclassifiable 247-style status).
                    if rc is None:
                        return 1
                    return 128 - rc if rc < 0 else (rc or 1)
                if cause in exitcause.TIER_SUSPECT:
                    self.degrade_level = min(self.degrade_level + 1,
                                             len(DEGRADE_LADDER) - 1)
                delay = min(60.0, self.backoff * (2 ** (retries - 1)))
                have_ckpt = bool(checkpoint_glob(self.workdir,
                                                 self.run_id))
                self.log(
                    f"child failed ({cause} {desc}); retry "
                    f"{retries}/{self.max_retries} in {delay:.1f}s "
                    + ("from newest checkpoint"
                       if have_ckpt else "from scratch (no checkpoint)")
                    + (f", degradation level {self.degrade_level} "
                       f"pins {self._pins()}"
                       if self._pins() else ""))
                time.sleep(delay)
                restarts_total += 1
        finally:
            child = self._child
            if child is not None and child.poll() is None:
                self._kill_group(child)
            self._restore_signals(prior)
            self._merge_metrics()

    # -- metrics ------------------------------------------------------------

    def _merge_metrics(self) -> None:
        """Fold the supervisor's evidence into the child's --metrics
        snapshot (the child rewrites the file at every exit, so the
        LAST attempt's registry is on disk; the supervisor's counters
        span all attempts).  Without --metrics, write nothing — the log
        lines remain the record."""
        if not self.metrics_file:
            return
        snap: dict = {}
        try:
            with open(self.metrics_file) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            snap = {}
        snap.setdefault("counters", {}).update(self.counters)
        snap.setdefault("gauges", {})["resilience.degrade_level"] = \
            self.degrade_level
        snap["resilience"] = {"attempts": self.attempts,
                              "final_pins": self._pins(),
                              "heartbeat_file": self.hb_path}
        try:
            with open(self.metrics_file, "w") as f:
                json.dump(snap, f, indent=2, sort_keys=True, default=str)
            self.log(f"metrics snapshot (merged) -> {self.metrics_file}")
        except OSError as exc:
            self.log(f"metrics merge failed ({exc})")


def supervise(argv: List[str], args, log=print) -> int:
    """CLI entry: run `argv` (the full original command line) under
    supervision.  `args` is the parsed namespace — only supervisor and
    file-placement flags are read; everything jax-flavored happens in
    the child."""
    workdir = getattr(args, "workdir", ".") or "."
    sup = Supervisor(
        argv, workdir=workdir, run_id=args.run_id,
        max_retries=getattr(args, "supervise_retries", DEFAULT_RETRIES),
        stall_timeout=getattr(args, "supervise_stall", DEFAULT_STALL),
        backoff=getattr(args, "supervise_backoff", 2.0),
        metrics_file=getattr(args, "metrics_file", None),
        log=log)
    return sup.run()
