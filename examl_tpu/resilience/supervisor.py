"""Self-healing run supervisor (`--supervise`): converts "lost window"
into "resumed run".

The supervisor is a thin, jax-free parent (IMPORT CONTRACT in the
package `__init__`: on exclusive-access accelerators the parent must
never take the device handle the child needs, and a hung accelerator
plugin must not be able to hang the watcher).  It:

* runs the search CLI as a KILLABLE child in its own process group
  (`python -m examl_tpu.cli.main`, `--supervise` stripped);
* exports `EXAML_HEARTBEAT_FILE` and watches it — once the search loop
  starts beating, a stall longer than `--supervise-stall` means a
  dispatch/collective wedge (the class the compile watchdog cannot
  see) and the whole child process group is SIGKILLed;
* classifies every death through the shared exit taxonomy
  (`resilience/exitcause.py`: SIGILL vs OOM vs hang-kill vs preempt);
* restarts from the newest checkpoint (`-R` once one exists) with
  capped retries, exponential backoff, and ESCALATING degradation pins
  mirroring the bank's escape hatches: retry 1 pins `EXAML_PALLAS=0`
  (pallas→chunk), retry 2 pins `EXAML_UNIVERSAL=force`
  (chunk→universal: the topology-as-data interpreter compiles ONE
  program regardless of topology, so a wedge inside a per-profile
  chunk compile cannot recur), retry 3+ pins the scan tier
  (`EXAML_FAST_TRAVERSAL=0`, `EXAML_UNIVERSAL=0`,
  `EXAML_BATCH_SCAN=0`, `EXAML_BATCH_THOROUGH=0`) — the one tier
  hardware-proven everywhere;
* advertises the exported program bank (ops/export_bank.py) to every
  respawned child via EXAML_EXPORT_BANK passthrough: a retry's load
  ladder deserializes executables instead of recompiling, so restart
  MTTR is the failure, not the bank phase.  "Exported bank unusable"
  is NOT a failure cause in this ladder — the child degrades to its
  normal bank/compile phase in-process with `bank.export.rejected.*`
  counters carrying the evidence;
* treats a child exit of EXIT_PREEMPTED (75) as RESUMABLE: restarted
  immediately, no retry consumed (capped separately so a preemption
  storm still terminates);
* forwards its own SIGTERM/SIGINT to the child as SIGTERM, so
  preempting the supervisor preempts the run gracefully end-to-end;
* merges its `resilience.*` counters into the child's `--metrics`
  snapshot, so one artifact carries both sides' evidence
  (`resilience.restarts`, `resilience.heartbeat_stalls`,
  `resilience.preempts`, plus the child's `engine.nonfinite_retries`).

`EXAML_RESTART_COUNT` is exported to each attempt so fault-injection
specs (`resilience/faults.py`) can target a single attempt — the
mechanism that makes "crash once, then recover" chaos tests converge.

`--launch N` (GangSupervisor, below) extends the same contract to
multi-process runs: the supervisor spawns all N ranks itself, watches
the per-rank heartbeat files, implements rank-level failure domains
(rank death / collective wedge / single-rank straggler), and restarts
the WHOLE gang — lockstep data parallelism makes partial survival
useless — from the newest coordinated checkpoint, shrinking the world
elastically when one rank keeps dying.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from examl_tpu.obs import ledger as _ledger
from examl_tpu.resilience import exitcause, heartbeat

# Degradation ladder, in escalation order (mirrors ops/bank.FALLBACK_ENV
# without importing it: bank pulls in obs/jax, this parent must not).
DEGRADE_LADDER = (
    {},
    {"EXAML_PALLAS": "0"},
    {"EXAML_PALLAS": "0", "EXAML_UNIVERSAL": "force"},
    {"EXAML_PALLAS": "0", "EXAML_FAST_TRAVERSAL": "0",
     "EXAML_UNIVERSAL": "0", "EXAML_BATCH_SCAN": "0",
     "EXAML_BATCH_THOROUGH": "0", "EXAML_GRAD_SMOOTH": "0"},
)

DEFAULT_RETRIES = 3
DEFAULT_STALL = 300.0
POLL_S = 0.25

# Supervisor flags stripped from the child's argv.  Values live with the
# flag (argparse two-token form) — single-token "--flag=value" is also
# handled by prefix match.
_SUPERVISOR_FLAGS = {"--supervise": 0, "--supervise-retries": 1,
                     "--supervise-stall": 1, "--supervise-backoff": 1,
                     "--launch": 1, "--launch-emulate": 0,
                     "--launch-min-ranks": 1}

# Elastic resume: after the SAME rank has caused this many CONSECUTIVE
# failed attempts, the gang degrades to N-1 ranks instead of burning the
# retry budget on a slot that keeps dying (site slices re-derive from
# the byteFile window at parse time; checkpoint state is topology+model,
# so a smaller world resumes the same search).
ELASTIC_CONSECUTIVE_DEATHS = 2

# Gang causes that count as a RANK DEATH (a process died) as opposed to
# a watcher stall verdict.
_RANK_DEATH_CAUSES = frozenset({
    exitcause.CAUSE_CRASH, exitcause.CAUSE_OOM_KILL,
    exitcause.CAUSE_SIGILL, exitcause.CAUSE_ERROR,
    exitcause.CAUSE_TERMINATED})


def backoff_delay(base: float, retry: int, key: str = "",
                  cap: float = 60.0) -> float:
    """Exponential restart backoff with deterministic-seeded jitter.

    N gang ranks — or a future fleet of supervised jobs — all sleeping
    the same `base * 2**k` ladder synchronize into restart storms that
    slam a recovering device or coordinator simultaneously.  The jitter
    fraction in [0.5, 1.0) is drawn from a blake2b hash of (key, retry),
    so one run's delay sequence is REPRODUCIBLE (unit-testable, and a
    resumed supervisor re-derives the same schedule) while distinct run
    ids decorrelate across the fleet.  The cap bounds both the raw
    exponential and the jittered result."""
    raw = min(cap, base * (2 ** max(0, int(retry) - 1)))
    h = int.from_bytes(hashlib.blake2b(f"{key}:{retry}".encode(),
                                       digest_size=8).digest(), "big")
    return min(cap, raw * (0.5 + 0.5 * h / 2.0 ** 64))


def classify_stall(ages: List[float], stall: float) -> Optional[str]:
    """The gang watcher's stall verdict from the LIVE ranks' beat ages.

    * every rank stale  -> collective wedge (the lockstep program is
      blocked inside a collective/dispatch on all ranks at once);
    * one rank stale while the freshest rank is actively beating
      (age <= stall/2) -> single-rank straggler;
    * one rank stale while the others are MERELY AGING (> stall/2 but
      not yet stale) -> ambiguous: a collective wedge reaches ranks an
      allreduce apart, so keep watching — either the fresh ranks beat
      again (straggler) or everyone crosses the line (collective).
      Deciding early here would misread a wedge's first victim as a
      straggler and skip the tier-degradation ladder.
    """
    if not ages:
        return None
    stale = [a > stall for a in ages]
    if all(stale):
        return exitcause.CAUSE_COLLECTIVE_WEDGE
    if any(stale) and min(ages) <= stall / 2.0:
        return exitcause.CAUSE_STRAGGLER
    return None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_argv(argv: List[str]) -> List[str]:
    """The supervised child's argument list: the original CLI argv minus
    the supervisor-only flags (`--inject-fault` passes THROUGH — the
    child arms the registry; attempt gating keeps retries clean)."""
    out: List[str] = []
    skip = 0
    for tok in argv:
        if skip:
            skip -= 1
            continue
        flag = tok.split("=", 1)[0]
        if flag in _SUPERVISOR_FLAGS:
            if "=" not in tok:
                skip = _SUPERVISOR_FLAGS[flag]
            continue
        out.append(tok)
    return out


def checkpoint_glob(workdir: str, run_id: str) -> List[str]:
    """Checkpoint files for (workdir, run_id) — the same naming
    CheckpointManager publishes (search/checkpoint.py; that module
    imports jax via the instance, so the pattern is mirrored here and
    pinned by a cross-check test)."""
    return sorted(glob.glob(os.path.join(
        workdir, f"ExaML_binaryCheckpoint.{run_id}.ckpt_*.json.gz")))


def resume_evidence(workdir: str, run_id: str) -> List[str]:
    """Everything a retry can resume FROM: published checkpoints plus
    the fleet results journal(s) (fleet/quarantine.py — written per
    finished job, so one can exist before the first checkpoint
    publishes when a crash lands between a batch and its checkpoint;
    run_fleet reconciles journal ∪ checkpoint under -R).  Leased gangs
    write one journal per rank (`.r<k>` suffix)."""
    return checkpoint_glob(workdir, run_id) + sorted(set(
        glob.glob(os.path.join(workdir, f"ExaML_fleetJournal.{run_id}"))
        + glob.glob(os.path.join(
            workdir, f"ExaML_fleetJournal.{run_id}.r*"))))


def _repo_env() -> Dict[str, str]:
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if repo not in pp:
        env["PYTHONPATH"] = os.pathsep.join([repo] + pp)
    return env


class Supervisor:
    def __init__(self, argv: List[str], workdir: str, run_id: str,
                 max_retries: int = DEFAULT_RETRIES,
                 stall_timeout: float = DEFAULT_STALL,
                 backoff: float = 2.0,
                 metrics_file: Optional[str] = None,
                 ledger_dir: Optional[str] = None,
                 log=print):
        self.base_argv = child_argv(argv)
        self.workdir = workdir
        self.run_id = run_id
        self.max_retries = max_retries
        self.stall_timeout = stall_timeout
        self.backoff = backoff
        self.metrics_file = metrics_file
        self.log = lambda msg: log(f"supervise: {msg}")
        os.makedirs(workdir, exist_ok=True)
        # Run ledger: the supervisor writes its OWN stream
        # (`ledger.psup.jsonl` — sharing the children's directory, never
        # their rank files) so kill/restart/elastic decisions land on
        # the same merged timeline as the children's compile/phase
        # events.  obs.ledger is stdlib-only, honoring the jax-free
        # parent contract.
        self.ledger_dir = _ledger.default_dir(ledger_dir, metrics_file)
        if self.ledger_dir:
            _ledger.enable(self.ledger_dir, proc="sup")
        self.hb_path = os.path.join(workdir,
                                    f".heartbeat.{run_id}.json")
        # Counters mirrored into the metrics snapshot at the end — the
        # supervisor is jax/obs-free, so it keeps its own dict.
        self.counters: Dict[str, float] = {}
        self.attempts: List[dict] = []
        self.degrade_level = 0
        self._preempt_signal: Optional[str] = None
        self._child: Optional[subprocess.Popen] = None
        self._last_argv: List[str] = []
        # Job-level fault domain (fleet runs): per-job hang-attempt
        # counts accumulated across fleet-job-stuck kills, exported to
        # every retry as EXAML_FLEET_HANG_ATTEMPTS so the fleet driver
        # can quarantine a job that keeps blowing its deadline instead
        # of burning run-level retries on it.
        self._hang_attempts: Dict[str, int] = {}
        self._last_stuck_jobs: List[str] = []
        self._job_stuck_kills = 0
        # Memory fault domain: an alloc-oom exit (EXIT_ALLOC_OOM — the
        # child's memory governor gave up on evict+shrink) pins the
        # admission budget fraction DOWN for every later attempt,
        # halving toward the floor — the tier ladder's discipline
        # applied to memory instead of program tiers.
        self._mem_fraction_pin: Optional[float] = None

    # -- bookkeeping --------------------------------------------------------

    def _inc(self, name: str, v: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + v

    def _pins(self) -> Dict[str, str]:
        pins = dict(DEGRADE_LADDER[min(self.degrade_level,
                                       len(DEGRADE_LADDER) - 1)])
        if self._mem_fraction_pin is not None:
            pins["EXAML_MEM_BUDGET_FRACTION"] = \
                f"{self._mem_fraction_pin:.4g}"
        return pins

    def _attempt_argv(self) -> List[str]:
        argv = list(self.base_argv)
        if "-R" not in argv and resume_evidence(self.workdir,
                                                self.run_id):
            argv.append("-R")
        return argv

    # Shared retry scalars (used verbatim by both supervision loops —
    # keep the semantics in ONE place so the single-child and gang
    # policies can never drift):

    def _escalate(self, cause: str) -> None:
        if cause == exitcause.CAUSE_ALLOC_OOM:
            # The child diagnosed a device-allocator OOM itself: the
            # program tier is fine, its working set is not — halve the
            # admission budget fraction instead of degrading the tier.
            # 0.90 mirrors memgov.DEFAULT_FRACTION, 0.05 its floor
            # (this parent is jax/obs-free by contract and must not
            # import memgov's dependency closure).
            cur = self._mem_fraction_pin
            if cur is None:
                try:
                    cur = float(os.environ.get(
                        "EXAML_MEM_BUDGET_FRACTION") or 0.90)
                except ValueError:
                    cur = 0.90
            self._mem_fraction_pin = max(0.05, cur / 2.0)
            self._inc("resilience.mem_budget_pins")
            _ledger.event("supervisor.mem_budget_pin",
                          fraction=self._mem_fraction_pin)
            return
        if cause in exitcause.TIER_SUSPECT:
            # The step guarantees the scan-tier FLOOR (the ladder's
            # last rung) is reached within the configured retry
            # budget: a --supervise-retries smaller than the ladder
            # skips intermediate rungs (e.g. the universal rung)
            # rather than dying with the hardware-proven floor
            # untried.
            floor = len(DEGRADE_LADDER) - 1
            step = -(-floor // max(1, self.max_retries))   # ceil div
            self.degrade_level = min(self.degrade_level + step, floor)

    def _retry_delay(self, retries: int) -> float:
        return backoff_delay(self.backoff, retries, key=self.run_id)

    @staticmethod
    def _exhausted_rc(rc: Optional[int]) -> int:
        """Final exit status when the retry budget is spent.  Signal
        deaths surface as the conventional 128+signum (a raw negative
        rc through sys.exit becomes an unclassifiable 247-style
        status)."""
        if rc is None:
            return 1
        return 128 - rc if rc < 0 else (rc or 1)

    # -- signal forwarding --------------------------------------------------

    def _live_children(self) -> List[subprocess.Popen]:
        """Children a preemption must be forwarded to (the gang
        supervisor overrides this with its whole rank list)."""
        return [self._child] if self._child is not None else []

    def _signal_children(self, sig) -> None:
        for child in self._live_children():
            if child is not None and child.poll() is None:
                try:
                    os.killpg(child.pid, sig)
                except (OSError, ProcessLookupError):
                    pass

    def _install_signals(self):
        if not hasattr(signal, "SIGTERM"):
            return None

        def handler(signum, frame):
            self._preempt_signal = signal.Signals(signum).name
            # graceful: the children checkpoint and exit resumable
            self._signal_children(signal.SIGTERM)

        try:
            return (signal.signal(signal.SIGTERM, handler),
                    signal.signal(signal.SIGINT, handler))
        except ValueError:                  # non-main thread (tests)
            return None

    def _restore_signals(self, prior) -> None:
        if prior is not None:
            signal.signal(signal.SIGTERM, prior[0])
            signal.signal(signal.SIGINT, prior[1])

    # -- one attempt --------------------------------------------------------

    def _spawn(self, restarts_total: int) -> subprocess.Popen:
        env = _repo_env()
        env["EXAML_HEARTBEAT_FILE"] = self.hb_path
        env["EXAML_RESTART_COUNT"] = str(restarts_total)
        if self._hang_attempts:
            # Fleet job-stuck evidence rides into the retry: the driver
            # bumps these jobs' attempt counts and quarantines any past
            # its cap (fleet/quarantine.py parses this).
            env["EXAML_FLEET_HANG_ATTEMPTS"] = ",".join(
                f"{jid}={n}" for jid, n in sorted(
                    self._hang_attempts.items()))
        env.update(self._pins())
        if restarts_total and (env.get("EXAML_EXPORT_BANK") or "") \
                .strip().lower() not in ("", "0", "off", "no"):
            # Zero-compile restart (ops/export_bank.py): the exported
            # program bank rides the environment into every respawned
            # child, whose load ladder deserializes executables instead
            # of re-running the bank/warm compile phase — MTTR is the
            # failure, not the recompilation.  An unusable exported
            # bank is a counter-carrying downgrade to the normal bank
            # phase inside the child (bank.export.rejected.*), never a
            # distinct exit cause this ladder reacts to.
            self.log("attempt %d: exported program bank advertised "
                     "(EXAML_EXPORT_BANK=%s)"
                     % (restarts_total, env["EXAML_EXPORT_BANK"]))
        argv = self._last_argv = self._attempt_argv()
        pins = self._pins()
        self.log(f"attempt {restarts_total}: starting "
                 + ("(resume -R) " if "-R" in argv else "")
                 + (f"[pins {pins}] " if pins else "")
                 + " ".join(argv))
        try:
            os.unlink(self.hb_path)         # stale beats must not mask
        except OSError:                     # a child that never starts
            pass
        return subprocess.Popen(
            [sys.executable, "-m", "examl_tpu.cli.main"] + argv,
            env=env, start_new_session=True)

    def _kill_group(self, child: subprocess.Popen) -> None:
        """SIGKILL the child's whole process group: bank workers and any
        other helpers must die with it, or the retry races them for the
        accelerator."""
        for target in (child.pid,):
            try:
                os.killpg(target, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    child.kill()
                except OSError:
                    pass
        child.wait()

    def _watch(self, child: subprocess.Popen) -> str:
        """Wait for exit or heartbeat stall; returns the exit cause."""
        spawned = time.time()
        # Startup (data load, banking, first compiles, the pre-search
        # model opt) legitimately produces no beats, so the deadline
        # for the FIRST beat is much more generous than the stall
        # window — but it must exist: a dispatch that wedges before the
        # first search iteration would otherwise hang the supervisor
        # forever.
        first_beat_deadline = max(4.0 * self.stall_timeout, 900.0)
        while True:
            rc = child.poll()
            if rc is not None:
                return exitcause.classify(rc)
            hb_age = heartbeat.age(self.hb_path)
            # Fleet job-level fault domain: the last beat may DECLARE
            # an in-flight batch (job ids + wall-clock deadline).  The
            # deadline is enforced INDEPENDENTLY of the generic stall
            # window — the kill lands when the DEADLINE expires (not at
            # max(stall, deadline)), and it works under
            # --supervise-stall 0, where only declared deadlines are
            # watched.  A completed batch clears the declaration, so a
            # fresh record without one can never trigger this verdict.
            deadline = None
            fl = {}
            if hb_age is not None:
                last_rec = heartbeat.read(self.hb_path) or {}
                fl = last_rec.get("fleet") or {}
                if fl.get("jobs") and fl.get("deadline"):
                    deadline = float(fl["deadline"])
            if deadline is not None and time.time() > deadline:
                jobs = [str(j) for j in fl["jobs"]]
                self._last_stuck_jobs = jobs
                self.log(
                    "fleet batch exceeded its per-job deadline "
                    f"(jobs {','.join(jobs)}; beat age "
                    + (f"{hb_age:.0f}s" if hb_age is not None
                       else "n/a")
                    + "); killing the child process group "
                    "(job-level fault domain: no run-level "
                    "retry consumed)")
                self._inc("resilience.fleet_job_stuck_kills")
                _ledger.event("supervisor.kill",
                              reason="fleet-job-stuck",
                              jobs=",".join(jobs),
                              beat_age_s=(round(hb_age, 1)
                                          if hb_age is not None
                                          else None))
                self._kill_group(child)
                return exitcause.CAUSE_FLEET_JOB_STUCK
            if self.stall_timeout:
                stalled = (hb_age > self.stall_timeout
                           if hb_age is not None else
                           time.time() - spawned > first_beat_deadline)
                if stalled and deadline is not None:
                    # A declared batch with a live deadline is
                    # legitimately allowed to outlast the stall window:
                    # keep watching until the deadline verdict above.
                    time.sleep(POLL_S)
                    continue
                if stalled:
                    # The search loop stopped beating (or never
                    # started): dispatch/collective wedge.  Kill the
                    # whole group and classify ourselves — our SIGKILL
                    # must not read as an OOM kill.
                    last = heartbeat.read(self.hb_path) or {}
                    self.log(
                        "heartbeat stalled ("
                        + (f"{hb_age:.0f}s > {self.stall_timeout:.0f}s"
                           if hb_age is not None else
                           f"no first beat within {first_beat_deadline:.0f}s")
                        + f"; last state {last.get('state')!r} seq "
                        f"{last.get('seq')}); killing the child process "
                        "group")
                    self._inc("resilience.heartbeat_stalls")
                    _ledger.event("supervisor.kill",
                                  reason="heartbeat-stall",
                                  beat_age_s=(round(hb_age, 1)
                                              if hb_age is not None
                                              else None),
                                  last_state=last.get("state"))
                    self._kill_group(child)
                    return exitcause.CAUSE_HANG_KILL
            time.sleep(POLL_S)

    # -- the supervision loop -----------------------------------------------

    def run(self) -> int:
        prior = self._install_signals()
        retries = 0
        preempts = 0
        restarts_total = 0
        rc = 1
        try:
            while True:
                if self._preempt_signal is not None:
                    # Preempted BETWEEN children (during the backoff
                    # sleep or before the first spawn): there is no
                    # child to forward to — exit resumable now instead
                    # of launching an attempt the grace window will
                    # just SIGKILL.
                    self.log(f"supervisor preempted "
                             f"({self._preempt_signal}) between "
                             "attempts; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                t0 = time.time()
                self._child = child = self._spawn(restarts_total)
                cause = self._watch(child)
                self._child = None
                rc = child.returncode
                rec = {
                    "attempt": restarts_total, "cause": cause,
                    "returncode": rc, "seconds": round(time.time() - t0, 2),
                    "pins": self._pins(),
                    "resumed": "-R" in self._last_argv}
                if cause != exitcause.CAUSE_OK:
                    rec["partial_counters"] = self._partial_counters(t0)
                self.attempts.append(rec)
                desc = exitcause.exit_desc(rc, none_desc="(hang-killed)")

                if cause == exitcause.CAUSE_OK:
                    self.log(f"run completed after {restarts_total} "
                             "restart(s)")
                    _ledger.event("supervisor.done",
                                  restarts=restarts_total)
                    return 0
                if self._preempt_signal is not None:
                    # WE were preempted: the child checkpointed (or
                    # died); do not restart — exit resumable ourselves.
                    self.log(f"supervisor preempted ({self._preempt_signal})"
                             f"; child exited {desc}; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                if cause == exitcause.CAUSE_PREEMPT:
                    # The CHILD was preempted externally but we were
                    # not: resume immediately, no retry consumed.
                    preempts += 1
                    self._inc("resilience.preempts")
                    if preempts > max(10, 5 * self.max_retries):
                        self.log("preemption storm: giving up")
                        return exitcause.EXIT_PREEMPTED
                    restarts_total += 1
                    self._inc("resilience.restarts")
                    _ledger.event("supervisor.restart", cause="preempt",
                                  retry_consumed=False)
                    self.log(f"child preempted {desc}; resuming "
                             "(no retry consumed)")
                    continue
                if cause == exitcause.CAUSE_FLEET_JOB_STUCK:
                    # JOB-level fault domain: the batch's jobs pay (the
                    # restarted driver bumps their hang-attempt counts
                    # and quarantines repeat offenders), the RUN does
                    # not — no retry consumed, no tier pin (the tier is
                    # not suspect; one job is).  Bounded separately: a
                    # storm of job-stuck kills beyond what the per-job
                    # attempt caps can produce means something else is
                    # wrong.
                    self._job_stuck_kills += 1
                    for jid in self._last_stuck_jobs:
                        self._hang_attempts[jid] = \
                            self._hang_attempts.get(jid, 0) + 1
                    if self._job_stuck_kills > max(10,
                                                   5 * self.max_retries):
                        self.log("fleet job-stuck kill storm: giving up")
                        return self._exhausted_rc(rc)
                    restarts_total += 1
                    self._inc("resilience.restarts")
                    _ledger.event("supervisor.restart",
                                  cause="fleet-job-stuck",
                                  retry_consumed=False,
                                  hang_attempts=dict(self._hang_attempts))
                    self.log(
                        "fleet job(s) "
                        + ",".join(self._last_stuck_jobs)
                        + " blew their deadline; resuming with "
                        f"hang-attempt record {self._hang_attempts} "
                        "(no retry consumed, no tier pin)")
                    continue
                if cause == exitcause.CAUSE_USAGE:
                    self.log(f"usage error {desc}: not retryable")
                    return rc
                # Failure: classify, maybe degrade, retry with backoff.
                retries += 1
                self._inc("resilience.restarts")
                self._inc(f"resilience.exits.{cause.replace('-', '_')}")
                if retries > self.max_retries:
                    self.log(f"child failed ({cause} {desc}); retry "
                             f"budget exhausted after {self.max_retries}")
                    return self._exhausted_rc(rc)
                self._escalate(cause)
                delay = self._retry_delay(retries)
                have_ckpt = bool(checkpoint_glob(self.workdir,
                                                 self.run_id))
                _ledger.event("supervisor.restart", cause=cause,
                              retry=retries, resumed=have_ckpt,
                              delay_s=round(delay, 2),
                              pins=sorted(self._pins()))
                self.log(
                    f"child failed ({cause} {desc}); retry "
                    f"{retries}/{self.max_retries} in {delay:.1f}s "
                    + ("from newest checkpoint"
                       if have_ckpt else "from scratch (no checkpoint)")
                    + (f", degradation level {self.degrade_level} "
                       f"pins {self._pins()}"
                       if self._pins() else ""))
                time.sleep(delay)
                restarts_total += 1
        finally:
            child = self._child
            if child is not None and child.poll() is None:
                self._kill_group(child)
            self._restore_signals(prior)
            self._merge_metrics()
            self._finalize_ledger()

    # -- metrics ------------------------------------------------------------

    def _finalize_ledger(self) -> None:
        """Close the supervisor's ledger stream and merge the directory
        into one ordered timeline — the children have exited, so their
        rank files (including a SIGKILLed attempt's crash-truncated
        one) are complete as far as they will ever be."""
        if self.ledger_dir:
            # finalize() runs the directory merge itself (proc "sup"
            # is in its auto-merge set) — one pass, no double I/O.
            merged = _ledger.finalize()
            if merged:
                self.log(f"run ledger (merged) -> {merged}")

    def _partial_counters(self, since: float) -> Optional[dict]:
        """The killed attempt's last-known counters: a SIGKILLed /
        hang-killed child never writes its exit snapshot, but the
        heartbeat-ticked periodic flush (obs.metrics.maybe_autoflush)
        leaves a `"partial": true` snapshot behind.  Read it NOW —
        before the restarted attempt overwrites the file — so the
        attempt record preserves where progress stopped.  `since` is
        the attempt's start time: a flush stamped before it belongs to
        a PREVIOUS attempt (this one died before its first flush) and
        must not be attributed here."""
        if not self.metrics_file:
            return None
        try:
            with open(self.metrics_file) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return None
        if not snap.get("partial"):
            return None               # a full exit snapshot: not a kill
        if snap.get("flushed_at", 0) < since:
            return None               # stale: an earlier attempt's flush
        return snap.get("counters") or {}

    def _resilience_blob(self) -> dict:
        blob = {"attempts": self.attempts,
                "final_pins": self._pins(),
                "heartbeat_file": self.hb_path}
        if self._hang_attempts:
            blob["fleet_hang_attempts"] = dict(self._hang_attempts)
        return blob

    def _merge_metrics(self) -> None:
        """Fold the supervisor's evidence into the child's --metrics
        snapshot (the child rewrites the file at every exit, so the
        LAST attempt's registry is on disk; the supervisor's counters
        span all attempts).  Without --metrics, write nothing — the log
        lines remain the record."""
        if not self.metrics_file:
            return
        snap: dict = {}
        try:
            with open(self.metrics_file) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            snap = {}
        snap.setdefault("counters", {}).update(self.counters)
        snap.setdefault("gauges", {})["resilience.degrade_level"] = \
            self.degrade_level
        snap["resilience"] = self._resilience_blob()
        try:
            with open(self.metrics_file, "w") as f:
                json.dump(snap, f, indent=2, sort_keys=True, default=str)
            self.log(f"metrics snapshot (merged) -> {self.metrics_file}")
        except OSError as exc:
            self.log(f"metrics merge failed ({exc})")


class GangSupervisor(Supervisor):
    """Rank-level failure domains for multi-process runs (`--launch N`).

    ExaML's parallelism is LOCKSTEP: every rank runs the search loop in
    unison and synchronizes through small allreduces, so one dead or
    wedged rank stalls the whole gang indefinitely — partial survival
    is useless, and the only sane recovery unit is the gang.  The gang
    supervisor therefore:

    * spawns all N ranks itself, each a killable process group with
      `EXAML_PROCID=<k>` / `EXAML_GANG_RANKS=<N>` exported (plus
      `--coordinator/--nprocs/--procid` in real distributed mode;
      EMULATED mode — `--launch-emulate`, for CPU containers whose
      jaxlib lacks multi-process collectives, and for the chaos tests —
      spawns N independent single-process ranks that follow the same
      rank contract);
    * aggregates the per-rank heartbeat files
      (`parallel/launch.install_heartbeat` suffixes `.p<k>`) and
      distinguishes the failure domains: RANK DEATH (a process died),
      COLLECTIVE WEDGE (every rank's beats went stale together — the
      blocked-allreduce class) and SINGLE-RANK STRAGGLER (one rank
      stale while peers actively beat) — see `classify_stall`;
    * on any failure kills the WHOLE gang, classifies the first-failing
      rank through the shared taxonomy, and restarts the gang from the
      newest COORDINATED checkpoint (two-phase publish,
      search/checkpoint.py) with the same backoff/retry/tier-pin
      ladder as the single-process supervisor, applied gang-wide;
    * ELASTIC RESUME: a rank that causes ELASTIC_CONSECUTIVE_DEATHS
      failed attempts in a row shrinks the gang to N-1 ranks (down to
      `--launch-min-ranks`) — checkpoint state is topology+model and
      site slices re-derive at parse time, so a smaller world resumes
      the same search instead of burning the window.
    """

    def __init__(self, argv: List[str], workdir: str, run_id: str,
                 ranks: int, emulate: bool = False, min_ranks: int = 1,
                 fleet: bool = False, **kwargs):
        super().__init__(argv, workdir, run_id, **kwargs)
        self.world = max(1, int(ranks))
        self._max_world = self.world
        self.emulate = bool(emulate)
        self.min_ranks = max(1, int(min_ranks))
        # Fleet gangs are NOT lockstep (ISSUE 14): every rank leases
        # independent jobs from the shared board, so the failure domain
        # is the RANK, not the gang — `run()` takes the leased loop
        # (`_run_fleet`) instead of the lockstep kill-the-world policy.
        self.fleet = bool(fleet)
        self._children: List[subprocess.Popen] = []
        self._death_streak = 0
        self._last_dead_rank: Optional[int] = None

    # -- plumbing -----------------------------------------------------------

    def _live_children(self) -> List[subprocess.Popen]:
        return list(self._children)

    def _kill_gang(self) -> None:
        for child in self._children:
            if child.poll() is None:
                self._kill_group(child)

    def _drain_gang(self, timeout: float = 30.0) -> None:
        """Graceful gang teardown (preemption): SIGTERM every live rank
        so each checkpoints, then SIGKILL whatever outlives the grace."""
        self._signal_children(signal.SIGTERM)
        deadline = time.time() + timeout
        while time.time() < deadline and any(
                c.poll() is None for c in self._children):
            time.sleep(POLL_S)
        self._kill_gang()

    def _spawn_gang(self, restarts_total: int) -> List[subprocess.Popen]:
        argv = self._last_argv = self._attempt_argv()
        pins = self._pins()
        port = None if self.emulate else _free_port()
        self.log(f"attempt {restarts_total}: starting gang of "
                 f"{self.world} rank(s) "
                 + ("(emulated, no process group) " if self.emulate else
                    f"(coordinator 127.0.0.1:{port}) ")
                 + ("(resume -R) " if "-R" in argv else "")
                 + (f"[pins {pins}] " if pins else "")
                 + " ".join(argv))
        # Stale beats (including ranks beyond a shrunken world) must not
        # mask a rank that never starts.
        for path in heartbeat.gang_paths(self.hb_path, self._max_world):
            try:
                os.unlink(path)
            except OSError:
                pass
        children = []
        for k in range(self.world):
            env = _repo_env()
            env["EXAML_HEARTBEAT_FILE"] = self.hb_path
            env["EXAML_RESTART_COUNT"] = str(restarts_total)
            env[heartbeat.PROCID_VAR] = str(k)
            env[heartbeat.GANG_VAR] = str(self.world)
            env.update(pins)
            rank_argv = list(argv)
            if not self.emulate:
                rank_argv += ["--coordinator", f"127.0.0.1:{port}",
                              "--nprocs", str(self.world),
                              "--procid", str(k)]
            children.append(subprocess.Popen(
                [sys.executable, "-m", "examl_tpu.cli.main"] + rank_argv,
                env=env, start_new_session=True))
        self._children = children
        return children

    # -- the gang watcher ---------------------------------------------------

    def _watch_gang(self) -> Tuple[str, Optional[int], Dict[str, str]]:
        """Wait for gang completion, first rank failure, or a stall
        verdict; returns (cause, guilty rank or None, per-rank exits)."""
        children = self._children
        spawned = time.time()
        first_beat_deadline = max(4.0 * self.stall_timeout, 900.0) \
            if self.stall_timeout else float("inf")
        grace = self.stall_timeout or 300.0
        done: Dict[int, str] = {}

        def exits(guilty: Optional[int], cause: str) -> Dict[str, str]:
            out = {}
            for k, ch in enumerate(children):
                if k == guilty:
                    out[f"r{k}"] = cause
                elif k in done:
                    out[f"r{k}"] = done[k]
                elif ch.poll() is None:
                    out[f"r{k}"] = "gang-killed"
                else:
                    out[f"r{k}"] = exitcause.classify(ch.returncode)
            return out

        while True:
            for k, ch in enumerate(children):
                if k in done:
                    continue
                rc = ch.poll()
                if rc is None:
                    continue
                cause = exitcause.classify(rc)
                if cause == exitcause.CAUSE_OK:
                    done[k] = exitcause.CAUSE_OK
                    continue
                if 0 in done and done[0] == exitcause.CAUSE_OK:
                    # Rank 0 already completed the run: a peer dying
                    # during teardown cannot un-finish it.  Record, do
                    # not fail the attempt.
                    self.log(f"rank {k} exited {cause} "
                             f"{exitcause.exit_desc(rc)} after rank 0 "
                             "completed; ignoring")
                    done[k] = cause
                    continue
                self.log(f"rank {k} died: {cause} "
                         f"{exitcause.exit_desc(rc)}; killing the gang "
                         "(lockstep — partial survival is useless)")
                _ledger.event("supervisor.kill", reason="rank-death",
                              rank=k, cause=cause, returncode=rc)
                return cause, k, exits(k, cause)
            if len(done) == len(children):
                return exitcause.CAUSE_OK, None, exits(None, "")
            if done.get(0) == exitcause.CAUSE_OK:
                # The primary finished; lockstep peers exit within an
                # allreduce of it.  Give them a grace window, then
                # sweep — their outputs are per-rank scratch.
                if not hasattr(self, "_rank0_done_t"):
                    self._rank0_done_t = time.time()
                if time.time() - self._rank0_done_t > grace:
                    self.log("rank 0 completed; sweeping "
                             f"{len(children) - len(done)} lingering "
                             "peer(s) after the grace window")
                    # Snapshot exits BEFORE our kill: swept peers must
                    # read "gang-killed", not the SIGKILL we send.
                    ex = exits(None, "")
                    self._kill_gang()
                    return exitcause.CAUSE_OK, None, ex
            elif self.stall_timeout:
                live = [k for k in range(len(children)) if k not in done]
                ages = []
                waiting_first_beat = False
                for k in live:
                    a = heartbeat.age(
                        heartbeat.rank_path(self.hb_path, k))
                    if a is None:
                        # Never beaten.  Within the (generous)
                        # first-beat deadline this rank's liveness is
                        # UNKNOWN — it may legitimately still be in
                        # setup/first compiles, and its lockstep peers
                        # may already be blocked waiting on it, so NO
                        # stall verdict can be attributed yet (calling
                        # the blocked-but-healthy peer a straggler
                        # would skip the tier ladder).  Past the
                        # deadline it is maximally stale.
                        elapsed = time.time() - spawned
                        if elapsed <= first_beat_deadline:
                            waiting_first_beat = True
                            break
                        a = elapsed
                    ages.append(a)
                if waiting_first_beat:
                    time.sleep(POLL_S)
                    continue
                verdict = classify_stall(ages, self.stall_timeout)
                if verdict is not None:
                    guilty = live[max(range(len(ages)),
                                      key=ages.__getitem__)]
                    self.log(
                        f"{verdict}: rank beat ages "
                        + ", ".join(f"r{k}={a:.0f}s"
                                    for k, a in zip(live, ages))
                        + f" against a {self.stall_timeout:.0f}s stall "
                        "window; killing the gang")
                    self._inc("resilience.heartbeat_stalls")
                    _ledger.event("supervisor.kill", reason=verdict,
                                  rank=guilty,
                                  beat_ages_s=[round(a, 1)
                                               for a in ages])
                    # Snapshot per-rank exits BEFORE our kill: the
                    # still-running peers must read "gang-killed", not
                    # the SIGKILL we are about to send them.
                    ex = exits(guilty, verdict)
                    self._kill_gang()
                    return verdict, guilty, ex
            time.sleep(POLL_S)

    # -- the leased fleet gang (non-lockstep rank domains) -------------------

    def _spawn_fleet_rank(self, k: int, attempt: int) -> subprocess.Popen:
        """One fleet rank, env-contract only: fleet ranks never join a
        collective process group (jobs are independent), so even
        non-emulated launches spawn plain single-process ranks with
        EXAML_PROCID/EXAML_GANG_RANKS exported.  NO tier pins: a fleet
        rank death indicts the rank's environment, never the program
        tier.  EXAML_EXPORT_BANK rides `_repo_env`'s passthrough, so a
        respawned rank deserializes its programs from the exported bank
        (ops/export_bank.py) and re-leases its first job without paying
        the compile phase that used to dominate rank-respawn MTTR."""
        argv = self._last_argv = self._attempt_argv()
        env = _repo_env()
        env["EXAML_HEARTBEAT_FILE"] = self.hb_path
        env["EXAML_RESTART_COUNT"] = str(attempt)
        env[heartbeat.PROCID_VAR] = str(k)
        env[heartbeat.GANG_VAR] = str(self.world)
        if self._hang_attempts:
            env["EXAML_FLEET_HANG_ATTEMPTS"] = ",".join(
                f"{jid}={n}" for jid, n in sorted(
                    self._hang_attempts.items()))
        try:
            os.unlink(heartbeat.rank_path(self.hb_path, k))
        except OSError:
            pass
        self.log(f"fleet rank {k}: starting (attempt {attempt}) "
                 + ("(resume -R) " if "-R" in argv else "")
                 + " ".join(argv))
        return subprocess.Popen(
            [sys.executable, "-m", "examl_tpu.cli.main"] + argv,
            env=env, start_new_session=True)

    def _rank_fleet_deadline(self, k: int):
        """(deadline, jobs) declared by rank k's last FLEET beat, or
        (None, []) — the per-rank version of `_watch`'s in-flight
        declaration read."""
        rec = heartbeat.read(heartbeat.rank_path(self.hb_path, k)) or {}
        fl = rec.get("fleet") or {}
        if fl.get("jobs") and fl.get("deadline"):
            try:
                return float(fl["deadline"]), [str(j) for j in
                                               fl["jobs"]]
            except (TypeError, ValueError):
                pass
        return None, []

    def _run_fleet(self) -> int:
        """The leased-gang loop: rank-level fault domains.  A dead rank
        costs ONLY its in-flight leases — the rank is restarted alone
        (cause `fleet-rank-death`, no gang-wide kill, no tier pin, no
        run-level retry), its expired leases are reaped by surviving
        ranks, and a rank that keeps dying is eventually ABANDONED
        while the rest of the gang serves on (the elastic-resume lesson
        applied at the rank level)."""
        prior = self._install_signals()
        respawn_cap = max(5, 3 * self.max_retries)
        children: Dict[int, subprocess.Popen] = {}
        respawns: Dict[int, int] = {k: 0 for k in range(self.world)}
        spawn_at: Dict[int, float] = {}
        spawned_t: Dict[int, float] = {}
        done: Dict[int, int] = {}
        abandoned: set = set()
        first_beat_deadline = (max(4.0 * self.stall_timeout, 900.0)
                               if self.stall_timeout else float("inf"))
        last_rc = 1

        def rank_died(k: int, cause: str, rc) -> None:
            nonlocal last_rc
            last_rc = rc if rc is not None else 1
            self._inc("resilience.gang.fleet_rank_deaths")
            self._inc("resilience.restarts")
            self._inc(f"resilience.gang.rank_exits.r{k}."
                      f"{cause.replace('-', '_')}")
            self.attempts.append({
                "rank": k, "cause": exitcause.CAUSE_FLEET_RANK_DEATH,
                "rank_cause": cause, "returncode": rc,
                "respawn": respawns[k],
                "seconds": round(time.time() - spawned_t.get(k, 0.0),
                                 2)})
            respawns[k] += 1
            if respawns[k] > respawn_cap:
                abandoned.add(k)
                self._inc("resilience.gang.rank_abandoned")
                _ledger.event("supervisor.rank_abandoned", rank=k,
                              respawns=respawns[k] - 1)
                self.log(f"fleet rank {k} died {respawns[k] - 1} "
                         "time(s); ABANDONING the rank slot (its "
                         "leases expire; peers absorb the queue)")
                return
            delay = backoff_delay(self.backoff, respawns[k],
                                  key=f"{self.run_id}:r{k}")
            spawn_at[k] = time.time() + delay
            _ledger.event("supervisor.restart",
                          cause=exitcause.CAUSE_FLEET_RANK_DEATH,
                          rank=k, rank_cause=cause,
                          retry_consumed=False,
                          delay_s=round(delay, 2))
            self.log(
                f"fleet rank {k} died ({cause} "
                f"{exitcause.exit_desc(rc, none_desc='(killed)')}); "
                f"restarting ONLY this rank in {delay:.1f}s — "
                "fleet-rank-death: its in-flight leases expire and "
                "peers reap them (no gang kill, no tier pin, no "
                "run-level retry)")

        try:
            for k in range(self.world):
                children[k] = self._spawn_fleet_rank(k, 0)
                spawned_t[k] = time.time()
            while True:
                self._children = [ch for k, ch in sorted(children.items())
                                  if k not in done]
                if self._preempt_signal is not None:
                    self.log(f"supervisor preempted "
                             f"({self._preempt_signal}); draining the "
                             "fleet gang")
                    self._inc("resilience.preempts")
                    self._drain_gang()
                    return exitcause.EXIT_PREEMPTED
                for k in sorted(children):
                    if k in done or k in abandoned:
                        continue
                    ch = children[k]
                    if k in spawn_at:
                        # waiting out the respawn backoff
                        if time.time() >= spawn_at[k]:
                            del spawn_at[k]
                            children[k] = self._spawn_fleet_rank(
                                k, respawns[k])
                            spawned_t[k] = time.time()
                        continue
                    rc = ch.poll()
                    if rc is not None:
                        cause = exitcause.classify(rc)
                        if cause == exitcause.CAUSE_OK:
                            done[k] = 0
                            self.log(f"fleet rank {k}: queue drained, "
                                     "exited cleanly")
                            continue
                        _ledger.event("supervisor.kill",
                                      reason="fleet-rank-death", rank=k,
                                      cause=cause, returncode=rc)
                        rank_died(k, cause, rc)
                        continue
                    # Per-rank liveness: a stalled or job-stuck rank is
                    # killed ALONE (the peers are not blocked on it —
                    # nothing is lockstep here) and restarted through
                    # the same rank-death path.
                    hb = heartbeat.rank_path(self.hb_path, k)
                    hb_age = heartbeat.age(hb)
                    deadline, jobs = self._rank_fleet_deadline(k)
                    if deadline is not None and time.time() > deadline:
                        for jid in jobs:
                            self._hang_attempts[jid] = \
                                self._hang_attempts.get(jid, 0) + 1
                        self._inc("resilience.fleet_job_stuck_kills")
                        _ledger.event("supervisor.kill",
                                      reason="fleet-job-stuck", rank=k,
                                      jobs=",".join(jobs))
                        self.log(f"fleet rank {k}: batch blew its "
                                 f"per-job deadline (jobs "
                                 f"{','.join(jobs)}); killing and "
                                 "restarting the rank (jobs pay "
                                 "attempts, the run pays nothing)")
                        self._kill_group(ch)
                        rank_died(k, exitcause.CAUSE_FLEET_JOB_STUCK,
                                  ch.returncode)
                        continue
                    if self.stall_timeout:
                        stalled = (
                            hb_age > self.stall_timeout
                            if hb_age is not None else
                            time.time() - spawned_t[k]
                            > first_beat_deadline)
                        if stalled and deadline is None:
                            self._inc("resilience.heartbeat_stalls")
                            _ledger.event("supervisor.kill",
                                          reason="heartbeat-stall",
                                          rank=k,
                                          beat_age_s=(round(hb_age, 1)
                                                      if hb_age
                                                      is not None
                                                      else None))
                            self.log(f"fleet rank {k}: heartbeat "
                                     "stalled; killing and restarting "
                                     "the rank")
                            self._kill_group(ch)
                            rank_died(k, exitcause.CAUSE_HANG_KILL,
                                      ch.returncode)
                            continue
                if len(done) + len(abandoned) >= self.world:
                    break
                time.sleep(POLL_S)
            if done:
                self.log(f"fleet gang completed: {len(done)} rank(s) "
                         f"drained the queue"
                         + (f", {len(abandoned)} abandoned"
                            if abandoned else ""))
                _ledger.event("supervisor.done", world=self.world,
                              ranks_ok=len(done),
                              ranks_abandoned=len(abandoned))
                return 0
            self.log("every fleet rank was abandoned; giving up")
            return self._exhausted_rc(last_rc)
        finally:
            self._children = list(children.values())
            self._kill_gang()
            self._restore_signals(prior)
            self._merge_metrics()
            self._finalize_ledger()

    # -- the gang supervision loop ------------------------------------------

    def run(self) -> int:
        if self.fleet:
            return self._run_fleet()
        prior = self._install_signals()
        retries = 0
        preempts = 0
        restarts_total = 0
        try:
            while True:
                if self._preempt_signal is not None:
                    self.log(f"supervisor preempted "
                             f"({self._preempt_signal}) between "
                             "attempts; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                if hasattr(self, "_rank0_done_t"):
                    del self._rank0_done_t
                t0 = time.time()
                self._spawn_gang(restarts_total)
                cause, rank, rank_exits = self._watch_gang()
                if cause == exitcause.CAUSE_PREEMPT:
                    self._drain_gang()       # peers checkpoint, then die
                elif cause != exitcause.CAUSE_OK:
                    self._kill_gang()
                rc = (self._children[rank].returncode
                      if rank is not None
                      else self._children[0].returncode)
                rec = {
                    "attempt": restarts_total, "cause": cause,
                    "rank": rank, "rank_exits": rank_exits,
                    "world": self.world, "returncode": rc,
                    "seconds": round(time.time() - t0, 2),
                    "pins": self._pins(),
                    "resumed": "-R" in self._last_argv}
                if cause != exitcause.CAUSE_OK:
                    rec["partial_counters"] = self._partial_counters(t0)
                self.attempts.append(rec)
                desc = exitcause.exit_desc(rc, none_desc="(gang-killed)")

                if cause == exitcause.CAUSE_OK:
                    self.log(f"gang run completed after {restarts_total} "
                             "restart(s)")
                    _ledger.event("supervisor.done",
                                  restarts=restarts_total,
                                  world=self.world)
                    return 0
                if self._preempt_signal is not None:
                    self.log(f"supervisor preempted "
                             f"({self._preempt_signal}); gang exited "
                             f"{desc}; not restarting")
                    self._inc("resilience.preempts")
                    return exitcause.EXIT_PREEMPTED
                if cause == exitcause.CAUSE_PREEMPT:
                    preempts += 1
                    self._inc("resilience.preempts")
                    if preempts > max(10, 5 * self.max_retries):
                        self.log("preemption storm: giving up")
                        return exitcause.EXIT_PREEMPTED
                    restarts_total += 1
                    self._inc("resilience.restarts")
                    _ledger.event("supervisor.restart", cause="preempt",
                                  rank=rank, retry_consumed=False)
                    self.log(f"rank {rank} preempted {desc}; resuming "
                             "the gang (no retry consumed)")
                    continue
                if cause == exitcause.CAUSE_USAGE:
                    self.log(f"usage error {desc}: not retryable")
                    return rc
                # Gang failure: count the domain, maybe shrink, retry.
                retries += 1
                self._inc("resilience.restarts")
                self._inc(f"resilience.exits.{cause.replace('-', '_')}")
                if rank is not None:
                    self._inc("resilience.gang.rank_exits."
                              f"r{rank}.{cause.replace('-', '_')}")
                if cause == exitcause.CAUSE_COLLECTIVE_WEDGE:
                    self._inc("resilience.gang.collective_wedges")
                elif cause == exitcause.CAUSE_STRAGGLER:
                    self._inc("resilience.gang.straggler_kills")
                elif cause in _RANK_DEATH_CAUSES:
                    self._inc("resilience.gang.rank_deaths")
                # Elastic resume bookkeeping: the streak tracks one
                # rank dying on consecutive attempts; any other outcome
                # resets it.
                if cause in _RANK_DEATH_CAUSES and rank is not None:
                    if rank == self._last_dead_rank:
                        self._death_streak += 1
                    else:
                        self._last_dead_rank = rank
                        self._death_streak = 1
                else:
                    self._last_dead_rank = None
                    self._death_streak = 0
                if (self._death_streak >= ELASTIC_CONSECUTIVE_DEATHS
                        and self.world > self.min_ranks):
                    self.world -= 1
                    self._inc("resilience.gang.elastic_resumes")
                    _ledger.event("supervisor.elastic_resume",
                                  dead_rank=rank, world=self.world)
                    self.log(
                        f"elastic resume: rank {rank} died "
                        f"{self._death_streak} consecutive time(s); "
                        f"degrading the gang to {self.world} rank(s) "
                        "(site slices re-derive at parse time; "
                        "checkpoint state is world-size independent)")
                    self._last_dead_rank = None
                    self._death_streak = 0
                if retries > self.max_retries:
                    self.log(f"gang failed ({cause} {desc}); retry "
                             f"budget exhausted after {self.max_retries}")
                    return self._exhausted_rc(rc)
                self._escalate(cause)
                delay = self._retry_delay(retries)
                have_ckpt = bool(checkpoint_glob(self.workdir,
                                                 self.run_id))
                _ledger.event("supervisor.restart", cause=cause,
                              rank=rank, retry=retries,
                              resumed=have_ckpt, world=self.world,
                              delay_s=round(delay, 2),
                              pins=sorted(self._pins()))
                self.log(
                    f"gang failed ({cause} {desc}); retry "
                    f"{retries}/{self.max_retries} in {delay:.1f}s "
                    + ("from newest coordinated checkpoint"
                       if have_ckpt else "from scratch (no checkpoint)")
                    + (f", degradation level {self.degrade_level} "
                       f"pins {self._pins()}"
                       if self._pins() else ""))
                time.sleep(delay)
                restarts_total += 1
        finally:
            self._kill_gang()
            self._restore_signals(prior)
            self._merge_metrics()
            self._finalize_ledger()

    def _resilience_blob(self) -> dict:
        blob = super()._resilience_blob()
        blob["gang"] = {"ranks_initial": self._max_world,
                        "ranks_final": self.world,
                        "emulate": self.emulate,
                        "min_ranks": self.min_ranks}
        return blob


def launch_gang(argv: List[str], args, log=print) -> int:
    """CLI entry for `--launch N`: spawn and supervise the whole gang.
    Like `supervise()`, this parent stays jax-free — every rank is a
    killable child process group.  Fleet modes (-b/-N/--serve) get the
    NON-LOCKSTEP leased-rank policy: a rank death restarts only that
    rank (`fleet-rank-death`) instead of killing the world."""
    workdir = getattr(args, "workdir", ".") or "."
    fleet = bool(getattr(args, "bootstrap", 0)
                 or getattr(args, "multi_start", 0)
                 or getattr(args, "serve", None))
    sup = GangSupervisor(
        argv, workdir=workdir, run_id=args.run_id,
        ranks=getattr(args, "launch", 1) or 1,
        emulate=getattr(args, "launch_emulate", False),
        min_ranks=getattr(args, "launch_min_ranks", 1),
        fleet=fleet,
        max_retries=getattr(args, "supervise_retries", DEFAULT_RETRIES),
        stall_timeout=getattr(args, "supervise_stall", DEFAULT_STALL),
        backoff=getattr(args, "supervise_backoff", 2.0),
        metrics_file=getattr(args, "metrics_file", None),
        ledger_dir=getattr(args, "ledger_dir", None),
        log=log)
    return sup.run()


def supervise(argv: List[str], args, log=print) -> int:
    """CLI entry: run `argv` (the full original command line) under
    supervision.  `args` is the parsed namespace — only supervisor and
    file-placement flags are read; everything jax-flavored happens in
    the child."""
    workdir = getattr(args, "workdir", ".") or "."
    sup = Supervisor(
        argv, workdir=workdir, run_id=args.run_id,
        max_retries=getattr(args, "supervise_retries", DEFAULT_RETRIES),
        stall_timeout=getattr(args, "supervise_stall", DEFAULT_STALL),
        backoff=getattr(args, "supervise_backoff", 2.0),
        metrics_file=getattr(args, "metrics_file", None),
        ledger_dir=getattr(args, "ledger_dir", None),
        log=log)
    return sup.run()
