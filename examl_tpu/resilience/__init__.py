"""examl_tpu.resilience — fault injection + self-healing run supervision.

Why this subsystem exists (VERDICT r04/r05): two accelerator windows
were lost to wedges even after AOT banking made *compiles* killable — a
dispatch/collective wedge, a SIGTERM, or a corrupt checkpoint still
killed the whole run.  The reference survives interruption through its
checkpoint/restart machinery (`searchAlgo.c:1102-1750`, SURVEY §5.4);
this package makes our version actually survive the failure modes we
have observed, and makes every recovery path *testable on CPU*:

* `faults`    — registry of named, deterministic injection points armed
                via `EXAML_FAULTS` / `--inject-fault`, wired into the
                real seams (engine dispatch, compile monitor, lnL
                boundary, checkpoint write, bank worker, heartbeat).
* `exitcause` — the ONE worker/child exit-classification used by
                bench.py, ops/bank.py and the supervisor (SIGILL vs
                OOM vs hang-kill vs preempt).
* `heartbeat` — per-iteration liveness file emitted by the search loop
                from the obs registry; the supervisor's only way to see
                a dispatch/collective wedge (the compile watchdog
                covers compiles, nothing covered dispatches).
* `preempt`   — SIGTERM/SIGINT → flag → emergency checkpoint at the
                next checkpoint-callback site → clean resumable exit
                (EXIT_PREEMPTED).
* `supervisor`— `--supervise`: runs the search as a killable child,
                watches the heartbeat, classifies failures, restarts
                from the newest checkpoint with capped retries, backoff
                and escalating degradation pins (pallas→chunk→scan).

IMPORT CONTRACT: this `__init__` and the `exitcause`/`faults` modules
are stdlib-only and must stay that way — the bench PARENT and the
supervisor parent import them and must never load jax (a broken
accelerator plugin can hang the importing process, and on
exclusive-access accelerators the parent must never take the device
handle the child needs).
"""
