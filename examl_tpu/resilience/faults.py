"""Deterministic fault injection (stdlib-only by contract).

Every recovery path in this runtime — supervisor restart, checkpoint
fallback, non-finite retry, bank worker kill, watchdog bark — must be
testable on CPU without waiting for real hardware to misbehave.  This
module is a registry of NAMED injection points wired into the real
seams; arming one makes the seam fail exactly the way the observed
failure mode does.

Arming (comma-separated specs, via `EXAML_FAULTS` or `--inject-fault`):

    point[@rank=R][:job=ID][:after=N][:attempt=K][:bytes=N][:signal=NAME][:hang[=SECS]][:raise]

* `@rank=R`   — RANK-TARGETED injection: fire only in the process whose
  gang rank (`EXAML_PROCID`, set per rank by the `--launch` gang
  supervisor and real multi-host launches) equals R.  Non-target ranks
  never tick the point's hit counter, so `after=N` keeps addressing
  "the Nth iteration of rank R".  Also accepted as a `:rank=R` field.
* `after=N`   — fire on the Nth check of the point (default 1).
* `attempt=K` — fire only when `EXAML_RESTART_COUNT` == K (default 0,
  i.e. only the supervisor's FIRST attempt; `attempt=*` fires on every
  attempt).  This is what lets a supervised chaos run crash once and
  then complete: the retry's environment carries RESTART_COUNT=1.
* `signal=NAME` / `hang[=SECS]` / `raise` override the point's default
  action: signal self (KILL/TERM/ILL/SEGV/...), sleep, or raise
  `FaultInjected`.

Registered points (seam → default action):

    engine.dispatch    instance.evaluate, before dispatch     → raise
    engine.nonfinite   instance.evaluate, poisons lnL to NaN  → flag
    compile.hang       engine._guard_first_call first call    → hang
    checkpoint.write   CheckpointManager.write, pre-publish   → raise
    bank.worker        ops/bank worker, at family start       → signal KILL
    bank.export.write  export_bank.export, pre-serialize      → raise
    bank.export.load   export_bank load ladder, pre-read      → raise
    search.kill        heartbeat.beat (per search iteration)  → signal KILL
    heartbeat.stall    heartbeat.beat, sticky beat suppressor → flag
    fleet.dispatch     fleet driver, before a batch dispatch  → raise
    fleet.job.poison   fleet dispatch, poisons ONE job's lnL  → flag (sticky)
    fleet.job.hang     fleet dispatch while job ID is batched → hang
    fleet.results.write  fleet results-journal append         → raise
    fleet.lease.write  lease-board publish (stage/fsync)      → raise
    fleet.lease.reap   expired-lease reap steal               → raise
    mem.oom            fleet/engine dispatch, synthetic OOM   → raise
    mem.pressure       memgov budget clamp (bytes=N)          → flag (sticky)

`flag` points have no side effect here — `fire()` returns True and the
seam implements the failure (NaN substitution, beat suppression).

JOB-TARGETED points (the `fleet.job.*` family) take a `job=ID` field:
the seam passes the job id it is about to dispatch, and the spec is
inert — hit counter untouched, like `@rank` — for every other job, so
`after=N` addresses "the Nth dispatch CONTAINING job ID".
`fleet.job.poison` is sticky: a poison job (bad data, pathological
topology) stays poison on every retry, which is exactly what the
per-job retry/quarantine ladder must converge against.
"""

from __future__ import annotations

import os
import signal as _signal
import time
from dataclasses import dataclass
from typing import Dict, Optional

ENV_VAR = "EXAML_FAULTS"
ATTEMPT_VAR = "EXAML_RESTART_COUNT"

POINTS = {
    "engine.dispatch": "raise at the engine dispatch boundary",
    "engine.nonfinite": "poison the dispatched log-likelihood with NaN",
    "compile.hang": "hang inside the first-call compile monitor",
    "checkpoint.write": "fail a checkpoint write before publish",
    "checkpoint.publish": "fail/kill between a fully-staged gang "
                          "checkpoint cycle and its publish rename",
    "bank.worker": "kill/hang a bank compile worker at family start",
    "bank.export.write": "fail an exported-artifact serialize/publish "
                         "(survivable: the run keeps its compiled "
                         "program, only the artifact is lost)",
    "bank.export.load": "fail an exported-artifact load (survivable: "
                        "the ladder falls through to the persistent "
                        "cache / fresh compile)",
    "search.kill": "signal self at the Nth search-loop heartbeat",
    "heartbeat.stall": "stop emitting heartbeats (sticky)",
    "fleet.dispatch": "raise at the fleet batched-dispatch boundary",
    "fleet.job.poison": "poison one fleet job's lnL to NaN (job=ID; "
                        "sticky — a poison job stays poison on retry)",
    "fleet.job.hang": "hang the fleet dispatch while job ID is batched",
    "fleet.results.write": "fail a fleet results-journal append",
    "fleet.lease.write": "fail a job-lease publish (stage/fsync seam)",
    "fleet.lease.reap": "fail an expired-lease reap steal mid-flight",
    "mem.oom": "raise a synthetic RESOURCE_EXHAUSTED at a dispatch seam",
    "mem.pressure": "clamp the memory governor's budget to N bytes "
                    "(bytes=N; sticky — pressure persists once applied)",
}

_DEFAULT_ACTION = {
    "compile.hang": ("hang", 3600.0),
    "bank.worker": ("signal", "KILL"),
    "search.kill": ("signal", "KILL"),
    "engine.nonfinite": ("flag", None),
    "heartbeat.stall": ("flag", None),
    "fleet.job.poison": ("flag", None),
    "fleet.job.hang": ("hang", 3600.0),
    "mem.pressure": ("flag", None),
}

_STICKY = frozenset({"heartbeat.stall", "fleet.job.poison",
                     "mem.pressure"})


class FaultInjected(RuntimeError):
    """Raised by `raise`-action injection points."""


@dataclass
class FaultSpec:
    point: str
    after: int = 1
    attempt: Optional[int] = 0          # None = every attempt ("*")
    action: str = "raise"               # raise | signal | hang | flag
    arg: object = None                  # signal name / hang seconds
    rank: Optional[int] = None          # None = every rank
    job: Optional[str] = None           # None = every job (fleet.job.*)


def parse_spec(text: str) -> Dict[str, FaultSpec]:
    """Parse an EXAML_FAULTS value into {point: FaultSpec}.

    Unknown points raise ValueError — a typo'd injection that silently
    never fires would make a chaos test pass vacuously.
    """
    specs: Dict[str, FaultSpec] = {}
    for item in (text or "").split(","):
        item = item.strip()
        if not item:
            continue
        fields = item.split(":")
        point, _, ranktag = fields[0].partition("@")
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (known: "
                + ", ".join(sorted(POINTS)) + ")")
        action, arg = _DEFAULT_ACTION.get(point, ("raise", None))
        spec = FaultSpec(point=point, action=action, arg=arg)
        if ranktag:
            key, _, val = ranktag.partition("=")
            if key != "rank" or not val:
                raise ValueError(
                    f"bad rank qualifier {ranktag!r} in {item!r} "
                    "(expected point@rank=R)")
            spec.rank = int(val)
        for f in fields[1:]:
            key, _, val = f.partition("=")
            if key == "after":
                spec.after = max(1, int(val))
            elif key == "attempt":
                spec.attempt = None if val == "*" else int(val)
            elif key == "signal":
                spec.action, spec.arg = "signal", (val or "KILL").upper()
            elif key == "hang":
                spec.action = "hang"
                spec.arg = float(val) if val else 3600.0
            elif key == "raise":
                spec.action, spec.arg = "raise", None
            elif key == "rank":
                spec.rank = int(val)
            elif key == "job":
                if not val:
                    raise ValueError(
                        f"empty job qualifier in {item!r} "
                        "(expected point:job=ID)")
                spec.job = val
            elif key == "bytes":
                # Value-carrying flag field (mem.pressure): the seam
                # reads spec.arg as the clamped budget in bytes.
                try:
                    spec.arg = int(val)
                except ValueError:
                    raise ValueError(
                        f"bad bytes qualifier {f!r} in {item!r} "
                        "(expected point:bytes=N)") from None
            else:
                raise ValueError(f"unknown fault field {f!r} in {item!r}")
        if point in specs:
            # One spec per point: silently keeping only the last would
            # make e.g. "search.kill@rank=0,search.kill@rank=1" arm a
            # DIFFERENT chaos scenario than written.  (To hit every
            # rank, omit the rank qualifier.)
            raise ValueError(
                f"duplicate spec for fault point {point!r}: only one "
                "spec per point may be armed")
        specs[point] = spec
    return specs


# Process state: specs are re-parsed whenever the env text changes (the
# CLI merges --inject-fault into EXAML_FAULTS; tests monkeypatch it),
# hit counters persist for the life of the process, sticky points stay
# fired once triggered.
_STATE = {"raw": None, "specs": {}, "hits": {}, "fired": set()}


def reset() -> None:
    """Clear hit counters and sticky state (one CLI run = one fault
    record; tests invoking main() repeatedly must not inherit counts)."""
    _STATE.update(raw=None, specs={}, hits={}, fired=set())


def _specs() -> Dict[str, FaultSpec]:
    raw = os.environ.get(ENV_VAR, "")
    if raw != _STATE["raw"]:
        _STATE["raw"] = raw
        try:
            _STATE["specs"] = parse_spec(raw)
        except ValueError as exc:
            # An unparseable env must be loud but not fatal mid-seam.
            import sys
            sys.stderr.write(f"EXAML: ignoring {ENV_VAR}: {exc}\n")
            _STATE["specs"] = {}
    return _STATE["specs"]


def arm(spec_text: str) -> None:
    """Append spec(s) to the environment registry (validates eagerly, so
    `--inject-fault typo.point` fails at argument time, not mid-run)."""
    parse_spec(spec_text)
    prior = os.environ.get(ENV_VAR, "")
    os.environ[ENV_VAR] = (prior + "," if prior else "") + spec_text


def _attempt() -> int:
    try:
        return int(os.environ.get(ATTEMPT_VAR, "0") or 0)
    except ValueError:
        return 0


def _rank() -> int:
    """This process's gang rank — one parser for EXAML_PROCID:
    resilience/heartbeat.py owns it (lazy import; heartbeat imports
    this module at load time)."""
    from examl_tpu.resilience import heartbeat
    return heartbeat.env_rank()


def armed(point: str, job: Optional[str] = None) -> Optional[FaultSpec]:
    """Check (and count) one hit of `point`; the spec when THIS hit
    fires, else None.  Sticky points keep firing once triggered.
    `job` is the fleet job id the calling seam is dispatching — a
    job-qualified spec is inert (no hit tick) for every other job."""
    spec = _specs().get(point)
    if spec is None:
        return None
    if spec.rank is not None and _rank() != spec.rank:
        # Rank-targeted spec in a non-target rank: inert, and it must
        # not tick the hit counter — `after=N` addresses rank R's own
        # iteration clock.
        return None
    if spec.job is not None and job != spec.job:
        # Job-targeted spec checked for a different job (or from a
        # seam with no job in hand): inert, counter untouched —
        # `after=N` addresses dispatches CONTAINING the target job.
        return None
    if spec.attempt is not None and _attempt() != spec.attempt:
        return None
    if point in _STATE["fired"] and point in _STICKY:
        return spec
    hits = _STATE["hits"].get(point, 0) + 1
    _STATE["hits"][point] = hits
    if hits != spec.after:
        return None
    _STATE["fired"].add(point)
    return spec


def fire(point: str, job: Optional[str] = None) -> bool:
    """Check `point` and perform its action.  Returns False when not
    armed; True for `flag` points (the seam implements the failure);
    raises / signals / hangs otherwise."""
    spec = armed(point, job=job)
    if spec is None:
        return False
    try:                              # count fired faults when obs exists
        from examl_tpu import obs
        obs.inc(f"faults.fired.{point}")
        obs.ledger_event("fault", point=point, action=spec.action,
                         job=job if spec.job is not None else None)
        obs.log(f"EXAML: fault injection: {point} fired "
                f"(action {spec.action})")
    except Exception:                 # noqa: BLE001 — stdlib-only callers
        pass
    if spec.action == "flag":
        return True
    if spec.action == "hang":
        time.sleep(float(spec.arg or 3600.0))
        return True
    if spec.action == "signal":
        name = str(spec.arg or "KILL")
        sig = getattr(_signal, "SIG" + name, None) \
            if not name.startswith("SIG") else getattr(_signal, name, None)
        if sig is None:
            raise ValueError(f"unknown signal {name!r} for fault {point}")
        os.kill(os.getpid(), int(sig))
        # A non-fatal signal (TERM with a handler installed) returns:
        # the seam continues and the handler's flag does the rest.
        return True
    raise FaultInjected(f"injected fault at {point}")
