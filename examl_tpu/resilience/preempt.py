"""Preemption safety: SIGTERM/SIGINT → flag → emergency checkpoint →
clean resumable exit.

Preemptible accelerator jobs get a SIGTERM and a short grace window.
Before this module that killed the run mid-phase: whatever the last
checkpoint missed was lost, and the exit looked identical to a crash.
Now the signal only sets a flag; the search loop's checkpoint-callback
cadence (the one place where inst+tree state is coherent enough to
serialize — reference `searchAlgo.c:1102-1146` writes at the same
sites) notices it, writes one final checkpoint, and the process exits
with EXIT_PREEMPTED (75, EX_TEMPFAIL) — which the supervisor treats as
resumable without consuming a retry, and which batch schedulers that
understand sysexits also retry.

A SECOND SIGTERM/SIGINT restores default disposition and re-raises, so
an operator mashing Ctrl-C still gets an immediate (unclean) exit.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional

from examl_tpu.resilience.exitcause import EXIT_PREEMPTED  # noqa: F401

_STATE = {"requested": None, "prior": None}


class PreemptCheckpointed(Exception):
    """Raised at a checkpoint site after the emergency write; the CLI
    converts it into EXIT_PREEMPTED."""

    def __init__(self, signame: str):
        super().__init__(f"preempted by {signame}; emergency checkpoint "
                         "written")
        self.signame = signame


def requested() -> Optional[str]:
    """Name of the preemption signal received, or None."""
    return _STATE["requested"]


def install(log: Optional[Callable[[str], None]] = None) -> bool:
    """Install the SIGTERM/SIGINT flag handlers.  Returns False (no-op)
    off the main thread — tests drive the CLI from worker threads, and
    `signal.signal` is main-thread-only."""
    if threading.current_thread() is not threading.main_thread():
        return False
    _STATE["requested"] = None

    def handler(signum, frame):
        name = signal.Signals(signum).name
        if _STATE["requested"] is not None:
            # Second signal: the operator means NOW.
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        _STATE["requested"] = name
        if log is not None:
            try:
                log(f"EXAML: received {name}: will write an emergency "
                    "checkpoint at the next checkpoint site and exit "
                    f"resumable (code {EXIT_PREEMPTED}); repeat the "
                    "signal to exit immediately")
            except Exception:         # noqa: BLE001 — never die in a handler
                pass

    _STATE["prior"] = (signal.signal(signal.SIGTERM, handler),
                       signal.signal(signal.SIGINT, handler))
    return True


def uninstall() -> None:
    """Restore prior signal dispositions and clear the flag (the CLI's
    try/finally — tests invoke main() repeatedly in one process)."""
    prior = _STATE["prior"]
    if prior is not None:
        if threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, prior[0])
            signal.signal(signal.SIGINT, prior[1])
        _STATE["prior"] = None
    _STATE["requested"] = None


def check_after_checkpoint(log: Optional[Callable[[str], None]] = None
                           ) -> None:
    """Call IMMEDIATELY AFTER a successful checkpoint write: raises
    PreemptCheckpointed when a preemption signal is pending, so the
    checkpoint just written becomes the resume point."""
    name = _STATE["requested"]
    if name is None:
        return
    try:
        from examl_tpu import obs
        obs.inc("resilience.preempt_checkpoints")
    except Exception:                 # noqa: BLE001
        pass
    if log is not None:
        log(f"EXAML: {name} honored: emergency checkpoint written; "
            f"exiting resumable (code {EXIT_PREEMPTED})")
    raise PreemptCheckpointed(name)
