"""Per-device HBM pressure governor: admission control over one budget.

The resilience stack (PR3/PR6/PR13) makes wedges, rank deaths, and
poison jobs cost a resume instead of the run — but an allocator
``RESOURCE_EXHAUSTED`` still killed the process with no admission
check, no shrink-and-retry, and no cause-specific supervision.  This
module closes that gap with the same ladder discipline applied to
memory, combining three evidence sources into ONE per-device budget:

* the program observatory's compiler-truth predicted peak bytes
  (``obs/programs.py`` registry rows, ``peak_bytes`` per family);
* live allocator telemetry (``sample_memory()`` →
  ``mem.device.<k>.{in_use,peak,limit}`` gauges, with the
  ``mem.host.rss`` host-RSS fallback on backends without
  ``memory_stats()``);
* the engine's arena gauges (``engine.clv_arena_bytes.*``) as the
  floor when neither allocator nor host telemetry exists.

The budget resolves as: ``EXAML_MEM_BUDGET_BYTES`` (absolute, wins)
→ ``EXAML_MEM_BUDGET_FRACTION`` × device limit → DEFAULT_FRACTION
(headroom) × device limit → unlimited when no device limit is known
(CPU).  The ``mem.pressure`` fault point (``bytes=N``) clamps the
resolved budget for chaos tests — sticky, so pressure persists for
the life of the run.

Three admission seams consult it where allocations are minted:

* engine first-call/``cache_put`` — a program whose predicted peak
  exceeds the remaining budget triggers eviction of cold cached
  executables and per-topology device caches BEFORE the compile,
  counted (``mem.evictions``) — never a silent crash;
* fleet ``_pick_jpad``/drain batch sizing — under pressure jpad
  growth is denied and the drain cuts smaller batches
  (``mem.admission_denials``): occupancy shrinks instead of OOM;
* arena provisioning (fleet batch arenas) — counted denials, never a
  block.

The recovery half: ``is_oom()`` classifies a caught dispatch
exception (RESOURCE_EXHAUSTED / XlaRuntimeError-OOM / the injected
``mem.oom`` fault) → ``mem.oom_events``; the fleet driver evicts and
re-dispatches through the quarantine halving path
(``mem.oom_retries``); repeated strikes raise
:class:`MemoryBudgetExhausted`, which the CLI maps to
``exitcause.EXIT_ALLOC_OOM`` — the supervisor's restart pins
``EXAML_MEM_BUDGET_FRACTION`` down instead of escalating the tier
ladder.

Pure admission math (``resolve_budget``, ``admit_math``,
``eviction_order``, ``clamp_fraction``) takes plain ints and is
testable without jax; the gauge-reading conveniences degrade to
"admit with counter" (``mem.admission_unknown``) whenever an input is
missing — the governor must never turn absent telemetry into a
blocked dispatch.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Tuple

from examl_tpu.resilience import exitcause, faults

ENV_BUDGET_BYTES = "EXAML_MEM_BUDGET_BYTES"
ENV_BUDGET_FRACTION = "EXAML_MEM_BUDGET_FRACTION"

# Headroom default: XLA's own allocator reserves a slice of HBM, and a
# dispatch's transient temps land on top of the steady arenas — 90 % of
# the device limit is the admission ceiling unless overridden.
# (supervisor.py mirrors this literal — it is jax/obs-free by contract
# and must not import this module's dependency closure.)
DEFAULT_FRACTION = 0.90

# Fraction pins are clamped to this floor: a supervisor halving ladder
# must converge on "tiny but runnable", not zero.
MIN_FRACTION = 0.05

# Consecutive unrecovered OOM strikes before the governor stops
# shrinking and escalates to the supervisor as alloc-oom
# (EXAML_MEM_OOM_STRIKES overrides; 0 = escalate on the first OOM).
OOM_STRIKE_LIMIT = 3
ENV_OOM_STRIKES = "EXAML_MEM_OOM_STRIKES"


def _strike_limit() -> int:
    try:
        return int(os.environ.get(ENV_OOM_STRIKES, "") or OOM_STRIKE_LIMIT)
    except ValueError:
        return OOM_STRIKE_LIMIT

_STATE = {"strikes": 0}


class MemoryBudgetExhausted(RuntimeError):
    """Device allocator OOM that survived evict+shrink retries: the
    in-process ladder is out of moves.  The CLI maps this to
    ``exitcause.EXIT_ALLOC_OOM`` so a supervisor restart pins the
    budget fraction down."""

    exit_code = exitcause.EXIT_ALLOC_OOM


def reset() -> None:
    """Clear strike state (one CLI run = one escalation ladder; tests
    invoking the driver repeatedly must not inherit strikes)."""
    _STATE["strikes"] = 0


# -- pure admission math (no jax, no gauges: unit-testable) ----------------


def clamp_fraction(frac: float) -> float:
    """Budget fractions live in [MIN_FRACTION, 1.0] — a pin ladder
    halves toward the floor, never to zero; >1 would admit more than
    the device holds."""
    return max(MIN_FRACTION, min(1.0, float(frac)))


def resolve_budget(limit_bytes: Optional[int],
                   budget_bytes_env: Optional[str] = None,
                   fraction_env: Optional[str] = None,
                   pressure_bytes: Optional[int] = None) -> Optional[int]:
    """The admission budget in bytes, or None for unlimited.

    Precedence: explicit ``EXAML_MEM_BUDGET_BYTES`` wins; else
    ``EXAML_MEM_BUDGET_FRACTION`` × device limit; else
    DEFAULT_FRACTION × device limit; no known device limit (CPU) →
    unlimited.  A ``mem.pressure`` clamp applies LAST and can only
    lower the result (or impose one where none existed)."""
    budget: Optional[int] = None
    if budget_bytes_env:
        try:
            budget = max(0, int(budget_bytes_env))
        except ValueError:
            budget = None
    if budget is None and limit_bytes is not None and limit_bytes > 0:
        frac = DEFAULT_FRACTION
        if fraction_env:
            try:
                frac = clamp_fraction(float(fraction_env))
            except ValueError:
                frac = DEFAULT_FRACTION
        budget = int(limit_bytes * frac)
    if pressure_bytes is not None:
        budget = pressure_bytes if budget is None \
            else min(budget, pressure_bytes)
    return budget


def admit_math(predicted_bytes: Optional[int], used_bytes: int,
               budget_bytes: Optional[int]) -> Tuple[bool, Optional[int]]:
    """(admitted, remaining_after) for one allocation request.

    None budget → always admitted (unlimited, remaining None); None
    prediction → the CALLER must admit-with-counter (this returns the
    raw headroom so it can decide)."""
    if budget_bytes is None:
        return True, None
    remaining = budget_bytes - max(0, int(used_bytes))
    if predicted_bytes is None:
        return True, remaining
    return (int(predicted_bytes) <= remaining,
            remaining - int(predicted_bytes))


def eviction_order(entries: Iterable[Tuple[object, float]]) -> List[object]:
    """Coldest-first eviction ordering: entries are (key, last_use
    sequence/stamp); lower stamps evict first.  The engine's LRU
    OrderedDicts already store this order — the helper is the pinned,
    unit-tested statement of the policy."""
    return [k for k, _ in sorted(entries, key=lambda kv: kv[1])]


# -- gauge-backed budget state (degrades to admit-with-counter) ------------


def _pressure_bytes() -> Optional[int]:
    """The chaos clamp: an armed sticky `mem.pressure` spec carries the
    budget in spec.arg (`bytes=N`)."""
    spec = faults.armed("mem.pressure")
    if spec is None or spec.arg is None:
        return None
    try:
        return int(spec.arg)
    except (TypeError, ValueError):
        return None


def _gauges() -> Dict[str, float]:
    try:
        from examl_tpu import obs
        return obs.registry().snapshot_light().get("gauges", {})
    except Exception:                    # noqa: BLE001 — telemetry only
        return {}


def _device_limit(gauges: Dict[str, float]) -> Optional[int]:
    """Per-device admission limit: the SMALLEST device limit gauge (a
    replicated fleet arena must fit on every lane)."""
    limits = [int(v) for k, v in gauges.items()
              if k.startswith("mem.device.") and k.endswith(".limit")]
    return min(limits) if limits else None


def used_bytes(gauges: Optional[Dict[str, float]] = None) -> int:
    """Live per-device usage: the BUSIEST device's in_use gauge; CPU
    runs fall back to the host RSS (`mem.host.rss`), then to the sum of
    the engines' arena gauges — the floor the governor always knows."""
    g = _gauges() if gauges is None else gauges
    in_use = [int(v) for k, v in g.items()
              if k.startswith("mem.device.") and k.endswith(".in_use")]
    if in_use:
        return max(in_use)
    rss = g.get("mem.host.rss")
    if rss:
        return int(rss)
    return int(sum(v for k, v in g.items()
                   if k.startswith("engine.clv_arena_bytes.")))


def budget_bytes(gauges: Optional[Dict[str, float]] = None) -> Optional[int]:
    """The resolved budget (env + device limit + pressure clamp), or
    None for unlimited.  Publishes the `mem.budget_bytes` gauge when a
    budget exists so report tools can render headroom."""
    g = _gauges() if gauges is None else gauges
    budget = resolve_budget(_device_limit(g),
                            os.environ.get(ENV_BUDGET_BYTES),
                            os.environ.get(ENV_BUDGET_FRACTION),
                            _pressure_bytes())
    if budget is not None:
        try:
            from examl_tpu import obs
            obs.gauge("mem.budget_bytes", budget)
        except Exception:                # noqa: BLE001 — telemetry only
            pass
    return budget


def predicted_peak(family: str) -> Optional[int]:
    """Compiler-truth peak bytes for a program family: the newest
    observatory row carrying a memory analysis, None when the
    observatory has no figure (rows mode, analysis missing)."""
    try:
        from examl_tpu.obs import programs
        peak = None
        for row in programs.table():
            if row.get("family") == family and \
                    row.get("peak_bytes") is not None:
                peak = int(row["peak_bytes"])
        return peak
    except Exception:                    # noqa: BLE001 — telemetry only
        return None


def _sample() -> None:
    """Refresh the live gauges (rate-limited by EXAML_MEM_SAMPLE_S) so
    admission reads telemetry no staler than the sample interval."""
    try:
        from examl_tpu.obs import programs
        programs.sample_memory()
    except Exception:                    # noqa: BLE001 — telemetry only
        pass


def under_pressure() -> bool:
    """True when live usage has reached the budget — the state in which
    jpad growth is denied and the drain cuts smaller batches."""
    _sample()
    g = _gauges()
    budget = budget_bytes(g)
    if budget is None:
        return False
    return used_bytes(g) >= budget


def admit_bytes(predicted: Optional[int], seam: str) -> bool:
    """One admission decision.  A missing prediction or missing budget
    admits (counting `mem.admission_unknown` for the former) — the
    governor turns absent telemetry into evidence, never into a
    blocked dispatch.  A denial only COUNTS here (`mem.admission_
    denials`); the seam owns its reaction (evict, shrink, proceed)."""
    _sample()
    g = _gauges()
    budget = budget_bytes(g)
    if budget is None:
        return True
    if predicted is None:
        inc("mem.admission_unknown")
        return True
    ok, _ = admit_math(predicted, used_bytes(g), budget)
    if not ok:
        inc("mem.admission_denials")
        _ledger("mem.admission_denied", seam=seam,
                predicted_bytes=int(predicted), budget_bytes=budget)
    return ok


def admit_program(family: str, seam: str) -> bool:
    """Admission for minting one more compiled program of `family`
    (engine cache_put, export-bank load): predicted peak vs remaining
    budget."""
    return admit_bytes(predicted_peak(family), seam)


def effective_cap(cap: int) -> int:
    """The drain's batch cap under the governor: proportional shrink
    (budget/used, floor 1) when live usage exceeds the budget, the
    configured cap otherwise.  A cut is a counted admission denial —
    occupancy shrinks instead of OOM."""
    cap = max(1, int(cap))
    _sample()
    g = _gauges()
    budget = budget_bytes(g)
    if budget is None:
        return cap
    used = used_bytes(g)
    if used <= budget or used <= 0:
        return cap
    shrunk = max(1, int(cap * budget / used))
    if shrunk >= cap:
        shrunk = cap - 1 if cap > 1 else 1
    if shrunk < cap:
        inc("mem.admission_denials")
        _ledger("mem.cap_shrunk", cap=cap, effective=shrunk,
                used_bytes=used, budget_bytes=budget)
    return shrunk


# -- eviction (the engine's cold cached executables) -----------------------


def evict_engine(engine, keep: int = 1) -> int:
    """Evict cold compiled programs and per-topology device caches from
    one engine, coldest-first, keeping the `keep` hottest shared-cache
    entries.  Returns the eviction count (also counted as
    `mem.evictions`).  Structure caches are content-keyed (staleness
    impossible) so clearing them is memory hygiene by construction."""
    n = 0
    cache = getattr(engine, "_fast_jit_cache", None)
    if cache:
        while len(cache) > max(0, keep):
            cache.popitem(last=False)
            n += 1
    for attr in ("_sched_cache", "_universal_tables", "_grad_structs"):
        side = getattr(engine, attr, None)
        if side:
            n += len(side)
            side.clear()
    if n:
        inc("mem.evictions", n)
        _ledger("mem.evicted", count=n)
    return n


# -- OOM classification + escalation ---------------------------------------

_OOM_MARKERS = ("resource_exhausted", "out of memory", "outofmemory",
                "allocation failure", "failed to allocate")


def is_oom(exc: Optional[BaseException]) -> bool:
    """Is this caught dispatch exception a device-allocator OOM?
    Matches XLA's RESOURCE_EXHAUSTED/XlaRuntimeError-OOM message forms
    and the injected `mem.oom` fault (FaultInjected carries the point
    name)."""
    if exc is None or not isinstance(exc, BaseException):
        return False
    text = str(exc)
    if isinstance(exc, faults.FaultInjected):
        return "mem.oom" in text
    low = text.lower()
    return any(m in low for m in _OOM_MARKERS)


def oom_event(exc: BaseException, seam: str) -> None:
    """Record one classified OOM at a dispatch seam (`mem.oom_events`)
    and advance the strike ladder; past OOM_STRIKE_LIMIT consecutive
    unrecovered strikes raise MemoryBudgetExhausted — the supervisor's
    alloc-oom restart (budget-fraction pin) takes over from in-process
    shrinking."""
    _STATE["strikes"] += 1
    inc("mem.oom_events")
    _ledger("mem.oom", seam=seam, strikes=_STATE["strikes"],
            error=str(exc)[:200])
    if _STATE["strikes"] > _strike_limit():
        raise MemoryBudgetExhausted(
            f"device allocator OOM at {seam} persisted through "
            f"{_STATE['strikes']} evict+shrink retries: {exc}") from exc


def oom_recovered() -> None:
    """A dispatch completed after an OOM: the evict+shrink ladder
    worked, so the strike counter resets (`mem.oom_retries` counts the
    recovery)."""
    if _STATE["strikes"]:
        _STATE["strikes"] = 0
        inc("mem.oom_retries")


# -- obs shims (memgov stays importable before obs is configured) ----------


def inc(name: str, v: float = 1) -> None:
    try:
        from examl_tpu import obs
        obs.inc(name, v)
    except Exception:                    # noqa: BLE001 — telemetry only
        pass


def _ledger(event: str, **fields) -> None:
    try:
        from examl_tpu import obs
        obs.ledger_event(event, **fields)
    except Exception:                    # noqa: BLE001 — telemetry only
        pass
