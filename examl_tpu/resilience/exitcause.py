"""Shared worker/child exit classification (stdlib-only by contract).

One taxonomy for every process that watches another process die: the
bench parent's stage workers, the bank's compile workers, and the run
supervisor.  Before this module each grew its own `_exit_desc` copy
(bench.py duplicated bank's "on purpose" because the bench parent must
never import jax — solved here by keeping this module stdlib-only; the
package `__init__` documents the contract).

Two layers:

* `exit_desc(rc)` — the human-readable suffix used in logs/artifacts:
  "(signal SIGILL)" / "(returncode 3)".  Negative returncodes name
  their signal so a SIGILL from a mis-featured cached kernel (the r05
  killer) reads differently from an OOM SIGKILL or a hang-kill.
* `classify(rc, hang_killed=...)` — the machine-readable cause the
  supervisor's retry/degradation policy branches on.
"""

from __future__ import annotations

import signal
from typing import Optional

# Clean "I was preempted and checkpointed" exit code: BSD EX_TEMPFAIL,
# the conventional "transient failure, retry me" status.  The supervisor
# treats it as resumable without consuming a retry; schedulers that
# understand sysexits do the right thing too.
EXIT_PREEMPTED = 75

# Argparse's usage-error status: retrying an invalid command line can
# never succeed, so the supervisor gives up immediately.
EXIT_USAGE = 2

# Device-allocator OOM the child classified ITSELF (memgov caught a
# RESOURCE_EXHAUSTED that survived evict+shrink retries and exited
# cleanly with this status).  Distinct from the OS oom-kill below: the
# kernel's SIGKILL carries no self-diagnosis, while this code means
# "HBM budget too high" — the supervisor's restart pins the budget
# fraction down instead of escalating the tier ladder.
EXIT_ALLOC_OOM = 76

# classify() causes, in rough severity order.
CAUSE_OK = "ok"
CAUSE_PREEMPT = "preempt"          # clean SIGTERM/SIGINT checkpoint+exit
CAUSE_HANG_KILL = "hang-kill"      # the watcher killed it (stall/deadline)
# Gang-watcher verdicts (never produced by classify(rc) — like
# hang-kill they are the WATCHER's judgement, which outranks the raw
# signal of the SIGKILL it sent):
CAUSE_COLLECTIVE_WEDGE = "collective-wedge"  # ALL ranks' beats went
                                   # stale together: a wedged collective
                                   # (psum/allreduce) or program hang
                                   # every rank is blocked inside
CAUSE_STRAGGLER = "straggler-stall"  # ONE rank stopped beating while
                                   # its peers stayed fresh: a rank-local
                                   # stall (lockstep means the fresh
                                   # peers are already blocked on it)
CAUSE_FLEET_RANK_DEATH = "fleet-rank-death"  # a LEASED fleet gang rank
                                   # died: fleet gangs are NOT lockstep
                                   # (jobs are independent, held under
                                   # per-rank leases), so the watcher
                                   # restarts ONLY that rank — no
                                   # gang-wide kill, no tier pin, no
                                   # run-level retry; the dead rank's
                                   # leases expire and peers reap them
CAUSE_FLEET_JOB_STUCK = "fleet-job-stuck"  # the fleet heartbeat named an
                                   # in-flight batch whose per-job
                                   # deadline expired: a JOB-level fault
                                   # domain — the supervisor kills the
                                   # attempt, records the suspect jobs,
                                   # and resumes WITHOUT consuming a
                                   # run-level retry or pinning a tier
CAUSE_OOM_KILL = "oom-kill"        # external SIGKILL: the kernel OOM
                                   # killer is the usual sender when the
                                   # watcher did not kill it itself
CAUSE_ALLOC_OOM = "alloc-oom"      # device-allocator RESOURCE_EXHAUSTED
                                   # the child diagnosed itself
                                   # (EXIT_ALLOC_OOM): retryable with a
                                   # LOWER memory budget pin, NOT a tier
                                   # suspect — the program tier is fine,
                                   # its working set is not
CAUSE_SIGILL = "sigill"            # mis-featured kernel / cache poisoning
CAUSE_CRASH = "crash"              # SIGSEGV/SIGBUS/SIGABRT/SIGFPE
CAUSE_TERMINATED = "terminated"    # SIGTERM that did NOT checkpoint
CAUSE_USAGE = "usage"              # argparse error: never retryable
CAUSE_ERROR = "error"              # plain nonzero exit (raised exception)
CAUSE_RUNNING = "running"

# Causes a supervisor may retry.  "usage" and "ok" are final; "preempt"
# is resumable but handled on a separate (non-retry-budget) path.
RETRYABLE = frozenset({CAUSE_HANG_KILL, CAUSE_OOM_KILL, CAUSE_SIGILL,
                       CAUSE_CRASH, CAUSE_TERMINATED, CAUSE_ERROR,
                       CAUSE_COLLECTIVE_WEDGE, CAUSE_STRAGGLER,
                       CAUSE_ALLOC_OOM})

# Causes that indicate the *program tier* (not the environment) may be
# at fault — these escalate the supervisor's degradation ladder
# (pallas→chunk→scan), mirroring the bank's `_is_wedge` rule that only
# deadline kills and deaths-by-signal justify routing around a family.
# A collective wedge is the program-wedge class by definition; a
# single-rank straggler is presumed environmental (one slow/blocked
# host) and retries on the same tier.
TIER_SUSPECT = frozenset({CAUSE_HANG_KILL, CAUSE_SIGILL, CAUSE_CRASH,
                          CAUSE_OOM_KILL, CAUSE_COLLECTIVE_WEDGE})

def exit_desc(rc: Optional[int], none_desc: str = "(still running)") -> str:
    """Human-readable exit cause for a Popen returncode.

    `none_desc` covers the rc-is-None case, which different watchers
    read differently: the bank polls (None = still running) while the
    bench names it after the action it just took (None = hang-killed).
    """
    if rc is None:
        return none_desc
    if rc < 0:
        try:
            return f"(signal {signal.Signals(-rc).name})"
        except ValueError:
            return f"(signal {-rc})"
    return f"(returncode {rc})"


def classify(rc: Optional[int], hang_killed: bool = False) -> str:
    """Map a child's returncode to a retry-policy cause.

    `hang_killed=True` means the WATCHER killed the child (heartbeat
    stall, compile deadline) — that verdict outranks the raw signal,
    because a SIGKILL we sent must not read as an OOM kill.
    """
    if hang_killed:
        return CAUSE_HANG_KILL
    if rc is None:
        return CAUSE_RUNNING
    if rc == 0:
        return CAUSE_OK
    if rc == EXIT_PREEMPTED:
        return CAUSE_PREEMPT
    if rc == EXIT_USAGE:
        return CAUSE_USAGE
    if rc == EXIT_ALLOC_OOM:
        return CAUSE_ALLOC_OOM
    if rc < 0:
        sig = -rc
        if sig == signal.SIGILL:
            return CAUSE_SIGILL
        if sig == signal.SIGKILL:
            return CAUSE_OOM_KILL
        if sig == signal.SIGTERM or sig == signal.SIGINT:
            return CAUSE_TERMINATED
        # Everything else (SEGV/BUS/ABRT/FPE and any exotic signal): the
        # process died involuntarily — a crash for retry purposes.
        return CAUSE_CRASH
    return CAUSE_ERROR
