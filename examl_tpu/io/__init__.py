from examl_tpu.io.phylip import read_phylip  # noqa: F401
from examl_tpu.io.partitions import parse_partition_file, PartitionSpec  # noqa: F401
from examl_tpu.io.alignment import AlignmentData, PartitionData, build_alignment_data  # noqa: F401
