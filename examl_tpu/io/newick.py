"""Newick tree string read/write.

Role of reference `treeIO.c` (`treeReadLen` :798, `Tree2String` :324) over
an in-memory string.  Branch lengths in newick are expected substitutions
per site t; internally branches are stored as z = exp(-t) like the
reference.  Parsing and formatting are iterative (explicit stacks): tree
height is O(n) on caterpillar trees and the reference ambition is ~120k
taxa (SURVEY §6), far past Python's recursion limit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class NewickNode:
    name: Optional[str] = None
    length: Optional[float] = None
    children: List["NewickNode"] = field(default_factory=list)

    @property
    def is_leaf(self) -> bool:
        return not self.children

    def leaves(self):
        stack = [self]
        while stack:
            n = stack.pop()
            if n.is_leaf:
                yield n
            else:
                stack.extend(reversed(n.children))


class _Parser:
    def __init__(self, text: str):
        self.text = text.strip()
        self.pos = 0

    def peek(self) -> str:
        while (self.pos < len(self.text)
               and self.text[self.pos] in " \t\n\r"):
            self.pos += 1
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def take(self) -> str:
        ch = self.peek()
        self.pos += 1
        return ch

    def parse(self) -> NewickNode:
        node = self.parse_clade()
        if self.peek() == ";":
            self.take()
        return node

    def parse_clade(self) -> NewickNode:
        # Iterative recursive-descent: `open_stack` holds clades whose
        # child list is still being read.
        open_stack: List[NewickNode] = []
        current: Optional[NewickNode] = None
        while True:
            if self.peek() == "(":
                if current is not None:
                    raise ValueError(
                        f"newick: unexpected '(' after clade at {self.pos}")
                self.take()
                parent = NewickNode()
                open_stack.append(parent)
                continue
            # parse one leaf/closed clade's label and length
            node = current if current is not None else NewickNode()
            current = None
            node.name = self.parse_label() or node.name
            if self.peek() == ":":
                self.take()
                node.length = self.parse_number()
            if not open_stack:
                return node
            open_stack[-1].children.append(node)
            ch = self.peek()
            if ch == ",":
                self.take()
                continue
            if ch == ")":
                self.take()
                current = open_stack.pop()
                continue
            raise ValueError(f"newick: expected ',' or ')' at {self.pos}")

    def parse_label(self) -> Optional[str]:
        if self.peek() == "'":
            self.take()
            out = []
            while True:                      # raw access: keep inner spaces
                ch = (self.text[self.pos]
                      if self.pos < len(self.text) else "")
                self.pos += 1
                if ch == "'":
                    if self.pos < len(self.text) and self.text[self.pos] == "'":
                        out.append("'")
                        self.pos += 1
                    else:
                        break
                elif not ch:
                    raise ValueError("newick: unterminated quoted label")
                else:
                    out.append(ch)
            return "".join(out)
        out = []
        while self.peek() and self.peek() not in "():,;[":
            out.append(self.take())
        label = "".join(out).strip()
        return label or None

    def parse_number(self) -> float:
        out = []
        while self.peek() and (self.peek().isdigit() or self.peek() in ".+-eE"):
            out.append(self.take())
        return float("".join(out))


def _parse_newick_native(text: str) -> Optional[NewickNode]:
    """Build the NewickNode tree from the C++ scanner's flat arrays
    (native/newickscan.cpp); None when the extension is unavailable."""
    try:
        from examl_tpu import _newickscan
    except ImportError:
        return None
    import math

    import numpy as np

    pb, lb, _fb, labels = _newickscan.scan(text)
    parent = np.frombuffer(pb, dtype=np.int32)
    length = np.frombuffer(lb, dtype=np.float64)
    nodes = [NewickNode() for _ in range(len(parent))]
    for i, node in enumerate(nodes):
        if labels[i]:
            node.name = labels[i]
        if not math.isnan(length[i]):
            node.length = float(length[i])
    root = None
    # children get smaller ids than their parent, so ascending order
    # appends children in their original left-to-right order
    for i, p in enumerate(parent):
        if p < 0:
            root = nodes[i]
        else:
            nodes[p].children.append(nodes[i])
    return root


def parse_newick(text: str) -> NewickNode:
    root = _parse_newick_native(text)
    if root is not None:
        return root
    return _Parser(text).parse()


def format_newick(root: NewickNode, with_lengths: bool = True,
                  fmt: str = "%.6f") -> str:
    out: List[str] = []
    # (node, state): state 0 = entering, child index otherwise.
    stack: List[tuple] = [(root, 0)]
    while stack:
        node, state = stack.pop()
        if node.is_leaf:
            out.append(node.name or "")
            if with_lengths and node.length is not None:
                out.append(":" + (fmt % node.length))
            continue
        if state == 0:
            out.append("(")
        else:
            if state < len(node.children):
                out.append(",")
            else:
                out.append(")")
                if node.name:
                    out.append(node.name)
                if with_lengths and node.length is not None:
                    out.append(":" + (fmt % node.length))
                continue
        stack.append((node, state + 1))
        stack.append((node.children[state], 0))
    return "".join(out) + ";"
