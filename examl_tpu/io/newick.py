"""Newick tree string read/write.

Role of reference `treeIO.c` (`treeReadLen` :798, `Tree2String` :324), as a
plain recursive-descent parser over an in-memory string.  Branch lengths in
newick are expected substitutions per site t; internally branches are stored
as z = exp(-t) like the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class NewickNode:
    name: Optional[str] = None
    length: Optional[float] = None
    children: List["NewickNode"] = field(default_factory=list)

    @property
    def is_leaf(self) -> bool:
        return not self.children

    def leaves(self):
        if self.is_leaf:
            yield self
        else:
            for c in self.children:
                yield from c.leaves()


class _Parser:
    def __init__(self, text: str):
        self.text = text.strip()
        self.pos = 0

    def peek(self) -> str:
        while (self.pos < len(self.text)
               and self.text[self.pos] in " \t\n\r"):
            self.pos += 1
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def take(self) -> str:
        ch = self.peek()
        self.pos += 1
        return ch

    def parse(self) -> NewickNode:
        node = self.parse_clade()
        if self.peek() == ";":
            self.take()
        return node

    def parse_clade(self) -> NewickNode:
        node = NewickNode()
        if self.peek() == "(":
            self.take()
            node.children.append(self.parse_clade())
            while self.peek() == ",":
                self.take()
                node.children.append(self.parse_clade())
            if self.take() != ")":
                raise ValueError(f"newick: expected ')' at {self.pos}")
        node.name = self.parse_label()
        if self.peek() == ":":
            self.take()
            node.length = self.parse_number()
        return node

    def parse_label(self) -> Optional[str]:
        if self.peek() == "'":
            self.take()
            out = []
            while True:                      # raw access: keep inner spaces
                ch = (self.text[self.pos]
                      if self.pos < len(self.text) else "")
                self.pos += 1
                if ch == "'":
                    if self.pos < len(self.text) and self.text[self.pos] == "'":
                        out.append("'")
                        self.pos += 1
                    else:
                        break
                elif not ch:
                    raise ValueError("newick: unterminated quoted label")
                else:
                    out.append(ch)
            return "".join(out)
        out = []
        while self.peek() and self.peek() not in "():,;[":
            out.append(self.take())
        label = "".join(out).strip()
        return label or None

    def parse_number(self) -> float:
        out = []
        while self.peek() and (self.peek().isdigit() or self.peek() in ".+-eE"):
            out.append(self.take())
        return float("".join(out))


def parse_newick(text: str) -> NewickNode:
    return _Parser(text).parse()


def format_newick(root: NewickNode, with_lengths: bool = True,
                  fmt: str = "%.6f") -> str:
    def rec(node: NewickNode) -> str:
        if node.is_leaf:
            s = node.name or ""
        else:
            s = "(" + ",".join(rec(c) for c in node.children) + ")"
            if node.name:
                s += node.name
        if with_lengths and node.length is not None:
            s += ":" + (fmt % node.length)
        return s
    return rec(root) + ";"
