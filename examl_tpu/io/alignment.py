"""Alignment preprocessing: encoding, pattern compression, empirical freqs.

Equivalent role to the reference's offline parser pipeline
(`parser/axml.c`: `sitesort`/`sitecombcrunch` pattern compression :1421-1675,
`baseFrequenciesGTR` :2617, undetermined-column removal), re-expressed with
array ops.  Pattern order within a partition is canonical-sorted rather than
qsort-stable; order never affects the likelihood.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

from examl_tpu import datatypes
from examl_tpu.datatypes import DataType
from examl_tpu.io.partitions import PartitionSpec, single_partition_spec
from examl_tpu.io.phylip import read_phylip


@dataclass
class PartitionData:
    """One partition after pattern compression."""
    name: str
    datatype: DataType
    model_name: str
    patterns: np.ndarray          # [ntaxa, npatterns] uint8 codes
    weights: np.ndarray           # [npatterns] int64 pattern multiplicities
    empirical_freqs: np.ndarray   # [states]
    use_empirical_freqs: bool
    optimize_freqs: bool
    lg4: bool = False
    auto: bool = False
    branch_index: int = 0
    # Set by selective byteFile reads (io/bytefile.py): the partition's
    # FULL pattern count, this slice's starting column within it, and
    # the GLOBAL weight sum (checkpoint fingerprints must not depend on
    # which slice a process holds).  None/0 means `patterns` holds the
    # whole partition.
    global_width: int | None = None
    global_col_offset: int = 0
    global_weight_sum: int | None = None

    @property
    def width(self) -> int:
        return self.patterns.shape[1]

    @property
    def states(self) -> int:
        return self.datatype.states


@dataclass
class AlignmentData:
    taxon_names: List[str]
    partitions: List[PartitionData]

    @property
    def ntaxa(self) -> int:
        return len(self.taxon_names)

    @property
    def total_patterns(self) -> int:
        return sum(p.width for p in self.partitions)


def compress_patterns(codes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Collapse duplicate columns of [ntaxa, width] into unique patterns +
    weights (reference `sitesort`/`sitecombcrunch`).

    Uses the native C++ core (examl_tpu._patterncrunch, built by
    setup.py) when available — the parser hot path on large alignments —
    with a bit-identical NumPy fallback."""
    try:
        from examl_tpu import _patterncrunch
    except ImportError:
        _patterncrunch = None
    if _patterncrunch is not None and codes.size:
        codes = np.ascontiguousarray(codes, dtype=np.uint8)
        pat_bytes, wgt_bytes, npat = _patterncrunch.compress_columns(codes)
        patterns = np.frombuffer(pat_bytes, dtype=np.uint8).reshape(
            codes.shape[0], npat)
        weights = np.frombuffer(wgt_bytes, dtype=np.int64)
        return patterns, weights
    cols = np.ascontiguousarray(codes.T)
    uniq, counts = np.unique(cols, axis=0, return_counts=True)
    return np.ascontiguousarray(uniq.T), counts.astype(np.int64)


def empirical_frequencies(codes: np.ndarray, weights: np.ndarray,
                          dt: DataType, smoothings: int = 32) -> np.ndarray:
    """EM-style empirical state frequencies with ambiguity-code mass splitting
    (same fixed-point iteration as reference `parser/axml.c:2331`)."""
    table = dt.tip_indicator_table()            # [codes, states]
    informative = table.sum(axis=1) < dt.states  # drop all-ambiguous chars
    counts = np.zeros(dt.num_codes, dtype=np.float64)
    w = np.broadcast_to(weights, codes.shape).reshape(-1).astype(np.float64)
    np.add.at(counts, codes.reshape(-1), w)
    counts = counts * informative
    if counts.sum() == 0:
        return np.full(dt.states, 1.0 / dt.states)
    freqs = np.full(dt.states, 1.0 / dt.states)
    for _ in range(smoothings):
        mass = table * freqs                    # [codes, states]
        norm = mass.sum(axis=1, keepdims=True)
        norm[norm == 0.0] = 1.0
        new = (counts[:, None] * mass / norm).sum(axis=0)
        new /= new.sum()
        if np.abs(new - freqs).max() < 1e-12:
            freqs = new
            break
        freqs = new
    return freqs


def build_alignment_data(names: Sequence[str], sequences: Sequence[str],
                         specs: Sequence[PartitionSpec] | None = None,
                         datatype_name: str = "DNA",
                         compress: bool = True) -> AlignmentData:
    nsites = len(sequences[0])
    if specs is None:
        specs = [single_partition_spec(datatype_name, nsites)]
    covered = np.concatenate([s.sites for s in specs])
    if covered.max(initial=-1) >= nsites:
        raise ValueError("partition range exceeds alignment length")
    # Every column must be assigned (the reference parser errors likewise,
    # parser/parsePartitions.c:642).
    mask = np.zeros(nsites, dtype=bool)
    mask[covered] = True
    if not mask.all():
        first = int(np.argmin(mask))
        raise ValueError(
            f"alignment position {first + 1} has not been assigned to any "
            f"partition ({int((~mask).sum())} unassigned positions total)")

    parts: List[PartitionData] = []
    for spec in specs:
        dt = datatypes.get(spec.datatype_name)
        rows = [dt.encode(seq)[spec.sites] for seq in sequences]
        codes = np.stack(rows)                          # [ntaxa, width]
        # Drop columns where every taxon is fully undetermined
        # (reference removes these before compression).
        undet = (codes == dt.undetermined_code).all(axis=0)
        codes = codes[:, ~undet]
        if compress:
            patterns, weights = compress_patterns(codes)
        else:
            patterns = codes
            weights = np.ones(codes.shape[1], dtype=np.int64)
        freqs = empirical_frequencies(patterns, weights, dt)
        parts.append(PartitionData(
            name=spec.name, datatype=dt, model_name=spec.model_name,
            patterns=patterns, weights=weights, empirical_freqs=freqs,
            use_empirical_freqs=spec.empirical_freqs,
            optimize_freqs=spec.optimize_freqs, lg4=spec.lg4, auto=spec.auto,
            branch_index=spec.branch_index))
    return AlignmentData(list(names), parts)


def load_alignment(phylip_path: str, model_path: str | None = None,
                   datatype_name: str = "DNA",
                   compress: bool = True) -> AlignmentData:
    from examl_tpu.io.partitions import parse_partition_file
    names, seqs = read_phylip(phylip_path)
    specs = parse_partition_file(model_path) if model_path else None
    return build_alignment_data(names, seqs, specs, datatype_name, compress)
