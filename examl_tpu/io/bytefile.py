"""Binary "byteFile" alignment format, compatible with the reference parser.

Layout (reference writer `parser/axml.c:2752-2887`, reader
`examl/byteFile.c:31-433`):

  int32  sizeof(size_t) on the writing system (must be 8)
  int32  version            (3022)
  int32  magic              (6517718)
  int32  numTax
  uint64 numPattern          (global, over all partitions)
  int32  numPartitions
  f64    gappyness
  int32[numPattern]          pattern weights
  per taxon:      int32 len; char[len] name (NUL-terminated)
  per partition:  int32 states; int32 maxTipStates; uint64 lower;
                  uint64 upper; uint64 width; int32 dataType;
                  int32 protModels; int32 protFreqs; int32 nonGTR;
                  int32 optimizeBaseFrequencies;
                  int32 len; char[len] name; f64[states] frequencies
  alignment:      per partition, per taxon: uint8[upper-lower] codes
                  (partition-major, taxon-major within partition)

State codes are the reference's meaning-table values, which
examl_tpu.datatypes reproduces (DNA: IUPAC bitmask 1-15; AA: 0-19 + B=20,
Z=21, X/-=22; BIN: 1, 2, 3).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

from examl_tpu import datatypes
from examl_tpu.io.alignment import (AlignmentData, PartitionData,
                                    empirical_frequencies)

BYTEFILE_VERSION = 3022
BYTEFILE_MAGIC = 6517718

# Reference enum values (examl/axml.h:240-264, 307-314).
DATATYPE_INT = {"BIN": 0, "DNA": 1, "AA": 2}
DATATYPE_NAME = {v: k for k, v in DATATYPE_INT.items()}
PROT_MODELS = ["DAYHOFF", "DCMUT", "JTT", "MTREV", "WAG", "RTREV", "CPREV",
               "VT", "BLOSUM62", "MTMAM", "LG", "MTART", "MTZOA", "PMB",
               "HIVB", "HIVW", "JTTDCMUT", "FLU", "STMTREV", "AUTO",
               "LG4M", "LG4X", "GTR"]
PROT_INDEX = {m: i for i, m in enumerate(PROT_MODELS)}
JTT = PROT_INDEX["JTT"]


def _w(f, fmt: str, *vals) -> None:
    f.write(struct.pack("<" + fmt, *vals))


def _r(f, fmt: str):
    size = struct.calcsize("<" + fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("truncated byteFile")
    return struct.unpack("<" + fmt, data)


def _write_string(f, s: str) -> None:
    b = s.encode("utf-8") + b"\0"
    _w(f, "i", len(b))
    f.write(b)


def _read_string(f) -> str:
    (n,) = _r(f, "i")
    return f.read(n).rstrip(b"\0").decode("utf-8")


def gappyness(parts: Sequence[PartitionData]) -> float:
    """Share of fully-undetermined characters, weighted by pattern counts."""
    undet = total = 0
    for p in parts:
        w = p.weights[None, :]
        undet += int(((p.patterns == p.datatype.undetermined_code) * w).sum())
        total += int(p.patterns.shape[0] * p.weights.sum())
    return undet / total if total else 0.0


def write_bytefile(path: str, data: AlignmentData) -> None:
    """Write an AlignmentData (already pattern-compressed) as a byteFile."""
    parts = data.partitions
    num_pattern = sum(p.width for p in parts)
    with open(path, "wb") as f:
        _w(f, "iii", 8, BYTEFILE_VERSION, BYTEFILE_MAGIC)
        _w(f, "i", data.ntaxa)
        _w(f, "Q", num_pattern)
        _w(f, "i", len(parts))
        _w(f, "d", gappyness(parts))
        weights = np.concatenate([p.weights for p in parts]).astype("<i4")
        f.write(weights.tobytes())
        for name in data.taxon_names:
            _write_string(f, name)
        lower = 0
        for p in parts:
            upper = lower + p.width
            if p.datatype.name == "AA":
                prot = PROT_INDEX.get("AUTO" if p.auto else p.model_name, JTT)
            else:
                prot = JTT                   # ignored for non-AA (ref default)
            _w(f, "ii", p.states, p.datatype.num_codes)
            _w(f, "QQQ", lower, upper, upper - lower)
            _w(f, "iiiii", DATATYPE_INT[p.datatype.name], prot,
               int(p.use_empirical_freqs), 0, int(p.optimize_freqs))
            _write_string(f, p.name)
            f.write(np.asarray(p.empirical_freqs, dtype="<f8").tobytes())
            lower = upper
        for p in parts:
            f.write(np.ascontiguousarray(p.patterns, dtype=np.uint8).tobytes())


@dataclass
class BytePartMeta:
    """Per-partition byteFile metadata plus the byte offset of its
    pattern data section (partition-major, taxon-major within)."""
    states: int
    lower: int                  # global pattern range [lower, upper)
    upper: int
    dtype_i: int
    prot: int
    prot_freqs: bool
    opt_freqs: bool
    name: str
    freqs: np.ndarray
    data_offset: int

    @property
    def width(self) -> int:
        return self.upper - self.lower


@dataclass
class ByteFileMeta:
    """Everything in a byteFile EXCEPT weights and pattern data — the
    seek map for selective per-process reads (reference `seekPos`,
    `byteFile.c:31-83`)."""
    path: str
    ntaxa: int
    num_pattern: int
    taxon_names: List[str]
    parts: List[BytePartMeta]
    weights_offset: int


def read_bytefile_meta(path: str) -> ByteFileMeta:
    """Parse header + taxon names + partition metadata; SEEK past the
    weights and pattern sections so host memory and IO stay O(metadata)
    regardless of alignment size."""
    with open(path, "rb") as f:
        szt, version, magic = _r(f, "iii")
        if magic != BYTEFILE_MAGIC:
            raise ValueError(f"{path}: not a byteFile (magic {magic})")
        if szt != 8:
            raise ValueError(f"{path}: written on a {8 * szt}-bit system")
        if version != BYTEFILE_VERSION:
            raise ValueError(f"{path}: byteFile version {version}, "
                             f"expected {BYTEFILE_VERSION}")
        (ntaxa,) = _r(f, "i")
        (num_pattern,) = _r(f, "Q")
        (num_parts,) = _r(f, "i")
        _r(f, "d")                                    # gappyness (stats only)
        weights_offset = f.tell()
        f.seek(4 * num_pattern, 1)
        names = [_read_string(f) for _ in range(ntaxa)]
        parts: List[BytePartMeta] = []
        for _ in range(num_parts):
            states, _max_tip = _r(f, "ii")
            lower, upper, _width = _r(f, "QQQ")
            dtype_i, prot, prot_freqs, _non_gtr, opt_freqs = _r(f, "iiiii")
            pname = _read_string(f)
            freqs = np.frombuffer(f.read(8 * states), dtype="<f8")
            parts.append(BytePartMeta(
                states=states, lower=int(lower), upper=int(upper),
                dtype_i=dtype_i, prot=prot, prot_freqs=bool(prot_freqs),
                opt_freqs=bool(opt_freqs), name=pname, freqs=freqs,
                data_offset=0))
        off = f.tell()
        for pm in parts:
            pm.data_offset = off
            off += ntaxa * pm.width
    return ByteFileMeta(path=path, ntaxa=ntaxa, num_pattern=int(num_pattern),
                        taxon_names=names, parts=parts,
                        weights_offset=weights_offset)


def _read_columns(f, meta: ByteFileMeta, pm: BytePartMeta, lo: int,
                  hi: int) -> np.ndarray:
    """[ntaxa, hi-lo] codes of partition columns [lo, hi) via one seek
    per taxon row (reference `readMyData`, `byteFile.c:278-382`)."""
    w = pm.width
    n = hi - lo
    out = np.empty((meta.ntaxa, n), dtype=np.uint8)
    for t in range(meta.ntaxa):
        f.seek(pm.data_offset + t * w + lo)
        row = f.read(n)
        if len(row) != n:
            raise ValueError("truncated byteFile")
        out[t] = np.frombuffer(row, dtype=np.uint8)
    return out


def _part_from_meta(pm: BytePartMeta, patterns: np.ndarray,
                    weights: np.ndarray, col_offset: int = 0,
                    global_weight_sum: int | None = None) -> PartitionData:
    dt = datatypes.get(DATATYPE_NAME[pm.dtype_i])
    if dt.name == "AA":
        model_name = PROT_MODELS[pm.prot]
    elif dt.name == "DNA":
        model_name = "DNA"
    else:
        model_name = "BIN"
    emp = np.asarray(pm.freqs, dtype=np.float64)
    if not np.isfinite(emp).all() or emp.sum() <= 0:
        if patterns.shape[1] != pm.width:
            # A sliced read MUST NOT salvage from its own columns: each
            # process would derive different frequencies from the same
            # file and the replicated model arrays would silently
            # diverge across the job.
            raise ValueError(
                f"partition {pm.name!r}: byteFile stores no usable "
                f"frequencies and this is a per-process sliced read; "
                f"re-run the parser or use a whole-file read")
        emp = empirical_frequencies(patterns, weights, dt)
    return PartitionData(
        name=pm.name, datatype=dt, model_name=model_name,
        patterns=np.ascontiguousarray(patterns),
        weights=weights.astype(np.int64),
        empirical_freqs=emp,
        use_empirical_freqs=pm.prot_freqs or dt.name != "AA",
        optimize_freqs=pm.opt_freqs,
        lg4=model_name in ("LG4M", "LG4X"), auto=model_name == "AUTO",
        global_width=pm.width if patterns.shape[1] != pm.width else None,
        global_col_offset=col_offset, global_weight_sum=global_weight_sum)


def read_bytefile(path: str) -> AlignmentData:
    """Read a byteFile (ours or the reference parser's) into AlignmentData."""
    meta = read_bytefile_meta(path)
    with open(path, "rb") as f:
        f.seek(meta.weights_offset)
        wbytes = f.read(4 * meta.num_pattern)
        if len(wbytes) != 4 * meta.num_pattern:
            raise ValueError("truncated byteFile")
        weights = np.frombuffer(wbytes, dtype="<i4")
        parts: List[PartitionData] = []
        for pm in meta.parts:
            f.seek(pm.data_offset)
            raw = np.frombuffer(f.read(meta.ntaxa * pm.width),
                                dtype=np.uint8)
            if raw.size != meta.ntaxa * pm.width:
                raise ValueError("truncated byteFile")
            parts.append(_part_from_meta(
                pm, raw.reshape(meta.ntaxa, pm.width),
                weights[pm.lower:pm.upper].astype(np.int64)))
    return AlignmentData(meta.taxon_names, parts)


def read_bytefile_slice(path: str,
                        columns: dict[int, tuple[int, int]]) -> AlignmentData:
    """Read only the given per-partition column windows.

    `columns` maps partition index -> (col_lo, col_hi) relative to the
    partition; partitions absent from the map come back with width 0
    (metadata — models, frequencies, names — is always global).  Host
    memory and IO are proportional to the WINDOW, not the alignment
    (the weights SECTION is still read whole — 4 bytes/pattern, needed
    for process-count-invariant checkpoint fingerprints): this is the
    TPU-native `readMyData` (`byteFile.c:278-382`), where each MPI rank
    seeks and reads only its assigned site blocks."""
    meta = read_bytefile_meta(path)
    with open(path, "rb") as f:
        f.seek(meta.weights_offset)
        wbytes = f.read(4 * meta.num_pattern)
        if len(wbytes) != 4 * meta.num_pattern:
            raise ValueError("truncated byteFile")
        all_weights = np.frombuffer(wbytes, dtype="<i4")
        parts: List[PartitionData] = []
        for gid, pm in enumerate(meta.parts):
            lo, hi = columns.get(gid, (0, 0))
            if not (0 <= lo <= hi <= pm.width):
                raise ValueError(
                    f"partition {gid}: window [{lo},{hi}) outside "
                    f"[0,{pm.width})")
            patterns = _read_columns(f, meta, pm, lo, hi)
            weights = all_weights[pm.lower + lo:pm.lower + hi].astype(
                np.int64)
            gsum = int(all_weights[pm.lower:pm.upper].sum())
            parts.append(_part_from_meta(pm, patterns, weights,
                                         col_offset=lo,
                                         global_weight_sum=gsum))
    return AlignmentData(meta.taxon_names, parts)


def read_bytefile_for_process(path: str, procid: int, nprocs: int,
                              block_multiple: int | None = None
                              ) -> AlignmentData:
    """Read only the site columns process `procid` of `nprocs` owns.

    The packed-bucket layout (parallel/packing.py) is a pure function of
    the header metadata, so the process's block range — and its pre-image
    in per-partition pattern columns — is computed WITHOUT touching
    pattern data; then only those columns are seek-read.  Peak host
    memory scales ~1/nprocs of the alignment.  `block_multiple` must
    match the packing used at instance build (defaults to nprocs)."""
    from examl_tpu.parallel.packing import pack_layout

    if not (0 <= procid < nprocs):
        raise ValueError(f"procid {procid} outside [0, {nprocs})")
    meta = read_bytefile_meta(path)
    layouts = pack_layout(
        [(gid, pm.states, pm.width) for gid, pm in enumerate(meta.parts)],
        block_multiple=block_multiple or nprocs)
    columns: dict[int, tuple[int, int]] = {}
    for lay in layouts.values():
        for gid, lo, hi in lay.process_columns(procid, nprocs):
            columns[gid] = (lo, hi)
    return read_bytefile_slice(path, columns)
