"""Binary "byteFile" alignment format, compatible with the reference parser.

Layout (reference writer `parser/axml.c:2752-2887`, reader
`examl/byteFile.c:31-433`):

  int32  sizeof(size_t) on the writing system (must be 8)
  int32  version            (3022)
  int32  magic              (6517718)
  int32  numTax
  uint64 numPattern          (global, over all partitions)
  int32  numPartitions
  f64    gappyness
  int32[numPattern]          pattern weights
  per taxon:      int32 len; char[len] name (NUL-terminated)
  per partition:  int32 states; int32 maxTipStates; uint64 lower;
                  uint64 upper; uint64 width; int32 dataType;
                  int32 protModels; int32 protFreqs; int32 nonGTR;
                  int32 optimizeBaseFrequencies;
                  int32 len; char[len] name; f64[states] frequencies
  alignment:      per partition, per taxon: uint8[upper-lower] codes
                  (partition-major, taxon-major within partition)

State codes are the reference's meaning-table values, which
examl_tpu.datatypes reproduces (DNA: IUPAC bitmask 1-15; AA: 0-19 + B=20,
Z=21, X/-=22; BIN: 1, 2, 3).
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from examl_tpu import datatypes
from examl_tpu.io.alignment import (AlignmentData, PartitionData,
                                    empirical_frequencies)

BYTEFILE_VERSION = 3022
BYTEFILE_MAGIC = 6517718

# Reference enum values (examl/axml.h:240-264, 307-314).
DATATYPE_INT = {"BIN": 0, "DNA": 1, "AA": 2}
DATATYPE_NAME = {v: k for k, v in DATATYPE_INT.items()}
PROT_MODELS = ["DAYHOFF", "DCMUT", "JTT", "MTREV", "WAG", "RTREV", "CPREV",
               "VT", "BLOSUM62", "MTMAM", "LG", "MTART", "MTZOA", "PMB",
               "HIVB", "HIVW", "JTTDCMUT", "FLU", "STMTREV", "AUTO",
               "LG4M", "LG4X", "GTR"]
PROT_INDEX = {m: i for i, m in enumerate(PROT_MODELS)}
JTT = PROT_INDEX["JTT"]


def _w(f, fmt: str, *vals) -> None:
    f.write(struct.pack("<" + fmt, *vals))


def _r(f, fmt: str):
    size = struct.calcsize("<" + fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("truncated byteFile")
    return struct.unpack("<" + fmt, data)


def _write_string(f, s: str) -> None:
    b = s.encode("utf-8") + b"\0"
    _w(f, "i", len(b))
    f.write(b)


def _read_string(f) -> str:
    (n,) = _r(f, "i")
    return f.read(n).rstrip(b"\0").decode("utf-8")


def gappyness(parts: Sequence[PartitionData]) -> float:
    """Share of fully-undetermined characters, weighted by pattern counts."""
    undet = total = 0
    for p in parts:
        w = p.weights[None, :]
        undet += int(((p.patterns == p.datatype.undetermined_code) * w).sum())
        total += int(p.patterns.shape[0] * p.weights.sum())
    return undet / total if total else 0.0


def write_bytefile(path: str, data: AlignmentData) -> None:
    """Write an AlignmentData (already pattern-compressed) as a byteFile."""
    parts = data.partitions
    num_pattern = sum(p.width for p in parts)
    with open(path, "wb") as f:
        _w(f, "iii", 8, BYTEFILE_VERSION, BYTEFILE_MAGIC)
        _w(f, "i", data.ntaxa)
        _w(f, "Q", num_pattern)
        _w(f, "i", len(parts))
        _w(f, "d", gappyness(parts))
        weights = np.concatenate([p.weights for p in parts]).astype("<i4")
        f.write(weights.tobytes())
        for name in data.taxon_names:
            _write_string(f, name)
        lower = 0
        for p in parts:
            upper = lower + p.width
            if p.datatype.name == "AA":
                prot = PROT_INDEX.get("AUTO" if p.auto else p.model_name, JTT)
            else:
                prot = JTT                   # ignored for non-AA (ref default)
            _w(f, "ii", p.states, p.datatype.num_codes)
            _w(f, "QQQ", lower, upper, upper - lower)
            _w(f, "iiiii", DATATYPE_INT[p.datatype.name], prot,
               int(p.use_empirical_freqs), 0, int(p.optimize_freqs))
            _write_string(f, p.name)
            f.write(np.asarray(p.empirical_freqs, dtype="<f8").tobytes())
            lower = upper
        for p in parts:
            f.write(np.ascontiguousarray(p.patterns, dtype=np.uint8).tobytes())


def read_bytefile(path: str) -> AlignmentData:
    """Read a byteFile (ours or the reference parser's) into AlignmentData."""
    with open(path, "rb") as f:
        szt, version, magic = _r(f, "iii")
        if magic != BYTEFILE_MAGIC:
            raise ValueError(f"{path}: not a byteFile (magic {magic})")
        if szt != 8:
            raise ValueError(f"{path}: written on a {8 * szt}-bit system")
        if version != BYTEFILE_VERSION:
            raise ValueError(f"{path}: byteFile version {version}, "
                             f"expected {BYTEFILE_VERSION}")
        (ntaxa,) = _r(f, "i")
        (num_pattern,) = _r(f, "Q")
        (num_parts,) = _r(f, "i")
        _r(f, "d")                                    # gappyness (stats only)
        wbytes = f.read(4 * num_pattern)
        if len(wbytes) != 4 * num_pattern:
            raise ValueError("truncated byteFile")
        weights = np.frombuffer(wbytes, dtype="<i4")
        names = [_read_string(f) for _ in range(ntaxa)]
        metas = []
        for _ in range(num_parts):
            states, _max_tip = _r(f, "ii")
            lower, upper, _width = _r(f, "QQQ")
            dtype_i, prot, prot_freqs, _non_gtr, opt_freqs = _r(f, "iiiii")
            pname = _read_string(f)
            freqs = np.frombuffer(f.read(8 * states), dtype="<f8")
            metas.append((states, lower, upper, dtype_i, prot,
                          bool(prot_freqs), bool(opt_freqs), pname, freqs))
        parts: List[PartitionData] = []
        for (states, lower, upper, dtype_i, prot, prot_freqs, opt_freqs,
             pname, freqs) in metas:
            dt = datatypes.get(DATATYPE_NAME[dtype_i])
            width = upper - lower
            raw = np.frombuffer(f.read(ntaxa * width), dtype=np.uint8)
            patterns = raw.reshape(ntaxa, width)
            w = weights[lower:upper].astype(np.int64)
            if dt.name == "AA":
                model_name = PROT_MODELS[prot]
            elif dt.name == "DNA":
                model_name = "DNA"
            else:
                model_name = "BIN"
            auto = model_name == "AUTO"
            lg4 = model_name in ("LG4M", "LG4X")
            emp = np.asarray(freqs, dtype=np.float64)
            if not np.isfinite(emp).all() or emp.sum() <= 0:
                emp = empirical_frequencies(patterns, w, dt)
            parts.append(PartitionData(
                name=pname, datatype=dt, model_name=model_name,
                patterns=np.ascontiguousarray(patterns), weights=w,
                empirical_freqs=emp,
                use_empirical_freqs=prot_freqs or dt.name != "AA",
                optimize_freqs=opt_freqs, lg4=lg4, auto=auto))
    return AlignmentData(names, parts)
