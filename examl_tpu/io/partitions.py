"""RAxML/ExaML partition ("model") file parser.

Grammar (reference: `parser/parsePartitions.c:383`, `parser/USAGE`):
    <MODEL>, <name> = <range>[, <range>...]
    range := a | a-b | a-b\\s          (1-based, inclusive, optional stride s)
    MODEL := DNA | BIN | <protein matrix name> | AUTO | GTR | LG4M | LG4X
             with optional suffix F (empirical frequencies) or X (ML-optimized
             frequencies); DNA defaults to empirical, DNAX optimizes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List

import numpy as np

from examl_tpu import datatypes

PROT_MODELS = [
    "DAYHOFF", "DCMUT", "JTT", "MTREV", "WAG", "RTREV", "CPREV", "VT",
    "BLOSUM62", "MTMAM", "LG", "MTART", "MTZOA", "PMB", "HIVB", "HIVW",
    "JTTDCMUT", "FLU", "STMTREV", "AUTO", "LG4M", "LG4X", "GTR",
]


@dataclass
class PartitionSpec:
    name: str
    datatype_name: str          # "DNA" | "AA" | "BIN"
    model_name: str             # "GTR" for DNA/BIN; matrix name for AA
    sites: np.ndarray           # 0-based global site indices
    empirical_freqs: bool = False
    optimize_freqs: bool = False
    lg4: bool = False
    auto: bool = False
    branch_index: int = 0       # per-partition branch-length slot (-M)
    extra: dict = field(default_factory=dict)


def _parse_ranges(text: str, nsites_hint: int | None = None) -> np.ndarray:
    sites: List[int] = []
    for piece in text.split(","):
        piece = piece.strip()
        if not piece:
            continue
        m = re.fullmatch(r"(\d+)(?:\s*-\s*(\d+))?(?:\s*\\\s*(\d+))?", piece)
        if not m:
            raise ValueError(f"bad partition range {piece!r}")
        a = int(m.group(1))
        b = int(m.group(2)) if m.group(2) else a
        stride = int(m.group(3)) if m.group(3) else 1
        sites.extend(range(a - 1, b, stride))
    return np.asarray(sorted(set(sites)), dtype=np.int64)


def _parse_model_token(tok: str) -> PartitionSpec:
    t = tok.strip().upper()
    if t in ("BIN", "BINX", "BINARY"):
        return PartitionSpec("", "BIN", "GTR", np.empty(0, np.int64),
                             empirical_freqs=True, optimize_freqs=t.endswith("X"))
    if t in ("DNA", "DNAF", "DNAX"):
        return PartitionSpec("", "DNA", "GTR", np.empty(0, np.int64),
                             empirical_freqs=True, optimize_freqs=t == "DNAX")
    # Protein models (note: bare "GTR" is the optimizable amino-acid GTR,
    # as in the reference's model-name table).
    base, emp, opt = t, False, False
    if t not in PROT_MODELS:
        if t.endswith("F") and t[:-1] in PROT_MODELS:
            base, emp = t[:-1], True
        elif t.endswith("X") and t[:-1] in PROT_MODELS:
            base, opt = t[:-1], True
        else:
            raise ValueError(f"unknown model {tok!r}")
    return PartitionSpec("", "AA", base, np.empty(0, np.int64),
                         empirical_freqs=emp or base == "GTR",
                         optimize_freqs=opt,
                         lg4=base in ("LG4M", "LG4X"), auto=base == "AUTO")


def parse_partition_file(path: str) -> List[PartitionSpec]:
    specs: List[PartitionSpec] = []
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            head, _, ranges = line.partition("=")
            if not ranges:
                raise ValueError(f"bad partition line {line!r}")
            model_tok, _, name = head.partition(",")
            if not name.strip():
                raise ValueError(f"bad partition line {line!r}")
            spec = _parse_model_token(model_tok)
            spec.name = name.strip()
            spec.sites = _parse_ranges(ranges)
            specs.append(spec)
    seen = np.concatenate([s.sites for s in specs]) if specs else np.empty(0)
    if len(seen) != len(set(seen.tolist())):
        raise ValueError(f"{path}: overlapping partition ranges")
    return specs


def single_partition_spec(datatype_name: str, nsites: int,
                          model_name: str = "GTR") -> PartitionSpec:
    dt = datatypes.get(datatype_name)
    spec = PartitionSpec("NoName", dt.name, model_name,
                         np.arange(nsites, dtype=np.int64))
    if dt.name != "AA":
        spec.empirical_freqs = True
    return spec
