"""Relaxed-PHYLIP alignment reader (sequential and interleaved).

Equivalent role to the reference parser's `getinput` (ExaML
`parser/axml.c:1027`): header "<ntaxa> <nsites>", then taxon rows.  Supports
both layouts:
  - sequential: each taxon's name followed by its sequence, possibly wrapped
    over several lines (greedy: continuation lines are consumed until the
    taxon has nsites characters);
  - interleaved: a first block of name+chunk rows, then bare chunk blocks
    appended round-robin.
The sequential parse is attempted first; on inconsistency the interleaved
interpretation is used.
"""

from __future__ import annotations

from typing import List, Tuple


def _clean(line: str) -> str:
    return line.replace(" ", "").replace("\t", "")


def _parse_sequential(lines: List[str], ntaxa: int,
                      nsites: int) -> Tuple[List[str], List[str]]:
    names: List[str] = []
    seqs: List[str] = []
    idx = 0
    for _ in range(ntaxa):
        if idx >= len(lines):
            raise ValueError("unexpected end of file")
        parts = lines[idx].split(None, 1)
        idx += 1
        name = parts[0]
        chars = _clean(parts[1]) if len(parts) > 1 else ""
        while len(chars) < nsites:
            if idx >= len(lines):
                raise ValueError(f"taxon {name}: sequence too short")
            chars += _clean(lines[idx])
            idx += 1
        if len(chars) != nsites:
            raise ValueError(f"taxon {name}: sequence length mismatch")
        names.append(name)
        seqs.append(chars)
    # Trailing lines are ignored, as the reference's getinput reads exactly
    # ntaxa records (parser/axml.c:1027) — testData/140 has junk after them.
    return names, seqs


def _parse_interleaved(lines: List[str], ntaxa: int,
                       nsites: int) -> Tuple[List[str], List[str]]:
    if len(lines) < ntaxa or len(lines) % ntaxa != 0:
        raise ValueError(f"interleaved PHYLIP needs a multiple of {ntaxa} rows")
    names: List[str] = []
    seqs: List[str] = [""] * ntaxa
    for i, line in enumerate(lines):
        row = i % ntaxa
        if i < ntaxa:
            parts = line.split(None, 1)
            names.append(parts[0])
            seqs[row] += _clean(parts[1]) if len(parts) > 1 else ""
        else:
            seqs[row] += _clean(line)
    for name, s in zip(names, seqs):
        if len(s) != nsites:
            raise ValueError(
                f"taxon {name} has {len(s)} sites, expected {nsites}")
    return names, seqs


def read_phylip(path: str) -> Tuple[List[str], List[str]]:
    """Returns (taxon_names, sequences) as raw character strings."""
    with open(path) as f:
        header = f.readline().split()
        if len(header) < 2:
            raise ValueError(f"{path}: bad PHYLIP header")
        ntaxa, nsites = int(header[0]), int(header[1])
        lines = [ln.strip() for ln in f if ln.strip()]

    try:
        names, seqs = _parse_sequential(lines, ntaxa, nsites)
    except ValueError:
        try:
            names, seqs = _parse_interleaved(lines, ntaxa, nsites)
        except ValueError as e:
            raise ValueError(f"{path}: cannot parse as PHYLIP: {e}")
    if len(names) != ntaxa:
        raise ValueError(f"{path}: expected {ntaxa} taxa, found {len(names)}")
    return names, seqs
