"""Site packing: partitions -> lane-aligned blocks for the TPU site axis.

This is the TPU-native replacement for the reference's load balancer
("Kassian's algorithm", ExaML `partitionAssignment.c:156-450`): instead of
assigning (partition, offset, width) chunks to MPI ranks, each partition's
pattern columns are padded with zero-weight sites to a multiple of the lane
width (the MIC backend's zero-weight `VECTOR_PADDING` trick, ExaML
`axml.c:2060-2073`, generalized), concatenated into one flat site axis, and
the resulting 128-site blocks are sharded uniformly over the device mesh.
Because every block belongs to exactly one partition, per-block P-matrix
gathers stay cheap and per-partition reductions are segment sums.

Partitions of different state counts (DNA=4 vs AA=20) go into separate
buckets, each compiled as its own device program — the same per-data-type
split the reference balancer performs (`partitionAssignment.c:398-450`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from examl_tpu.constants import TPU_LANE
from examl_tpu.io.alignment import PartitionData


@dataclass
class PackedBucket:
    """All partitions of one state count packed into a flat padded site axis.

    A bucket is either GLOBAL (arrays cover the whole packed axis;
    `block_offset` 0, `global_blocks` None) or a LOCAL WINDOW of the
    global axis (multi-host selective loading: arrays cover only this
    process's contiguous block range; `num_blocks` still reports the
    GLOBAL count because every jitted program is shaped globally —
    reference analogue: each MPI rank's `partitionData` holds only its
    site slice, `byteFile.c:278-382`)."""
    states: int
    lane: int
    tip_codes: np.ndarray       # [ntaxa, S_local] uint8 (padding = undet code)
    weights: np.ndarray         # [S_local] float64, 0.0 on padding sites
    site_part: np.ndarray       # [S_local] int32 local partition id
    block_part: np.ndarray      # [B_local] int32 local partition id per block
    part_ids: List[int]         # local id -> global partition index
    part_offsets: np.ndarray    # [M] start of each partition's padded range
    part_widths: np.ndarray     # [M] true (unpadded) pattern counts
    block_offset: int = 0       # first local block's GLOBAL block index
    global_blocks: int | None = None   # None = this bucket IS global

    @property
    def num_sites(self) -> int:
        """GLOBAL padded site-axis length (jit program shapes)."""
        return self.num_blocks * self.lane

    @property
    def local_num_sites(self) -> int:
        return self.tip_codes.shape[1]

    @property
    def num_blocks(self) -> int:
        """GLOBAL block count (jit program shapes)."""
        if self.global_blocks is not None:
            return self.global_blocks
        return self.local_num_sites // self.lane

    @property
    def local_num_blocks(self) -> int:
        return self.local_num_sites // self.lane

    @property
    def is_local(self) -> bool:
        return self.global_blocks is not None

    @property
    def num_parts(self) -> int:
        return len(self.part_ids)

    def site_indices(self, local_part: int) -> np.ndarray:
        """GLOBAL padded-axis indices of partition's true patterns (only
        meaningful on a global bucket)."""
        o = int(self.part_offsets[local_part])
        w = int(self.part_widths[local_part])
        return np.arange(o, o + w)


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


@dataclass
class BucketLayout:
    """The pure ARITHMETIC of one bucket's packed site axis, computable
    from (global id, states, width) triples alone — no pattern data.

    This is what lets a multi-process run seek-read only its own site
    columns from a byteFile (reference `byteFile.c:278-382` readMyData /
    seekPos :31-83): the padded layout, hence every process's block
    range and its pre-image in per-partition pattern columns, is a
    function of the header metadata only."""
    states: int
    lane: int
    gids: List[int]             # local index -> global partition index
    offsets: np.ndarray         # [M] padded-axis start of each partition
    padded: np.ndarray          # [M] padded width of each partition
    widths: np.ndarray          # [M] true pattern counts
    total: int                  # padded site-axis length (incl. tail pad)

    @property
    def num_blocks(self) -> int:
        return self.total // self.lane

    def process_columns(self, procid: int, nprocs: int
                        ) -> List[Tuple[int, int, int]]:
        """(global partition id, col_lo, col_hi) of the TRUE pattern
        columns process `procid` of `nprocs` owns, assuming the block
        axis shards contiguously and evenly over processes (the 1-D
        sites mesh lists each process's devices contiguously, so a
        process's shard union is one contiguous block range).  Build
        the layout with block_multiple divisible by nprocs."""
        B = self.num_blocks
        if B % nprocs:
            raise ValueError(
                f"{B} blocks do not divide over {nprocs} processes; "
                f"pack with block_multiple a multiple of nprocs")
        s0 = (procid * B // nprocs) * self.lane
        s1 = ((procid + 1) * B // nprocs) * self.lane
        out: List[Tuple[int, int, int]] = []
        for li, gid in enumerate(self.gids):
            off = int(self.offsets[li])
            w = int(self.widths[li])
            lo = max(s0, off) - off
            hi = min(s1, off + w) - off
            if hi > lo:
                out.append((gid, lo, hi))
        return out


def pack_layout(specs: Sequence[Tuple[int, int, int]],
                lane: int = TPU_LANE,
                block_multiple: int = 1) -> Dict[int, BucketLayout]:
    """Bucket (gid, states, width) triples by state count and lay out each
    bucket's padded site axis — the metadata-only core of
    pack_partitions, shared with the selective byteFile reader."""
    by_states: Dict[int, List[Tuple[int, int]]] = {}
    for gid, states, width in specs:
        by_states.setdefault(states, []).append((gid, width))
    layouts: Dict[int, BucketLayout] = {}
    for states, group in sorted(by_states.items()):
        padded = np.array([_round_up(max(w, 1), lane) for _, w in group],
                          dtype=np.int64)
        total = _round_up(int(padded.sum()), lane * block_multiple)
        offsets = np.concatenate(([0], np.cumsum(padded)[:-1]))
        layouts[states] = BucketLayout(
            states=states, lane=lane, gids=[g for g, _ in group],
            offsets=offsets, padded=padded,
            widths=np.array([w for _, w in group], dtype=np.int64),
            total=total)
    return layouts


def pack_partitions(partitions: Sequence[PartitionData],
                    lane: int = TPU_LANE,
                    block_multiple: int = 1) -> Dict[int, PackedBucket]:
    """Group partitions by state count and pack each group.

    block_multiple: total block count is rounded up to a multiple of this
    (set to the mesh's site-axis size so sharding divides evenly).
    """
    by_states: Dict[int, List[Tuple[int, PartitionData]]] = {}
    for gid, part in enumerate(partitions):
        by_states.setdefault(part.states, []).append((gid, part))

    layouts = pack_layout(
        [(gid, part.states, part.width)
         for gid, part in enumerate(partitions)],
        lane=lane, block_multiple=block_multiple)

    buckets: Dict[int, PackedBucket] = {}
    from examl_tpu.resilience import heartbeat
    for states, group in sorted(by_states.items()):
        # Liveness per bucket: packing a reference-scale (~120k taxon)
        # alignment is minutes of host work the --supervise stall
        # detector must not read as a wedge.
        heartbeat.phase_beat("PACK")
        ntaxa = group[0][1].patterns.shape[0]
        undet = group[0][1].datatype.undetermined_code
        lay = layouts[states]
        padded = [int(x) for x in lay.padded]
        total = lay.total

        tip_codes = np.full((ntaxa, total), undet, dtype=np.uint8)
        weights = np.zeros(total, dtype=np.float64)
        site_part = np.zeros(total, dtype=np.int32)
        offsets = np.zeros(len(group), dtype=np.int64)
        widths = np.zeros(len(group), dtype=np.int64)

        off = 0
        for li, ((gid, part), pw) in enumerate(zip(group, padded)):
            w = part.width
            tip_codes[:, off:off + w] = part.patterns
            weights[off:off + w] = part.weights
            site_part[off:off + pw] = li
            offsets[li] = off
            widths[li] = w
            off += pw
        # Trailing alignment blocks keep partition id of the last partition.
        site_part[off:] = len(group) - 1

        block_part = site_part.reshape(-1, lane)[:, 0].copy()
        buckets[states] = PackedBucket(
            states=states, lane=lane, tip_codes=tip_codes, weights=weights,
            site_part=site_part, block_part=block_part,
            part_ids=[gid for gid, _ in group],
            part_offsets=offsets, part_widths=widths)
    return buckets


def pack_partitions_local(partitions: Sequence[PartitionData],
                          procid: int, nprocs: int,
                          lane: int = TPU_LANE,
                          block_multiple: int = 1
                          ) -> Dict[int, PackedBucket]:
    """Pack SLICED partitions (from `read_bytefile_for_process`) into the
    LOCAL WINDOW of the global packed axis this process owns.

    Each partition's `global_width`/`global_col_offset` (set by the
    selective reader) recover the global layout, so the local arrays are
    positioned exactly where `pack_partitions` on the full alignment
    would put them — the per-rank half of the reference's
    `partitionAssignment` + `readMyData` pipeline.  `block_multiple`
    must match the global packing (the mesh's device count) and be
    divisible by nprocs."""
    specs = []
    for gid, part in enumerate(partitions):
        gw = part.global_width if part.global_width is not None else part.width
        specs.append((gid, part.states, gw))
    layouts = pack_layout(specs, lane=lane, block_multiple=block_multiple)

    by_states: Dict[int, List[Tuple[int, PartitionData]]] = {}
    for gid, part in enumerate(partitions):
        by_states.setdefault(part.states, []).append((gid, part))

    buckets: Dict[int, PackedBucket] = {}
    for states, group in sorted(by_states.items()):
        lay = layouts[states]
        B = lay.num_blocks
        if B % nprocs:
            raise ValueError(
                f"{B} blocks do not divide over {nprocs} processes; "
                f"pack with block_multiple a multiple of nprocs")
        b0 = procid * B // nprocs
        b1 = (procid + 1) * B // nprocs
        s0, s1 = b0 * lane, b1 * lane
        total = s1 - s0
        ntaxa = group[0][1].patterns.shape[0]
        undet = group[0][1].datatype.undetermined_code

        tip_codes = np.full((ntaxa, total), undet, dtype=np.uint8)
        weights = np.zeros(total, dtype=np.float64)
        site_part = np.zeros(total, dtype=np.int32)

        for li, (gid, part) in enumerate(group):
            off_g = int(lay.offsets[li])
            w_g = int(lay.widths[li])
            pw_g = int(lay.padded[li])
            # padded-range intersection -> local partition id for blocks
            plo = max(s0, off_g)
            phi = min(s1, off_g + pw_g)
            if phi > plo:
                site_part[plo - s0:phi - s0] = li
            # true-column intersection -> this process's slice of the data
            lo = max(s0, off_g) - off_g
            hi = min(s1, off_g + w_g) - off_g
            if hi <= lo:
                if part.width:
                    raise ValueError(
                        f"partition {gid}: slice has {part.width} columns "
                        f"but process {procid} owns none — sliced read "
                        f"and packing disagree (block_multiple mismatch?)")
                continue
            if part.global_col_offset != lo or part.width != hi - lo:
                raise ValueError(
                    f"partition {gid}: slice [{part.global_col_offset},"
                    f"{part.global_col_offset + part.width}) does not "
                    f"match process window [{lo},{hi})")
            dest = off_g + lo - s0
            tip_codes[:, dest:dest + hi - lo] = part.patterns
            weights[dest:dest + hi - lo] = part.weights
        # Trailing alignment blocks keep the last partition's id, like
        # the global packer.
        last_cover = min(s1, int(lay.offsets[-1]) + int(lay.padded[-1]))
        if last_cover < s1:
            site_part[max(last_cover - s0, 0):] = len(group) - 1

        block_part = site_part.reshape(-1, lane)[:, 0].copy()
        buckets[states] = PackedBucket(
            states=states, lane=lane, tip_codes=tip_codes, weights=weights,
            site_part=site_part, block_part=block_part,
            part_ids=[gid for gid, _ in group],
            part_offsets=lay.offsets, part_widths=lay.widths,
            block_offset=b0, global_blocks=B)
    return buckets
