"""Site packing: partitions -> lane-aligned blocks for the TPU site axis.

This is the TPU-native replacement for the reference's load balancer
("Kassian's algorithm", ExaML `partitionAssignment.c:156-450`): instead of
assigning (partition, offset, width) chunks to MPI ranks, each partition's
pattern columns are padded with zero-weight sites to a multiple of the lane
width (the MIC backend's zero-weight `VECTOR_PADDING` trick, ExaML
`axml.c:2060-2073`, generalized), concatenated into one flat site axis, and
the resulting 128-site blocks are sharded uniformly over the device mesh.
Because every block belongs to exactly one partition, per-block P-matrix
gathers stay cheap and per-partition reductions are segment sums.

Partitions of different state counts (DNA=4 vs AA=20) go into separate
buckets, each compiled as its own device program — the same per-data-type
split the reference balancer performs (`partitionAssignment.c:398-450`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from examl_tpu.constants import TPU_LANE
from examl_tpu.io.alignment import PartitionData


@dataclass
class PackedBucket:
    """All partitions of one state count packed into a flat padded site axis."""
    states: int
    lane: int
    tip_codes: np.ndarray       # [ntaxa, S] uint8 (padding = undetermined code)
    weights: np.ndarray         # [S] float64, 0.0 on padding sites
    site_part: np.ndarray       # [S] int32 local partition id
    block_part: np.ndarray      # [B] int32 local partition id per block
    part_ids: List[int]         # local id -> global partition index
    part_offsets: np.ndarray    # [M] start of each partition's padded range
    part_widths: np.ndarray     # [M] true (unpadded) pattern counts

    @property
    def num_sites(self) -> int:
        return self.tip_codes.shape[1]

    @property
    def num_blocks(self) -> int:
        return self.num_sites // self.lane

    @property
    def num_parts(self) -> int:
        return len(self.part_ids)

    def site_indices(self, local_part: int) -> np.ndarray:
        """Padded-axis indices of partition's true patterns."""
        o = int(self.part_offsets[local_part])
        w = int(self.part_widths[local_part])
        return np.arange(o, o + w)


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def pack_partitions(partitions: Sequence[PartitionData],
                    lane: int = TPU_LANE,
                    block_multiple: int = 1) -> Dict[int, PackedBucket]:
    """Group partitions by state count and pack each group.

    block_multiple: total block count is rounded up to a multiple of this
    (set to the mesh's site-axis size so sharding divides evenly).
    """
    by_states: Dict[int, List[Tuple[int, PartitionData]]] = {}
    for gid, part in enumerate(partitions):
        by_states.setdefault(part.states, []).append((gid, part))

    buckets: Dict[int, PackedBucket] = {}
    for states, group in sorted(by_states.items()):
        ntaxa = group[0][1].patterns.shape[0]
        undet = group[0][1].datatype.undetermined_code
        padded = [_round_up(max(p.width, 1), lane) for _, p in group]
        total = _round_up(sum(padded), lane * block_multiple)

        tip_codes = np.full((ntaxa, total), undet, dtype=np.uint8)
        weights = np.zeros(total, dtype=np.float64)
        site_part = np.zeros(total, dtype=np.int32)
        offsets = np.zeros(len(group), dtype=np.int64)
        widths = np.zeros(len(group), dtype=np.int64)

        off = 0
        for li, ((gid, part), pw) in enumerate(zip(group, padded)):
            w = part.width
            tip_codes[:, off:off + w] = part.patterns
            weights[off:off + w] = part.weights
            site_part[off:off + pw] = li
            offsets[li] = off
            widths[li] = w
            off += pw
        # Trailing alignment blocks keep partition id of the last partition.
        site_part[off:] = len(group) - 1

        block_part = site_part.reshape(-1, lane)[:, 0].copy()
        buckets[states] = PackedBucket(
            states=states, lane=lane, tip_codes=tip_codes, weights=weights,
            site_part=site_part, block_part=block_part,
            part_ids=[gid for gid, _ in group],
            part_offsets=offsets, part_widths=widths)
    return buckets
