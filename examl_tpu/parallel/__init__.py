from examl_tpu.parallel.packing import PackedBucket, pack_partitions  # noqa: F401
