"""Device-mesh sharding of the packed site axis.

TPU-native replacement for the reference's MPI rank layout (ExaML
`partitionAssignment.c` + `communication.c`): instead of assigning site
chunks to ranks, the packed block axis produced by `parallel/packing.py` is
sharded uniformly over a 1-D `jax.sharding.Mesh` axis ("sites").  Model
tensors and the traversal descriptor stay replicated — exactly the
reference's design, where every rank holds the whole tree and model and
only per-site state is distributed.  The per-partition lnL / derivative
reductions (`MPI_Allreduce` at `evaluateGenericSpecial.c:968-973` and
`makenewzGenericSpecial.c:1241-1248`) need no explicit collective here:
the segment sums over the sharded block axis make XLA insert the
all-reduce over ICI.

Multi-host scale-out uses the same mesh: `jax.distributed` process groups
present a global device list, and the "sites" axis spans all chips; the
only cross-host traffic is the small lnL reduction, riding DCN exactly as
the reference's Allreduce rides the interconnect.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SITE_AXIS = "sites"


@dataclass
class SiteSharding:
    """NamedShardings for each engine tensor layout, all over one mesh axis.

    Attribute names match what the engine's placement helpers
    (`LikelihoodEngine._put_blocks` / `_zeros_sharded`) consume:
      clv     [rows, B, lane, R, K]  — blocks on axis 1
      scaler  [rows, B, lane]        — blocks on axis 1
      sites   [B, lane]              — blocks on axis 0 (weights)
      blocks  [B]                    — blocks on axis 0 (block_part)
      replicated                     — models / traversal descriptors
    """
    mesh: Mesh
    clv: NamedSharding
    scaler: NamedSharding
    sites: NamedSharding
    blocks: NamedSharding
    replicated: NamedSharding

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the site axis (the framework's only sharded axis,
    mirroring the reference's single data-parallel strategy, SURVEY §2.3)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SITE_AXIS,))


def site_sharding(mesh: Mesh) -> SiteSharding:
    return SiteSharding(
        mesh=mesh,
        clv=NamedSharding(mesh, P(None, SITE_AXIS)),
        scaler=NamedSharding(mesh, P(None, SITE_AXIS)),
        sites=NamedSharding(mesh, P(SITE_AXIS)),
        blocks=NamedSharding(mesh, P(SITE_AXIS)),
        replicated=NamedSharding(mesh, P()),
    )


def default_site_sharding(n_devices: Optional[int] = None) -> SiteSharding:
    return site_sharding(make_mesh(n_devices=n_devices))
