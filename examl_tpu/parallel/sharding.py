"""Device-mesh sharding of the packed site axis.

TPU-native replacement for the reference's MPI rank layout (ExaML
`partitionAssignment.c` + `communication.c`): instead of assigning site
chunks to ranks, the packed block axis produced by `parallel/packing.py` is
sharded uniformly over a 1-D `jax.sharding.Mesh` axis ("sites").  Model
tensors and the traversal descriptor stay replicated — exactly the
reference's design, where every rank holds the whole tree and model and
only per-site state is distributed.  The per-partition lnL / derivative
reductions (`MPI_Allreduce` at `evaluateGenericSpecial.c:968-973` and
`makenewzGenericSpecial.c:1241-1248`) need no explicit collective here:
the segment sums over the sharded block axis make XLA insert the
all-reduce over ICI.

Multi-host scale-out uses the same mesh: `jax.distributed` process groups
present a global device list, and the "sites" axis spans all chips; the
only cross-host traffic is the small lnL reduction, riding DCN exactly as
the reference's Allreduce rides the interconnect.

**The likelihood fabric (ISSUE 17 / ROADMAP §7)** adds a second named
axis: a 2-D `Mesh(devices.reshape(S, T), ("sites", "tree"))` composes
the site axis with the fleet's tree-batch axis on the SAME devices.
Engine tensors keep their site-only `PartitionSpec`s (unnamed axes
replicate, so each tree slice holds the whole model and its site
shards — the reference's invariant per rank); the fleet's stacked
per-job leaves carry `P("tree", ...)` on the leading job axis
(`fleet/shard.py: MeshShard`).  GSPMD partitions jobs over `tree` and
each job's blocks over `sites`; the root lnL segment-sum stays the one
cross-shard collective (an all-reduce over `sites` — ExaML's single
Allreduce), and the per-job outputs shard over `tree` with no
tree-axis collective at all.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SITE_AXIS = "sites"
TREE_AXIS = "tree"


@dataclass
class SiteSharding:
    """NamedShardings for each engine tensor layout, all over one mesh axis.

    Attribute names match what the engine's placement helpers
    (`LikelihoodEngine._put_blocks` / `_zeros_sharded`) consume:
      clv     [rows, B, lane, R, K]  — blocks on axis 1
      scaler  [rows, B, lane]        — blocks on axis 1
      sites   [B, lane]              — blocks on axis 0 (weights)
      blocks  [B]                    — blocks on axis 0 (block_part)
      replicated                     — models / traversal descriptors

    The mesh may be 1-D ("sites" only) or the 2-D (sites, tree) fabric;
    the specs above never mention the tree axis, so on a fabric every
    tree slice replicates the engine state over its site shards — the
    composition contract `fleet/shard.py: MeshShard` builds on.
    """
    mesh: Mesh
    clv: NamedSharding
    scaler: NamedSharding
    sites: NamedSharding
    blocks: NamedSharding
    replicated: NamedSharding

    @property
    def num_devices(self) -> int:
        """SITE-axis shard count — the divisor of the packed block axis
        (block_multiple padding, -S region counts).  Identical to the
        mesh size on a 1-D mesh; on the 2-D fabric the tree axis does
        not split blocks, so it must not inflate this number."""
        return int(dict(zip(self.mesh.axis_names,
                            self.mesh.devices.shape))[SITE_AXIS])

    @property
    def site_shards(self) -> int:
        return self.num_devices

    @property
    def tree_shards(self) -> int:
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        return int(shape.get(TREE_AXIS, 1))

    @property
    def is_fabric(self) -> bool:
        """True when the mesh carries the named tree axis (even T=1):
        the fleet then commits its job stacks over `tree` instead of
        cutting per-device lanes."""
        return TREE_AXIS in self.mesh.axis_names


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the site axis (the framework's only sharded axis,
    mirroring the reference's single data-parallel strategy, SURVEY §2.3)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SITE_AXIS,))


def site_sharding(mesh: Mesh) -> SiteSharding:
    return SiteSharding(
        mesh=mesh,
        clv=NamedSharding(mesh, P(None, SITE_AXIS)),
        scaler=NamedSharding(mesh, P(None, SITE_AXIS)),
        sites=NamedSharding(mesh, P(SITE_AXIS)),
        blocks=NamedSharding(mesh, P(SITE_AXIS)),
        replicated=NamedSharding(mesh, P()),
    )


def default_site_sharding(n_devices: Optional[int] = None) -> SiteSharding:
    return site_sharding(make_mesh(n_devices=n_devices))


# -- the (sites, tree) fabric (ISSUE 17 / ROADMAP §7) ------------------------


def parse_mesh_spec(spec: str) -> Tuple[int, int]:
    """`--mesh SxT` / `EXAML_MESH=SxT` -> (site_shards, tree_shards).
    Accepts 'x' or 'X' as the separator; both axes must be positive."""
    text = str(spec).strip().lower()
    parts = text.split("x")
    if len(parts) != 2:
        raise ValueError(
            f"mesh spec {spec!r} is not SxT (e.g. 2x2: 2 site shards "
            "x 2 tree shards)")
    try:
        s, t = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"mesh spec {spec!r}: both axes must be integers")
    if s < 1 or t < 1:
        raise ValueError(f"mesh spec {spec!r}: both axes must be >= 1")
    return s, t


def make_fabric_mesh(site_shards: int, tree_shards: int,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The 2-D (sites, tree) device mesh: S*T devices reshaped so the
    site axis is outermost (site shards of one tree slice sit on
    consecutive devices — on real topologies that keeps the lnL
    all-reduce, the fabric's only collective, on neighbor links)."""
    if devices is None:
        devices = jax.devices()
    need = site_shards * tree_shards
    if need > len(devices):
        raise ValueError(
            f"mesh {site_shards}x{tree_shards} needs {need} devices; "
            f"only {len(devices)} visible (raise "
            "--xla_force_host_platform_device_count on CPU, or shrink "
            "the mesh)")
    arr = np.asarray(devices[:need]).reshape(site_shards, tree_shards)
    return Mesh(arr, (SITE_AXIS, TREE_AXIS))


def fabric_sharding(mesh: Mesh) -> SiteSharding:
    """Engine-tensor shardings over the 2-D fabric: identical specs to
    `site_sharding` (site axis only — the tree axis replicates engine
    state), just declared on the fabric mesh so fleet job stacks
    committed with `P(TREE_AXIS, ...)` compose in one jitted dispatch."""
    return site_sharding(mesh)


def declared_specs(sharding: SiteSharding) -> dict:
    """The fabric's declared-sharding record (ROADMAP §4's
    declared-sharding half): axis names, mesh shape and per-leaf
    PartitionSpecs, JSON-ready for `bank_manifest.json` — a relocating
    loader re-declares the same NamedShardings from this block instead
    of trusting procid-implicit placement."""
    leaf_specs = {
        "clv": str(sharding.clv.spec),
        "scaler": str(sharding.scaler.spec),
        "sites": str(sharding.sites.spec),
        "blocks": str(sharding.blocks.spec),
        "replicated": str(sharding.replicated.spec),
    }
    if sharding.is_fabric:
        leaf_specs["fleet_jobs"] = str(P(TREE_AXIS))
        leaf_specs["fleet_clv"] = str(P(TREE_AXIS, None, SITE_AXIS))
    return {
        "axis_names": list(sharding.mesh.axis_names),
        "mesh_shape": [int(d) for d in sharding.mesh.devices.shape],
        "site_shards": sharding.site_shards,
        "tree_shards": sharding.tree_shards,
        "leaf_specs": leaf_specs,
    }


def declared_fabric_specs(site_shards: int, tree_shards: int) -> dict:
    """`declared_specs` without constructing the mesh: byte-identical
    JSON for an (S, T) fabric, computable in contexts that must not
    touch devices (the bank's manifest stamping runs before/without the
    main process's fabric being live)."""
    return {
        "axis_names": [SITE_AXIS, TREE_AXIS],
        "mesh_shape": [int(site_shards), int(tree_shards)],
        "site_shards": int(site_shards),
        "tree_shards": int(tree_shards),
        "leaf_specs": {
            "clv": str(P(None, SITE_AXIS)),
            "scaler": str(P(None, SITE_AXIS)),
            "sites": str(P(SITE_AXIS)),
            "blocks": str(P(SITE_AXIS)),
            "replicated": str(P()),
            "fleet_jobs": str(P(TREE_AXIS)),
            "fleet_clv": str(P(TREE_AXIS, None, SITE_AXIS)),
        },
    }
