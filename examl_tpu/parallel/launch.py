"""Process/device launch: the reference's MPI startup, TPU-native.

ExaML starts as `mpirun -np N examl ...` — MPI_Init, rank discovery,
and per-rank site assignment (`axml.c: main`, `communication.c:120-182`).
The TPU equivalent has two layers:

* **multi-host**: `jax.distributed.initialize(coordinator, nprocs,
  procid)` joins this process to the cluster; afterwards `jax.devices()`
  is the GLOBAL device list and every process runs the same SPMD
  program.  Driven by `--coordinator/--nprocs/--procid` or the standard
  cluster env (JAX auto-detects on supported platforms when flags are
  omitted but --nprocs > 1).
* **single-host, multi-device**: no init needed; the site axis simply
  shards over the local mesh.

Either way the result is one 1-D "sites" mesh over all visible chips
(`parallel/sharding.py`); per-site tensors shard, the tree/model stay
replicated, and the lnL/derivative reductions become XLA collectives —
the reference's Allreduce, inserted by the compiler.
"""

from __future__ import annotations

import os
from typing import Optional

from examl_tpu.parallel.sharding import (SiteSharding, fabric_sharding,
                                         make_fabric_mesh, make_mesh,
                                         parse_mesh_spec, site_sharding)


def add_launch_args(parser) -> None:
    g = parser.add_argument_group("distributed launch")
    g.add_argument("--coordinator", default=None,
                   help="coordinator address host:port for multi-host "
                        "runs (jax.distributed)")
    g.add_argument("--nprocs", type=int, default=None,
                   help="number of processes in the multi-host job")
    g.add_argument("--procid", type=int, default=None,
                   help="this process's index in the multi-host job")
    g.add_argument("--single-device", action="store_true",
                   help="disable site-axis sharding even when several "
                        "devices are visible")
    g.add_argument("--mesh", dest="mesh", default=None, metavar="SxT",
                   help="declared (sites, tree) likelihood fabric: "
                        "shard each tree's packed site blocks over S "
                        "devices AND the fleet's job axis over T "
                        "device slices of the same mesh (e.g. "
                        "--mesh 4x2 on 8 devices).  T>1 requires a "
                        "fleet mode (-b/-N/--serve); Sx1 is the "
                        "classic site sharding with an explicit "
                        "shape.  EXAML_MESH=SxT is the env "
                        "equivalent (the flag wins)")


def init_distributed(args, log=lambda msg: None) -> None:
    """Join the multi-host job when requested; no-op otherwise."""
    if args.coordinator is None and args.nprocs is None:
        if args.procid is not None:
            raise ValueError(
                "--procid requires --nprocs/--coordinator: without them "
                "this process would run as a second primary and clobber "
                "process 0's output files")
        return
    import jax

    kwargs = {}
    if args.coordinator is not None:
        kwargs["coordinator_address"] = args.coordinator
    if args.nprocs is not None:
        kwargs["num_processes"] = args.nprocs
    if args.procid is not None:
        kwargs["process_id"] = args.procid
    jax.distributed.initialize(**kwargs)
    log(f"distributed: process {jax.process_index()} of "
        f"{jax.process_count()}, {jax.local_device_count()} local / "
        f"{jax.device_count()} global devices")


def bank_barrier(args, log=lambda msg: None) -> None:
    """Synchronize a multi-host job after per-process program banking
    (ops/bank.py): each process banks against its OWN host's persistent
    cache (local disk, local CPU fingerprint), and no process may enter
    the collective SPMD program while a peer is still compiling — a
    straggler inside a collective looks exactly like the wedge banking
    exists to prevent.  The reference's analogue is MPI_Barrier after
    per-rank setup (`axml.c: main` before the first Allreduce).

    Single-process runs (and jaxlib builds without multi-process
    collectives on this backend) fall through: the first collective
    dispatch then synchronizes, as before banking existed."""
    if getattr(args, "nprocs", None) is None and \
            getattr(args, "coordinator", None) is None:
        return
    import jax

    if jax.process_count() <= 1:
        return
    try:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("examl_bank")
        log(f"bank: {jax.process_count()} processes banked "
            "(barrier passed)")
    except Exception as exc:                 # noqa: BLE001
        log(f"bank: cross-process barrier unavailable ({exc}); the "
            "first collective dispatch will synchronize instead")


def install_heartbeat(args, log=lambda msg: None) -> Optional[str]:
    """Point this process's search-loop heartbeat at a PER-PROCESS file
    (resilience/heartbeat.py, `$EXAML_HEARTBEAT_FILE`).  Process 0
    keeps the configured path — its supervisor watches exactly that
    file; processes >0 of a multi-host job append `.p<procid>` so the
    job's beats never clobber one file (one shared file would mask a
    single wedged peer behind its neighbors' beats).  EMULATED gang
    ranks (`--launch N --launch-emulate`: EXAML_GANG_RANKS/EXAML_PROCID
    set with no distributed flags) follow the identical naming — the
    gang watcher aggregates the same files either way.  Call AFTER
    init_distributed so the procid is the job's, not a guess."""
    from examl_tpu.resilience import heartbeat

    base = os.environ.get(heartbeat.ENV_VAR)
    if not base:
        return None
    path = base
    if getattr(args, "nprocs", None) is not None or \
            getattr(args, "coordinator", None) is not None:
        import jax
        path = heartbeat.rank_path(base, jax.process_index())
    elif heartbeat.env_gang_size():
        path = heartbeat.rank_path(base, heartbeat.env_rank())
    path = heartbeat.install(path)
    log(f"heartbeat -> {path}")
    return path


def enable_process_tracing(trace_dir: str,
                           log=lambda msg: None) -> Optional[str]:
    """Open this process's span-trace file under `trace_dir`, named by
    process index (`trace.p<procid>.jsonl`) so a multi-host job's
    processes never share a writer; process 0 merges a cross-process
    `summary.json` when it exits (obs.trace.finalize).  Call AFTER
    init_distributed so the procid is the job's, not a guess."""
    from examl_tpu import obs

    try:
        # procid=None delegates to the canonical resolver
        # (obs.trace._default_procid): EXAML_PROCID override first, then
        # jax.process_index() when a distributed client exists, else 0.
        path = obs.enable_tracing(trace_dir)
    except OSError as exc:
        log(f"trace events disabled ({exc})")
        return None
    log(f"trace events -> {path}")
    return path


def mesh_spec_requested(args) -> Optional[str]:
    """The raw SxT mesh spec in force, or None: the --mesh flag wins
    over EXAML_MESH (registered in tools/graftlint/envregistry.py)."""
    flag = getattr(args, "mesh", None)
    if flag:
        return flag
    return os.environ.get("EXAML_MESH") or None


def select_sharding(args, save_memory: bool,
                    log=lambda msg: None) -> Optional[SiteSharding]:
    """A site-axis sharding over every visible device, or None for the
    single-device case (-S shards too: per-device pool regions).

    With a declared mesh (`--mesh SxT` / EXAML_MESH) the result is the
    2-D (sites, tree) fabric instead: S site shards per tree slice, T
    tree slices, on exactly S*T devices.  A 1x1 mesh is an explicit
    single-device run (the parity-matrix anchor)."""
    spec = mesh_spec_requested(args)
    if spec is not None:
        s, t = parse_mesh_spec(spec)          # caller pre-validated; a
        if s == t == 1:                       # raise here is a bug trap
            return None
        import jax

        sh = fabric_sharding(make_fabric_mesh(s, t))
        if save_memory:
            log(f"-S (SEV) sharded: per-device CLV pool regions over "
                f"{s} devices (mesh {s}x{t})")
        else:
            log(f"likelihood fabric {s}x{t}: {s} site shard(s) x {t} "
                f"tree slice(s) over {s * t} of {len(jax.devices())} "
                "devices")
        return sh
    if getattr(args, "single_device", False):
        return None
    import jax

    n = len(jax.devices())
    if n <= 1:
        return None
    sh = site_sharding(make_mesh())
    if save_memory:
        log(f"-S (SEV) sharded: per-device CLV pool regions over {n} "
            "devices (shard_map, incl. the batched SPR scan)")
    else:
        log(f"site axis sharded over {n} devices "
            f"({jax.process_count()} process(es))")
    return sh
