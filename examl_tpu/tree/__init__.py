from examl_tpu.tree.topology import Node, Tree, TraversalEntry  # noqa: F401
