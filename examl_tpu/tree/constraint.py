"""Multifurcating constraint trees (-g): random resolution + SPR gating.

Reference: `treeReadLenMULT` (`treeIO.c:920-1160`) reads a comprehensive
multifurcating constraint tree, labels every taxon with the id of its
enclosing constraint node (`constraintVector`), and randomly resolves the
multifurcations into a binary starting tree (seeded by -p); during the
search, `testInsertBIG`'s constraint check (`searchAlgo.c:697-722` with
`checker` :69-93) only admits insertions whose pruned subtree lands next
to a subtree of its own constraint cluster.

Deviation from the reference noted for the record: the reference's
`checker` is a first-labeled-node heuristic over labels cached at
tree-reading time, which can admit moves that break a constraint cluster
once the topology has drifted.  Here the admission rule is exact: a
regraft is allowed iff every constraint cluster remains monophyletic
afterwards, decided from the cluster content of the pruned subtree and of
the two insertion-branch sides (O(n) per scored insertion).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from examl_tpu.io.newick import NewickNode, parse_newick
from examl_tpu.tree.topology import Node, Tree

ROOT_CLUSTER = 0


class TreeConstraint:
    """Tip-cluster labels + the exact SPR insertion admission rule."""

    def __init__(self, tree: Tree, tip_cluster: Dict[int, int]):
        self._tree = tree
        self.tip_cluster = tip_cluster

    def clusters_behind(self, slot: Node) -> frozenset:
        """Set of cluster ids of all tips behind slot (away from
        slot.back); detached slots (the prune cut) contribute nothing.
        Iterative — safe on deep pectinate trees."""
        out = set()
        stack = [slot]
        while stack:
            s = stack.pop()
            if self._tree.is_tip(s.number):
                out.add(self.tip_cluster[s.number])
                continue
            for t in (s.next, s.next.next):
                if t.back is not None:
                    stack.append(t.back)
        return frozenset(out)

    def insertion_ok(self, p: Node, q: Node,
                     pruned_clusters: frozenset | None = None) -> bool:
        """May the subtree pruned at p be regrafted onto branch (q, q.back)?

        Exact rule per constrained cluster C (S = pruned tip set,
        side_q / side_r = the insertion branch's two sides):
        - C disjoint from S: reject iff the branch lies strictly inside
          C's clade (C present on both sides).
        - C entirely inside S: fine.
        - S pure-C but C also outside S: the branch must lie inside or on
          the boundary of C's remainder clade.
        - S mixed and C split between S and the rest: never repairable.

        pruned_clusters caches clusters_behind(p.back), constant for all
        candidate insertions of one prune (the SPR driver supplies it).
        """
        s_cl = (pruned_clusters if pruned_clusters is not None
                else self.clusters_behind(p.back))
        side_q = self.clusters_behind(q)
        side_r = self.clusters_behind(q.back)
        constrained = (s_cl | side_q | side_r) - {ROOT_CLUSTER}
        for c in constrained:
            in_s = c in s_cl
            if not in_s:
                if c in side_q and c in side_r:
                    return False
                continue
            if c not in side_q and c not in side_r:
                continue                      # C fully inside S
            if s_cl != frozenset((c,)):
                return False                  # mixed S carries part of C
            inside = c in side_q and c in side_r
            boundary = side_q == frozenset((c,)) or side_r == frozenset((c,))
            if not (inside or boundary):
                return False
        return True


def _binarize(nw: NewickNode, rng: np.random.Generator,
              at_root: bool) -> None:
    """Randomly resolve a multifurcation in place: repeatedly merge two
    random children under a fresh node, keeping 3 children at the unrooted
    root and 2 elsewhere (the role of the random resolution in
    `addElementLenMULT`)."""
    for child in nw.children:
        _binarize(child, rng, at_root=False)
    target = 3 if at_root else 2
    while len(nw.children) > target:
        i, j = sorted(rng.choice(len(nw.children), size=2, replace=False))
        merged = NewickNode(children=[nw.children[i], nw.children[j]])
        rest = [c for k, c in enumerate(nw.children) if k not in (i, j)]
        nw.children = rest + [merged]


def load_constraint(text: str, taxon_names: Sequence[str], seed: int,
                    num_branches: int = 1) -> tuple[Tree, TreeConstraint]:
    """Parse a comprehensive multifurcating constraint tree, randomly
    resolve it into a binary starting Tree, and return the constraint
    checker (reference `getStartingTree` -g path)."""
    root = parse_newick(text)
    leaves = [l.name for l in root.leaves()]
    if sorted(leaves) != sorted(taxon_names):
        missing = set(taxon_names) - set(leaves)
        extra = set(leaves) - set(taxon_names)
        raise ValueError(
            "the constraint tree must contain exactly the alignment's "
            f"taxa (missing: {sorted(missing)[:5]}, "
            f"unknown: {sorted(extra)[:5]})")

    # Cluster ids: each internal constraint node below the root gets a
    # fresh id; tips are labeled with their parent's id (root level = 0).
    name_to_num = {n: i + 1 for i, n in enumerate(taxon_names)}
    tip_cluster: Dict[int, int] = {}
    counter = [ROOT_CLUSTER]

    def assign(nw: NewickNode, cluster: int) -> None:
        for child in nw.children:
            if child.is_leaf:
                tip_cluster[name_to_num[child.name]] = cluster
            else:
                counter[0] += 1
                assign(child, counter[0])

    assign(root, ROOT_CLUSTER)

    rng = np.random.default_rng(seed)
    # Collapse a rooted constraint into the unrooted trifurcation first.
    while len(root.children) == 2:
        a, b = root.children
        inner = a if not a.is_leaf else b
        if inner.is_leaf:
            raise ValueError("two-taxon constraint tree is not supported")
        other = b if inner is a else a
        root = NewickNode(children=list(inner.children) + [other])
    _binarize(root, rng, at_root=True)

    tree = Tree.from_newick(_format(root) + ";", taxon_names, num_branches)
    return tree, TreeConstraint(tree, tip_cluster)


def _format(nw: NewickNode) -> str:
    if nw.is_leaf:
        return nw.name
    return "(" + ",".join(_format(c) for c in nw.children) + ")"
