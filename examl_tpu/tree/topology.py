"""Unrooted binary tree with node-triple inner nodes and CLV orientation flags.

Host-side topology bookkeeping, the same data model as the reference
(ExaML `axml.h:492-506` `node`/`nodeptr`, `newviewGenericSpecial.c:691`
`computeTraversalInfo`): tips are numbered 1..n, inner nodes n+1..2n-2; an
inner node is a cycle of three slots (`next` pointers); each slot has a
`back` pointer across a branch; the `x` flag marks which of a cycle's slots
the node's single CLV is currently oriented towards (the CLV summarizes the
subtree away from that slot's `back`).

The device engine (ops/engine.py) never sees this structure — only flat
traversal descriptors produced here.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from examl_tpu.constants import DEFAULTZ, ZMAX, ZMIN
from examl_tpu.io.newick import NewickNode, format_newick, parse_newick


class Node:
    __slots__ = ("number", "back", "next", "z", "x")

    def __init__(self, number: int):
        self.number = number
        self.back: Optional[Node] = None
        self.next: Optional[Node] = None
        self.z: List[float] = []
        self.x: bool = False

    def __repr__(self):
        b = self.back.number if self.back else None
        return f"<Node {self.number} back={b} x={self.x}>"


def hookup(p: Node, q: Node, z: Sequence[float]) -> None:
    """Connect two slots with a shared branch-length vector."""
    p.back = q
    q.back = p
    shared = [min(max(v, ZMIN), ZMAX) for v in z]
    p.z = shared
    q.z = shared


class TraversalEntry:
    """One inner-node CLV update: parent from (left, right) children."""
    __slots__ = ("parent", "left", "right", "zl", "zr")

    def __init__(self, parent: int, left: int, right: int,
                 zl: Sequence[float], zr: Sequence[float]):
        self.parent = parent
        self.left = left
        self.right = right
        self.zl = tuple(zl)
        self.zr = tuple(zr)

    def __repr__(self):
        return f"TE(p={self.parent},l={self.left},r={self.right})"


class Tree:
    """Unrooted strictly-binary tree over tips 1..ntips."""

    def __init__(self, ntips: int, num_branches: int = 1):
        if ntips < 3:
            raise ValueError("need at least 3 taxa for an unrooted tree")
        self.ntips = ntips
        self.num_branches = num_branches
        self.nodep: Dict[int, Node] = {}          # canonical slot per number
        for i in range(1, ntips + 1):
            self.nodep[i] = Node(i)
        self._next_inner = ntips + 1

    # -- structure helpers -------------------------------------------------

    @property
    def max_nodes(self) -> int:
        return 2 * self.ntips - 2

    def is_tip(self, number: int) -> bool:
        return number <= self.ntips

    def new_inner(self) -> Node:
        """Allocate an inner node (cycle of three slots)."""
        num = self._next_inner
        if num > self.max_nodes:
            raise RuntimeError("inner node overflow")
        self._next_inner += 1
        a, b, c = Node(num), Node(num), Node(num)
        a.next, b.next, c.next = b, c, a
        self.nodep[num] = a
        return a

    def slots(self, number: int):
        p = self.nodep[number]
        if self.is_tip(number):
            return (p,)
        return (p, p.next, p.next.next)

    def default_z(self) -> List[float]:
        return [DEFAULTZ] * self.num_branches

    @property
    def start(self) -> Node:
        return self.nodep[1]

    def orient(self, p: Node) -> None:
        """Set the x flag of p's cycle onto slot p."""
        if self.is_tip(p.number):
            return
        p.x = True
        p.next.x = False
        p.next.next.x = False

    # -- construction ------------------------------------------------------

    @classmethod
    def from_newick(cls, text: str, taxon_names: Sequence[str],
                    num_branches: int = 1) -> "Tree":
        root = parse_newick(text)
        root = _deroot(root)
        name_to_num = {n: i + 1 for i, n in enumerate(taxon_names)}
        leaves = list(root.leaves())
        if len(leaves) != len(taxon_names):
            raise ValueError(
                f"tree has {len(leaves)} taxa, alignment has {len(taxon_names)}")
        tree = cls(len(taxon_names), num_branches)

        def build(nw: NewickNode) -> Node:
            """Return the slot representing subtree nw, to be hooked upward.

            Iterative post-order (results memoized by id) — reference-scale
            trees exceed the recursion limit (SURVEY §6)."""
            done: Dict[int, Node] = {}
            stack: List[Tuple[NewickNode, bool]] = [(nw, False)]
            while stack:
                n, expanded = stack.pop()
                if n.is_leaf:
                    try:
                        done[id(n)] = tree.nodep[name_to_num[n.name]]
                    except KeyError:
                        raise ValueError(f"taxon {n.name!r} not in alignment")
                    continue
                if len(n.children) != 2:
                    raise ValueError(
                        "multifurcating inner node (resolve first)")
                if not expanded:
                    stack.append((n, True))
                    stack.extend((c, False) for c in n.children)
                    continue
                inner = tree.new_inner()
                for slot, child in zip((inner.next, inner.next.next),
                                       n.children):
                    hookup(slot, done.pop(id(child)),
                           _z_of(child, num_branches))
                done[id(n)] = inner
            return done[id(nw)]

        if len(root.children) != 3:
            raise ValueError("expected unrooted (trifurcating) tree after derooting")
        center = tree.new_inner()
        c0, c1, c2 = root.children
        hookup(center, build(c0), _z_of(c0, num_branches))
        hookup(center.next, build(c1), _z_of(c1, num_branches))
        hookup(center.next.next, build(c2), _z_of(c2, num_branches))
        tree._check_connected()
        return tree

    @classmethod
    def random(cls, taxon_names: Sequence[str], seed: int = 0,
               num_branches: int = 1) -> "Tree":
        """Stepwise random-addition topology (no likelihood): start from a
        3-taxon star, insert each remaining tip on a uniformly random branch."""
        rng = np.random.default_rng(seed)
        n = len(taxon_names)
        tree = cls(n, num_branches)
        order = rng.permutation(n) + 1
        center = tree.new_inner()
        hookup(center, tree.nodep[int(order[0])], tree.default_z())
        hookup(center.next, tree.nodep[int(order[1])], tree.default_z())
        hookup(center.next.next, tree.nodep[int(order[2])], tree.default_z())
        # Incremental branch list: each insertion splits one branch into
        # three, so the candidate set updates in O(1) instead of a full
        # all_branches() sweep — O(n) total, which is what makes the
        # reference-scale ~120k-taxon regime (SURVEY §6) reachable
        # (the O(n^2) sweep took hours at 50k taxa).
        branches = [(center, center.back),
                    (center.next, center.next.back),
                    (center.next.next, center.next.next.back)]
        for num in order[3:]:
            i = int(rng.integers(len(branches)))
            p, q = branches[i]
            inner = tree.new_inner()
            hookup(inner.next, p, p.z)
            hookup(inner.next.next, q, tree.default_z())
            tip = tree.nodep[int(num)]
            hookup(inner, tip, tree.default_z())
            branches[i] = (p, p.back)
            branches.append((q, q.back))
            branches.append((tip, tip.back))
        tree._check_connected()
        return tree

    def _check_connected(self) -> None:
        for num in range(1, self._next_inner):
            for slot in self.slots(num):
                assert slot.back is not None, f"dangling slot at node {num}"

    # -- traversal descriptors --------------------------------------------

    def compute_traversal(self, p: Node, full: bool) -> List[TraversalEntry]:
        """Post-order list of CLV updates so that slot p's CLV is valid.

        The top node p is ALWAYS recomputed (orientation flags do not track
        branch-length changes, so the point-of-use CLV must be refreshed);
        partial traversals prune only descendants whose x flag is already
        oriented correctly.  Exactly the reference `computeTraversalInfo`
        semantics (`newviewGenericSpecial.c:691-813`: children recurse only
        on `!x || !partialTraversal`, while p itself is appended
        unconditionally).

        Iterative post-order (explicit stack): the reference ambition is
        ~120k taxa (SURVEY §6), far beyond Python's recursion limit.
        """
        entries: List[TraversalEntry] = []
        # (slot, expanded?) — post-order via a two-visit stack.
        stack: List[Tuple[Node, bool]] = [(p, False)]
        top = True
        while stack:
            s, expanded = stack.pop()
            if self.is_tip(s.number):
                continue
            if expanded:
                q = s.next.back
                r = s.next.next.back
                entries.append(
                    TraversalEntry(s.number, q.number, r.number, q.z, r.z))
                self.orient(s)
                continue
            if not full and s.x and not top:
                continue
            top = False
            stack.append((s, True))
            stack.append((s.next.next.back, False))
            stack.append((s.next.back, False))
        return entries

    @staticmethod
    def schedule_waves(entries: List[TraversalEntry]) -> List[List[TraversalEntry]]:
        """Group a post-order traversal into dependency waves.

        Wave k contains entries whose children are tips, stale-free CLVs, or
        parents of waves < k (ASAP level scheduling).  All entries of one
        wave are independent, so the device executes them as one batched
        newview step — the TPU replacement for the reference's strictly
        sequential traversal replay (`newviewIterative`,
        `newviewGenericSpecial.c:917-1515`).
        """
        level: Dict[int, int] = {}
        waves: List[List[TraversalEntry]] = []
        for e in entries:
            lv = max(level.get(e.left, 0), level.get(e.right, 0))
            level[e.parent] = lv + 1
            if lv == len(waves):
                waves.append([])
            waves[lv].append(e)
        return waves

    def full_traversal(self) -> Tuple[Node, List[TraversalEntry]]:
        """Traversal making both ends of the branch at `start` valid."""
        p = self.start.back
        entries = self.compute_traversal(p, full=True)
        return p, entries

    def centroid_branch(self) -> Node:
        """A slot on the topological center branch of the tree.

        Rooting full traversals here minimizes the dependency depth of the
        wave schedule (≈ tree radius instead of height from an arbitrary
        tip), which on TPU sets the number of sequential newview steps —
        the analogue of picking a good virtual root, a freedom the
        reference's strictly sequential `newviewIterative` never needed.
        Classic double-BFS: the middle edge of a diameter path.
        """
        from collections import deque

        def bfs(src: Node):
            # Walk slots; returns (farthest tip number, parents map by id).
            dist = {src.number: 0}
            prev: Dict[int, int] = {}
            dq = deque([src])
            far = src
            while dq:
                s = dq.popleft()
                for slot in self.slots(s.number):
                    nb = slot.back
                    if nb is None or nb.number in dist:
                        continue
                    dist[nb.number] = dist[s.number] + 1
                    prev[nb.number] = s.number
                    if dist[nb.number] > dist[far.number]:
                        far = self.nodep[nb.number]
                    dq.append(self.nodep[nb.number])
            return far, dist, prev

        a, _, _ = bfs(self.start)
        b, dist, prev = bfs(a)
        # middle of the a->b path
        path = [b.number]
        while path[-1] != a.number:
            path.append(prev[path[-1]])
        mid = path[len(path) // 2]
        mid_next = path[max(len(path) // 2 - 1, 0)]
        # return the slot of `mid` whose back is `mid_next`
        for slot in self.slots(mid):
            if slot.back is not None and slot.back.number == mid_next:
                return slot
        return self.nodep[mid]

    def full_traversal_centroid(self) -> Tuple[Node, List[TraversalEntry]]:
        """Full traversal rooted at the centroid branch (minimum wave depth)."""
        s = self.centroid_branch()
        if self.is_tip(s.number):
            s = s.back
        self.invalidate_all()
        entries = self.compute_traversal(s, full=True)
        if not self.is_tip(s.back.number):
            entries += self.compute_traversal(s.back, full=True)
        return s, entries

    def reset_branches(self) -> None:
        """Set every branch back to the default length (reference
        `resetBranches`, `optimizeModel.c:2510-2530`)."""
        for p, _ in self.all_branches():
            p.z[:] = [DEFAULTZ] * len(p.z)
        self.invalidate_all()

    def invalidate_all(self) -> None:
        for num in range(self.ntips + 1, self._next_inner):
            for slot in self.slots(num):
                slot.x = False

    # -- enumeration -------------------------------------------------------

    def all_branches(self) -> List[Tuple[Node, Node]]:
        """Each branch once, as (slot, slot.back)."""
        out: List[Tuple[Node, Node]] = []
        seen = set()
        for num in range(1, self._next_inner):
            for slot in self.slots(num):
                if slot.back is None:
                    continue
                key = id(slot.z)
                if key in seen:
                    continue
                seen.add(key)
                out.append((slot, slot.back))
        return out

    def inner_numbers(self) -> List[int]:
        return list(range(self.ntips + 1, self._next_inner))

    # -- newick export -----------------------------------------------------

    def to_newick(self, taxon_names: Sequence[str], with_lengths: bool = True,
                  branch_index: int = 0) -> str:
        def t_of(z: float) -> float:
            return -np.log(min(max(z, ZMIN), ZMAX))

        def rec(slot: Node) -> NewickNode:
            # Iterative post-order build (tree height can exceed the
            # recursion limit at reference scale, SURVEY §6).
            top = NewickNode()
            stack = [(slot, top)]
            while stack:
                s, nw = stack.pop()
                if self.is_tip(s.number):
                    nw.name = taxon_names[s.number - 1]
                    continue
                for sl in (s.next, s.next.next):
                    child = NewickNode(length=t_of(sl.z[branch_index]))
                    nw.children.append(child)
                    stack.append((sl.back, child))
            return top

        # Standard unrooted export: trifurcation at start.back with the
        # starting tip as one child (reference Tree2String starts at
        # tr->start->back, `treeIO.c:324`).
        start = self.start           # tip 1
        root = NewickNode()
        inner = rec(start.back)
        root.children = [NewickNode(name=taxon_names[start.number - 1],
                                    length=t_of(start.z[branch_index]))]
        root.children.extend(inner.children)
        return format_newick(root, with_lengths=with_lengths)


def _z_of(nw: NewickNode, num_branches: int) -> List[float]:
    if nw.length is None:
        return [DEFAULTZ] * num_branches
    z = float(np.exp(-max(nw.length, 0.0)))
    z = min(max(z, ZMIN), ZMAX)
    return [z] * num_branches


def _deroot(root: NewickNode) -> NewickNode:
    """Collapse a bifurcating root into an unrooted trifurcation."""
    while len(root.children) == 2:
        a, b = root.children
        if a.is_leaf and b.is_leaf:
            raise ValueError("two-taxon tree is not supported")
        inner, other = (a, b) if not a.is_leaf else (b, a)
        ta = a.length or 0.0
        tb = b.length or 0.0
        other.length = ta + tb
        new_root = NewickNode(children=list(inner.children) + [other])
        root = new_root
    return root
