"""Unrooted binary tree with node-triple inner nodes and CLV orientation flags.

Host-side topology bookkeeping, the same data model as the reference
(ExaML `axml.h:492-506` `node`/`nodeptr`, `newviewGenericSpecial.c:691`
`computeTraversalInfo`): tips are numbered 1..n, inner nodes n+1..2n-2; an
inner node is a cycle of three slots (`next` pointers); each slot has a
`back` pointer across a branch; the `x` flag marks which of a cycle's slots
the node's single CLV is currently oriented towards (the CLV summarizes the
subtree away from that slot's `back`).

The device engine (ops/engine.py) never sees this structure — only flat
traversal descriptors produced here.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from examl_tpu.constants import DEFAULTZ, ZMAX, ZMIN
from examl_tpu.io.newick import NewickNode, format_newick, parse_newick


class Node:
    __slots__ = ("number", "back", "next", "z", "x")

    def __init__(self, number: int):
        self.number = number
        self.back: Optional[Node] = None
        self.next: Optional[Node] = None
        self.z: List[float] = []
        self.x: bool = False

    def __repr__(self):
        b = self.back.number if self.back else None
        return f"<Node {self.number} back={b} x={self.x}>"


_TOPO_CLOCK = [0]
"""Global monotonic topology clock: bumped by every `hookup` that
CHANGES a back pointer (pure branch-length rewrites of an existing
branch don't count).  Every topology mutation in the codebase — SPR
prune/regraft, NNI-style swaps, tree construction, snapshot restore —
passes through `hookup` with at least one changed back pointer, so a
tree whose traversal caches carry an unchanged clock value is
guaranteed structurally identical (the cheap validity check behind
`Tree.flat_full_traversal`'s host-side caching)."""


def hookup(p: Node, q: Node, z: Sequence[float]) -> None:
    """Connect two slots with a shared branch-length vector."""
    if p.back is not q or q.back is not p:
        _TOPO_CLOCK[0] += 1
    p.back = q
    q.back = p
    shared = [min(max(v, ZMIN), ZMAX) for v in z]
    p.z = shared
    q.z = shared


class TraversalEntry:
    """One inner-node CLV update: parent from (left, right) children."""
    __slots__ = ("parent", "left", "right", "zl", "zr")

    def __init__(self, parent: int, left: int, right: int,
                 zl: Sequence[float], zr: Sequence[float]):
        self.parent = parent
        self.left = left
        self.right = right
        self.zl = tuple(zl)
        self.zr = tuple(zr)

    def __repr__(self):
        return f"TE(p={self.parent},l={self.left},r={self.right})"


class FlatTraversal:
    """Array-form FULL traversal rooted at an edge (tentpole of the host-
    path scale work): entry i recomputes inner node ``parent[i]`` from
    children ``(left[i], right[i])`` with branch-length vectors
    ``zl[i]/zr[i]``.  Entries are wave-major (ASAP level order, exactly
    `Tree.schedule_waves` semantics) so consumers never re-derive the
    dependency structure.

    ``topo_key`` digests ONLY the structural arrays (parent/left/right
    + ntips) — it identifies the schedule STRUCTURE independent of
    branch lengths, which is what lets the engine cache the expensive
    chunk layout and refresh only z on repeated fixed-topology
    traversals (ops/engine.py sched cache).  The digest is 128-bit
    blake2b: self-validating, so SPR/NNI topology changes can never be
    served a stale structure even without an explicit invalidation
    call.
    """

    __slots__ = ("parent", "left", "right", "zl", "zr", "wave_sizes",
                 "n", "ntips", "topo_key", "_entries")

    def __init__(self, parent, left, right, zl, zr, wave_sizes,
                 ntips: int):
        import hashlib
        self.parent = parent          # [n] int64 node numbers
        self.left = left              # [n] int64
        self.right = right            # [n] int64
        self.zl = zl                  # [n, C] float64
        self.zr = zr                  # [n, C] float64
        self.wave_sizes = wave_sizes  # [n_waves] int64
        self.n = int(parent.shape[0])
        self.ntips = ntips
        h = hashlib.blake2b(digest_size=16)
        h.update(np.int64(ntips).tobytes())
        h.update(np.ascontiguousarray(parent, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(left, dtype=np.int64).tobytes())
        h.update(np.ascontiguousarray(right, dtype=np.int64).tobytes())
        self.topo_key = h.digest()
        self._entries: Optional[List[TraversalEntry]] = None

    def to_entries(self) -> List[TraversalEntry]:
        """Materialize as the legacy TraversalEntry list (scan-tier /
        PSR / SEV consumers).  Wave-major order is a valid post-order,
        so `Tree.schedule_waves` reproduces the identical waves.
        Memoized — multiple engines share one conversion."""
        if self._entries is None:
            zl = self.zl.tolist()
            zr = self.zr.tolist()
            self._entries = [
                TraversalEntry(p, l, r, a, b)
                for p, l, r, a, b in zip(self.parent.tolist(),
                                         self.left.tolist(),
                                         self.right.tolist(), zl, zr)]
        return self._entries

    def __len__(self) -> int:
        return self.n


class Tree:
    """Unrooted strictly-binary tree over tips 1..ntips."""

    def __init__(self, ntips: int, num_branches: int = 1):
        if ntips < 3:
            raise ValueError("need at least 3 taxa for an unrooted tree")
        self.ntips = ntips
        self.num_branches = num_branches
        self.nodep: Dict[int, Node] = {}          # canonical slot per number
        for i in range(1, ntips + 1):
            self.nodep[i] = Node(i)
        self._next_inner = ntips + 1
        # Host-side traversal caches, validated against _TOPO_CLOCK
        # (flat_full_traversal structures; the memoized centroid edge).
        self._flat_caches: Dict[int, dict] = {}
        self._centroid_cache: Optional[Tuple[int, Node]] = None

    # -- structure helpers -------------------------------------------------

    @property
    def max_nodes(self) -> int:
        return 2 * self.ntips - 2

    def is_tip(self, number: int) -> bool:
        return number <= self.ntips

    def new_inner(self) -> Node:
        """Allocate an inner node (cycle of three slots)."""
        num = self._next_inner
        if num > self.max_nodes:
            raise RuntimeError("inner node overflow")
        self._next_inner += 1
        a, b, c = Node(num), Node(num), Node(num)
        a.next, b.next, c.next = b, c, a
        self.nodep[num] = a
        return a

    def slots(self, number: int):
        p = self.nodep[number]
        if self.is_tip(number):
            return (p,)
        return (p, p.next, p.next.next)

    def default_z(self) -> List[float]:
        return [DEFAULTZ] * self.num_branches

    @property
    def start(self) -> Node:
        return self.nodep[1]

    def orient(self, p: Node) -> None:
        """Set the x flag of p's cycle onto slot p."""
        if self.is_tip(p.number):
            return
        p.x = True
        p.next.x = False
        p.next.next.x = False

    # -- construction ------------------------------------------------------

    @classmethod
    def from_newick(cls, text: str, taxon_names: Sequence[str],
                    num_branches: int = 1) -> "Tree":
        root = parse_newick(text)
        root = _deroot(root)
        name_to_num = {n: i + 1 for i, n in enumerate(taxon_names)}
        leaves = list(root.leaves())
        if len(leaves) != len(taxon_names):
            raise ValueError(
                f"tree has {len(leaves)} taxa, alignment has {len(taxon_names)}")
        tree = cls(len(taxon_names), num_branches)

        def build(nw: NewickNode) -> Node:
            """Return the slot representing subtree nw, to be hooked upward.

            Iterative post-order (results memoized by id) — reference-scale
            trees exceed the recursion limit (SURVEY §6)."""
            from examl_tpu.resilience import heartbeat
            done: Dict[int, Node] = {}
            stack: List[Tuple[NewickNode, bool]] = [(nw, False)]
            steps = 0
            while stack:
                steps += 1
                if not (steps & 0xFFFF):
                    # Liveness during a reference-scale (~120k taxon)
                    # build: a --supervise stall detector must see setup
                    # phases breathing, not just the search loop.
                    heartbeat.phase_beat("PARSE")
                n, expanded = stack.pop()
                if n.is_leaf:
                    try:
                        done[id(n)] = tree.nodep[name_to_num[n.name]]
                    except KeyError:
                        raise ValueError(f"taxon {n.name!r} not in alignment")
                    continue
                if len(n.children) != 2:
                    raise ValueError(
                        "multifurcating inner node (resolve first)")
                if not expanded:
                    stack.append((n, True))
                    stack.extend((c, False) for c in n.children)
                    continue
                inner = tree.new_inner()
                for slot, child in zip((inner.next, inner.next.next),
                                       n.children):
                    hookup(slot, done.pop(id(child)),
                           _z_of(child, num_branches))
                done[id(n)] = inner
            return done[id(nw)]

        if len(root.children) != 3:
            raise ValueError("expected unrooted (trifurcating) tree after derooting")
        center = tree.new_inner()
        c0, c1, c2 = root.children
        hookup(center, build(c0), _z_of(c0, num_branches))
        hookup(center.next, build(c1), _z_of(c1, num_branches))
        hookup(center.next.next, build(c2), _z_of(c2, num_branches))
        tree._check_connected()
        return tree

    @classmethod
    def random(cls, taxon_names: Sequence[str], seed: int = 0,
               num_branches: int = 1) -> "Tree":
        """Stepwise random-addition topology (no likelihood): start from a
        3-taxon star, insert each remaining tip on a uniformly random branch."""
        rng = np.random.default_rng(seed)
        n = len(taxon_names)
        tree = cls(n, num_branches)
        order = rng.permutation(n) + 1
        center = tree.new_inner()
        hookup(center, tree.nodep[int(order[0])], tree.default_z())
        hookup(center.next, tree.nodep[int(order[1])], tree.default_z())
        hookup(center.next.next, tree.nodep[int(order[2])], tree.default_z())
        # Incremental branch list: each insertion splits one branch into
        # three, so the candidate set updates in O(1) instead of a full
        # all_branches() sweep — O(n) total, which is what makes the
        # reference-scale ~120k-taxon regime (SURVEY §6) reachable
        # (the O(n^2) sweep took hours at 50k taxa).
        branches = [(center, center.back),
                    (center.next, center.next.back),
                    (center.next.next, center.next.next.back)]
        from examl_tpu.resilience import heartbeat
        for step, num in enumerate(order[3:]):
            if not (step & 0xFFFF):
                heartbeat.phase_beat("PARSE")
            i = int(rng.integers(len(branches)))
            p, q = branches[i]
            inner = tree.new_inner()
            hookup(inner.next, p, p.z)
            hookup(inner.next.next, q, tree.default_z())
            tip = tree.nodep[int(num)]
            hookup(inner, tip, tree.default_z())
            branches[i] = (p, p.back)
            branches.append((q, q.back))
            branches.append((tip, tip.back))
        tree._check_connected()
        return tree

    def _check_connected(self) -> None:
        for num in range(1, self._next_inner):
            for slot in self.slots(num):
                assert slot.back is not None, f"dangling slot at node {num}"

    # -- traversal descriptors --------------------------------------------

    def compute_traversal(self, p: Node, full: bool) -> List[TraversalEntry]:
        """Post-order list of CLV updates so that slot p's CLV is valid.

        The top node p is ALWAYS recomputed (orientation flags do not track
        branch-length changes, so the point-of-use CLV must be refreshed);
        partial traversals prune only descendants whose x flag is already
        oriented correctly.  Exactly the reference `computeTraversalInfo`
        semantics (`newviewGenericSpecial.c:691-813`: children recurse only
        on `!x || !partialTraversal`, while p itself is appended
        unconditionally).

        Iterative post-order (explicit stack): the reference ambition is
        ~120k taxa (SURVEY §6), far beyond Python's recursion limit.
        """
        entries: List[TraversalEntry] = []
        # (slot, expanded?) — post-order via a two-visit stack.
        stack: List[Tuple[Node, bool]] = [(p, False)]
        top = True
        while stack:
            s, expanded = stack.pop()
            if self.is_tip(s.number):
                continue
            if expanded:
                q = s.next.back
                r = s.next.next.back
                entries.append(
                    TraversalEntry(s.number, q.number, r.number, q.z, r.z))
                self.orient(s)
                continue
            if not full and s.x and not top:
                continue
            top = False
            stack.append((s, True))
            stack.append((s.next.next.back, False))
            stack.append((s.next.back, False))
        return entries

    @staticmethod
    def schedule_waves(entries: List[TraversalEntry]) -> List[List[TraversalEntry]]:
        """Group a post-order traversal into dependency waves.

        Wave k contains entries whose children are tips, stale-free CLVs, or
        parents of waves < k (ASAP level scheduling).  All entries of one
        wave are independent, so the device executes them as one batched
        newview step — the TPU replacement for the reference's strictly
        sequential traversal replay (`newviewIterative`,
        `newviewGenericSpecial.c:917-1515`).

        Large traversals (full-tree rebuilds at reference scale, SURVEY
        §6) take a vectorized path: level propagation runs as numpy
        scatter/gather per wave instead of a per-entry dict crawl, which
        is what keeps a 120k-taxon wave schedule at array rate.  The
        vectorized branch requires each parent to appear once (always
        true for full traversals); repeated parents — merged multi-root
        partial traversals (search/batchscan.py) — keep the loop, whose
        last-write-wins level semantics they rely on.
        """
        n = len(entries)
        if n >= 512:
            parent = np.fromiter((e.parent for e in entries), np.int64, n)
            uniq = np.unique(parent)
            if uniq.shape[0] == n:
                left = np.fromiter((e.left for e in entries), np.int64, n)
                right = np.fromiter((e.right for e in entries), np.int64, n)
                order, wave_sizes = _wave_order(parent, left, right)
                waves = []
                off = 0
                for w in wave_sizes:
                    waves.append([entries[i] for i in order[off:off + w]])
                    off += w
                return waves
        level: Dict[int, int] = {}
        waves: List[List[TraversalEntry]] = []
        for e in entries:
            lv = max(level.get(e.left, 0), level.get(e.right, 0))
            level[e.parent] = lv + 1
            if lv == len(waves):
                waves.append([])
            waves[lv].append(e)
        return waves

    def flat_full_traversal(self, p: Node) -> FlatTraversal:
        """Array-rate full traversal rooted at the edge (p, p.back).

        The vectorized replacement for the full-traversal branch of
        `compute_traversal` + `schedule_waves` + per-entry schedule
        assembly: ONE minimal Python pass extracts the pointer structure
        into numpy arrays (the unavoidable cost of leaving the
        reference's node-cycle data model), then rooting (frontier BFS),
        ASAP wave levels (Kahn), and entry assembly all run as array
        ops.  Equivalent to `invalidate_all()` followed by
        `compute_traversal(p, full=True)` + `compute_traversal(p.back,
        full=True)`: the same entry set, the same wave partition, and
        the same final x-flag orientation (every inner node oriented
        toward the root edge) — proven by tests/test_sched_cache.py.

        The structural result (rooting, wave order, child arrays) is a
        function of topology + root edge only, so it is cached on the
        tree and validated against the module topology clock (`hookup`
        bumps it on every back-pointer change): the branch-length-only
        traversals that dominate model optimization and makenewz rounds
        re-read just the z vectors and re-orient the x flags.
        """
        cache = self._flat_caches.get(id(p))
        if (cache is not None and cache["clock"] == _TOPO_CLOCK[0]
                and cache["root"] is p):
            return self._flat_from_cache(cache)
        cache = self._flat_build_cache(p)
        self._flat_caches[id(p)] = cache
        while len(self._flat_caches) > 4:
            self._flat_caches.pop(next(iter(self._flat_caches)))
        return self._flat_from_cache(cache)

    def _flat_build_cache(self, p: Node) -> dict:
        """The structural (topology + root only) half of a flat full
        traversal; everything here is skipped on a cache hit."""
        from examl_tpu.resilience import heartbeat

        ntips = self.ntips
        n_inner = self._next_inner - ntips - 1
        q = p.back
        heartbeat.phase_beat("SCHEDULE")
        # 1. Extraction: canonical slot triples -> neighbor numbers
        #    (tight loop, tiny body; flat int list -> one np.fromiter).
        nodep = self.nodep
        nb_flat: List[int] = []
        extend = nb_flat.extend
        slot0: List[Node] = []
        sappend = slot0.append
        for num in range(ntips + 1, self._next_inner):
            s0 = nodep[num]
            s1 = s0.next
            s2 = s1.next
            extend((s0.back.number, s1.back.number, s2.back.number))
            sappend(s0)
            if not (num & 0xFFFF):
                heartbeat.phase_beat("SCHEDULE")
        nb = np.fromiter(nb_flat, np.int64, 3 * n_inner).reshape(-1, 3)
        # 2. Rooting: frontier BFS from the edge endpoints assigns each
        #    inner node the slot index facing the root edge.
        parent_j = np.full(n_inner, -1, dtype=np.int64)
        init = []
        for s in (p, q):
            if s.number > ntips:
                i = s.number - ntips - 1
                c = nodep[s.number]
                j = 0 if s is c else (1 if s is c.next else 2)
                parent_j[i] = j
                init.append(i)
        frontier = np.asarray(init, dtype=np.int64)
        while frontier.size:
            k = frontier.shape[0]
            keep = np.ones((k, 3), dtype=bool)
            keep[np.arange(k), parent_j[frontier]] = False
            cand = nb[frontier][keep]                     # [2k] slot order
            m = cand > ntips
            new_nums = cand[m]
            if not new_nums.size:
                break
            new_idx = new_nums - ntips - 1
            par_nums = np.repeat(frontier + ntips + 1, 2)[m]
            parent_j[new_idx] = np.argmax(
                nb[new_idx] == par_nums[:, None], axis=1)
            frontier = new_idx
        assert (parent_j >= 0).all(), "tree not connected from root edge"
        # 3. Children in slot order from the parent-facing slot — exactly
        #    compute_traversal's (s.next.back, s.next.next.back).
        ar = np.arange(n_inner)
        lj = (parent_j + 1) % 3
        rj = (parent_j + 2) % 3
        left = nb[ar, lj]
        right = nb[ar, rj]
        parent_nums = ar + ntips + 1
        # 4. ASAP wave order (vectorized Kahn).
        order, wave_sizes = _wave_order(parent_nums, left, right)
        heartbeat.phase_beat("SCHEDULE")
        # 5. The z-read plan: the slot objects owning each sorted entry's
        #    two branch vectors (z lists may be REBOUND by hookup, so the
        #    cache holds the slots, not the lists).
        slot_at = {0: slot0, 1: [s.next for s in slot0],
                   2: [s.next.next for s in slot0]}
        ot = order.tolist()
        ljt = lj.tolist()
        rjt = rj.tolist()
        zl_slots = [slot_at[ljt[i]][i] for i in ot]
        zr_slots = [slot_at[rjt[i]][i] for i in ot]
        proto = FlatTraversal(parent_nums[order], left[order],
                              right[order],
                              np.ones((n_inner, self.num_branches)),
                              np.ones((n_inner, self.num_branches)),
                              wave_sizes, ntips)
        return {"clock": _TOPO_CLOCK[0], "root": p, "proto": proto,
                "slot0": slot0, "pj": parent_j.tolist(),
                "zl_slots": zl_slots, "zr_slots": zr_slots}

    def _flat_from_cache(self, cache: dict) -> FlatTraversal:
        """The per-call half: re-read branch vectors through the cached
        slot plan, re-orient the x flags, stamp fresh z arrays onto the
        cached structural prototype."""
        proto = cache["proto"]
        C = self.num_branches
        if C == 1:
            zl = np.fromiter((s.z[0] for s in cache["zl_slots"]),
                             np.float64, proto.n).reshape(-1, 1)
            zr = np.fromiter((s.z[0] for s in cache["zr_slots"]),
                             np.float64, proto.n).reshape(-1, 1)
        else:
            zl = np.asarray([s.z for s in cache["zl_slots"]], np.float64)
            zr = np.asarray([s.z for s in cache["zr_slots"]], np.float64)
        for s0, j in zip(cache["slot0"], cache["pj"]):
            s1 = s0.next
            s2 = s1.next
            s0.x = j == 0
            s1.x = j == 1
            s2.x = j == 2
        flat = FlatTraversal.__new__(FlatTraversal)
        flat.parent = proto.parent
        flat.left = proto.left
        flat.right = proto.right
        flat.zl = zl
        flat.zr = zr
        flat.wave_sizes = proto.wave_sizes
        flat.n = proto.n
        flat.ntips = proto.ntips
        flat.topo_key = proto.topo_key
        flat._entries = None
        return flat

    def full_traversal(self) -> Tuple[Node, List[TraversalEntry]]:
        """Traversal making both ends of the branch at `start` valid."""
        p = self.start.back
        entries = self.compute_traversal(p, full=True)
        return p, entries

    def centroid_branch(self) -> Node:
        """A slot on the topological center branch of the tree.

        Rooting full traversals here minimizes the dependency depth of the
        wave schedule (≈ tree radius instead of height from an arbitrary
        tip), which on TPU sets the number of sequential newview steps —
        the analogue of picking a good virtual root, a freedom the
        reference's strictly sequential `newviewIterative` never needed.
        Classic double-BFS: the middle edge of a diameter path.
        Memoized against the topology clock — the centroid is a function
        of topology alone, and the double-BFS is an interpreter-rate
        walk that would otherwise dominate every cached full traversal
        at reference scale.
        """
        from collections import deque

        if (self._centroid_cache is not None
                and self._centroid_cache[0] == _TOPO_CLOCK[0]):
            return self._centroid_cache[1]

        def bfs(src: Node):
            # Walk slots; returns (farthest tip number, parents map by id).
            dist = {src.number: 0}
            prev: Dict[int, int] = {}
            dq = deque([src])
            far = src
            while dq:
                s = dq.popleft()
                for slot in self.slots(s.number):
                    nb = slot.back
                    if nb is None or nb.number in dist:
                        continue
                    dist[nb.number] = dist[s.number] + 1
                    prev[nb.number] = s.number
                    if dist[nb.number] > dist[far.number]:
                        far = self.nodep[nb.number]
                    dq.append(self.nodep[nb.number])
            return far, dist, prev

        a, _, _ = bfs(self.start)
        b, dist, prev = bfs(a)
        # middle of the a->b path
        path = [b.number]
        while path[-1] != a.number:
            path.append(prev[path[-1]])
        mid = path[len(path) // 2]
        mid_next = path[max(len(path) // 2 - 1, 0)]
        # return the slot of `mid` whose back is `mid_next`
        out = self.nodep[mid]
        for slot in self.slots(mid):
            if slot.back is not None and slot.back.number == mid_next:
                out = slot
                break
        self._centroid_cache = (_TOPO_CLOCK[0], out)
        return out

    def full_traversal_centroid(self) -> Tuple[Node, List[TraversalEntry]]:
        """Full traversal rooted at the centroid branch (minimum wave depth)."""
        s = self.centroid_branch()
        if self.is_tip(s.number):
            s = s.back
        self.invalidate_all()
        entries = self.compute_traversal(s, full=True)
        if not self.is_tip(s.back.number):
            entries += self.compute_traversal(s.back, full=True)
        return s, entries

    def reset_branches(self) -> None:
        """Set every branch back to the default length (reference
        `resetBranches`, `optimizeModel.c:2510-2530`)."""
        for p, _ in self.all_branches():
            p.z[:] = [DEFAULTZ] * len(p.z)
        self.invalidate_all()

    def invalidate_all(self) -> None:
        for num in range(self.ntips + 1, self._next_inner):
            for slot in self.slots(num):
                slot.x = False

    # -- enumeration -------------------------------------------------------

    def all_branches(self) -> List[Tuple[Node, Node]]:
        """Each branch once, as (slot, slot.back)."""
        out: List[Tuple[Node, Node]] = []
        seen = set()
        for num in range(1, self._next_inner):
            for slot in self.slots(num):
                if slot.back is None:
                    continue
                key = id(slot.z)
                if key in seen:
                    continue
                seen.add(key)
                out.append((slot, slot.back))
        return out

    def inner_numbers(self) -> List[int]:
        return list(range(self.ntips + 1, self._next_inner))

    # -- newick export -----------------------------------------------------

    def to_newick(self, taxon_names: Sequence[str], with_lengths: bool = True,
                  branch_index: int = 0) -> str:
        def t_of(z: float) -> float:
            return -np.log(min(max(z, ZMIN), ZMAX))

        def rec(slot: Node) -> NewickNode:
            # Iterative post-order build (tree height can exceed the
            # recursion limit at reference scale, SURVEY §6).
            top = NewickNode()
            stack = [(slot, top)]
            while stack:
                s, nw = stack.pop()
                if self.is_tip(s.number):
                    nw.name = taxon_names[s.number - 1]
                    continue
                for sl in (s.next, s.next.next):
                    child = NewickNode(length=t_of(sl.z[branch_index]))
                    nw.children.append(child)
                    stack.append((sl.back, child))
            return top

        # Standard unrooted export: trifurcation at start.back with the
        # starting tip as one child (reference Tree2String starts at
        # tr->start->back, `treeIO.c:324`).
        start = self.start           # tip 1
        root = NewickNode()
        inner = rec(start.back)
        root.children = [NewickNode(name=taxon_names[start.number - 1],
                                    length=t_of(start.z[branch_index]))]
        root.children.extend(inner.children)
        return format_newick(root, with_lengths=with_lengths)


def _wave_order(parent: np.ndarray, left: np.ndarray,
                right: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized ASAP wave scheduling over entry arrays (parents must
    be unique).  Returns (order, wave_sizes): `order` lists entry
    indices wave-major, ascending within each wave — identical
    membership AND order to the dict-based `Tree.schedule_waves` on the
    same input.  Per-wave work is numpy scatter/gather, so the total
    cost is O(n) plus a small fixed overhead per wave (= schedule
    depth), instead of a per-entry interpreter crawl."""
    n = parent.shape[0]
    if n == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    maxnum = int(max(parent.max(), left.max(), right.max())) + 1
    pos = np.full(maxnum, -1, dtype=np.int64)
    pos[parent] = np.arange(n)
    li = pos[left]                    # entry computing the left child, -1
    ri = pos[right]                   # if the child is a tip / external
    remaining = (li >= 0).astype(np.int64) + (ri >= 0)
    # Reverse adjacency (entry -> dependents), grouped by sorting.
    child_idx = np.concatenate([li, ri])
    dep_entry = np.concatenate([np.arange(n), np.arange(n)])
    m = child_idx >= 0
    child_idx = child_idx[m]
    dep_entry = dep_entry[m]
    so = np.argsort(child_idx, kind="stable")
    child_sorted = child_idx[so]
    dep_sorted = dep_entry[so]
    starts = np.searchsorted(child_sorted, np.arange(n))
    ends = np.searchsorted(child_sorted, np.arange(n), side="right")
    order_parts: List[np.ndarray] = []
    wave_sizes: List[int] = []
    frontier = np.flatnonzero(remaining == 0)
    scheduled = 0
    while frontier.size:
        order_parts.append(frontier)
        wave_sizes.append(int(frontier.size))
        scheduled += int(frontier.size)
        counts = ends[frontier] - starts[frontier]
        total = int(counts.sum())
        if total == 0:
            break
        seg0 = np.cumsum(counts) - counts
        idx = (np.repeat(starts[frontier], counts)
               + np.arange(total) - np.repeat(seg0, counts))
        deps = dep_sorted[idx]
        np.subtract.at(remaining, deps, 1)
        cand = np.unique(deps)
        frontier = cand[remaining[cand] == 0]
    if scheduled != n:
        raise ValueError(
            f"cyclic or disconnected traversal: scheduled {scheduled} "
            f"of {n} entries")
    return np.concatenate(order_parts), np.asarray(wave_sizes, np.int64)


def _z_of(nw: NewickNode, num_branches: int) -> List[float]:
    if nw.length is None:
        return [DEFAULTZ] * num_branches
    z = float(np.exp(-max(nw.length, 0.0)))
    z = min(max(z, ZMIN), ZMAX)
    return [z] * num_branches


def _deroot(root: NewickNode) -> NewickNode:
    """Collapse a bifurcating root into an unrooted trifurcation."""
    while len(root.children) == 2:
        a, b = root.children
        if a.is_leaf and b.is_leaf:
            raise ValueError("two-taxon tree is not supported")
        inner, other = (a, b) if not a.is_leaf else (b, a)
        ta = a.length or 0.0
        tb = b.length or 0.0
        other.length = ta + tb
        new_root = NewickNode(children=list(inner.children) + [other])
        root = new_root
    return root
