"""Small shared helpers."""

from __future__ import annotations

from typing import Sequence

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (shape-bucketing helper: the dense and
    -S scan regions, wave widths, and chunk counts all bucket on it so
    recompilation stays O(log n))."""
    p = 1
    while p < n:
        p *= 2
    return p


def bucket_len(n: int) -> int:
    """Round a count up to a bucketed size: multiples of 4 up to 16,
    then geometric buckets with <=25% padding (n rounded up to a
    multiple of 2^(floor(log2 n) - 2)).  Shared by the engine's
    traversal-length bucketing and the fast path's scan-group lengths:
    O(log n) distinct compiled variants, bounded padding waste."""
    if n <= 16:
        return 4 * ((n + 3) // 4)
    step = next_pow2(n + 1) // 8
    return step * ((n + step - 1) // step)


def z_slots(z: "Sequence[float] | float", num_slots: int) -> np.ndarray:
    """Normalize a branch-length vector to [num_slots] float64.

    A scalar (or length-1 vector) broadcasts to every branch slot; longer
    vectors are truncated (a tree built with more slots than the instance
    uses).  The single source of truth for the reference's
    z[NUM_BRANCHES] handling (`axml.h:134`, branch vectors sized by
    numBranches but often written from scalars).
    """
    z = np.atleast_1d(np.asarray(z, dtype=np.float64))
    if len(z) == num_slots:
        return z
    if len(z) == 1:
        return np.full(num_slots, z[0])
    if len(z) > num_slots:
        return z[:num_slots]
    raise ValueError(f"branch vector length {len(z)} vs slots {num_slots}")
