"""Inference driver CLI — the counterpart of the reference `examl` binary.

Flag surface and output files mirror the reference driver (`examl/axml.c`:
`get_args` :935-1302, `printREADME` :777-900, `makeFileNames` :1316-1357;
modes dispatched at `main` :2719-2781):

  -s byteFile  -n runId  -t startTree | -R (restart from checkpoint)
  -m GAMMA|PSR  -a (median gamma)  -c #categories (PSR)
  -f d|o|e|E|q  -e lnL-epsilon  -i radius  -D (RF convergence)
  -B #best trees  -M (per-partition branches)  -S (memory saving)
  -w workdir  --auto-prot=ml|bic|aic|aicc

Outputs in workdir: ExaML_info.RUNID (config + progress),
ExaML_log.RUNID ("seconds lnL" rows), ExaML_result.RUNID (newick),
ExaML_modelFile.RUNID (final model parameters),
ExaML_TreeFile.RUNID (-f e/E per-tree results).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
import time


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="examl-tpu", description="TPU-native maximum-likelihood "
        "phylogenetic tree inference")
    ap.add_argument("-s", dest="bytefile", required=True,
                    help="binary alignment file from the parser "
                         "(PHYLIP also accepted)")
    ap.add_argument("-n", dest="run_id", required=True, help="run name")
    ap.add_argument("-t", dest="tree_file", default=None,
                    help="starting tree (newick)")
    ap.add_argument("-R", dest="restart", action="store_true",
                    help="restart from the newest checkpoint")
    ap.add_argument("-m", dest="model", default="GAMMA",
                    choices=["GAMMA", "PSR"], help="rate heterogeneity model")
    ap.add_argument("-a", dest="median", action="store_true",
                    help="median instead of mean discrete gamma rates")
    ap.add_argument("-c", dest="categories", type=int, default=25,
                    help="maximum PSR rate categories")
    ap.add_argument("-f", dest="mode", default="d",
                    choices=["d", "o", "e", "E", "q"], help="algorithm: "
                    "d/o tree search (o disables the lnL cutoff), "
                    "e/E evaluate trees (E re-optimizes the model per "
                    "tree), q quartets")
    ap.add_argument("-e", dest="epsilon", type=float, default=0.1,
                    help="lnL epsilon for quartet-mode model optimization "
                         "(the search and tree-evaluation modes use the "
                         "reference's fixed modOpt schedule)")
    ap.add_argument("-i", dest="initial", type=int, default=None,
                    help="fixed initial rearrangement radius")
    ap.add_argument("-D", dest="rf_convergence", action="store_true",
                    help="stop when consecutive SPR cycles are <=1%% RF "
                         "apart")
    ap.add_argument("-B", dest="save_best", type=int, default=0,
                    help="also report the N best distinct trees found")
    ap.add_argument("-M", dest="per_partition_bl", action="store_true",
                    help="estimate per-partition branch lengths")
    ap.add_argument("-S", dest="save_memory", action="store_true",
                    help="memory saving for gappy alignments")
    ap.add_argument("-w", dest="workdir", default=".",
                    help="output directory")
    ap.add_argument("-b", "--bootstrap", dest="bootstrap", type=int,
                    default=0, metavar="K",
                    help="fleet mode: evaluate K bootstrap weight "
                         "replicates of the -t topology (site-"
                         "multiplicity resampling, seeds derived from "
                         "-p; one shared CLV pass + a batched weight "
                         "matrix in the lnL reduction)")
    ap.add_argument("-N", "--multi-start", dest="multi_start", type=int,
                    default=0, metavar="K",
                    help="fleet mode: evaluate K random starting trees "
                         "(seeds derived from -p), batching same-"
                         "profile topologies through one vmapped "
                         "program; --fleet-cycles adds branch-length "
                         "smoothing rounds per tree")
    ap.add_argument("--serve", dest="serve", default=None, metavar="JOBS",
                    help="fleet mode: drain a JSONL jobs file "
                         "(fleet/jobs.py format), polling for appended "
                         "jobs until an {\"op\": \"stop\"} line; "
                         "--serve-poll 0 drains once and exits")
    ap.add_argument("--serve-poll", dest="serve_poll", type=float,
                    default=1.0,
                    help="seconds between jobs-file polls under --serve "
                         "(0 = drain current contents and exit; "
                         "default 1)")
    ap.add_argument("--serve-max-pending", dest="serve_max_pending",
                    type=int, default=10000,
                    help="admission control: stop consuming new jobs-"
                         "file lines while this many jobs are pending "
                         "(the queue drains, then ingestion resumes; "
                         "default 10000)")
    ap.add_argument("--fleet-job-attempts", dest="fleet_job_attempts",
                    type=int, default=2,
                    help="per-job attempt cap: a job whose dispatch "
                         "fails this many times (non-finite lnL, "
                         "dispatch error, blown deadline) is "
                         "quarantined to ExaML_fleetFailed.<run> "
                         "instead of retried (default 2)")
    ap.add_argument("--fleet-job-deadline", dest="fleet_job_deadline",
                    type=float, default=0.0,
                    help="wall-clock seconds one batched fleet dispatch "
                         "may take before a --supervise parent kills "
                         "the attempt as JOB-stuck (no run-level retry "
                         "consumed; repeat offenders quarantine).  "
                         "0 disables the per-job deadline (default)")
    ap.add_argument("--fleet-batch", dest="fleet_batch", type=int,
                    default=16,
                    help="max jobs per batched fleet dispatch "
                         "(padded to a power of two; default 16)")
    ap.add_argument("--fleet-cycles", dest="fleet_cycles", type=int,
                    default=1,
                    help="evaluation cycles per fleet job; cycles "
                         "after the first smooth branch lengths "
                         "before re-scoring (default 1)")
    ap.add_argument("--fleet-devices", dest="fleet_devices", type=int,
                    default=1,
                    help="tree-axis device sharding: cut one batch per "
                         "local device lane and round-robin the "
                         "profile groups across them (0 = every local "
                         "device; default 1 = classic single-lane; a "
                         "device that fails init degrades the set, "
                         "never aborts)")
    ap.add_argument("--fleet-lease-ttl", dest="fleet_lease_ttl",
                    type=float, default=60.0,
                    help="leased gang serving (--launch N + a fleet "
                         "mode): seconds a rank's job lease stays "
                         "live without renewal; a dead rank's leases "
                         "expire after this and surviving ranks reap "
                         "them (default 60)")
    ap.add_argument("--bank", dest="bank", action="store_true",
                    help="ahead-of-time program banking: compile every "
                         "device-program family this run will dispatch "
                         "in parallel killable subprocess workers at "
                         "startup (persistent host-fingerprinted cache); "
                         "a family whose compile exceeds "
                         "--compile-timeout is killed and the run "
                         "degrades to the scan tier instead of wedging")
    ap.add_argument("--compile-timeout", dest="compile_timeout",
                    type=float, default=180.0,
                    help="per-family compile deadline in seconds: hard "
                         "(kill + scan-tier fallback) for --bank "
                         "workers, watchdog-bark threshold for any "
                         "in-process compile (default 180)")
    ap.add_argument("--launch", dest="launch", type=int, default=None,
                    metavar="N",
                    help="gang mode: the supervisor spawns all N ranks "
                         "itself (per-rank EXAML_PROCID, killable "
                         "process groups, local coordinator), watches "
                         "the per-rank heartbeats, and on any rank "
                         "death / single-rank straggler / collective "
                         "wedge kills and restarts the WHOLE gang from "
                         "the newest coordinated checkpoint "
                         "(--supervise-* flags apply gang-wide); a rank "
                         "that keeps dying shrinks the gang to N-1 "
                         "(elastic resume, down to --launch-min-ranks)")
    ap.add_argument("--launch-emulate", dest="launch_emulate",
                    action="store_true",
                    help="spawn the --launch gang WITHOUT a jax "
                         "distributed process group (N independent "
                         "single-process ranks honoring the same "
                         "rank/heartbeat/checkpoint contract) — for "
                         "backends without multi-process collectives "
                         "and for chaos tests")
    ap.add_argument("--launch-min-ranks", dest="launch_min_ranks",
                    type=int, default=1,
                    help="elastic-resume floor: never shrink the gang "
                         "below this many ranks (default 1)")
    ap.add_argument("--supervise", dest="supervise", action="store_true",
                    help="self-healing supervision: run the search as a "
                         "killable child, watch its search-loop "
                         "heartbeat, and on crash/stall restart from "
                         "the newest checkpoint with capped retries, "
                         "backoff and escalating degradation pins "
                         "(pallas->chunk->scan); SIGTERM/SIGINT "
                         "preemptions resume without consuming a retry")
    ap.add_argument("--supervise-retries", dest="supervise_retries",
                    type=int, default=3,
                    help="max failure restarts under --supervise "
                         "(preemption resumes are not counted; "
                         "default 3)")
    ap.add_argument("--supervise-stall", dest="supervise_stall",
                    type=float, default=300.0,
                    help="seconds without a search-loop heartbeat "
                         "before the supervisor declares a dispatch/"
                         "collective wedge and kills the child "
                         "(default 300; 0 disables stall detection)")
    ap.add_argument("--supervise-backoff", dest="supervise_backoff",
                    type=float, default=2.0,
                    help="base seconds for the supervisor's exponential "
                         "restart backoff (default 2)")
    ap.add_argument("--inject-fault", dest="inject_fault",
                    action="append", metavar="SPEC", default=None,
                    help="arm a named fault-injection point (repeatable; "
                         "resilience/faults.py): "
                         "point[@rank=R][:after=N][:attempt=K]"
                         "[:signal=NAME][:hang[=S]] — e.g. "
                         "search.kill:after=10 or "
                         "search.kill@rank=1:after=10 (gang rank 1 "
                         "only); equivalent to EXAML_FAULTS entries")
    ap.add_argument("--profile", dest="profile_dir", default=None,
                    help="write a jax profiler trace to this directory "
                         "(SURVEY §5.1; view with xprof/tensorboard)")
    ap.add_argument("--metrics", dest="metrics_file", default=None,
                    help="write the runtime metrics-registry snapshot "
                         "(dispatch/compile/cache counters, phase timers) "
                         "to this JSON file at exit (process 0 only)")
    ap.add_argument("--trace-events", dest="trace_events_dir", default=None,
                    help="write Chrome-trace/Perfetto span events to "
                         "per-process JSONL files in this directory "
                         "(trace.p<procid>.jsonl; open in ui.perfetto.dev)")
    ap.add_argument("--ledger", dest="ledger_dir", default=None,
                    help="write the append-only run ledger (compiles, "
                         "phases, faults, checkpoint cycles, supervisor "
                         "decisions) to per-rank JSONL files in this "
                         "directory (ledger.p<procid>.jsonl; rank 0 "
                         "merges ledger.merged.jsonl at exit).  Defaults "
                         "to the --metrics file's directory when "
                         "--metrics is given")
    ap.add_argument("-g", dest="constraint_file", default=None,
                    help="multifurcating constraint tree")
    ap.add_argument("-p", dest="seed", type=int, default=12345,
                    help="random seed (constraint-tree resolution)")
    ap.add_argument("-Y", "-Q", dest="quartet_file", default=None,
                    help="quartet grouping file (-f q; the reference "
                         "spells this -Y, axml.c:1063 — -Q kept as an "
                         "alias for earlier revisions of this CLI)")
    ap.add_argument("-r", dest="quartet_samples", type=int, default=0,
                    help="number of random quartets to evaluate (-f q)")
    ap.add_argument("-I", dest="quartet_ckpt_interval", type=int,
                    default=10000,
                    help="quartet checkpoint interval (-f q)")
    ap.add_argument("--auto-prot", dest="auto_prot", default="ml",
                    choices=["ml", "bic", "aic", "aicc"],
                    help="criterion for AUTO protein model selection")
    from examl_tpu.parallel.launch import add_launch_args
    add_launch_args(ap)
    return ap


class RunFiles:
    """Rank-0 output files (reference `makeFileNames`/`printBothOpen`).

    On a -R restart, existing info/log files are appended to, preserving
    the interrupted run's history (the reference appends likewise)."""

    def __init__(self, workdir: str, run_id: str, append: bool = False,
                 primary: bool = True):
        """primary=False (non-zero process of a multi-host job) computes
        the same SPMD program but writes NO output files — the
        reference's processID==0 gating (`axml.c`, every print site)."""
        self.primary = primary
        os.makedirs(workdir, exist_ok=True)
        pre = os.path.join(workdir, "ExaML_")
        self.info_path = f"{pre}info.{run_id}"
        self.log_path = f"{pre}log.{run_id}"
        self.result_path = f"{pre}result.{run_id}"
        self.model_path = f"{pre}modelFile.{run_id}"
        self.treefile_path = f"{pre}TreeFile.{run_id}"
        self.quartets_path = f"{pre}quartets.{run_id}"
        self.start_time = time.time()
        self._phases = {}
        if not append and primary:
            for p in (self.info_path, self.log_path):
                open(p, "w").close()

    def info(self, msg: str) -> None:
        if not self.primary:
            return
        print(msg)
        with open(self.info_path, "a") as f:
            f.write(msg + "\n")

    # -- per-phase wall-time accounting (SURVEY §5.1: the reference has
    # only gettime()/accumulatedTime; phase times feed the metrics
    # registry as `phase.<name>` timers and emit trace spans, so the
    # info-file report, --metrics, and --trace-events share one record) --

    @contextlib.contextmanager
    def phase(self, name: str):
        from examl_tpu import obs
        t0 = time.time()
        obs.ledger_event("phase", name=name, status="begin")
        try:
            with obs.span(f"phase:{name}", cat="phase"):
                yield
        finally:
            dt = time.time() - t0
            self._phases[name] = self._phases.get(name, 0.0) + dt
            obs.observe(f"phase.{name}", dt)
            obs.ledger_event("phase", name=name, status="end",
                             seconds=round(dt, 3))

    def report_phases(self) -> None:
        # This instance's phases, merged with any `phase.*` timers other
        # components recorded straight into the registry.
        phases = dict(self._phases)
        try:
            from examl_tpu import obs
            for name, t in obs.snapshot().get("timers", {}).items():
                if name.startswith("phase.") and name[6:] not in phases:
                    phases[name[6:]] = t["total_s"]
        except Exception:
            pass
        if not phases:
            return
        total = time.time() - self.start_time
        self.info("")
        self.info("Wall-clock by phase:")
        for name, dt in phases.items():
            # Guard total == 0: a run whose phases are all ~0 s (mocked
            # clocks, sub-tick runs) must report, not ZeroDivisionError.
            pct = 100.0 * dt / total if total > 0 else 0.0
            self.info(f"  {name:24s} {dt:10.2f} s  ({pct:5.1f}%)")
        self.info(f"  {'total':24s} {total:10.2f} s")

    def log_lnl(self, lnl: float) -> None:
        if not self.primary:
            return
        with open(self.log_path, "a") as f:
            f.write(f"{time.time() - self.start_time:.6f} {lnl:.6f}\n")

    def write_result(self, text: str) -> None:
        if not self.primary:
            return
        with open(self.result_path, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")


def write_model_params(path: str, inst) -> None:
    """Final model parameters (reference `printModelParams`,
    `axml.c:1733-1835`)."""
    with open(path, "w") as f:
        for gid, (part, m) in enumerate(
                zip(inst.alignment.partitions, inst.models)):
            name = inst.auto_prot_models.get(gid, part.model_name)
            f.write(f"Partition: {gid} {part.name}\n")
            f.write(f"DataType: {part.datatype.name}\n")
            f.write(f"Substitution model: {name}\n")
            if getattr(inst, "psr", False):
                psr = inst.per_site_rates[gid]
                f.write(f"categories: {len(psr)}\n")
                f.write("category rates: "
                        + " ".join(f"{r:.6f}" for r in psr) + "\n")
            else:
                f.write(f"alpha: {m.alpha:.6f}\n")
            f.write("rates: " + " ".join(f"{r:.6f}" for r in m.rates) + "\n")
            f.write("freqs: " + " ".join(f"{x:.6f}" for x in m.freqs) + "\n")
            f.write("\n")


def selective_read_decision(model: str, is_bytefile: bool,
                            has_auto_aa: bool, nprocs: int,
                            save_memory: bool = False):
    """("slice" | "whole" | "error"), reason — the per-process data-
    loading policy, pure so it is unit-testable without a process group:

    * "slice": each process seeks only its site blocks (readMyData,
      `byteFile.c:278-382`) — including -m PSR, whose per-site rate
      state stays host-global via allgathers (engine.rate_scan output;
      one weight-window gather, instance.psr_packed_weights — the
      reference's CAT Gatherv/Scatterv, `optimizeModel.c:2135-2254`,
      as collectives);
    * "whole": every process reads the full file (single-process jobs;
      AUTO-protein partitions, whose BIC/AICc sample sizes must be
      global; non-byteFile inputs);
    * "error": currently unreachable — kept for future hard
      incompatibilities so callers keep handling it.
    """
    if nprocs <= 1:
        return "whole", "single process"
    if not is_bytefile:
        return "whole", "input is not a byteFile"
    if has_auto_aa:
        return "whole", ("AUTO protein model selection needs global "
                         "sample sizes")
    note = ""
    if model == "PSR":
        note = " (-m PSR rate state allgathers to every process)"
    if save_memory:
        note += " (-S gap bookkeeping follows the window)"
    return "slice", "selective byteFile read" + note


def _is_bytefile(path: str) -> bool:
    from examl_tpu.io.bytefile import BYTEFILE_MAGIC
    import struct
    with open(path, "rb") as f:
        head = f.read(12)
    return (len(head) == 12
            and struct.unpack("<iii", head)[2] == BYTEFILE_MAGIC)


def _load_alignment(path: str, local_window=None, block_multiple: int = 1):
    """Full read, or — in a multi-process job — only this process's site
    columns (reference per-rank loading, `byteFile.c:278-382`)."""
    if _is_bytefile(path):
        if local_window is not None:
            from examl_tpu.io.bytefile import read_bytefile_for_process
            procid, nprocs = local_window
            return read_bytefile_for_process(path, procid, nprocs,
                                             block_multiple=block_multiple)
        from examl_tpu.io.bytefile import read_bytefile
        return read_bytefile(path)
    from examl_tpu.io.alignment import load_alignment
    return load_alignment(path)             # convenience: raw PHYLIP, DNA


def _read_trees(path: str):
    with open(path) as f:
        text = f.read()
    return [t.strip() + ";" for t in text.split(";") if t.strip()]


def _checkpoint_manager(args, **kwargs):
    """The run's CheckpointManager: gang ranks (`--launch N`) share the
    two-phase manager over the ORIGINAL workdir — rank>0's output files
    are diverted to per-rank scratch, but checkpoint cycles must stage
    and publish in ONE directory or the commit protocol has nothing to
    coordinate."""
    from examl_tpu.search.checkpoint import CheckpointManager
    gang = getattr(args, "_gang", None)
    if gang is not None:
        rank, size, shared = gang
        return CheckpointManager(shared, args.run_id, gang_rank=rank,
                                 gang_size=size, **kwargs)
    return CheckpointManager(args.workdir, args.run_id, **kwargs)


def run_search(args, inst, files: RunFiles) -> int:
    from examl_tpu.search.convergence import RfConvergence
    from examl_tpu.search.raxml_search import (SearchOptions,
                                               compute_big_rapid)

    mgr = _checkpoint_manager(args)
    resume = None
    constraint = None
    if args.restart:
        tree = inst.random_tree(seed=args.seed)     # overwritten by restore
        resume = mgr.restore(inst, tree)
        if resume is None:
            files.info("no checkpoint found; cannot restart")
            return 1
        files.info(f"restart from state {resume['state']} with likelihood "
                   f"{inst.likelihood:.6f}")
        if args.constraint_file:
            # Keep enforcing the constraint after the restart (the
            # restored tree already honors it; only the checker is
            # rebuilt — the random resolution is NOT redone).
            from examl_tpu.tree.constraint import load_constraint
            with open(args.constraint_file) as f:
                _, constraint = load_constraint(
                    f.read(), inst.alignment.taxon_names, args.seed,
                    inst.num_branch_slots)
            constraint._tree = tree
    elif args.constraint_file:
        from examl_tpu.tree.constraint import load_constraint
        with open(args.constraint_file) as f:
            tree, constraint = load_constraint(
                f.read(), inst.alignment.taxon_names, args.seed,
                inst.num_branch_slots)
        inst.evaluate(tree, full=True)
        files.info(f"constraint tree randomly resolved (seed {args.seed}), "
                   f"lnL {inst.likelihood:.6f}")
    else:
        if not args.tree_file:
            files.info("a starting tree (-t), a constraint tree (-g), or "
                       "-R is required for the tree search")
            return 1
        tree = inst.tree_from_newick(_read_trees(args.tree_file)[0])
        inst.evaluate(tree, full=True)
        files.info(f"starting tree lnL {inst.likelihood:.6f}")
    files.log_lnl(inst.likelihood)

    def log(msg: str) -> None:
        files.info(msg)
        files.log_lnl(inst.likelihood)

    opts = SearchOptions(
        initial=args.initial if args.initial is not None else 10,
        initial_set=args.initial is not None,
        save_best_trees=args.save_best,
        constraint=constraint,
        do_cutoff=args.mode != "o",
        search_convergence=args.rf_convergence,
        log=log)
    from examl_tpu.search.spr import batched_scan_enabled
    files.info("SPR lazy-arm scan: "
               + ("batched (one dispatch per pruned node)"
                  if batched_scan_enabled(inst) else "sequential"))
    conv = (RfConvergence(inst.alignment.ntaxa, log=files.info)
            if args.rf_convergence else None)
    if conv is not None and resume is not None:
        blob = resume.get("extras", {}).get("rf_history")
        if blob:
            conv.load_blob(blob)
            files.info("restored RF-convergence history from checkpoint")
    inner_cb = mgr.callback(inst, tree)

    def checkpoint_cb(state: str, extras: dict) -> None:
        # Persist the -D convergence evidence with every checkpoint so a
        # restart keeps comparing against the pre-restart cycle's tree
        # (reference restores this via stored newick strings,
        # `restartHashTable.c:279-357`).
        if conv is not None:
            extras = dict(extras, rf_history=conv.to_blob())
        inner_cb(state, extras)
        # Preemption cadence: the checkpoint just written is coherent,
        # so a pending SIGTERM/SIGINT exits resumable HERE (raises
        # PreemptCheckpointed -> EXIT_PREEMPTED in main).
        from examl_tpu.resilience import preempt
        preempt.check_after_checkpoint(log=files.info)

    res = compute_big_rapid(inst, tree, opts, convergence_cb=conv,
                            checkpoint_cb=checkpoint_cb,
                            resume=resume)

    files.info(f"Likelihood of best tree: {res.likelihood:.6f}")
    files.write_result(tree.to_newick(inst.alignment.taxon_names))
    if files.primary:       # processID==0 gating (axml.c, every output)
        _write_per_gene_trees(args, inst, tree, files)
        write_model_params(files.model_path, inst)
    if res.good_trees and files.primary:
        good = os.path.join(args.workdir,
                            f"ExaML_goodTrees.{args.run_id}")
        with open(good, "w") as f:
            for snap in res.good_trees:
                snap.restore_into(tree)
                f.write(tree.to_newick(inst.alignment.taxon_names) + "\n")
        files.info(f"{len(res.good_trees)} other good trees written to "
                   f"{good}")
    return 0


def _write_per_gene_trees(args, inst, tree, files: RunFiles) -> None:
    """Under -M, write one tree per partition with that partition's own
    branch lengths (reference `printTreePerGene`, `treeIO.c:348`)."""
    if not args.per_partition_bl:
        return
    path = os.path.join(args.workdir,
                        f"ExaML_perGeneBranchLengths.{args.run_id}")
    with open(path, "w") as f:
        for gid, part in enumerate(inst.alignment.partitions):
            f.write(f"[partition {gid} {part.name}]\n")
            f.write(tree.to_newick(inst.alignment.taxon_names,
                                   branch_index=gid) + "\n")
    files.info(f"Per-partition branch-length trees written to {path}")


def run_fleet(args, inst, files: RunFiles) -> int:
    """Fleet modes (-b K / -N K / --serve): the profile-grouped batched
    job queue (examl_tpu/fleet/driver.py) with per-job checkpoints and
    `-R` resume through the normal CheckpointManager stack, job-level
    fault domains (retry/quarantine, fleet/quarantine.py) and a
    durable per-job results journal reconciled at resume."""
    from examl_tpu.fleet import jobs as jobs_mod
    from examl_tpu.fleet import lease as lease_mod
    from examl_tpu.fleet import quarantine
    from examl_tpu.fleet.driver import FleetDriver

    # Leased gang serving (ISSUE 14): under `--launch N` (or the
    # manually-launched rank contract) every rank runs its OWN driver
    # against the shared workdir — jobs are held under durable per-rank
    # leases, results journal per rank, and there are NO coordinated
    # checkpoints (fleet ranks are deliberately not in lockstep; the
    # per-job fsync'd journal is the durable record).
    gang = getattr(args, "_gang", None)
    rank, world, shared_dir = (gang if gang is not None
                               else (0, 1, args.workdir))
    leased = gang is not None
    board = None
    peer_journals = None
    if leased:
        mgr = None
        board = lease_mod.LeaseBoard(
            lease_mod.lease_dir(shared_dir, args.run_id), rank,
            ttl_s=args.fleet_lease_ttl,
            attempt=int(os.environ.get("EXAML_RESTART_COUNT", "0") or 0))
        # Incremental tail reads: the absorb loop polls these journals
        # for the rank's whole life, so each poll parses only appended
        # records, not every journal from byte 0.
        peer_journals = quarantine.JournalTail(shared_dir,
                                               args.run_id).records
        files.info(f"fleet: leased serving rank {rank} of {world} "
                   f"(lease board {board.path}, ttl "
                   f"{args.fleet_lease_ttl:.0f}s)")
    else:
        mgr = _checkpoint_manager(args, keep_last=2)
    journal = quarantine.ResultsJournal(quarantine.journal_path(
        shared_dir, args.run_id, rank if leased else None))
    deadletters = quarantine.DeadLetters(os.path.join(
        shared_dir, f"ExaML_fleetFailed.{args.run_id}"
        + (f".r{rank}" if leased else "")))
    if not args.restart:
        # A FRESH run (no -R) reusing a run id must not inherit an
        # abandoned incarnation's journal/dead letters: `-R` later
        # would reconcile the OLD records as done and silently skip
        # jobs whose inputs changed.  Checkpoints rotate via keep_last;
        # these files are removed so they exist only once this
        # incarnation appends (the supervisor keys its automatic -R on
        # that existence).
        stale_files = [journal.path, deadletters.path]
        if leased and rank == 0:
            # The primary also clears records NO rank of this world
            # will write (so they cannot race a live writer): the
            # BASE (single-process) journal/dead letters a previous
            # unleased incarnation left, and rank journals beyond the
            # current world size.  Peers' own `.r<k>` files are each
            # rank's own fresh-run cleanup.
            import glob as _glob
            for pat in (f"ExaML_fleetJournal.{args.run_id}",
                        f"ExaML_fleetFailed.{args.run_id}"):
                stale_files.append(os.path.join(shared_dir, pat))
                for p in _glob.glob(os.path.join(shared_dir,
                                                 pat + ".r*")):
                    try:
                        r = int(p.rsplit(".r", 1)[1])
                    except ValueError:
                        continue
                    if r >= world:
                        stale_files.append(p)
        for stale in stale_files:
            try:
                os.unlink(stale)
            except OSError:
                pass
    policy = quarantine.JobFaultPolicy(
        max_attempts=args.fleet_job_attempts,
        deadline_s=args.fleet_job_deadline)
    start_tree = None
    if args.tree_file:
        start_tree = inst.tree_from_newick(_read_trees(args.tree_file)[0])
        inst.evaluate(start_tree, full=True)
        files.info(f"starting tree lnL {inst.likelihood:.6f}")
        files.log_lnl(inst.likelihood)
    resume = None
    if args.restart and leased:
        # Leased ranks resume from the MERGED per-rank journals alone
        # (no coordinated checkpoints exist on purpose); a restarted
        # rank with no evidence yet — it died before any rank finished
        # a job — simply starts serving against the lease board.
        journal_recs = quarantine.read_all_journals(shared_dir,
                                                    args.run_id)
        resume = quarantine.reconcile_extras({}, journal_recs)
        files.info(f"restart (leased rank {rank}): "
                   f"{len(journal_recs)} journal record(s) reconciled "
                   "across ranks")
    elif args.restart:
        scaffold = (start_tree if start_tree is not None
                    else inst.random_tree(seed=args.seed))
        # GC-ordering contract: the journal is read and reconciled
        # HERE, strictly before the driver's first checkpoint write —
        # the only place keep_last pruning runs — and the journal /
        # dead-letter files never match the checkpoint glob, so a
        # concurrent-looking resume can never have its evidence
        # collected out from under it (tests/test_quarantine.py pins
        # both properties).
        res = mgr.restore(inst, scaffold)
        journal_recs = journal.read()
        if res is not None and res["state"] != "FLEET":
            files.info(f"checkpoint state {res['state']} is not a fleet "
                       "checkpoint")
            return 1
        if res is None and not journal_recs:
            if os.path.exists(journal.path):
                # A journal that exists but yields no intact record (a
                # kill inside the very first append): nothing finished,
                # so a fresh start IS the correct resume.
                files.info("no checkpoint and no intact journal "
                           "record; starting the fleet from scratch")
            else:
                files.info("no checkpoint found; cannot restart")
                return 1
        # Journal ∪ checkpoint: a SIGKILL between a batch and its
        # checkpoint must not replay the batch's finished jobs — the
        # journal (written per job, fsync'd) is the fresher record.
        resume = quarantine.reconcile_extras(
            res["extras"] if res is not None else {}, journal_recs)
        files.info(
            "restart from fleet "
            + ("checkpoint" if res is not None else "results journal")
            + (f" (+ {len(journal_recs)} journal record(s) reconciled)"
               if journal_recs and res is not None else ""))
    # Zero-recompile serving: under --serve (a long-lived process that
    # keeps meeting novel topology profiles) tree jobs route through
    # the universal interpreter by default; finite -b/-N batches keep
    # the specialized batched tier (their profiles amortize).
    # EXAML_FLEET_UNIVERSAL=1 forces routing everywhere, =0 disables.
    _uni_env = os.environ.get("EXAML_FLEET_UNIVERSAL", "")
    route_universal = (_uni_env == "1"
                       or (bool(args.serve) and _uni_env != "0"))
    driver = FleetDriver(inst, start_tree=start_tree,
                         batch_cap=args.fleet_batch,
                         cycles=args.fleet_cycles, mgr=mgr,
                         log=files.info, policy=policy,
                         journal=journal, deadletters=deadletters,
                         route_universal=route_universal,
                         devices=args.fleet_devices,
                         leases=board, peer_journals=peer_journals)
    if board is not None:
        # Keepalive: a long blocking dispatch (a cold first-call
        # compile easily outlasts any sane ttl) must not let this
        # rank's leases expire under it.
        board.start_keepalive()
    try:
        if args.serve:
            jobs = _serve_loop(args, driver, files, resume)
        else:
            if args.bootstrap:
                jobs = jobs_mod.make_jobs("bootstrap", args.bootstrap,
                                          args.seed, cycles=1)
                files.info(f"fleet: {len(jobs)} bootstrap replicates "
                           "of the starting topology")
                if args.fleet_cycles > 1:
                    files.info("note: --fleet-cycles applies to tree "
                               "jobs; bootstrap replicates are "
                               "weights-only (always 1 cycle)")
            else:
                jobs = jobs_mod.make_jobs("start", args.multi_start,
                                          args.seed,
                                          cycles=args.fleet_cycles)
                files.info(f"fleet: {len(jobs)} multi-start trees, "
                           f"{args.fleet_cycles} cycle(s) each")
            jobs = driver.run(jobs, resume)
    finally:
        if board is not None:
            # Release whatever this rank still holds (a stop sentinel
            # with jobs in retry backoff, an exception): leases left
            # behind would make peers wait out the ttl for jobs nobody
            # owns.
            board.close()
        journal.close()
    return _write_fleet_results(args, inst, files, jobs)


def _reject_job(files: RunFiles, job_id, reason: str) -> None:
    """Admission rejection: ledger event + counter + operator line —
    the driver never sees the spec, so a rejected job can neither
    crash the loop nor occupy the queue."""
    from examl_tpu import obs
    obs.inc("fleet.rejected")
    obs.ledger_event("job.rejected", job=job_id, reason=reason[:200])
    files.info(f"fleet: job "
               + (f"{job_id!r} " if job_id else "")
               + f"REJECTED at admission ({reason})")


def _serve_loop(args, driver, files: RunFiles, resume):
    """Drain + poll the jobs file until a stop sentinel (or, with
    --serve-poll 0, until the current contents are drained).  Jobs are
    addressed by line index, so appends never re-seed earlier jobs and
    a resume re-parses the whole file and skips finished ones.

    ADMISSION CONTROL: specs that parse but cannot run (bad tree
    strings, taxa mismatch vs the alignment, duplicate ids, malformed
    lines) are rejected with a `job.rejected` event instead of joining
    the queue, and ingestion pauses — `--serve-max-pending` — while the
    pending queue is full, so a runaway producer bounds memory instead
    of growing the job table without limit."""
    from examl_tpu import obs
    from examl_tpu.fleet import quarantine
    from examl_tpu.fleet.jobs import parse_jobs_lines
    from examl_tpu.resilience import heartbeat, preempt

    max_pending = max(1, int(getattr(args, "serve_max_pending", 10000)))
    processed = 0
    stop = False
    torn_prev = None
    driver.jobs = []
    while True:
        try:
            with open(args.serve) as f:
                lines = f.readlines()
        except OSError as exc:
            files.info(f"fleet: jobs file unreadable ({exc}); stopping")
            break
        # A producer appending non-atomically can leave a torn final
        # line (no trailing newline): leave it unconsumed until the
        # next poll completes it.  A line UNCHANGED across two polls is
        # taken as complete — a producer that stops mid-write forever
        # (or writes its last line via `echo -n`, stop sentinel
        # included) must not starve the queue.  In drain-once mode
        # (poll <= 0) no more appends are coming, so take it as is.
        if lines and args.serve_poll > 0 and not lines[-1].endswith("\n"):
            if lines[-1] != torn_prev:
                torn_prev = lines[-1]
                lines = lines[:-1]
        else:
            torn_prev = None
        # Bounded pending queue (--serve-max-pending): consume at most
        # `budget` new jobs per poll; the rest of the file (line
        # indexing keeps the derived seeds stable) re-parses once the
        # queue drains.  The budget subtracts live pending jobs
        # defensively — today drain() empties the queue before each
        # poll, so the bound is enforced by the per-poll cut alone.
        budget = max_pending - len(driver.pending())
        if len(lines) > processed and budget > 0:
            tail = lines[processed:]
            if any(ln.strip() and not ln.strip().startswith("#")
                   for ln in tail):
                errors = []
                specs, stop_seen = parse_jobs_lines(
                    tail, args.seed,
                    default_cycles=args.fleet_cycles,
                    start_index=processed, on_error=errors.append)
                if len(specs) > budget:
                    # Cut at the first unadmitted spec's line and
                    # RE-PARSE only the consumed prefix: its errors are
                    # reported exactly once, and a stop sentinel before
                    # the cut is honored (forcing stop_seen=False here
                    # would consume and permanently lose it), while
                    # everything past the cut re-parses next poll.
                    cut = specs[budget].index
                    errors = []
                    specs, stop_seen = parse_jobs_lines(
                        tail[:cut - processed], args.seed,
                        default_cycles=args.fleet_cycles,
                        start_index=processed, on_error=errors.append)
                    processed = cut
                else:
                    processed = len(lines)
                for msg in errors:
                    _reject_job(files, None, f"malformed line: {msg}")
                stop = stop or stop_seen
                # Duplicate ids — within a poll or ACROSS polls — would
                # alias the driver's per-job caches and collapse
                # table/resume records: first definition wins, later
                # ones are rejected (visibly, not silently dropped).
                existing = {j.job_id for j in driver.jobs}
                fresh = []
                for s in specs:
                    if s.job_id in existing:
                        _reject_job(files, s.job_id, "duplicate job id")
                        continue
                    # The admission parse seeds the driver's tree cache
                    # (one parse per eval job) — but NOT on a resumed
                    # loop: restore_jobs below may replace job.newick
                    # with the checkpointed current tree, and a
                    # pre-seeded cache would serve the stale original
                    # (and pin trees for already-done jobs forever).
                    reason = quarantine.admission_error(
                        s, driver.inst, driver.start_tree,
                        tree_cache=None if resume else driver._trees)
                    if reason is not None:
                        _reject_job(files, s.job_id, reason)
                        continue
                    existing.add(s.job_id)
                    fresh.append(s)
                specs = fresh
                if specs:
                    driver.jobs.extend(specs)
                    if resume:
                        # Apply the checkpoint snapshot to the FRESH
                        # specs only — each job sees it exactly once,
                        # as it joins the queue.  A whole-table
                        # re-application would regress jobs completed
                        # after the resume; a one-shot application
                        # would miss a finished job whose torn final
                        # line is consumed a poll later (re-running it
                        # and double-counting job.done).
                        driver.restore_jobs(resume, specs)
                    driver.apply_hang_attempts(specs)
                    files.info(f"fleet: {len(specs)} new jobs from "
                               f"{args.serve} (queue {len(driver.jobs)})")
                obs.gauge("fleet.jobs_total", len(driver.jobs))
            else:
                # Whitespace/comment-only append: a no-op, not a parse
                # attempt (and not a log line per poll).
                processed = len(lines)
        if driver.pending():
            driver.drain()
            continue
        if stop:
            files.info("fleet: stop sentinel seen and queue drained")
            break
        if args.serve_poll <= 0:
            break
        heartbeat.phase_beat("SERVE")
        preempt.check_after_checkpoint(log=files.info)
        time.sleep(args.serve_poll)
    return driver.jobs


def _write_fleet_results(args, inst, files: RunFiles, jobs) -> int:
    """Per-job results table + result trees (rank-0 gated like every
    other output).  Failed rows carry their failure cause and attempt
    count — `fleet.jobs_failed` equals the quarantine count, and each
    quarantined job's full record is in ExaML_fleetFailed.<run>."""
    ok = [j for j in jobs if j.done and not j.failed]
    failed = [j for j in jobs if j.failed]
    files.info(f"fleet: {len(ok)} jobs done, {len(failed)} failed, "
               f"{len(jobs) - len(ok) - len(failed)} pending")
    if failed:
        files.info(f"fleet: {len(failed)} quarantined job(s) with cause/"
                   "attempts/last-error in "
                   + os.path.join(args.workdir,
                                  f"ExaML_fleetFailed.{args.run_id}"))
    if ok:
        best = max(ok, key=lambda j: j.lnl)
        files.info(f"fleet: best job {best.job_id} ({best.kind}) "
                   f"likelihood {best.lnl:.6f}")
        files.log_lnl(best.lnl)
    if files.primary:
        table = os.path.join(args.workdir, f"ExaML_fleet.{args.run_id}")
        with open(table, "w") as f:
            f.write("# job_id kind index seed cycles lnl status "
                    "cause attempts\n")
            for j in jobs:
                lnl = f"{j.lnl:.6f}" if j.lnl is not None else "nan"
                status = ("failed" if j.failed
                          else "done" if j.done else "pending")
                f.write(f"{j.job_id} {j.kind} {j.index} {j.seed} "
                        f"{j.cycles_done}/{j.cycles} {lnl} {status} "
                        f"{j.cause or '-'} {j.attempts}\n")
        files.info(f"fleet results -> {table}")
        trees = [j for j in ok if j.newick]
        if trees:
            tf = os.path.join(args.workdir,
                              f"ExaML_fleetTrees.{args.run_id}")
            with open(tf, "w") as f:
                for j in trees:
                    f.write(j.newick.strip() + "\n")
            files.info(f"{len(trees)} fleet trees -> {tf}")
    return 0 if ok or not jobs else 1


def run_tree_evaluation(args, inst, files: RunFiles) -> int:
    """-f e / -f E: optimize model+branches on each tree in the file
    (reference `optimizeTrees`, `axml.c:2251-2356`), checkpointing with
    the MOD_OPT state per optimizer round and per finished tree
    (reference `axml.h:655-659`, restart dispatch `searchAlgo.c:1730-1749`
    and the -f e checkpoint leg `axml.c:2276-2296`)."""
    from examl_tpu.optimize.branch import tree_evaluate
    from examl_tpu.optimize.model_opt import mod_opt

    if not args.tree_file:
        files.info("tree evaluation mode requires -t")
        return 1
    trees_txt = _read_trees(args.tree_file)
    if not trees_txt:
        files.info(f"no trees found in {args.tree_file}")
        return 1
    files.info(f"Found {len(trees_txt)} trees to evaluate")
    fast = args.mode == "e"
    # -f e over thousands of trees: keep only the last 2 numbered
    # checkpoints (each embeds the accumulated results) and rate-limit
    # the mid-optimization cadence, else checkpoint bytes grow O(N^2).
    mgr = _checkpoint_manager(args, keep_last=2)
    last_ckpt = [0.0]
    # Gang runs (--launch) must skip the wall-clock mid-tree cadence
    # below: two-phase cycle numbers are each rank's write COUNT, and a
    # per-rank wall-clock gate would let ranks' counts drift apart —
    # once the drift exceeds keep_last the staged halves of a cycle
    # never meet and publishing stalls until the next restart resyncs
    # counters from the published set.  Gang ranks checkpoint per
    # FINISHED tree (a deterministic, rank-aligned cadence); a pending
    # preemption still stages immediately, which is safe even when
    # ranks sit on different trees — an incomplete cycle never
    # publishes, restore GCs it, and at most the in-flight tree is
    # redone.
    gang = getattr(args, "_gang", None) is not None

    start_i = 0
    results = []
    lnls = []
    resumed_tree = None
    if args.restart:
        tree = inst.tree_from_newick(trees_txt[0])   # scaffold for restore
        resume = mgr.restore(inst, tree)
        if resume is None:
            files.info("no checkpoint found; cannot restart")
            return 1
        if resume["state"] != "MOD_OPT":
            files.info(f"checkpoint state {resume['state']} is not a "
                       "tree-evaluation checkpoint")
            return 1
        ex = resume["extras"]
        start_i = ex["tree_iteration"]
        results = list(ex.get("results", []))
        lnls = list(ex.get("lnls", []))
        # Only a mid-optimization checkpoint carries a tree worth resuming
        # into; a per-finished-tree checkpoint restarts at trees_txt[i+1].
        resumed_tree = tree if ex.get("mid_tree") else None
        files.info(f"restart at tree {start_i} with likelihood "
                   f"{inst.likelihood:.6f}")

    for i in range(start_i, len(trees_txt)):
        if i == start_i and resumed_tree is not None:
            tree = resumed_tree        # mid-optimization topology+branches
        else:
            tree = inst.tree_from_newick(trees_txt[i])
        inst.evaluate(tree, full=True)

        def ckpt_cb(state: str, extras: dict, i=i, tree=tree) -> None:
            from examl_tpu.resilience import preempt
            if gang and not preempt.requested():
                return                      # gang cadence: per finished tree
            if (time.time() - last_ckpt[0] < 60.0
                    and not preempt.requested()):
                return                      # mid-tree cadence: >= 60 s apart
            merged = dict(extras)           # (a pending preemption writes
            merged.update(tree_iteration=i,  # regardless of the cadence)
                          results=results, lnls=lnls, mid_tree=True)
            mgr.write(state, merged, inst, tree)
            last_ckpt[0] = time.time()
            preempt.check_after_checkpoint(log=files.info)

        if fast and i > 0:
            tree_evaluate(inst, tree, 2.0)
        else:
            tree_evaluate(inst, tree, 1.0)
            mod_opt(inst, tree, 0.1, checkpoint_cb=ckpt_cb)
        files.info(f"Likelihood tree {i}: {inst.likelihood:.6f}")
        files.log_lnl(inst.likelihood)
        results.append(tree.to_newick(inst.alignment.taxon_names))
        lnls.append(inst.likelihood)
        # Per-finished-tree checkpoint so a restart moves on to tree i+1.
        mgr.write("MOD_OPT", {"tree_iteration": i + 1, "results": results,
                              "lnls": lnls}, inst, tree)
        last_ckpt[0] = time.time()
        from examl_tpu.resilience import heartbeat, preempt
        heartbeat.beat("TREE_EVAL")
        preempt.check_after_checkpoint(log=files.info)
    best = max(range(len(lnls)), key=lambda i: lnls[i])
    files.info(f"Evaluated {len(lnls)} trees; best is tree {best} "
               f"with likelihood {lnls[best]:.6f}")
    if files.primary:       # processID==0 gating (axml.c, every output)
        with open(files.treefile_path, "w") as f:
            f.write("\n".join(results) + "\n")
        write_model_params(files.model_path, inst)
    return 0


def _packing_report(inst, files: RunFiles) -> None:
    """Startup site-packing / load report (the reference's
    `printAssignments`/`printLoad`, `partitionAssignment.c:461-502` —
    here the 'load balance' is lane padding per state bucket)."""
    for states, bucket in sorted(inst.buckets.items()):
        true_sites = int(sum(bucket.part_widths))
        padded = bucket.num_sites
        files.info(
            f"bucket states={states}: {bucket.num_parts} partitions, "
            f"{true_sites} patterns -> {bucket.num_blocks} blocks x "
            f"{bucket.lane} lanes ({padded - true_sites} padding sites, "
            f"{100.0 * (padded - true_sites) / padded:.1f}% pad)")
        if getattr(inst, "save_memory", False):
            files.info(f"  SEV (-S) pool active for this bucket")


def main(argv=None) -> int:
    raw_argv = list(argv) if argv is not None else sys.argv[1:]
    ap = build_argparser()
    args = ap.parse_args(argv)

    # The reference's quartet flag-combination checks (axml.c:1206-1222):
    # -Y and -r belong to -f q only, and are mutually exclusive.
    if args.quartet_file and args.mode != "q":
        ap.error('you must specify "-Y quartetGroupingFileName" in '
                 'combination with "-f q"')
    if args.quartet_samples > 0 and args.mode != "q":
        ap.error('you must specify "-r randomQuartetNumber" in '
                 'combination with "-f q"')
    if args.quartet_samples > 0 and args.quartet_file:
        ap.error('you must specify either "-r randomQuartetNumber" or '
                 '"-Y quartetGroupingFileName"')

    # Fleet-mode flag hygiene: one fleet mode at a time, and the modes
    # that conflict with the batched tier's assumptions error up front.
    if args.bootstrap < 0 or args.multi_start < 0:
        ap.error("-b/-N replicate counts must be positive")
    fleet_modes = sum(bool(x) for x in (args.bootstrap, args.multi_start,
                                        args.serve))
    if fleet_modes > 1:
        ap.error("-b, -N and --serve are mutually exclusive fleet modes")
    # Declared (sites, tree) likelihood fabric (ISSUE 17): parse the
    # mesh spec at argument time so every shape error is an ap.error,
    # and pin down exactly which (S, T) combinations cannot compose.
    from examl_tpu.parallel.launch import mesh_spec_requested
    mesh_shape = None
    _mesh_spec = mesh_spec_requested(args)
    if _mesh_spec is not None:
        from examl_tpu.parallel.sharding import parse_mesh_spec
        try:
            mesh_shape = parse_mesh_spec(_mesh_spec)
        except ValueError as exc:
            ap.error(f"--mesh: {exc}")
        if args.single_device and mesh_shape != (1, 1):
            ap.error("--mesh SxT declares a device mesh; it cannot "
                     "combine with --single-device (use --mesh 1x1 "
                     "for an explicit single-device run)")
        if mesh_shape[1] > 1 and not fleet_modes:
            ap.error(f"mesh {mesh_shape[0]}x{mesh_shape[1]}: the tree "
                     "axis batches independent fleet jobs, so T>1 "
                     "needs a fleet mode (-b/-N/--serve); a single "
                     f"-f search uses --mesh {mesh_shape[0]}x1")
        if args.save_memory and mesh_shape[1] > 1:
            ap.error(f"mesh {mesh_shape[0]}x{mesh_shape[1]} cannot "
                     "compose with -S: the SEV pool holds ONE arena "
                     "per instance, so per-job arenas cannot stack "
                     "along the tree axis — only Sx1 meshes support "
                     f"-S (use --mesh {mesh_shape[0]}x1 without a "
                     "fleet mode)")
    if fleet_modes:
        if args.mode == "q":
            ap.error("fleet modes (-b/-N/--serve) replace the -f "
                     "algorithm; they cannot combine with -f q")
        if args.save_memory:
            # The one genuinely unsupported composition: the SEV pool
            # holds ONE arena per instance, so per-job arenas cannot
            # stack along the tree axis for ANY (S, T) — the precise
            # shape is named so the operator knows the mesh router
            # looked and declined, not that routing is missing.
            s_sh = mesh_shape[0] if mesh_shape else 1
            t_sh = mesh_shape[1] if mesh_shape else "J"
            ap.error(f"fleet modes do not support -S: the (S={s_sh}, "
                     f"T={t_sh}) combination cannot compose because "
                     "the SEV pool holds one arena per instance and "
                     "per-job arenas cannot stack along the tree "
                     "axis; drop -S, or run Sx1 site sharding "
                     "without a fleet mode")
        if mesh_shape is not None and args.fleet_devices != 1:
            ap.error("--mesh and --fleet-devices are mutually "
                     "exclusive: the fabric's tree axis replaces the "
                     "per-device lane round-robin (T slices of one "
                     "mesh instead of whole-device lanes)")
        if args.bootstrap and not args.tree_file:
            ap.error("-b bootstrap replicates resample weights on a "
                     "fixed topology: a starting tree (-t) is required")
        if args.fleet_job_attempts < 1:
            ap.error("--fleet-job-attempts must be at least 1")
        if args.fleet_job_deadline < 0:
            ap.error("--fleet-job-deadline must be >= 0")
        if args.serve_max_pending < 1:
            ap.error("--serve-max-pending must be at least 1")
        if args.fleet_devices < 0:
            ap.error("--fleet-devices must be >= 0 (0 = all local)")
        if args.fleet_lease_ttl <= 0:
            ap.error("--fleet-lease-ttl must be positive")
        if args.launch is None and (args.nprocs is not None
                                    or args.coordinator is not None):
            # Manually-launched multi-rank fleets route into the LEASED
            # rank contract instead of erroring: fleet ranks are NOT a
            # lockstep SPMD gang (jobs are independent), so the ranks
            # never join a collective process group — each becomes an
            # emulated gang rank leasing jobs from the shared board.
            if (args.nprocs or 1) > 1 and args.procid is None:
                # Two ranks silently sharing slot 0 would steal each
                # other's LIVE leases through the own-rank reclaim
                # path — the rank id must be explicit.
                ap.error("fleet ranks never join a collective process "
                         "group; every rank needs an explicit id: use "
                         "--nprocs N --procid K per rank (or --launch "
                         "N, which spawns the ranks itself)")
            if args.coordinator is not None and args.procid is None:
                ap.error("fleet ranks never join a collective process "
                         "group; use --nprocs N --procid K per rank "
                         "(or --launch N, which spawns the ranks)")
            # Applied to the environment inside the run (with restore),
            # so repeated in-process main() calls never leak a rank.
            args._fleet_rank = (args.procid or 0, args.nprocs or 1)
            args.nprocs = args.coordinator = args.procid = None
        # Without a declared mesh the batched tier owns the whole LOCAL
        # device set: per-job arenas stack along a leading tree axis
        # and round-robin across device lanes instead of sharding one
        # tree's site axis (exactly BEAGLE's multi-analysis
        # device-sharing trade).  A `--mesh SxT` run composes BOTH
        # instead (ISSUE 17): site shards and tree slices on one
        # fabric, so the blanket single-device pin must not fire.
        if mesh_shape is None and not getattr(args, "single_device",
                                              False):
            args.single_device = True

    from examl_tpu.resilience import faults as _faults
    if args.inject_fault:
        try:                         # validate at argument time, arm later
            _faults.parse_spec(",".join(args.inject_fault))
        except ValueError as exc:
            ap.error(f"--inject-fault: {exc}")

    if args.launch is not None:
        if args.launch < 1:
            ap.error("--launch requires at least 1 rank")
        if args.procid is not None or args.coordinator is not None \
                or args.nprocs is not None:
            ap.error("--launch spawns every rank itself (it supplies "
                     "--coordinator/--nprocs/--procid per rank); it "
                     "cannot be combined with --nprocs/--procid/"
                     "--coordinator — for a manually-launched multi-host "
                     "job drop --launch")
        # Gang mode: this process becomes the jax-free gang supervisor
        # (resilience/supervisor.GangSupervisor); every rank is a
        # killable child with EXAML_PROCID/EXAML_GANG_RANKS exported.
        # --supervise is implied (the gang IS the supervision unit).
        from examl_tpu.resilience import supervisor as _supervisor
        return _supervisor.launch_gang(raw_argv, args, log=print)

    if args.supervise:
        # Self-healing supervision: this process becomes a thin, jax-free
        # watcher (resilience/supervisor.py) and the ENTIRE run — faults,
        # banking, search — happens in killable child processes.  The
        # child gets the original argv minus the supervisor flags;
        # --inject-fault passes through so the child arms the registry.
        from examl_tpu.resilience import supervisor as _supervisor
        return _supervisor.supervise(raw_argv, args, log=print)

    from examl_tpu import obs
    from examl_tpu.parallel.launch import (enable_process_tracing,
                                           init_distributed)
    from examl_tpu.resilience import heartbeat as _heartbeat
    from examl_tpu.resilience import memgov as _memgov
    from examl_tpu.resilience import preempt as _preempt

    # One run = one metrics record: callers invoking main() repeatedly in
    # a single process (tests) must not accumulate counters across runs
    # (nor inherit a previous run's bank verdicts, fault hit-counts, or
    # heartbeat stream).
    obs.reset()
    from examl_tpu.ops import bank as _bank
    from examl_tpu.ops import export_bank as _export_bank
    _bank.reset()
    _export_bank.reset()
    _faults.reset()
    _heartbeat.reset()
    _memgov.reset()
    prior_faults_env = os.environ.get(_faults.ENV_VAR)
    from examl_tpu.obs import ledger as _ledger_mod
    _ledger_mod.reset()
    prior_ledger_env = os.environ.get(_ledger_mod.ENV_VAR)
    for spec in (args.inject_fault or []):
        _faults.arm(spec)
    # Manually-launched leased fleet rank (--nprocs/--procid routed at
    # parse time): publish the rank contract through the same env vars
    # the gang supervisor exports, restored at exit so in-process
    # callers (tests) never inherit a rank identity.
    prior_rank_env = {k: os.environ.get(k)
                      for k in (_heartbeat.PROCID_VAR,
                                _heartbeat.GANG_VAR)}
    if getattr(args, "_fleet_rank", None) is not None:
        k, n = args._fleet_rank
        os.environ[_heartbeat.PROCID_VAR] = str(k)
        if n > 1:
            os.environ[_heartbeat.GANG_VAR] = str(n)
    # One deadline definition for every compile monitor: the bank
    # workers' hard per-family kill AND the in-process watchdog bark
    # read the same knob (exported so subprocess workers inherit it).
    os.environ["EXAML_COMPILE_TIMEOUT"] = repr(float(args.compile_timeout))
    # Join the multi-host job BEFORE any output: only process 0 writes
    # run files (the reference's processID==0 gating); other processes
    # compute the same SPMD program with their files diverted to a
    # per-process scratch dir so nothing clobbers.
    init_distributed(args, log=print)
    primary = True
    gang_rank = 0
    gang_dir = args.workdir            # shared dir, BEFORE any diversion
    if args.nprocs is not None or args.coordinator is not None:
        import jax
        gang_rank = jax.process_index()
        primary = gang_rank == 0
        # Canonicalize the rank into EXAML_PROCID for manually-launched
        # multi-host jobs too (the gang supervisor already exports it):
        # rank-targeted fault specs (`point@rank=R`) and the trace
        # procid resolver key off this env var.
        os.environ.setdefault(_heartbeat.PROCID_VAR, str(gang_rank))
    elif _heartbeat.env_gang_size():
        # Emulated gang rank (--launch N --launch-emulate): no process
        # group exists, but the rank contract — process-0 output
        # gating, per-rank scratch dirs, per-rank heartbeats,
        # coordinated checkpoints in the SHARED dir — is identical.
        gang_rank = _heartbeat.env_rank()
        primary = gang_rank == 0
    if not primary:
        args.workdir = os.path.join(args.workdir, f".proc{gang_rank}")
    # Coordinated (two-phase) checkpointing applies exactly when the
    # gang supervisor spawned us: it guarantees one shared filesystem
    # and exports the world size.  Manually-launched multi-host jobs
    # keep the classic per-process checkpoint behavior.
    gang_size = _heartbeat.env_gang_size()
    args._gang = ((gang_rank, gang_size, gang_dir)
                  if gang_size and gang_size > 1 else None)
    files = RunFiles(args.workdir, args.run_id, append=args.restart,
                     primary=primary)
    # Observability wiring: per-process trace files named by procid
    # (process 0 merges a summary at exit), TraceAnnotation scopes when
    # any tracer is active, and the operator log sink into the info file
    # so watchdog barks name the guilty program family there too.
    if args.trace_events_dir:
        enable_process_tracing(args.trace_events_dir, log=files.info)
    if args.profile_dir or args.trace_events_dir:
        obs.set_annotations(True)
    # Run ledger: per-rank JSONL event stream (explicit --ledger DIR, or
    # auto-on next to the --metrics file).  Exported so subprocesses
    # (bank compile workers) append their events to the same timeline.
    from examl_tpu.obs import ledger as _ledger
    ledger_dir = _ledger.default_dir(args.ledger_dir, args.metrics_file)
    if ledger_dir:
        lpath = obs.enable_ledger(ledger_dir, proc=gang_rank)
        if lpath:
            os.environ[_ledger.ENV_VAR] = ledger_dir
            files.info(f"run ledger -> {lpath}")
    obs.ledger_event("run", status="start", run_id=args.run_id,
                     mode=args.mode, restart=bool(args.restart),
                     rank=gang_rank,
                     attempt=os.environ.get("EXAML_RESTART_COUNT"))
    # Periodic --metrics flush (heartbeat-ticked): a SIGKILLed child
    # must leave its last-known counters for the supervisor to merge,
    # not nothing (the exit-time snapshot below still wins when the
    # run ends normally).
    if args.metrics_file and files.primary:
        obs.set_autoflush(args.metrics_file)
    obs.set_log_sink(files.info)
    # Preemption safety: SIGTERM/SIGINT only SET A FLAG; the search
    # loop's checkpoint cadence turns it into an emergency checkpoint
    # and a clean resumable exit (EXIT_PREEMPTED) — no-op off the main
    # thread (threaded test drivers).  Heartbeats publish to
    # $EXAML_HEARTBEAT_FILE when set (the supervisor sets it).
    preempt_installed = _preempt.install(log=obs.log)
    from examl_tpu.parallel.launch import install_heartbeat
    install_heartbeat(args, log=files.info)
    rc = 1
    try:
        rc = _run(args, files)
        return rc
    except _preempt.PreemptCheckpointed as exc:
        obs.ledger_event("run", status="preempted", signame=exc.signame)
        files.info(f"run preempted ({exc.signame}): emergency checkpoint "
                   "written; restart with -R to resume (a --supervise "
                   "parent resumes automatically)")
        rc = _preempt.EXIT_PREEMPTED
        return rc
    except _memgov.MemoryBudgetExhausted as exc:
        # The memory governor's in-process ladder (evict + shrink +
        # halving re-dispatch) is out of moves: exit with the
        # self-diagnosed allocator-OOM status so a --supervise parent
        # classifies alloc-oom and restarts with the budget fraction
        # pinned down (NOT a tier pin — the program tier is fine).
        obs.ledger_event("run", status="alloc-oom", error=str(exc)[:200])
        files.info(f"run stopped on device-allocator OOM: {exc} "
                   "(a --supervise parent retries with a lower "
                   "EXAML_MEM_BUDGET_FRACTION pin)")
        rc = _memgov.MemoryBudgetExhausted.exit_code
        return rc
    finally:
        # The metrics snapshot and trace finalize must survive FAILED
        # runs — a wedged compile or mid-search crash is exactly when
        # the counters and the last completed span matter (the round-4
        # postmortem this subsystem exists for).
        obs.ledger_event("run", status="end", rc=rc)
        obs.set_autoflush(None)      # exit snapshot below is the record
        if args.metrics_file and files.primary:
            import json

            try:
                with open(args.metrics_file, "w") as f:
                    json.dump(obs.snapshot(), f, indent=2, sort_keys=True,
                              default=str)
                files.info(f"metrics snapshot -> {args.metrics_file}")
            except OSError as exc:
                files.info(f"metrics snapshot failed ({exc})")
        obs.set_log_sink(None)       # don't leak this run's info file
        obs.set_annotations(False)   # no TraceAnnotation cost after the run
        obs.finalize_tracing()
        obs.finalize_ledger()   # every rank merges; last exit completes it
        if preempt_installed:
            _preempt.uninstall()
        _heartbeat.reset()
        # --inject-fault arming is per-run: restore the env so repeated
        # in-process main() calls (tests) never inherit armed faults.
        if args.inject_fault:
            if prior_faults_env is None:
                os.environ.pop(_faults.ENV_VAR, None)
            else:
                os.environ[_faults.ENV_VAR] = prior_faults_env
        # Ledger export is per-run likewise.
        if prior_ledger_env is None:
            os.environ.pop(_ledger_mod.ENV_VAR, None)
        else:
            os.environ[_ledger_mod.ENV_VAR] = prior_ledger_env
        # Routed fleet-rank identity is per-run too.
        if getattr(args, "_fleet_rank", None) is not None:
            for key, val in prior_rank_env.items():
                if val is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = val


def _run(args, files: RunFiles) -> int:
    from examl_tpu.instance import PhyloInstance
    from examl_tpu.parallel.launch import select_sharding

    files.info("examl-tpu: TPU-native maximum likelihood inference "
               "(capability parity with ExaML 3.0.22)")
    files.info(f"alignment: {args.bytefile}  mode: -f {args.mode}  "
               f"model: {args.model}")

    # Validate EXAML_EXPORT_BANK ONCE, before the bank phase: a typo'd
    # opt-in must fail here in seconds, not as a per-worker engine
    # error minutes into banking (enabled()/family_coverage swallow
    # the ValueError by design — they run in seams that must not
    # crash).
    from examl_tpu.ops import export_bank as _eb
    try:
        _eb.mode()
    except ValueError as exc:
        files.info(f"ERROR: {exc}")
        return 1

    bank_report = None
    if getattr(args, "bank", False):
        # Ahead-of-time program banking, BEFORE this process touches
        # its backend: killable subprocess workers populate the
        # persistent cache (and must be able to own an
        # exclusive-access accelerator, then release it to us), wedged
        # families get their scan-tier escape hatches pinned, and —
        # multi-host — every process banks before the collective
        # barrier so no peer enters the SPMD program while another is
        # still compiling.
        from examl_tpu.ops import bank
        from examl_tpu.parallel.launch import bank_barrier
        with files.phase("bank (aot compile)"):
            bank_report = bank.run_bank(args, log=files.info)
            bank_barrier(args, log=files.info)

    with files.phase("startup (io + engines)"):
        from examl_tpu.config import enable_persistent_compilation_cache
        cache = enable_persistent_compilation_cache()
        if cache:
            files.info(f"persistent compile cache: {cache}")
        from examl_tpu.ops import export_bank
        if export_bank.enabled():
            # Zero-compile restart path (ops/export_bank.py): engines
            # built below resolve exported-artifact -> persistent-XLA-
            # cache -> fresh-compile per program; a restarted or cold
            # process reaches its first dispatch without compiling.
            files.info(export_bank.startup_info())
        try:
            sharding = select_sharding(args, args.save_memory,
                                       log=files.info)
        except ValueError as exc:
            # A declared mesh that does not fit the visible devices
            # (e.g. --mesh 4x2 on 4 chips): a configuration error with
            # the exact (S, T)-vs-devices arithmetic, not a traceback.
            files.info(f"ERROR: {exc}")
            return 1
        # Multi-process jobs read only their own site columns (the
        # reference's readMyData) — policy in selective_read_decision.
        local_window = None
        if sharding is not None:
            import jax
            nprocs = jax.process_count()
            is_bf = _is_bytefile(args.bytefile)
            has_auto = False
            if nprocs > 1 and is_bf:
                from examl_tpu.io.bytefile import (PROT_MODELS,
                                                   read_bytefile_meta)
                meta = read_bytefile_meta(args.bytefile)
                has_auto = any(PROT_MODELS[pm.prot] == "AUTO"
                               for pm in meta.parts if pm.dtype_i == 2)
            policy, reason = selective_read_decision(
                args.model, is_bf, has_auto, nprocs,
                save_memory=getattr(args, "save_memory", False))
            if policy == "error":
                files.info("ERROR: " + reason)
                return 1
            if policy == "slice":
                local_window = (jax.process_index(), nprocs)
                files.info(
                    f"{reason}: process {local_window[0]} of "
                    f"{local_window[1]} loads only its site blocks")
            elif nprocs > 1:
                files.info(f"whole-file reads per process ({reason})")
        # Setup-phase liveness (PARSE/PACK, plus SCHEDULE beats from the
        # traversal builders): large-tree host phases are minutes of
        # legitimate silence the --supervise stall detector must not
        # hang-kill — until now it only saw beats from the search loop.
        from examl_tpu.resilience import heartbeat as _hb
        _hb.phase_beat("PARSE")
        data = _load_alignment(
            args.bytefile, local_window=local_window,
            block_multiple=(sharding.num_devices if sharding else 1))
        files.info(f"{data.ntaxa} taxa, {data.total_patterns} patterns"
                   + (" (this process)" if local_window else "")
                   + f", {len(data.partitions)} partitions")

        _hb.phase_beat("PACK")
        inst = PhyloInstance(
            data, ncat=4, use_median=args.median,
            per_partition_branches=args.per_partition_bl,
            rate_model=args.model, psr_categories=args.categories,
            save_memory=args.save_memory, sharding=sharding,
            block_multiple=(sharding.num_devices if sharding else 1),
            local_window=local_window)
        inst.auto_prot_criterion = args.auto_prot
        _packing_report(inst, files)

    if bank_report is not None:
        # First-call every banked family NOW, as persistent-cache hits:
        # the engine's compile monitors fire inside this phase (counted
        # as engine.compile_count.bank_phase), so the search performs
        # zero first-call compiles — any later shape-variant compile is
        # a cache-warm member of a banked family.
        from examl_tpu.ops import bank
        with files.phase("bank (warm programs)"):
            try:
                warm_tree = (inst.tree_from_newick(
                    _read_trees(args.tree_file)[0])
                    if args.tree_file else inst.random_tree(args.seed))
                bank.warm_instance(inst, warm_tree, bank_report,
                                   files.info)
            except Exception as exc:       # noqa: BLE001 — warm is an
                # optimization; its failure must not kill the run
                files.info(f"bank warm pass failed ({exc}); programs "
                           "compile lazily (watchdogged)")

    with contextlib.ExitStack() as stack:
        if args.profile_dir:
            import jax

            stack.enter_context(jax.profiler.trace(args.profile_dir))
            files.info(f"profiler trace -> {args.profile_dir}")
        fleet = bool(args.bootstrap or args.multi_start or args.serve)
        phase_name = ("inference (fleet)" if fleet
                      else f"inference (-f {args.mode})")
        with files.phase(phase_name):
            if fleet:
                rc = run_fleet(args, inst, files)
            elif args.mode in ("d", "o"):
                rc = run_search(args, inst, files)
            elif args.mode in ("e", "E"):
                rc = run_tree_evaluation(args, inst, files)
            elif args.mode == "q":
                from examl_tpu.cli.quartets import run_quartets
                rc = run_quartets(args, inst, files)
            else:
                raise AssertionError(args.mode)
    if getattr(inst, "save_memory", False):
        for states, eng in inst.engines.items():
            st = eng.sev.stats()
            files.info(
                f"SEV bucket states={states}: {st['allocated_cells']} of "
                f"{st['dense_cells']} CLV cells allocated "
                f"({100.0 * st['saving_ratio']:.1f}% saved)")
    files.report_phases()
    return rc


if __name__ == "__main__":
    sys.exit(main())
