"""Quartet evaluation mode (-f q) entry point.

Reference: `examl/quartets.c` (`computeQuartets` :349-616).  The evaluator
lives in examl_tpu.search.quartets; this module adapts CLI arguments.
"""

from __future__ import annotations


def run_quartets(args, inst, files) -> int:
    from examl_tpu.cli.main import _checkpoint_manager
    from examl_tpu.search.quartets import QuartetOptions, compute_quartets

    # Gang-aware (--launch): quartet checkpoint cycles fire at the
    # deterministic per-interval sites, so ranks' cycle counts stay
    # aligned and the two-phase commit applies unchanged.
    mgr = _checkpoint_manager(args)
    resume = None
    if args.restart:
        tree = inst.random_tree(seed=args.seed)     # overwritten by restore
        resume = mgr.restore(inst, tree)
        if resume is None or resume["state"] != "QUARTETS":
            files.info("no quartet checkpoint found; cannot restart")
            return 1
    else:
        if not args.tree_file:
            files.info("quartet mode requires a model/full tree via -t")
            return 1
        with open(args.tree_file) as f:
            tree = inst.tree_from_newick(f.read())
    opts = QuartetOptions(
        grouping_file=args.quartet_file,
        random_samples=args.quartet_samples,
        seed=args.seed,
        epsilon=args.epsilon,
        checkpoint_interval=args.quartet_ckpt_interval,
        checkpoint_mgr=mgr,
        resume=resume)
    out = files.quartets_path
    n = compute_quartets(inst, tree, opts, out, log=files.info)
    files.info(f"{n} quartets written to {out}")
    return 0
