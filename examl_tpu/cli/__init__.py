"""Command-line entry points: the offline parser and the inference driver."""
