"""Offline alignment parser CLI: PHYLIP -> binary byteFile.

The counterpart of the reference's separate `parse-examl` binary
(`parser/axml.c`, `parser/USAGE`): reads a relaxed-PHYLIP alignment and an
optional RAxML-style partition model file, pattern-compresses each
partition, computes empirical base frequencies, prints the CAT/GAMMA
memory forecast, and writes `<name>.binary`.

Usage:  python -m examl_tpu.cli.parse -s ALN -m DNA|PROT|BIN -n NAME
                                      [-q partitionFile] [-c]
"""

from __future__ import annotations

import argparse
import sys

MODEL_TO_DATATYPE = {"DNA": "DNA", "PROT": "AA", "BIN": "BIN",
                     "BINARY": "BIN"}


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="parse-examl-tpu",
        description="convert a PHYLIP alignment into the binary byteFile "
                    "format read by the inference driver")
    ap.add_argument("-s", dest="alignment", required=True,
                    help="relaxed PHYLIP alignment file")
    ap.add_argument("-n", dest="name", required=True,
                    help="output name (writes <name>.binary)")
    ap.add_argument("-m", dest="model", default="DNA",
                    choices=sorted(MODEL_TO_DATATYPE),
                    help="data type when no -q file is given")
    ap.add_argument("-q", dest="partition_file", default=None,
                    help="RAxML-style partition model file")
    ap.add_argument("-c", dest="no_compression", action="store_true",
                    help="disable pattern compression")
    return ap


def memory_forecast(data) -> str:
    """CAT/GAMMA CLV memory forecast (reference `parser/axml.c:2846-2882`)."""
    ntaxa = data.ntaxa
    unique = sum(p.width for p in data.partitions)
    clv_cat = sum(p.states * p.width for p in data.partitions) * ntaxa * 8
    clv_gamma = clv_cat * 4
    tips = ntaxa * unique
    lines = [f"Your alignment has {unique} unique patterns"]
    for label, req in (("CAT (PSR)", clv_cat + tips),
                       ("GAMMA", clv_gamma + tips)):
        lines.append(
            f"Under {label} the memory required for storing CLVs and tip "
            f"vectors will be {req} bytes ({req / 2**20:.1f} MB, "
            f"{req / 2**30:.2f} GB)")
    lines.append("Note these are only the likelihood-buffer requirements; "
                 "leave headroom for the rest of the run.")
    return "\n".join(lines)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    from examl_tpu.io.alignment import load_alignment
    from examl_tpu.io.bytefile import write_bytefile

    data = load_alignment(args.alignment, args.partition_file,
                          datatype_name=MODEL_TO_DATATYPE[args.model],
                          compress=not args.no_compression)
    print(f"Pattern compression: "
          f"{'OFF' if args.no_compression else 'ON'}")
    print(memory_forecast(data))
    out = f"{args.name}.binary"
    write_bytefile(out, data)
    print(f"Binary and compressed alignment file written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
