"""PhyloInstance: alignment + models + device engines behind one facade.

The host-side counterpart of the reference's `tree` master struct plus its
generic entry points (`evaluateGeneric`, `newviewGeneric`,
`makenewzGeneric` — ExaML `axml.h:1223-1256`): owns per-partition model
parameters, the packed site buckets (one device program per state count),
and the CLV orientation bookkeeping against a host `Tree`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from examl_tpu.io.alignment import AlignmentData
from examl_tpu.models import protein as protein_mod
from examl_tpu.models.gtr import ModelParams, build_model
from examl_tpu.ops.engine import LikelihoodEngine
from examl_tpu.parallel.packing import pack_partitions
from examl_tpu.tree.topology import Node, Tree, TraversalEntry


def packed_site_rates(bucket, per_site_rates, rate_category) -> np.ndarray:
    """GLOBAL packed per-site rate multipliers [B, lane] for a bucket
    (padding sites keep rate 1): `perSiteRates[rateCategory]` scattered
    through the bucket's global layout.  Pure layout arithmetic — the
    same on every process of a selective-loading job because the rate
    state is host-global (each engine then materializes only its block
    window, engine._local_block_window)."""
    packed = np.ones(bucket.num_sites)
    for li, gid in enumerate(bucket.part_ids):
        packed[bucket.site_indices(li)] = \
            per_site_rates[gid][rate_category[gid]]
    return packed.reshape(bucket.num_blocks, bucket.lane)


class PhyloInstance:
    def __init__(self, alignment: AlignmentData, dtype=None,
                 ncat: int = 4, use_median: bool = False,
                 per_partition_branches: bool = False,
                 block_multiple: int = 1, sharding=None,
                 rate_model: str = "GAMMA", psr_categories: int = 25,
                 save_memory: bool = False,
                 local_window: Optional[tuple] = None):
        from examl_tpu.config import default_dtype
        if rate_model not in ("GAMMA", "PSR"):
            raise ValueError(f"unknown rate model {rate_model!r}")
        self.rate_model = rate_model
        self.psr = rate_model == "PSR"
        if self.psr:
            ncat = 1                      # one rate per site, weight 1
        self.psr_categories = psr_categories
        self.save_memory = save_memory       # SEV mode (ops/sev.py)
        self.alignment = alignment
        self.dtype = jnp.dtype(dtype if dtype is not None else default_dtype())
        self.ncat = ncat
        self.use_median = use_median
        M = len(alignment.partitions)
        self.num_parts = M
        self.per_partition_branches = per_partition_branches
        self.num_branch_slots = M if per_partition_branches else 1

        # Initial models (reference initModel `models.c:4180`): GTR rates all
        # 1.0, empirical frequencies (or the protein matrix's own), alpha 1.0.
        self.models: List[ModelParams] = []
        # AUTO partitions start from WAG (reference `models.c:4222`) until
        # autoProtein selection replaces them during modOpt.
        self.auto_prot_models: Dict[int, str] = {
            gid: "WAG" for gid, p in enumerate(alignment.partitions) if p.auto}
        self.auto_prot_freqs: Dict[int, str] = {
            gid: "fixed" for gid in self.auto_prot_models}
        for gid, part in enumerate(alignment.partitions):
            name = self.auto_prot_models.get(gid, part.model_name)
            if part.lg4:
                from examl_tpu.models.lg4 import build_lg4
                if self.psr:
                    raise ValueError(
                        "LG4 models are not supported under PSR "
                        "(the reference likewise restricts LG4 to GAMMA)")
                if ncat != 4:
                    raise ValueError("LG4 models require 4 rate categories")
                if part.optimize_freqs or part.use_empirical_freqs:
                    raise ValueError(
                        f"partition {part.name}: LG4 models carry one "
                        "frequency vector per rate category; the F/X "
                        "frequency suffixes are not applicable")
                self.models.append(build_lg4(name, alpha=1.0,
                                             use_median=use_median))
                continue
            rates, freqs = None, part.empirical_freqs
            if part.datatype.name == "AA" and name != "GTR":
                rates, model_freqs = protein_mod.get_matrix(name)
                if not part.use_empirical_freqs and not part.optimize_freqs:
                    freqs = model_freqs
            self.models.append(build_model(
                part.datatype, freqs, rates=rates, alpha=1.0, ncat=ncat,
                use_median=use_median))

        if local_window is not None:
            # Multi-host selective loading: `alignment` holds only this
            # process's site columns (io/bytefile.read_bytefile_for_process)
            # and the buckets are the matching local window of the global
            # packed axis (reference per-rank loading, byteFile.c:278-382).
            from examl_tpu.parallel.packing import pack_partitions_local
            procid, nprocs = local_window
            self.buckets = pack_partitions_local(
                alignment.partitions, procid, nprocs,
                block_multiple=block_multiple)
        else:
            self.buckets = pack_partitions(alignment.partitions,
                                           block_multiple=block_multiple)
        self.engines: Dict[int, LikelihoodEngine] = {}
        for states, bucket in self.buckets.items():
            branch_indices = ([bucket.part_ids[i] for i in range(bucket.num_parts)]
                              if per_partition_branches
                              else [0] * bucket.num_parts)
            self.engines[states] = LikelihoodEngine(
                bucket, [self.models[g] for g in bucket.part_ids],
                alignment.ntaxa, num_branch_slots=self.num_branch_slots,
                branch_indices=branch_indices, dtype=self.dtype,
                sharding=sharding, psr=self.psr, save_memory=save_memory)

        # PSR per-site rate state (reference patrat / rateCategory /
        # perSiteRates, `axml.h:585-600`): host copies per partition,
        # sized GLOBAL even under selective loading — the rate scan
        # allgathers per-site lnls to every process and the
        # categorization then runs identically everywhere (the
        # reference's Gatherv/Scatterv CAT pipeline,
        # `optimizeModel.c:2135-2254`, as one collective).
        if self.psr:
            widths = [p.global_width if p.global_width is not None
                      else p.width for p in alignment.partitions]
            self.patrat = [np.ones(w) for w in widths]
            self.site_lhs = [np.zeros(w) for w in widths]
            self.rate_category = [np.zeros(w, dtype=np.int32)
                                  for w in widths]
            self.per_site_rates = [np.ones(1) for _ in alignment.partitions]
            self.psr_invocations = 0
            self.cat_opt_rounds = 0
            self._psr_global_weights: Optional[Dict[int, np.ndarray]] = None
            self._psr_packed_weights: Dict[int, np.ndarray] = {}

        self.per_partition_lnl = np.full(M, np.nan)
        self.likelihood = np.nan
        # Smoothing state (reference partitionSmoothed/partitionConverged).
        self.partition_smoothed = np.zeros(self.num_branch_slots, dtype=bool)
        self.partition_converged = np.zeros(self.num_branch_slots, dtype=bool)

    # -- model push --------------------------------------------------------

    def push_models(self, only_states=None) -> None:
        for states, bucket in self.buckets.items():
            if only_states is not None and states not in only_states:
                continue
            self.engines[states].set_models(
                [self.models[g] for g in bucket.part_ids])

    def set_model(self, gid: int, model: ModelParams, push: bool = True) -> None:
        self.models[gid] = model
        if push:
            self.push_models()

    def push_site_rates(self) -> None:
        """Install the CATEGORIZED per-site rates into the engines' packed
        [B, lane] site-rate buffers (padding sites keep rate 1).

        Evaluation always runs under the <=25 category representatives
        (`perSiteRates[rateCategory]`); `patrat` holds each site's
        un-snapped scan optimum and only seeds the next scan (reference
        distinction between patrat and perSiteRates, `axml.h:585-600`)."""
        assert self.psr
        for states, bucket in self.buckets.items():
            self.engines[states].set_site_rates(packed_site_rates(
                bucket, self.per_site_rates, self.rate_category))

    # -- PSR global per-site state under selective loading ------------------
    # The scan/categorize pipeline is host-GLOBAL on every process (the
    # per-site lnls allgather in engine.rate_scan; the categorization is
    # deterministic), but under selective loading each process's bucket
    # holds only its window of the packed weights.  One host allgather
    # of the weight windows (contiguous, procid-ordered — they tile the
    # axis) recovers the global view every process needs for the
    # weighted crawl and the weighted-mean-rate-1 normalization — the
    # per-site-rate-state allgather replacing the reference's
    # Gatherv/Scatterv legs (`optimizeModel.c:2135-2254`).

    def psr_packed_weights(self, bucket) -> np.ndarray:
        """GLOBAL packed pattern weights [B, lane] for a bucket.
        Weights are static, so the cross-process gather runs ONCE per
        bucket and is cached — every PSR scan/normalize round reuses
        it rather than re-collecting on the search path."""
        cached = self._psr_packed_weights.get(bucket.states)
        if cached is not None:
            return cached
        w = np.asarray(bucket.weights, dtype=np.float64).reshape(
            bucket.local_num_blocks, bucket.lane)
        if bucket.is_local:
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                w = np.asarray(
                    multihost_utils.process_allgather(w, tiled=True))
            # else: a 1-process window IS global — keep w as is
        self._psr_packed_weights[bucket.states] = w
        return w

    def psr_pattern_weights(self, gid: int) -> np.ndarray:
        """GLOBAL pattern weights of partition `gid` (== the partition's
        own weights on a full read)."""
        part = self.alignment.partitions[gid]
        if getattr(part, "global_width", None) is None:
            return np.asarray(part.weights, dtype=np.float64)
        if self._psr_global_weights is None:
            self._psr_global_weights = {}
            for states, bucket in self.buckets.items():
                flat = self.psr_packed_weights(bucket).reshape(-1)
                for li, g in enumerate(bucket.part_ids):
                    self._psr_global_weights[g] = flat[
                        bucket.site_indices(li)].copy()
        return self._psr_global_weights[gid]

    # -- tree construction -------------------------------------------------

    def tree_from_newick(self, text: str) -> Tree:
        return Tree.from_newick(text, self.alignment.taxon_names,
                                self.num_branch_slots)

    def random_tree(self, seed: int = 0) -> Tree:
        return Tree.random(self.alignment.taxon_names, seed,
                           self.num_branch_slots)

    # -- CLV orientation / traversal ---------------------------------------

    def _collect(self, tree: Tree, slot: Node, full: bool) -> List[TraversalEntry]:
        if tree.is_tip(slot.number):
            return []
        return tree.compute_traversal(slot, full)

    def new_view(self, tree: Tree, slot: Node) -> None:
        """Make slot's CLV valid (reference newviewGeneric)."""
        entries = self._collect(tree, slot, full=False)
        self.run_traversal(entries)

    def run_traversal(self, entries: List[TraversalEntry],
                      only_states=None, full: bool = False) -> None:
        if not len(entries):
            return
        for states, eng in self.engines.items():
            if only_states is not None and states not in only_states:
                continue
            eng.run_traversal(entries, full=full)

    def batch_evaluator(self):
        """The fleet tier's batched many-tree evaluator over this
        instance (examl_tpu/fleet/batch.py), or None when the instance
        is ineligible (-S SEV pools, multi-process sharded arenas) —
        one evaluator per instance so its compiled-pad bookkeeping and
        prepared-job caches persist across fleet batches.  A
        fabric-sharded instance (--mesh SxT) gets the MeshShard
        evaluator: job stacks commit over the mesh's tree axis so one
        dispatch spans every slice (fleet/shard.py)."""
        ev = getattr(self, "_batch_evaluator", None)
        if ev is None:
            from examl_tpu.fleet.batch import BatchEvaluator, batch_eligible
            if batch_eligible(self) is not None:
                return None
            sh = next(iter(self.engines.values())).sharding \
                if self.engines else None
            if sh is not None and getattr(sh, "is_fabric", False):
                from examl_tpu.fleet.shard import MeshShard
                ev = self._batch_evaluator = MeshShard(self)
            else:
                ev = self._batch_evaluator = BatchEvaluator(self)
        return ev

    def invalidate_schedules(self) -> None:
        """Drop every engine's cached schedule structures.  Called from
        the search's topology-commit seams (SPR regraft, best-tree
        recall, checkpoint restore); the signature keys already make
        staleness impossible, so this is hygiene + obs evidence
        (engine.sched_cache.invalidate)."""
        for eng in self.engines.values():
            eng.sched_cache_invalidate()

    # -- likelihood --------------------------------------------------------

    def evaluate(self, tree: Tree, p: Optional[Node] = None,
                 full: bool = False, only_states=None) -> float:
        """lnL at branch (p, p.back); reference evaluateGeneric
        (`evaluateGenericSpecial.c:897-1001`).

        only_states restricts traversal+evaluation to the named state
        buckets (the reference's executeModel masking during model
        optimization): other partitions keep their cached lnL, which stays
        valid because their parameters and the tree are unchanged.  Callers
        must finish with an unrestricted evaluate before changing topology.
        """
        if p is None:
            # Full traversals root at the topological centroid, not the
            # reference's tr->start tip edge: lnL is rooting-invariant,
            # but the centroid halves the wave-schedule depth (fewer
            # sequential newview steps on device) AND maximizes -S
            # savings — subtree windows stay small on BOTH sides, so
            # far more (node, block) cells are all-gap (measured
            # tools/sev_ratio.py: 57% vs 34% block cells saved on the
            # clade-structured fixture; the reference's own per-site
            # compaction at its tip rooting saves 49%).
            p = tree.centroid_branch() if full else tree.start
        q = p.back
        if full:
            # Array-rate full traversal (tree/topology.py): one host
            # pass + numpy scheduling, carrying the topology signature
            # the engines' schedule-structure caches key on.  Subsumes
            # invalidate_all + the two compute_traversal calls (every
            # inner node recomputed and re-oriented toward this edge).
            from examl_tpu import obs
            with obs.timer("host_schedule"):
                entries = tree.flat_full_traversal(p)
        else:
            entries = (self._collect(tree, p, full)
                       + self._collect(tree, q, full))
        per_part = self.per_partition_lnl
        from examl_tpu.resilience import faults
        faults.fire("engine.dispatch")
        for states, eng in self.engines.items():
            if only_states is not None and states not in only_states:
                continue
            # Fused traversal + root evaluation: one dispatch per engine.
            vals = eng.traverse_evaluate(entries, p.number, q.number, p.z,
                                         full=full)
            if faults.fire("engine.nonfinite"):
                vals = np.full_like(np.asarray(vals, dtype=float), np.nan)
            if not np.all(np.isfinite(vals)):
                vals = self._nonfinite_retry(tree, eng, p, q)
            for li, gid in enumerate(eng.bucket.part_ids):
                per_part[gid] = vals[li]
        if only_states is not None and np.isnan(per_part).any():
            raise RuntimeError(
                "restricted evaluate before any unrestricted one: cached "
                "per-partition lnL is uninitialized for the skipped buckets")
        self.likelihood = float(per_part.sum())
        return self.likelihood

    def _nonfinite_retry(self, tree: Tree, eng, p: Node, q: Node):
        """Non-finite guard at the dispatch boundary: a NaN/−inf lnL
        from one engine means poisoned CLVs or a miscompiled fast-tier
        program (bf16 underflow past the rescaler, a bad cached kernel)
        — not a recoverable search state.  Retry ONCE on the scan tier
        with a full recompute of this engine's CLVs (the one program
        hardware-proven on every backend, the same escape hatch the
        bank pins); a second non-finite result is a hard error — a
        search step taken on a poisoned lnL silently corrupts the tree.
        Counted as engine.nonfinite_retries / .nonfinite_recovered."""
        from examl_tpu import obs
        obs.inc("engine.nonfinite_retries")
        obs.log(f"EXAML: non-finite lnL from the states={eng.bucket.states} "
                "engine; recomputing once on the scan tier")
        prior = eng.force_scan
        eng.force_scan = True
        try:
            tree.invalidate_all()
            entries = (self._collect(tree, p, True)
                       + self._collect(tree, q, True))
            vals = eng.traverse_evaluate(entries, p.number, q.number, p.z,
                                         full=True)
        finally:
            eng.force_scan = prior
        if not np.all(np.isfinite(vals)):
            raise FloatingPointError(
                "non-finite log-likelihood persists on the scan-tier "
                f"retry (states={eng.bucket.states}); refusing to search "
                "on a poisoned lnL")
        obs.inc("engine.nonfinite_recovered")
        return vals

    # -- branch-length optimization (Newton-Raphson) ------------------------

    def makenewz(self, tree: Tree, p: Node, q: Node, z0: Sequence[float],
                 maxiter: int = 1, mask_converged: bool = False) -> np.ndarray:
        """Optimize the branch (p,q) starting from z0; returns new z [C].

        Mirrors reference `topLevelMakenewz`
        (`makenewzGenericSpecial.c:1133-1349`) including curvature guards.
        """
        from examl_tpu.constants import ZMAX, ZMIN

        if len(self.engines) == 1:
            # Single state bucket (the common case): the entire operation —
            # both partial traversals, the sumtable, and the NR loop to
            # convergence — is ONE device dispatch (lax.while_loop), vs the
            # reference's one Allreduce per NR iteration
            # (`makenewzGenericSpecial.c:1241-1248`).
            from examl_tpu.utils import z_slots
            (eng,) = self.engines.values()
            entries = (self._collect(tree, p, False)
                       + self._collect(tree, q, False))
            conv = self.partition_converged if mask_converged else None
            return eng.newton_branch(entries, p.number, q.number,
                                     z_slots(z0, self.num_branch_slots),
                                     maxiter, conv)

        # Mixed state buckets: derivatives must sum across engines each NR
        # iteration, so the loop runs on host over per-engine sumtables.
        self.new_view(tree, p)
        self.new_view(tree, q)
        sts = {s: eng.make_sumtable(p.number, q.number)
               for s, eng in self.engines.items()}

        C = self.num_branch_slots
        z = np.asarray(z0, dtype=np.float64).copy()
        zprev = z.copy()
        zstep = np.zeros(C)
        maxiters = np.full(C, maxiter)
        outer_conv = np.zeros(C, dtype=bool)
        curvat_ok = np.ones(C, dtype=bool)
        if mask_converged:
            outer_conv |= self.partition_converged

        while not outer_conv.all():
            fresh = ~outer_conv & curvat_ok
            zprev = np.where(fresh, z, zprev)
            zstep = np.where(fresh, (1.0 - ZMAX) * z + ZMIN, zstep)
            curvat_ok = np.where(fresh, False, curvat_ok)

            z = np.clip(z, ZMIN, ZMAX)
            d1 = np.zeros(C)
            d2 = np.zeros(C)
            for s, eng in self.engines.items():
                e1, e2 = eng.branch_derivatives(sts[s], z)
                d1 += e1
                d2 += e2

            active = ~outer_conv & ~curvat_ok
            bad = active & (d2 >= 0.0) & (z < ZMAX)
            z = np.where(bad, 0.37 * z + 0.63, z)
            zprev = np.where(bad, z, zprev)
            curvat_ok = np.where(active & ~bad, True, curvat_ok)

            step = curvat_ok & ~outer_conv
            if step.any():
                with np.errstate(over="ignore"):
                    tantmp = np.where(d2 < 0.0, -d1 / np.where(d2 < 0, d2, 1.0),
                                      np.inf)
                    znew = np.where(tantmp < 100.0,
                                    np.clip(z * np.exp(np.minimum(tantmp, 100.0)),
                                            ZMIN, None),
                                    0.25 * zprev + 0.75)
                    znew = np.minimum(znew, 0.25 * zprev + 0.75)
                z = np.where(step & (d2 < 0.0), znew, z)
                z = np.minimum(z, ZMAX)
                maxiters = np.where(step, maxiters - 1, maxiters)
                moving = np.abs(z - zprev) > zstep
                gave_up = moving & (maxiters < -20)
                z = np.where(step & gave_up, np.asarray(z0), z)
                outer_conv = np.where(step, ~moving | gave_up, outer_conv)
        return z


def default_instance(phylip_path: str, model_path: Optional[str] = None,
                     **kwargs) -> PhyloInstance:
    from examl_tpu.io.alignment import load_alignment
    ad = load_alignment(phylip_path, model_path)
    return PhyloInstance(ad, **kwargs)
