"""Checkpoint / restart subsystem.

Reference semantics (ExaML `searchAlgo.c:1102-1750`, SURVEY §5.4):
checkpoints cover every long-running phase (REARR_SETTING / FAST_SPRS /
SLOW_SPRS / MOD_OPT, later QUARTETS), files are monotonically numbered and
never overwritten, and a restart refuses mismatched command-line flags
(`checkCommandLineArguments` :1383-1500).  Unlike the reference's raw
`node`-array dump with pointer rebasing (:1335-1370) — a design SURVEY
flags as non-portable — state is serialized as gzipped JSON: edge-list
tree snapshots, raw model parameters (rates/freqs/alpha; eigensystems are
recomputed), search counters, and the best-tree list.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Optional

import numpy as np

from examl_tpu.instance import PhyloInstance
from examl_tpu.models import protein as protein_mod
from examl_tpu.models.gtr import build_model
from examl_tpu.search.snapshots import TreeSnapshot
from examl_tpu.tree.topology import Tree

CKPT_VERSION = 1
CKPT_MAGIC = "examl-tpu-checkpoint"


class CorruptCheckpoint(ValueError):
    """A checkpoint file that cannot be parsed (truncated/corrupt gzip,
    invalid JSON, missing magic or required sections) — the restore
    fallback skips these; genuine config mismatches raise ValueError."""


def _fingerprint(inst: PhyloInstance) -> dict:
    """Alignment/flag identity that must match between run and restart."""
    al = inst.alignment
    return {
        "ntaxa": al.ntaxa,
        # Under per-process selective loading p.weights is a slice;
        # global_weight_sum (read from the byteFile's weights section)
        # keeps the fingerprint identical across any process count.
        "partitions": [[p.name, p.states,
                        int(p.global_weight_sum
                            if p.global_weight_sum is not None
                            else np.sum(p.weights))]
                       for p in al.partitions],
        "ncat": inst.ncat,
        "use_median": inst.use_median,
        "per_partition_branches": inst.per_partition_branches,
        "rate_model": getattr(inst, "rate_model", "GAMMA"),
    }


def _models_blob(inst: PhyloInstance) -> list:
    from examl_tpu.models.lg4 import LG4Params

    out = []
    for gid, m in enumerate(inst.models):
        if isinstance(m, LG4Params):
            d = {
                "lg4": m.name,
                "alpha": float(m.alpha),
                "gamma_rates": np.asarray(m.gamma_rates).tolist(),
                "rate_weights": np.asarray(m.rate_weights).tolist(),
            }
            out.append(d)
            continue
        d = {
            "rates": np.asarray(m.rates).tolist(),
            "freqs": np.asarray(m.freqs).tolist(),
            "alpha": float(m.alpha),
            "auto_name": inst.auto_prot_models.get(gid),
            "auto_freqs": inst.auto_prot_freqs.get(gid),
        }
        if getattr(inst, "psr", False):
            # Per-site rate state (reference gathers the distributed CAT
            # arrays before writing, `searchAlgo.c:1122-1146`; ours are
            # host-resident per partition already).
            d["rate_category"] = inst.rate_category[gid].tolist()
            d["per_site_rates"] = inst.per_site_rates[gid].tolist()
            # patrat = un-snapped per-site scan optima; distinct state
            # from the categorized evaluation rates (reference
            # patrat vs perSiteRates, `axml.h:585-600`).
            d["patrat"] = inst.patrat[gid].tolist()
        out.append(d)
    return out


def _restore_models(inst: PhyloInstance, blob: list) -> None:
    from dataclasses import replace as dc_replace

    from examl_tpu.models.lg4 import build_lg4

    for gid, d in enumerate(blob):
        part = inst.alignment.partitions[gid]
        if d.get("lg4"):
            m = build_lg4(d["lg4"], alpha=d["alpha"],
                          use_median=inst.use_median)
            inst.models[gid] = dc_replace(
                m, gamma_rates=np.asarray(d["gamma_rates"]),
                rate_weights=np.asarray(d["rate_weights"]))
            continue
        if d.get("auto_name"):
            inst.auto_prot_models[gid] = d["auto_name"]
        if d.get("auto_freqs"):
            inst.auto_prot_freqs[gid] = d["auto_freqs"]
        inst.models[gid] = build_model(
            part.datatype, np.asarray(d["freqs"]),
            rates=np.asarray(d["rates"]), alpha=d["alpha"],
            ncat=inst.ncat, use_median=inst.use_median)
        if getattr(inst, "psr", False) and "rate_category" in d:
            inst.rate_category[gid] = np.asarray(d["rate_category"],
                                                 dtype=np.int32)
            inst.per_site_rates[gid] = np.asarray(d["per_site_rates"])
            inst.patrat[gid] = np.asarray(
                d.get("patrat", inst.per_site_rates[gid][
                    inst.rate_category[gid]].tolist()))
    inst.push_models()
    if getattr(inst, "psr", False):
        inst.push_site_rates()


class CheckpointManager:
    """Writes numbered checkpoint files and restores the newest one.

    Usage: mgr = CheckpointManager(workdir, run_id);
    compute_big_rapid(..., checkpoint_cb=mgr.callback(inst, tree)),
    and on restart resume = mgr.restore(inst, tree).
    """

    FILE_RE = re.compile(r"\.ckpt_(\d+)\.json\.gz$")

    def __init__(self, workdir: str, run_id: str,
                 keep_last: Optional[int] = None):
        self.workdir = workdir
        self.run_id = run_id
        # keep_last: prune checkpoints older than the newest N after each
        # write (None = keep all, the search default mirroring the
        # reference's never-overwritten numbered files).  Modes that write
        # per work item (e.g. -f e over thousands of trees) pass a small
        # N so disk use stays linear.
        self.keep_last = keep_last
        os.makedirs(workdir, exist_ok=True)
        self.counter = self._max_existing() + 1

    def _pattern(self) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            ".ckpt_*.json.gz")

    def _max_existing(self) -> int:
        nums = [int(m.group(1)) for f in glob.glob(self._pattern())
                if (m := self.FILE_RE.search(f))]
        return max(nums, default=-1)

    def path_for(self, n: int) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            f".ckpt_{n}.json.gz")

    # -- write --------------------------------------------------------------

    def write(self, state: str, extras: dict, inst: PhyloInstance,
              tree: Tree, tree_dict: Optional[dict] = None) -> str:
        """tree_dict overrides the captured tree — used by quartet mode,
        where the live tree is a scaffold with asymmetric hookups that an
        edge-list snapshot cannot represent (the comprehensive model tree
        is checkpointed instead)."""
        if tree_dict is None:
            tree_dict = TreeSnapshot.capture(
                tree, getattr(inst, "likelihood", 0.0),
                with_key=False).to_dict()
        blob = {
            "magic": CKPT_MAGIC,
            "version": CKPT_VERSION,
            "state": state,
            "counter": self.counter,
            "fingerprint": _fingerprint(inst),
            "models": _models_blob(inst),
            "tree": tree_dict,
            "extras": extras,
        }
        path = self.path_for(self.counter)
        tmp = path + ".tmp"
        from examl_tpu.resilience import faults
        try:
            with gzip.open(tmp, "wt") as f:
                json.dump(blob, f)
            # fsync the CLOSED tmp (the gzip trailer — final deflate
            # block + CRC/ISIZE — is only written at close) BEFORE the
            # rename, and fsync the DIRECTORY after: os.replace alone
            # is only atomic against concurrent readers — after a hard
            # kill or power loss an un-fsynced "published" checkpoint
            # can come back truncated or as a dangling directory entry,
            # which is exactly the artifact the restore fallback exists
            # to route around; the write side must not manufacture it.
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            # Fault seam: `checkpoint.write` fires between the tmp
            # write and the publish — a raise (default) models a full
            # disk / I/O error, `:signal=KILL` models dying mid-write:
            # either way the previously PUBLISHED checkpoint is intact.
            faults.fire("checkpoint.write")
            os.replace(tmp, path)   # atomic publish; never overwrite older
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        try:                        # directory-entry durability: best
            dirfd = os.open(self.workdir, os.O_RDONLY)  # effort on
            try:                    # filesystems that reject dir fsync
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass
        self.counter += 1
        self._prune()
        return path

    def _prune(self) -> None:
        """Sweep EVERY on-disk index older than the newest keep_last: a
        crash between publish and prune, or a keep_last that shrank
        across a restart, leaves older orphans that a newest-expired-only
        removal would leak forever."""
        if self.keep_last is None:
            return
        cutoff = self.counter - self.keep_last
        for f in glob.glob(self._pattern()):
            m = self.FILE_RE.search(f)
            if m and int(m.group(1)) < cutoff:
                try:
                    os.remove(f)
                except FileNotFoundError:
                    pass

    def callback(self, inst: PhyloInstance, tree: Tree):
        def cb(state: str, extras: dict) -> None:
            self.write(state, extras, inst, tree)
        return cb

    # -- restore ------------------------------------------------------------

    def latest_path(self) -> Optional[str]:
        n = self._max_existing()
        return self.path_for(n) if n >= 0 else None

    def restore(self, inst: PhyloInstance, tree: Tree,
                path: Optional[str] = None) -> Optional[dict]:
        """Load the newest readable checkpoint into inst+tree; returns
        the resume blob for compute_big_rapid, or None if no (intact)
        checkpoint exists.

        A checkpoint that fails to PARSE — truncated/corrupt gzip,
        invalid JSON, wrong magic — is skipped with a logged warning
        and the next-newest numbered file is tried: a kill or power
        loss at exactly the wrong moment must cost one checkpoint
        interval, not every restart attempt forever.  An explicit
        `path` disables the fallback (the caller asked for THAT file).

        Raises ValueError on an incompatible run configuration (the
        reference aborts on mismatched restart flags) — configuration
        mismatch is operator error, not corruption, and silently
        resuming an older file would hide it."""
        if path is not None:
            return self._restore_one(inst, tree, path)
        from examl_tpu import obs
        nums = sorted(
            (int(m.group(1)) for f in glob.glob(self._pattern())
             if (m := self.FILE_RE.search(f))), reverse=True)
        for n in nums:
            p = self.path_for(n)
            try:
                return self._restore_one(inst, tree, p)
            except CorruptCheckpoint as exc:
                obs.inc("checkpoint.corrupt_skipped")
                obs.log(f"EXAML: checkpoint {p} unreadable ({exc}); "
                        "falling back to the next-newest checkpoint")
        if nums:
            obs.log(f"EXAML: all {len(nums)} checkpoint(s) for run "
                    f"'{self.run_id}' are unreadable; nothing to resume")
        return None

    def _restore_one(self, inst: PhyloInstance, tree: Tree,
                     path: str) -> dict:
        try:
            with gzip.open(path, "rt") as f:
                blob = json.load(f)
        except (OSError, EOFError, ValueError, gzip.BadGzipFile) as exc:
            # EOFError/BadGzipFile: truncated/garbage gzip stream (the
            # partial-write-at-kill-time artifact); ValueError covers
            # json.JSONDecodeError and bad gzip headers.
            raise CorruptCheckpoint(f"{type(exc).__name__}: {exc}") \
                from exc
        if not isinstance(blob, dict) or blob.get("magic") != CKPT_MAGIC:
            raise CorruptCheckpoint(f"not an examl-tpu checkpoint: {path}")
        missing = [k for k in ("fingerprint", "models", "tree", "state")
                   if k not in blob]
        if missing:
            raise CorruptCheckpoint(
                f"checkpoint missing section(s) {missing}: {path}")
        if blob.get("version") != CKPT_VERSION:
            raise ValueError(f"checkpoint version {blob.get('version')} "
                             f"unsupported")
        fp_now = _fingerprint(inst)
        fp_ckpt = blob["fingerprint"]
        if fp_now != fp_ckpt:
            raise ValueError(
                "checkpoint was written for a different run configuration "
                f"(checkpoint {fp_ckpt} vs current {fp_now}); restart must "
                "use the same alignment, partitions, and model flags")
        _restore_models(inst, blob["models"])
        TreeSnapshot.from_dict(blob["tree"]).restore_into(tree)
        # -R restore: the resumed search starts from a COLD schedule
        # cache — a pre-restore structure must not linger (the signature
        # keys would reject it anyway; this makes the cold start
        # explicit and counted).
        inst.invalidate_schedules()
        inst.evaluate(tree, full=True)
        return {"state": blob["state"], "extras": blob["extras"]}
