"""Checkpoint / restart subsystem.

Reference semantics (ExaML `searchAlgo.c:1102-1750`, SURVEY §5.4):
checkpoints cover every long-running phase (REARR_SETTING / FAST_SPRS /
SLOW_SPRS / MOD_OPT, later QUARTETS), files are monotonically numbered and
never overwritten, and a restart refuses mismatched command-line flags
(`checkCommandLineArguments` :1383-1500).  Unlike the reference's raw
`node`-array dump with pointer rebasing (:1335-1370) — a design SURVEY
flags as non-portable — state is serialized as gzipped JSON: edge-list
tree snapshots, raw model parameters (rates/freqs/alpha; eigensystems are
recomputed), search counters, and the best-tree list.

GANG RUNS (`--launch N`, resilience/supervisor.py) make the checkpoint
cycle a TWO-PHASE COMMIT: every rank fsyncs a per-rank staging record
into the shared workdir (rank 0 stages the full blob, peers stage tiny
attest markers), and the published `.ckpt_N.json.gz` appears — one
atomic rename of rank 0's fsynced blob — only once EVERY rank of the
current attempt has staged cycle N, so a mid-cycle gang kill can never
serve a checkpoint some rank never reached.  Stale partial cycles are
garbage-collected at restore (`checkpoint.partial_cycles_gced`).

ELASTIC RESTORE: the fingerprint records the world size (`nprocs`) but
the mismatch check ALLOWLISTS it — site slices are re-derived from the
byteFile window at parse time and checkpoint state is topology+model,
so a gang may resume under a different rank count.  Anything genuinely
sliced still hard-fails (every other fingerprint key, and a PSR
rate-state section whose length does not tile the global pattern
count).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Optional

import numpy as np

from examl_tpu.instance import PhyloInstance
from examl_tpu.models import protein as protein_mod
from examl_tpu.models.gtr import build_model
from examl_tpu.search.snapshots import TreeSnapshot
from examl_tpu.tree.topology import Tree

CKPT_VERSION = 1
CKPT_MAGIC = "examl-tpu-checkpoint"

# Fingerprint keys allowed to DIFFER between write and restore: the
# world-size-independent allowlist of the elastic-resume contract.
# Everything else is identity (alignment, partitions, model flags) and
# hard-fails, exactly as before.
ELASTIC_FP_KEYS = frozenset({"nprocs"})


def _world_size() -> int:
    """The world size recorded in fingerprints: the gang size when the
    gang supervisor spawned us (`EXAML_GANG_RANKS`, set in BOTH real
    and emulated gang modes), else jax's process count (1 when no
    distributed client exists)."""
    env = os.environ.get("EXAML_GANG_RANKS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    try:
        import jax
        return jax.process_count()
    except Exception:                 # noqa: BLE001 — jax-free callers
        return 1


def _gang_attempt() -> int:
    """The supervisor attempt this process belongs to — stage markers
    are attempt-stamped so a dead attempt's leftovers can never
    complete a NEW attempt's checkpoint cycle.  One parser for
    EXAML_RESTART_COUNT: resilience/faults.py owns it."""
    from examl_tpu.resilience import faults
    return faults._attempt()


class CorruptCheckpoint(ValueError):
    """A checkpoint file that cannot be parsed (truncated/corrupt gzip,
    invalid JSON, missing magic or required sections) — the restore
    fallback skips these; genuine config mismatches raise ValueError."""


def _fingerprint(inst: PhyloInstance) -> dict:
    """Alignment/flag identity that must match between run and restart."""
    al = inst.alignment
    return {
        "ntaxa": al.ntaxa,
        # Under per-process selective loading p.weights is a slice;
        # global_weight_sum (read from the byteFile's weights section)
        # keeps the fingerprint identical across any process count.
        "partitions": [[p.name, p.states,
                        int(p.global_weight_sum
                            if p.global_weight_sum is not None
                            else np.sum(p.weights))]
                       for p in al.partitions],
        "ncat": inst.ncat,
        "use_median": inst.use_median,
        "per_partition_branches": inst.per_partition_branches,
        "rate_model": getattr(inst, "rate_model", "GAMMA"),
        # Recorded for the artifact trail; ALLOWLISTED at restore
        # (ELASTIC_FP_KEYS) — a gang may resume under a different
        # world size.
        "nprocs": _world_size(),
    }


def _models_blob(inst: PhyloInstance) -> list:
    from examl_tpu.models.lg4 import LG4Params

    out = []
    for gid, m in enumerate(inst.models):
        if isinstance(m, LG4Params):
            d = {
                "lg4": m.name,
                "alpha": float(m.alpha),
                "gamma_rates": np.asarray(m.gamma_rates).tolist(),
                "rate_weights": np.asarray(m.rate_weights).tolist(),
            }
            out.append(d)
            continue
        d = {
            "rates": np.asarray(m.rates).tolist(),
            "freqs": np.asarray(m.freqs).tolist(),
            "alpha": float(m.alpha),
            "auto_name": inst.auto_prot_models.get(gid),
            "auto_freqs": inst.auto_prot_freqs.get(gid),
        }
        if getattr(inst, "psr", False):
            # Per-site rate state (reference gathers the distributed CAT
            # arrays before writing, `searchAlgo.c:1122-1146`; ours are
            # host-resident per partition already).
            d["rate_category"] = inst.rate_category[gid].tolist()
            d["per_site_rates"] = inst.per_site_rates[gid].tolist()
            # patrat = un-snapped per-site scan optima; distinct state
            # from the categorized evaluation rates (reference
            # patrat vs perSiteRates, `axml.h:585-600`).
            d["patrat"] = inst.patrat[gid].tolist()
        out.append(d)
    return out


def _restore_models(inst: PhyloInstance, blob: list) -> None:
    from dataclasses import replace as dc_replace

    from examl_tpu.models.lg4 import build_lg4

    for gid, d in enumerate(blob):
        part = inst.alignment.partitions[gid]
        if d.get("lg4"):
            m = build_lg4(d["lg4"], alpha=d["alpha"],
                          use_median=inst.use_median)
            inst.models[gid] = dc_replace(
                m, gamma_rates=np.asarray(d["gamma_rates"]),
                rate_weights=np.asarray(d["rate_weights"]))
            continue
        if d.get("auto_name"):
            inst.auto_prot_models[gid] = d["auto_name"]
        if d.get("auto_freqs"):
            inst.auto_prot_freqs[gid] = d["auto_freqs"]
        inst.models[gid] = build_model(
            part.datatype, np.asarray(d["freqs"]),
            rates=np.asarray(d["rates"]), alpha=d["alpha"],
            ncat=inst.ncat, use_median=inst.use_median)
        if getattr(inst, "psr", False) and "rate_category" in d:
            # Elastic-restore guard: PSR rate state is kept GLOBAL-width
            # on every process (PR2's allgather contract), so a section
            # whose length does not match the partition's global pattern
            # count was written SLICED — genuinely world-size-dependent
            # state the elastic allowlist must never paper over.
            cat = np.asarray(d["rate_category"], dtype=np.int32)
            want = int(part.global_width
                       if getattr(part, "global_width", None) is not None
                       else part.width)
            if cat.size != want:
                raise ValueError(
                    f"checkpoint section models[{gid}].rate_category "
                    f"carries {cat.size} sites but partition "
                    f"'{part.name}' has {want} global patterns — a "
                    "world-size-dependent (sliced) section cannot "
                    "restore elastically")
            inst.rate_category[gid] = cat
            inst.per_site_rates[gid] = np.asarray(d["per_site_rates"])
            inst.patrat[gid] = np.asarray(
                d.get("patrat", inst.per_site_rates[gid][
                    inst.rate_category[gid]].tolist()))
    inst.push_models()
    if getattr(inst, "psr", False):
        inst.push_site_rates()


class CheckpointManager:
    """Writes numbered checkpoint files and restores the newest one.

    Usage: mgr = CheckpointManager(workdir, run_id);
    compute_big_rapid(..., checkpoint_cb=mgr.callback(inst, tree)),
    and on restart resume = mgr.restore(inst, tree).
    """

    FILE_RE = re.compile(r"\.ckpt_(\d+)\.json\.gz$")
    STAGE_RE = re.compile(r"\.ckpt_(\d+)\.stage\.(blob|r\d+)$")

    def __init__(self, workdir: str, run_id: str,
                 keep_last: Optional[int] = None,
                 gang_rank: int = 0, gang_size: int = 1):
        self.workdir = workdir
        self.run_id = run_id
        # keep_last: prune checkpoints older than the newest N after each
        # write (None = keep all, the search default mirroring the
        # reference's never-overwritten numbered files).  Modes that write
        # per work item (e.g. -f e over thousands of trees) pass a small
        # N so disk use stays linear.
        self.keep_last = keep_last
        # Gang runs (--launch N): `workdir` is the SHARED gang directory
        # (every rank's manager points at the same one — lockstep keeps
        # their cycle counters aligned), writes become the two-phase
        # stage/publish protocol, and only published cycles are ever
        # restored.  gang_size <= 1 is the classic single-writer path.
        self.gang_rank = int(gang_rank)
        self.gang_size = max(1, int(gang_size))
        os.makedirs(workdir, exist_ok=True)
        self.counter = self._max_existing() + 1

    def _pattern(self) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            ".ckpt_*.json.gz")

    def _stage_pattern(self) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            ".ckpt_*.stage.*")

    def _stage_blob(self, n: int) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            f".ckpt_{n}.stage.blob")

    def _stage_marker(self, n: int, rank: int) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            f".ckpt_{n}.stage.r{rank}")

    def _max_existing(self) -> int:
        nums = [int(m.group(1)) for f in glob.glob(self._pattern())
                if (m := self.FILE_RE.search(f))]
        return max(nums, default=-1)

    def path_for(self, n: int) -> str:
        return os.path.join(self.workdir,
                            f"ExaML_binaryCheckpoint.{self.run_id}"
                            f".ckpt_{n}.json.gz")

    # -- write --------------------------------------------------------------

    def _fsync_file(self, path: str) -> None:
        """fsync a CLOSED file (the gzip trailer — final deflate block +
        CRC/ISIZE — is only written at close) BEFORE any rename: after a
        hard kill or power loss an un-fsynced "published" file can come
        back truncated or as a dangling directory entry, which is
        exactly the artifact the restore fallback exists to route
        around; the write side must not manufacture it."""
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _fsync_dir(self) -> None:
        try:                        # directory-entry durability: best
            dirfd = os.open(self.workdir, os.O_RDONLY)  # effort on
            try:                    # filesystems that reject dir fsync
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass

    def write(self, state: str, extras: dict, inst: PhyloInstance,
              tree: Tree, tree_dict: Optional[dict] = None) -> str:
        """tree_dict overrides the captured tree — used by quartet mode,
        where the live tree is a scaffold with asymmetric hookups that an
        edge-list snapshot cannot represent (the comprehensive model tree
        is checkpointed instead).

        Gang managers (gang_size > 1) take the two-phase path: rank 0
        stages the full blob, every rank stages an attest marker, and
        the cycle PUBLISHES (atomic rename of the fsynced blob) only
        once all ranks of the current attempt have staged — whichever
        rank completes the set performs the rename, so nobody blocks.
        Returns the (eventual) published path either way."""
        if self.gang_size > 1:
            return self._write_gang(state, extras, inst, tree, tree_dict)
        if tree_dict is None:
            tree_dict = TreeSnapshot.capture(
                tree, getattr(inst, "likelihood", 0.0),
                with_key=False).to_dict()
        blob = self._blob(state, extras, inst, tree_dict)
        path = self.path_for(self.counter)
        tmp = path + ".tmp"
        from examl_tpu.resilience import faults
        try:
            with gzip.open(tmp, "wt") as f:
                json.dump(blob, f)
            self._fsync_file(tmp)
            # Fault seam: `checkpoint.write` fires between the tmp
            # write and the publish — a raise (default) models a full
            # disk / I/O error, `:signal=KILL` models dying mid-write:
            # either way the previously PUBLISHED checkpoint is intact.
            faults.fire("checkpoint.write")
            os.replace(tmp, path)   # atomic publish; never overwrite older
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fsync_dir()
        try:
            from examl_tpu import obs
            obs.ledger_event("checkpoint.publish", cycle=self.counter,
                             state=state)
        except Exception:             # noqa: BLE001
            pass
        self.counter += 1
        self._prune()
        return path

    def _blob(self, state: str, extras: dict, inst: PhyloInstance,
              tree_dict: dict) -> dict:
        return {
            "magic": CKPT_MAGIC,
            "version": CKPT_VERSION,
            "state": state,
            "counter": self.counter,
            "fingerprint": _fingerprint(inst),
            "models": _models_blob(inst),
            "tree": tree_dict,
            "extras": extras,
        }

    # -- gang two-phase commit ----------------------------------------------

    def _write_gang(self, state: str, extras: dict, inst: PhyloInstance,
                    tree, tree_dict: Optional[dict]) -> str:
        """Phase 1 of the gang checkpoint cycle: STAGE.  Rank 0 fsyncs
        the full blob to `.ckpt_N.stage.blob`; every rank then fsyncs
        its attest marker `.ckpt_N.stage.r<k>` (attempt-stamped, so a
        dead attempt's leftovers can never complete a new attempt's
        cycle).  Phase 2 (`_try_publish`) runs after staging."""
        import time as _time
        n = self.counter
        from examl_tpu.resilience import faults
        if self.gang_rank == 0:
            if tree_dict is None:
                tree_dict = TreeSnapshot.capture(
                    tree, getattr(inst, "likelihood", 0.0),
                    with_key=False).to_dict()
            blob = self._blob(state, extras, inst, tree_dict)
            stage = self._stage_blob(n)
            tmp = stage + ".tmp"
            try:
                with gzip.open(tmp, "wt") as f:
                    json.dump(blob, f)
                self._fsync_file(tmp)
                # Same seam/semantics as the single-writer path: dying
                # here leaves the previously PUBLISHED cycle intact.
                faults.fire("checkpoint.write")
                os.replace(tmp, stage)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        else:
            faults.fire("checkpoint.write")
        marker = self._stage_marker(n, self.gang_rank)
        tmp = f"{marker}.tmp.{os.getpid()}"
        rec = {"rank": self.gang_rank, "cycle": n,
               "attempt": _gang_attempt(), "pid": os.getpid(),
               "t": _time.time()}
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f)
            self._fsync_file(tmp)
            os.replace(tmp, marker)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fsync_dir()
        self.counter += 1
        self._try_publish(n)
        return self.path_for(n)

    def _read_marker(self, path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _try_publish(self, n: int) -> bool:
        """Phase 2: PUBLISH cycle `n` iff rank 0's blob and EVERY rank's
        current-attempt marker are staged.  Ranks stage each cycle
        exactly once, so the last rank to stage is the one that sees the
        complete set; racing publishers are harmless (the atomic rename
        has one winner; the loser's FileNotFoundError means 'already
        published')."""
        blob = self._stage_blob(n)
        if not os.path.exists(blob):
            return False
        attempt = _gang_attempt()
        for k in range(self.gang_size):
            rec = self._read_marker(self._stage_marker(n, k))
            if rec is None or rec.get("attempt") != attempt:
                return False
        # Fault seam: `checkpoint.publish` fires between the completed
        # staging phase and the publish rename — `:signal=KILL` models a
        # gang dying exactly between the two phases; restore must fall
        # back to the previous COMPLETE cycle.
        from examl_tpu.resilience import faults
        faults.fire("checkpoint.publish")
        try:
            # graftlint: disable=GL007 -- the blob was fsynced at STAGE
            # time (_write_gang fsyncs tmp before renaming to .stage);
            # phase 2 is a rename of already-durable bytes.
            os.replace(blob, self.path_for(n))
        except FileNotFoundError:
            return True               # a peer won the publish race
        self._fsync_dir()
        for k in range(self.gang_size):
            try:
                os.unlink(self._stage_marker(n, k))
            except OSError:
                pass
        try:
            from examl_tpu import obs
            obs.inc("checkpoint.gang_publishes")
            obs.ledger_event("checkpoint.publish", cycle=n,
                             rank=self.gang_rank, world=self.gang_size)
        except Exception:             # noqa: BLE001
            pass
        self._prune()
        return True

    def gc_partial_cycles(self) -> int:
        """Remove ALL staging leftovers (no cycle is in flight at
        restore time) and count the distinct cycles that never
        published — the mid-cycle-kill evidence
        (`checkpoint.partial_cycles_gced`).  A published cycle's
        leftover markers (publisher killed mid-unlink) are swept
        silently: that cycle committed."""
        published = {int(m.group(1)) for f in glob.glob(self._pattern())
                     if (m := self.FILE_RE.search(f))}
        partial = set()
        for f in glob.glob(self._stage_pattern()):
            m = self.STAGE_RE.search(f)
            if m and int(m.group(1)) not in published:
                partial.add(int(m.group(1)))
            try:
                os.unlink(f)
            except OSError:
                pass
        if partial:
            try:
                from examl_tpu import obs
                obs.inc("checkpoint.partial_cycles_gced", len(partial))
                obs.ledger_event("checkpoint.gc", cycles=sorted(partial))
                obs.log(f"EXAML: garbage-collected {len(partial)} "
                        "partially-staged checkpoint cycle(s) "
                        f"{sorted(partial)} (gang killed mid-cycle); "
                        "restoring the newest COMPLETE cycle")
            except Exception:         # noqa: BLE001
                pass
        return len(partial)

    def _prune(self) -> None:
        """Sweep EVERY on-disk index older than the newest keep_last: a
        crash between publish and prune, or a keep_last that shrank
        across a restart, leaves older orphans that a newest-expired-only
        removal would leak forever.  Staging leftovers age out on the
        same cutoff."""
        if self.keep_last is None:
            return
        cutoff = self.counter - self.keep_last
        for pattern, regex in ((self._pattern(), self.FILE_RE),
                               (self._stage_pattern(), self.STAGE_RE)):
            for f in glob.glob(pattern):
                m = regex.search(f)
                if m and int(m.group(1)) < cutoff:
                    try:
                        os.remove(f)
                    except FileNotFoundError:
                        pass

    def callback(self, inst: PhyloInstance, tree: Tree):
        def cb(state: str, extras: dict) -> None:
            self.write(state, extras, inst, tree)
        return cb

    # -- restore ------------------------------------------------------------

    def latest_path(self) -> Optional[str]:
        n = self._max_existing()
        return self.path_for(n) if n >= 0 else None

    def restore(self, inst: PhyloInstance, tree: Tree,
                path: Optional[str] = None) -> Optional[dict]:
        """Load the newest readable checkpoint into inst+tree; returns
        the resume blob for compute_big_rapid, or None if no (intact)
        checkpoint exists.

        A checkpoint that fails to PARSE — truncated/corrupt gzip,
        invalid JSON, wrong magic — is skipped with a logged warning
        and the next-newest numbered file is tried: a kill or power
        loss at exactly the wrong moment must cost one checkpoint
        interval, not every restart attempt forever.  An explicit
        `path` disables the fallback (the caller asked for THAT file).

        Raises ValueError on an incompatible run configuration (the
        reference aborts on mismatched restart flags) — configuration
        mismatch is operator error, not corruption, and silently
        resuming an older file would hide it."""
        if path is not None:
            return self._restore_one(inst, tree, path)
        from examl_tpu import obs
        # Two-phase hygiene: sweep staging leftovers BEFORE choosing a
        # cycle, so a gang killed between stage and publish resumes
        # from the newest COMPLETE cycle and the evidence lands in
        # `checkpoint.partial_cycles_gced`.  Rank 0 only: gang ranks
        # restore at independent moments, and a slow peer's restore
        # must not unlink a cycle a fast peer has already re-staged.
        # (Residual race — a peer stages before rank 0's own restore —
        # costs at most one unpublished interval, never correctness:
        # the next cycle stages on every rank and publishes normally.)
        if self.gang_rank == 0:
            self.gc_partial_cycles()
        nums = sorted(
            (int(m.group(1)) for f in glob.glob(self._pattern())
             if (m := self.FILE_RE.search(f))), reverse=True)
        for n in nums:
            p = self.path_for(n)
            try:
                return self._restore_one(inst, tree, p)
            except CorruptCheckpoint as exc:
                obs.inc("checkpoint.corrupt_skipped")
                obs.ledger_event("checkpoint.corrupt_skipped", cycle=n,
                                 error=str(exc)[:200])
                obs.log(f"EXAML: checkpoint {p} unreadable ({exc}); "
                        "falling back to the next-newest checkpoint")
        if nums:
            obs.log(f"EXAML: all {len(nums)} checkpoint(s) for run "
                    f"'{self.run_id}' are unreadable; nothing to resume")
        return None

    def _restore_one(self, inst: PhyloInstance, tree: Tree,
                     path: str) -> dict:
        try:
            with gzip.open(path, "rt") as f:
                blob = json.load(f)
        except (OSError, EOFError, ValueError, gzip.BadGzipFile) as exc:
            # EOFError/BadGzipFile: truncated/garbage gzip stream (the
            # partial-write-at-kill-time artifact); ValueError covers
            # json.JSONDecodeError and bad gzip headers.
            raise CorruptCheckpoint(f"{type(exc).__name__}: {exc}") \
                from exc
        if not isinstance(blob, dict) or blob.get("magic") != CKPT_MAGIC:
            raise CorruptCheckpoint(f"not an examl-tpu checkpoint: {path}")
        missing = [k for k in ("fingerprint", "models", "tree", "state")
                   if k not in blob]
        if missing:
            raise CorruptCheckpoint(
                f"checkpoint missing section(s) {missing}: {path}")
        if blob.get("version") != CKPT_VERSION:
            raise ValueError(f"checkpoint version {blob.get('version')} "
                             f"unsupported")
        fp_now = _fingerprint(inst)
        fp_ckpt = blob["fingerprint"]
        hard, elastic = [], []
        for k in sorted(set(fp_now) | set(fp_ckpt)):
            if k in fp_now and k in fp_ckpt and fp_now[k] == fp_ckpt[k]:
                continue
            if k in ELASTIC_FP_KEYS:
                # World-size-independent by design (site slices
                # re-derive at parse time); a key missing on one side
                # is an older-format checkpoint — tolerated silently.
                if k in fp_now and k in fp_ckpt:
                    elastic.append(k)
                continue
            hard.append(k)
        if hard:
            raise ValueError(
                "checkpoint was written for a different run configuration "
                f"(mismatched section(s) {hard}: checkpoint {fp_ckpt} vs "
                f"current {fp_now}); restart must use the same alignment, "
                "partitions, and model flags")
        if elastic:
            from examl_tpu import obs
            obs.inc("checkpoint.elastic_restores")
            obs.log(
                "EXAML: elastic restore: checkpoint written at nprocs="
                f"{fp_ckpt.get('nprocs')}, resuming at nprocs="
                f"{fp_now.get('nprocs')} — checkpoint state is "
                "topology+model and site slices re-derive from the "
                "byteFile window at parse time")
        _restore_models(inst, blob["models"])
        TreeSnapshot.from_dict(blob["tree"]).restore_into(tree)
        # -R restore: the resumed search starts from a COLD schedule
        # cache — a pre-restore structure must not linger (the signature
        # keys would reject it anyway; this makes the cold start
        # explicit and counted).
        inst.invalidate_schedules()
        inst.evaluate(tree, full=True)
        try:
            from examl_tpu import obs
            obs.ledger_event("checkpoint.restore",
                             cycle=blob.get("counter"),
                             state=blob["state"])
        except Exception:             # noqa: BLE001
            pass
        return {"state": blob["state"], "extras": blob["extras"]}
