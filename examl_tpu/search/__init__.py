"""Tree-search layer: SPR hill climbing, tree snapshots, search driver."""
